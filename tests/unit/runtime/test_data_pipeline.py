"""Data-efficiency pipeline: curriculum schedules/sampling + random-LTD."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler, CurriculumSampler, DeepSpeedDataSampler,
    RandomLTDScheduler, random_ltd_apply)
from deepspeed_tpu.runtime.data_pipeline.data_sampler import truncate_batch

pytestmark = pytest.mark.slow  # jit/engine-heavy; smoke tier runs -m "not slow"


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------

def test_fixed_linear_schedule_monotone_and_quantized():
    s = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 64,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100,
                            "difficulty_step": 8}})
    vals = [s.get_difficulty(t) for t in range(0, 140, 10)]
    assert vals[0] == 8 and vals[-1] == 64
    assert all(b >= a for a, b in zip(vals, vals[1:]))
    assert all(v % 8 == 0 for v in vals)


def test_fixed_root_reaches_max_faster_than_linear():
    common = dict(min_difficulty=0, max_difficulty=100,
                  schedule_config={"total_curriculum_step": 100,
                                   "difficulty_step": 1})
    lin = CurriculumScheduler({**common, "schedule_type": "fixed_linear"})
    root = CurriculumScheduler({**common, "schedule_type": "fixed_root"})
    assert root.get_difficulty(25) > lin.get_difficulty(25)


def test_fixed_discrete_schedule():
    s = CurriculumScheduler({
        "min_difficulty": 10, "max_difficulty": 40,
        "schedule_type": "fixed_discrete",
        "schedule_config": {"difficulty": [10, 20, 40],
                            "max_step": [5, 10, 10 ** 9]}})
    assert s.get_difficulty(3) == 10
    assert s.get_difficulty(7) == 20
    assert s.get_difficulty(100) == 40


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

def test_curriculum_sampler_pool_grows():
    diffs = np.arange(100)  # sample i has difficulty i
    s = CurriculumScheduler({
        "min_difficulty": 10, "max_difficulty": 100,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 50,
                            "difficulty_step": 10}})
    samp = CurriculumSampler(diffs, s, seed=7)
    early = samp.sample(step=0, batch_size=256)
    late = samp.sample(step=100, batch_size=256)
    assert early.max() <= 10          # only easy samples at step 0
    assert late.max() > 50            # full pool later
    # deterministic
    np.testing.assert_array_equal(early, samp.sample(0, 256))


def test_data_sampler_iterates_batches():
    data = [{"input_ids": np.full((8,), i)} for i in range(50)]
    ds = DeepSpeedDataSampler(
        data, difficulties=np.arange(50), batch_size=4,
        curriculum_config={
            "min_difficulty": 5, "max_difficulty": 50,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 10,
                                "difficulty_step": 5}})
    b = next(ds)
    assert b["input_ids"].shape == (4, 8)
    assert b["input_ids"].max() <= 5


def test_truncate_batch_seqlen_curriculum():
    batch = {"input_ids": np.ones((2, 64)), "labels": np.ones((2, 64)),
             "extra": np.ones((3,))}
    out = truncate_batch(batch, 16)
    assert out["input_ids"].shape == (2, 16)
    assert out["labels"].shape == (2, 16)
    assert out["extra"].shape == (3,)


# ---------------------------------------------------------------------------
# random-LTD
# ---------------------------------------------------------------------------

def test_random_ltd_identity_outside_subset():
    """Dropped tokens pass through bit-exact; kept tokens are processed."""
    B, S, H, keep = 2, 16, 8, 6
    x = jnp.asarray(np.random.RandomState(0).randn(B, S, H))
    layer = lambda t: t + 100.0
    out = random_ltd_apply(layer, x, keep, jax.random.PRNGKey(0))
    delta = np.asarray(out - x)
    changed = np.abs(delta).sum(-1) > 1.0
    assert changed.sum(axis=1).tolist() == [keep, keep]
    # unchanged rows are exactly identity
    assert np.all(delta[~changed] == 0)


def test_random_ltd_full_keep_is_layer():
    B, S, H = 2, 8, 4
    x = jnp.asarray(np.random.RandomState(1).randn(B, S, H))
    layer = lambda t: t * 2.0
    out = random_ltd_apply(layer, x, S, jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 2.0)


def test_random_ltd_gradients_flow():
    B, S, H, keep = 2, 12, 4, 4
    x = jnp.asarray(np.random.RandomState(2).randn(B, S, H).astype(np.float32))
    w = jnp.ones((H,), jnp.float32)

    def loss(w):
        layer = lambda t: t * w
        return jnp.sum(random_ltd_apply(layer, x, keep, jax.random.PRNGKey(3)))

    g = jax.grad(loss)(w)
    assert np.all(np.isfinite(np.asarray(g)))
    assert np.abs(np.asarray(g)).sum() > 0


def test_random_ltd_scheduler_reference_schema():
    cfg = {"random_ltd_layer_id": [1, 2],
           "random_ltd_schedule": {
               "min_value": 128, "max_value": 512,
               "schedule_type": "fixed_linear",
               "schedule_config": {"require_steps": 100,
                                   "seq_per_step": 64}}}
    s = RandomLTDScheduler(cfg, seq_len=512)
    assert s.keep_count(0) == 128
    assert s.keep_count(100) == 512
    assert s.keep_count(50) % 64 == 0
    assert s.applies_to(1) and not s.applies_to(0)


def test_random_ltd_under_jit_static_keep():
    """keep is a static shape parameter — jit compiles per keep bucket."""
    B, S, H = 2, 16, 4
    x = jnp.asarray(np.random.RandomState(4).randn(B, S, H).astype(np.float32))

    import functools

    @functools.partial(jax.jit, static_argnums=(1,))
    def step(x, keep, rng):
        return random_ltd_apply(lambda t: t + 1.0, x, keep, rng)

    a = step(x, 8, jax.random.PRNGKey(0))
    b = step(x, 16, jax.random.PRNGKey(0))
    assert a.shape == b.shape == x.shape


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

def _mlm_data(vocab, n_samples=32, seq=64, seed=0):
    rng = np.random.RandomState(seed)
    data = []
    for _ in range(n_samples):
        ids = rng.randint(4, vocab, size=(seq,))
        labels = np.where(rng.rand(seq) < 0.15, ids, -100)
        data.append({"input_ids": ids, "labels": labels})
    return data


def test_curriculum_dataloader_wired_through_initialize():
    import deepspeed_tpu
    from deepspeed_tpu.models import BertConfig, BertModel
    from deepspeed_tpu.parallel import MeshLayout
    from deepspeed_tpu.utils import groups

    groups.reset_mesh()
    cfg = BertConfig.tiny(num_layers=2, max_seq_len=64, dtype=jnp.float32)
    mesh = groups.initialize_mesh(MeshLayout.infer(8, dp=8))
    model = BertModel(cfg, mesh=mesh)
    engine, _, dl, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, training_data=_mlm_data(cfg.vocab_size),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0},
                "steps_per_print": 0,
                "curriculum_learning": {
                    "enabled": True, "min_difficulty": 16,
                    "max_difficulty": 64,
                    "schedule_type": "fixed_linear",
                    "schedule_config": {"total_curriculum_step": 4,
                                        "difficulty_step": 16}}})
    it = iter(dl)
    first = next(it)
    assert first["input_ids"].shape[1] == 16      # truncated at step 0
    m = engine.train_step(first)
    assert np.isfinite(float(m["loss"]))
    engine.global_steps = 10                      # past the schedule
    late = next(it)
    assert late["input_ids"].shape[1] == 64       # full length restored


def test_random_ltd_wired_through_engine():
    """BERT + random_ltd config: buckets compile per keep count, training
    converges, and keep grows along the schedule."""
    import deepspeed_tpu
    from deepspeed_tpu.models import BertConfig, BertModel
    from deepspeed_tpu.parallel import MeshLayout
    from deepspeed_tpu.utils import groups

    groups.reset_mesh()
    cfg = BertConfig.tiny(num_layers=4, max_seq_len=32, dtype=jnp.float32)
    mesh = groups.initialize_mesh(MeshLayout.infer(8, dp=8))
    model = BertModel(cfg, mesh=mesh)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh,
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                "zero_optimization": {"stage": 0},
                "steps_per_print": 0,
                "data_efficiency": {
                    "enabled": True,
                    "data_routing": {"random_ltd": {
                        "enabled": True,
                        "random_ltd_layer_id": [1, 2],
                        "random_ltd_schedule": {
                            "min_value": 16, "max_value": 32,
                            "schedule_type": "fixed_linear",
                            "schedule_config": {"require_steps": 6,
                                                "seq_per_step": 8}}}}}})
    assert engine.module.ltd_layer_ids == (1, 2)
    rng = np.random.RandomState(1)
    ids = rng.randint(4, cfg.vocab_size, size=(8, 32))
    labels = np.where(rng.rand(8, 32) < 0.15, ids, -100)
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}
    first = float(engine.train_step(batch)["loss"])   # keep=16 bucket
    for _ in range(8):
        last = float(engine.train_step(batch)["loss"])
    assert last < first
    # schedule crossed 16 → 24 → full(32≡off): several compiled buckets
    assert len(engine._ltd_fns) >= 2
    assert -1 in engine._ltd_fns                      # full-keep bucket
    assert engine.module.ltd_keep is None             # LTD off at the end
