"""ZeRO-Infinity layer streaming: trains correctly with trunk params living
on host (cpu tier) or NVMe (aio tier), matching on-device training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import LlamaConfig, LlamaModel
from deepspeed_tpu.ops.op_builder import AsyncIOBuilder, CPUAdamBuilder
from deepspeed_tpu.parallel import MeshLayout
from deepspeed_tpu.utils import groups

pytestmark = [
    pytest.mark.slow,  # jit/engine-heavy; smoke tier runs -m "not slow"
    pytest.mark.skipif(not CPUAdamBuilder.is_compatible(),
                       reason="no g++ toolchain"),
]


def make_engine(mesh, offload_param=None, nvme_path=None):
    cfg = LlamaConfig.tiny(num_layers=4, dtype=jnp.float32)
    model = LlamaModel(cfg, mesh=None)  # single-chip streaming
    params = model.init_params(jax.random.PRNGKey(0))
    zero = {"stage": 0}
    if offload_param:
        entry = {"device": offload_param}
        if nvme_path:
            entry["nvme_path"] = str(nvme_path)
            entry["buffer_count"] = 2  # force ring < num_layers
        zero["offload_param"] = entry
    ds = {"train_micro_batch_size_per_gpu": 4,
          "gradient_accumulation_steps": 1,
          "optimizer": {"type": "AdamW",
                        "params": {"lr": 1e-3, "betas": [0.9, 0.999],
                                   "eps": 1e-8, "weight_decay": 0.0}},
          "zero_optimization": zero}
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds, mesh=mesh)
    return engine


def batch():
    ids = np.random.RandomState(0).randint(0, 512, size=(4, 32))
    return {"input_ids": jnp.asarray(ids)}


def _mesh():
    groups.reset_mesh()
    return groups.initialize_mesh(MeshLayout.infer(1))


def test_streaming_matches_on_device():
    b = batch()
    eng = make_engine(_mesh(), offload_param="cpu")
    assert eng.infinity is not None
    losses_stream = [float(eng.train_step(b)["loss"]) for _ in range(4)]

    dev = make_engine(_mesh(), offload_param=None)
    losses_dev = [float(dev.train_step(b)["loss"]) for _ in range(4)]
    np.testing.assert_allclose(losses_stream, losses_dev, rtol=2e-4, atol=2e-4)
    assert losses_stream[-1] < losses_stream[0]


def test_streaming_moe_aux_loss_matches():
    """Mixtral streaming: router aux loss (and its gradient, via the vjp
    cotangent) must match the fused on-device path."""
    from deepspeed_tpu.models import MixtralConfig, MixtralModel

    cfg = MixtralConfig.tiny(num_layers=2, dtype=jnp.float32)
    ds = {"train_micro_batch_size_per_gpu": 4,
          "gradient_accumulation_steps": 1,
          "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
          "zero_optimization": {"stage": 0,
                                "offload_param": {"device": "cpu"}}}
    b = batch()

    model = MixtralModel(cfg, mesh=None)
    params = model.init_params(jax.random.PRNGKey(0))
    eng, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                       config=ds, mesh=_mesh())
    losses_stream = [float(eng.train_step(b)["loss"]) for _ in range(3)]

    ds2 = {k: v for k, v in ds.items() if k != "zero_optimization"}
    ds2["zero_optimization"] = {"stage": 0}
    model2 = MixtralModel(cfg, mesh=None)
    params2 = model2.init_params(jax.random.PRNGKey(0))
    eng2, *_ = deepspeed_tpu.initialize(model=model2, model_parameters=params2,
                                        config=ds2, mesh=_mesh())
    losses_dev = [float(eng2.train_step(b)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(losses_stream, losses_dev, rtol=3e-4, atol=3e-4)


def test_streaming_checkpoint_roundtrip(tmp_path):
    b = batch()
    eng = make_engine(_mesh(), offload_param="cpu")
    eng.train_step(b)
    eng.train_step(b)
    eng.save_checkpoint(str(tmp_path))
    loss_next = float(eng.train_step(b)["loss"])

    eng2 = make_engine(_mesh(), offload_param="cpu")
    eng2.load_checkpoint(str(tmp_path))
    assert eng2.infinity.swapper.state_step == 2
    loss_resumed = float(eng2.train_step(b)["loss"])
    np.testing.assert_allclose(loss_resumed, loss_next, rtol=1e-5)


def test_streaming_eval_loss():
    b = batch()
    eng = make_engine(_mesh(), offload_param="cpu")
    ev = float(eng.eval_loss(b))
    tr = float(eng.train_step(b)["loss"])
    np.testing.assert_allclose(ev, tr, rtol=1e-5)


def test_streaming_multichip_matches_fused_zero3():
    """Round 3: layer streaming composes with a dp=4 × tp=2 mesh — wire
    params land h2d in their TP sharding, activations ride the DP axes;
    trajectory matches the fused ZeRO-3 engine on the SAME mesh."""
    b = {"input_ids": jnp.asarray(
        np.random.RandomState(0).randint(0, 512, size=(8, 32)))}
    cfg = LlamaConfig.tiny(num_layers=4, dtype=jnp.float32)
    ds = {"train_micro_batch_size_per_gpu": 8,
          "gradient_accumulation_steps": 1,
          "optimizer": {"type": "AdamW",
                        "params": {"lr": 1e-3, "betas": [0.9, 0.999],
                                   "eps": 1e-8, "weight_decay": 0.0}},
          "zero_optimization": {"stage": 3,
                                "offload_param": {"device": "cpu"}}}

    groups.reset_mesh()
    mesh = groups.initialize_mesh(MeshLayout.infer(8, tp=2))  # dp=4 × tp=2
    model = LlamaModel(cfg, mesh=mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    eng, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                       config=ds, mesh=mesh)
    assert eng.infinity is not None
    losses_stream = [float(eng.train_step(b)["loss"]) for _ in range(3)]
    # streamed layer params really are TP-sharded on device
    lp0 = eng.infinity.swapper.get_device(0)
    assert not lp0["attn"]["wq"].sharding.is_fully_replicated

    groups.reset_mesh()
    mesh = groups.initialize_mesh(MeshLayout.infer(8, tp=2))
    ds2 = dict(ds)
    ds2["zero_optimization"] = {"stage": 3}
    model2 = LlamaModel(cfg, mesh=mesh)
    params2 = model2.init_params(jax.random.PRNGKey(0))
    eng2, *_ = deepspeed_tpu.initialize(model=model2,
                                        model_parameters=params2,
                                        config=ds2, mesh=mesh)
    losses_fused = [float(eng2.train_step(b)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(losses_stream, losses_fused,
                               rtol=3e-4, atol=3e-4)


def test_streaming_gas_and_clipping_match_fused():
    """gas=2 + global-norm clipping: the streamed two-pass (stash → norm →
    apply) trajectory matches the fused engine with identical settings, and
    grad_norm is real (not NaN)."""
    b = {"input_ids": jnp.asarray(
        np.random.RandomState(1).randint(0, 512, size=(8, 32)))}
    cfg = LlamaConfig.tiny(num_layers=2, dtype=jnp.float32)
    ds = {"train_micro_batch_size_per_gpu": 4,
          "gradient_accumulation_steps": 2,
          "gradient_clipping": 0.5,
          "optimizer": {"type": "AdamW",
                        "params": {"lr": 1e-3, "betas": [0.9, 0.999],
                                   "eps": 1e-8, "weight_decay": 0.0}},
          "zero_optimization": {"stage": 0,
                                "offload_param": {"device": "cpu"}}}

    model = LlamaModel(cfg, mesh=None)
    params = model.init_params(jax.random.PRNGKey(0))
    eng, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                       config=ds, mesh=_mesh())
    m = [eng.train_step(b) for _ in range(3)]
    losses_stream = [float(x["loss"]) for x in m]
    norms = [float(x["grad_norm"]) for x in m]
    assert all(np.isfinite(n) and n > 0 for n in norms)

    ds2 = dict(ds)
    ds2["zero_optimization"] = {"stage": 0}
    model2 = LlamaModel(cfg, mesh=None)
    params2 = model2.init_params(jax.random.PRNGKey(0))
    eng2, *_ = deepspeed_tpu.initialize(model=model2,
                                        model_parameters=params2,
                                        config=ds2, mesh=_mesh())
    m2 = [eng2.train_step(b) for _ in range(3)]
    losses_dev = [float(x["loss"]) for x in m2]
    norms_dev = [float(x["grad_norm"]) for x in m2]
    np.testing.assert_allclose(losses_stream, losses_dev, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(norms, norms_dev, rtol=2e-3, atol=2e-3)


@pytest.mark.skipif(not AsyncIOBuilder.is_compatible(),
                    reason="no aio toolchain")
def test_streaming_nvme_tier(tmp_path):
    import os

    b = batch()
    eng = make_engine(_mesh(), offload_param="nvme", nvme_path=tmp_path)
    losses = [float(eng.train_step(b)["loss"]) for _ in range(3)]
    assert losses[-1] < losses[0]
    # ring held fewer layers than the trunk
    sw = eng.infinity.swapper
    assert sw.buffer_count < sw.L
    files = os.listdir(tmp_path)
    assert sum(f.endswith(".master") for f in files) == sw.L
    assert sum(f.endswith(".wire") for f in files) == sw.L

    dev = make_engine(_mesh(), offload_param=None)
    losses_dev = [float(dev.train_step(b)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(losses, losses_dev, rtol=2e-4, atol=2e-4)
