"""Engine integration of the Pallas kernel plane (ISSUE 12).

kernels.fused_adam: the two-pass fused step must reproduce the optax
chain's training trajectory exactly (the whole point of the bit-parity
kernel); kernels.overlap_collectives: the chunked-ring stage-3 branch
must reproduce plain GSPMD stage 3.  Plus the memory-ledger attribution
for kernel scratch and the config-gating fallbacks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import LlamaConfig, LlamaModel
from deepspeed_tpu.parallel import MeshLayout
from deepspeed_tpu.utils import groups

pytestmark = pytest.mark.slow


def make_engine(extra=None, zero=2, clip=1.0, opt="Adam", dp=8,
                opt_params=None, attn="xla"):
    groups.reset_mesh()
    mesh = groups.initialize_mesh(MeshLayout.infer(dp, dp=dp))
    cfg = LlamaConfig.tiny(num_layers=2, dtype=jnp.float32, remat=False,
                           attn_impl=attn)
    model = LlamaModel(cfg, mesh=mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    conf = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": opt,
                      "params": dict(opt_params or {"lr": 1e-3})},
        # persistence threshold 0: tiny-model leaves must actually shard
        # at stage 3 or the overlap ring would be a silent no-op (the
        # census test below exists to catch exactly that)
        "zero_optimization": {"stage": zero,
                              "stage3_param_persistence_threshold": 0},
        "gradient_clipping": clip,
    }
    if extra:
        conf.update(extra)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=conf, mesh=mesh)
    return engine


def batch(rows=16, seq=32, seed=0):
    ids = np.random.RandomState(seed).randint(0, 512, size=(rows, seq))
    return {"input_ids": jnp.asarray(ids)}


def run(engine, b, steps=4):
    return [float(engine.train_step(b)["loss"]) for _ in range(steps)]


def test_fused_adam_matches_optax_chain_with_clipping():
    b = batch()
    base = make_engine()
    losses_b = run(base, b)
    gn_b = base.get_global_grad_norm()

    fused = make_engine({"kernels": {"fused_adam": True}})
    assert fused.fused_adam_enabled
    losses_f = run(fused, b)
    gn_f = fused.get_global_grad_norm()

    np.testing.assert_allclose(losses_b, losses_f, rtol=1e-5)
    np.testing.assert_allclose(gn_b, gn_f, rtol=1e-4)
    # optax state layout preserved: count marched with the steps
    from deepspeed_tpu.ops.pallas.fused_optimizer import find_adam_state

    _, adam = find_adam_state(fused.state.opt_state)
    assert int(adam.count) == 4


def test_fused_adam_adamw_weight_decay_matches():
    b = batch(seed=1)
    kw = {"opt": "AdamW", "opt_params": {"lr": 1e-3,
                                         "weight_decay": 0.01}}
    base = make_engine(**kw)
    fused = make_engine({"kernels": {"fused_adam": True}}, **kw)
    assert fused.fused_adam_enabled
    assert fused._fused_adam_cfg.decoupled_wd
    np.testing.assert_allclose(run(base, b), run(fused, b), rtol=1e-5)


def test_fused_adam_gates_off_for_non_adam_and_logs():
    eng = make_engine({"kernels": {"fused_adam": True}}, opt="SGD",
                      clip=0.0)
    assert not eng.fused_adam_enabled  # optax chain kept, no crash
    losses = run(eng, batch(), steps=2)
    assert losses[1] < losses[0]


def test_overlap_zero3_matches_gspmd_stage3():
    b = batch(seed=2)
    base = make_engine(zero=3, clip=0.0)
    losses_b = run(base, b)
    ov = make_engine({"kernels": {"overlap_collectives": True,
                                  "overlap_chunks": 2}}, zero=3, clip=0.0)
    assert ov.overlap_zero3
    losses_o = run(ov, b)
    np.testing.assert_allclose(losses_b, losses_o, rtol=2e-4)


def test_overlap_with_fused_adam_compose():
    b = batch(seed=3)
    base = make_engine(zero=3)
    both = make_engine({"kernels": {"overlap_collectives": True,
                                    "overlap_chunks": 2,
                                    "fused_adam": True}}, zero=3)
    assert both.overlap_zero3 and both.fused_adam_enabled
    np.testing.assert_allclose(run(base, b), run(both, b), rtol=2e-4)


def test_overlap_ring_rides_the_comm_verbs():
    """The stage-3 overlap branch's ring hops must land in the
    CollectiveLedger census (the dslint/ledger contract for every new
    collective path)."""
    from deepspeed_tpu.comm.comm import comms_logger
    from deepspeed_tpu.telemetry.collective_ledger import CollectiveLedger

    led = CollectiveLedger(max_entries=4096, tail=256, enabled=True)
    old = comms_logger.ledger
    comms_logger.ledger = led
    try:
        eng = make_engine({"kernels": {"overlap_collectives": True,
                                       "overlap_chunks": 2}}, zero=3,
                          clip=0.0)
        run(eng, batch(), steps=1)
    finally:
        comms_logger.ledger = old
    ops = [e["op"] for e in led.snapshot().get("tail", [])]
    assert "ppermute" in ops


def test_kernel_scratch_registers_in_memory_ledger():
    from deepspeed_tpu.telemetry.memory import get_memory_ledger

    eng = make_engine({"kernels": {"overlap_collectives": True,
                                   "overlap_chunks": 2},
                       "telemetry": {"enabled": True, "jsonl": False,
                                     "prometheus": False}},
                      zero=3, clip=0.0, attn="flash")
    led = eng.memory_ledger or get_memory_ledger()
    keys = [e["key"] for e in led.entries()
            if e["pool"] == "collective_scratch"]
    assert "engine/overlap_ring_staging" in keys
    # flash scratch keys on the MODEL route (attn_impl), not the config
    # knob — the knob without routing would attribute bytes that don't
    # exist
    assert "engine/flash_softmax_stats" in keys
    get_memory_ledger().reset()  # process-global: scrub the prior
    # engine's entries so the xla build is judged on its own
    xla_eng = make_engine({"kernels": {"flash_attention": True},
                           "telemetry": {"enabled": True, "jsonl": False,
                                         "prometheus": False}},
                          zero=3, clip=0.0, attn="xla")
    xla_keys = [e["key"] for e in (xla_eng.memory_ledger
                                   or get_memory_ledger()).entries()
                if e["pool"] == "collective_scratch"]
    assert "engine/flash_softmax_stats" not in xla_keys


def test_fused_adam_engine_checkpoint_state_interchanges():
    """A fused engine's opt_state must load back into a non-fused engine
    shape-for-shape (same optax layout)."""
    fused = make_engine({"kernels": {"fused_adam": True}})
    run(fused, batch(), steps=2)
    base = make_engine()
    flat_f = jax.tree.leaves(fused.state.opt_state)
    flat_b = jax.tree.leaves(base.state.opt_state)
    assert len(flat_f) == len(flat_b)
    for a, c in zip(flat_f, flat_b):
        assert np.shape(a) == np.shape(c)
