"""Ring attention: sequence-parallel numerics past the head-count limit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.parallel import MeshLayout
from deepspeed_tpu.runtime.sequence_parallel.ring import (_plain_attention,
                                                          ring_attention)
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.jax_compat import partial_manual_shard_map_ok

pytestmark = pytest.mark.slow  # jit/engine-heavy; smoke tier runs -m "not slow"

needs_partial_manual = pytest.mark.skipif(
    not partial_manual_shard_map_ok(),
    reason="jaxlib<0.5 SPMD partitioner CHECK-fails on partial-manual shard_map with size>1 auto axes (process abort, not catchable)")


def _qkv(B=2, S=64, h=2, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, h, d) * 0.3, jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("sp,causal", [(4, True), (4, False),
                                       (8, True), (2, True)])
def test_ring_matches_dense(sp, causal):
    """sp devices, only h=2 heads — BEYOND the Ulysses sp<=h limit for
    sp>2 — still bit-close to dense attention."""
    if sp < 8 and not partial_manual_shard_map_ok():
        pytest.skip("partial-manual shard_map with dp>1 auto axis "
                    "aborts on this jaxlib")
    groups.reset_mesh()
    mesh = groups.initialize_mesh(MeshLayout.infer(8, sp=sp,
                                                   dp=8 // sp))
    q, k, v = _qkv()
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=causal,
                                                 mesh=mesh))(q, k, v)
    want = _plain_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_dense():
    groups.reset_mesh()
    mesh = groups.initialize_mesh(MeshLayout.infer(8, sp=8, dp=1))
    q, k, v = _qkv(S=32, seed=1)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, causal=True, mesh=mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_plain_attention(q, k, v, True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_ring_seq_not_divisible_raises():
    groups.reset_mesh()
    mesh = groups.initialize_mesh(MeshLayout.infer(8, sp=8, dp=1))
    q, k, v = _qkv(S=60)
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q, k, v, mesh=mesh)


def test_ring_sp1_is_plain():
    groups.reset_mesh()
    mesh = groups.initialize_mesh(MeshLayout.infer(8, dp=8))
    q, k, v = _qkv(S=16)
    out = ring_attention(q, k, v, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_plain_attention(q, k, v, True)),
                               rtol=1e-6)


@needs_partial_manual
def test_llama_ring_sp_beyond_head_count_matches_single_device():
    """End-to-end: Llama with attn_impl='ring' trains under sp=4 with only
    2 heads (Ulysses would need sp<=2) and tracks the unsharded trace."""
    import deepspeed_tpu
    from deepspeed_tpu.models import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(num_layers=2, num_heads=2, num_kv_heads=2,
                           dtype=jnp.float32, attn_impl="ring")
    rng = np.random.RandomState(2)
    batch = {"input_ids": jnp.asarray(
        rng.randint(0, cfg.vocab_size, size=(8, 32)))}

    def run(mesh, n_steps=3):
        model = LlamaModel(cfg, mesh=mesh)
        params = model.init_params(jax.random.PRNGKey(0))
        engine, *_ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, mesh=mesh,
            config={"train_micro_batch_size_per_gpu": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3},
                    "steps_per_print": 0})
        return [float(engine.train_step(batch)["loss"])
                for _ in range(n_steps)]

    groups.reset_mesh()
    ring_losses = run(groups.initialize_mesh(
        MeshLayout.infer(8, sp=4, dp=2)))
    groups.reset_mesh()
    single_losses = run(groups.initialize_mesh(MeshLayout.infer(1, dp=1)))
    for a, b in zip(ring_losses, single_losses):
        assert abs(a - b) < 5e-3, (ring_losses, single_losses)
    assert ring_losses[-1] < ring_losses[0]


@needs_partial_manual
def test_ring_gqa_rotates_kv_width():
    """GQA: K/V circulate at kv-head width; output matches dense with
    expanded heads."""
    groups.reset_mesh()
    mesh = groups.initialize_mesh(MeshLayout.infer(8, sp=4, dp=2))
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 32, 8, 16) * .3, jnp.float32)
    k = jnp.asarray(rng.randn(2, 32, 2, 16) * .3, jnp.float32)  # kv_h=2
    v = jnp.asarray(rng.randn(2, 32, 2, 16) * .3, jnp.float32)
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=True,
                                                 mesh=mesh))(q, k, v)
    want = _plain_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_config_meshless_gqa_forward():
    """attn_impl='ring' without a mesh falls back to local attention and
    must expand GQA KV heads (regression: mismatched-head einsum crash)."""
    from deepspeed_tpu.models import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(num_layers=2, dtype=jnp.float32,
                           attn_impl="ring")  # tiny is GQA: 8 q / 4 kv heads
    model = LlamaModel(cfg)  # mesh=None
    params = model.init_params(jax.random.PRNGKey(0))
    logits = model.forward(params, jnp.asarray([[1, 2, 3, 4]]))
    assert logits.shape == (1, 4, cfg.vocab_size)
