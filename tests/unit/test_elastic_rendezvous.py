"""Cross-host elastic recovery (VERDICT round-2 missing #6).

Reference behavior being mirrored: torch-elastic rendezvous + agent
(``DSElasticAgent`` [K], SURVEY §5.3) — N node agents coordinate through a
store; a worker failure on ANY node restarts the gang on every node; a
NODE loss (agent killed hard) is detected via heartbeats and the survivors
re-form at the smaller world.

"Multi-node" here = multiple agent PROCESSES on localhost sharing one TCP
store (the same one-box pattern the reference's elastic tests use).
"""

import os
import pathlib
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from deepspeed_tpu.elasticity.rendezvous import (ElasticRendezvous,
                                                 RendezvousClient,
                                                 RendezvousServer)

_REPO = str(pathlib.Path(__file__).resolve().parents[2])


# ---------------------------------------------------------------------------
# store + rounds (in-process, threads)
# ---------------------------------------------------------------------------

def test_store_ops():
    srv = RendezvousServer()
    try:
        c = RendezvousClient(srv.endpoint)
        c.set("k", {"a": 1})
        assert c.get("k") == {"a": 1}
        assert c.add("n", 2) == 2
        assert c.add("n", 3) == 5
        assert c.append("lst", "x") == ["x"]
        assert c.append("lst", "x") == ["x"]  # idempotent
        assert c.append("lst", "y") == ["x", "y"]
        assert c.wait_ge("n", 5, timeout=1.0)
        assert not c.wait_ge("n", 99, timeout=0.2)
    finally:
        srv.shutdown()


def test_rendezvous_assigns_deterministic_ranks():
    srv = RendezvousServer()
    try:
        import threading

        results = {}

        def join(node_id):
            r = ElasticRendezvous(RendezvousClient(srv.endpoint), node_id,
                                  min_nodes=3, settle_s=0.2)
            results[node_id] = r.next_round()

        ts = [threading.Thread(target=join, args=(f"n{i}",))
              for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert len(results) == 3
        rounds = {v[0] for v in results.values()}
        worlds = {v[2] for v in results.values()}
        coords = {v[3] for v in results.values()}
        assert len(rounds) == 1 and worlds == {3} and len(coords) == 1
        ranks = sorted((nid, v[1]) for nid, v in results.items())
        assert [r for _, r in ranks] == [0, 1, 2]  # sorted-node-id order
    finally:
        srv.shutdown()


def test_heartbeats_are_store_stamped_and_graced():
    """Heartbeat staleness math uses the STORE's clock (op=hb stamps
    server-side), and a peer with no heartbeat yet is graced for a full
    ttl instead of being declared dead on the first check (round-3
    advisor findings)."""
    srv = RendezvousServer()
    try:
        c = RendezvousClient(srv.endpoint)
        r = ElasticRendezvous(c, "me", min_nodes=1)
        # a peer that sealed but hasn't heartbeaten: graced, not stale
        assert r.stale_peers(["late"], ttl_s=0.3) == []
        time.sleep(0.4)
        assert r.stale_peers(["late"], ttl_s=0.3) == ["late"]
        # a fresh server-stamped heartbeat clears it — even if this
        # host's clock were skewed far ahead, the store clock governs
        c.hb("rdzv/hb/late")
        assert r.stale_peers(["late"], ttl_s=0.3) == []
        assert isinstance(c.now(), float)
    finally:
        srv.shutdown()


def test_membership_restarts_do_not_consume_failure_budget():
    """_RestartSignal (scale-up / peer-death teardowns) restarts without
    burning max_restarts; only real failures do (round-3 advisor)."""
    from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent,
                                                        WorkerSpec,
                                                        _RestartSignal)
    calls = {"n": 0}

    def worker(restart_count, ckpt_dir):
        calls["n"] += 1
        if calls["n"] <= 5:  # 5 membership churns — more than max_restarts
            raise _RestartSignal("round moved")
        return "ok"

    agent = DSElasticAgent(WorkerSpec(fn=worker, max_restarts=2,
                                      monitor_interval=0.01))
    assert agent.run() == "ok"
    assert agent.failure_count == 0 and agent.restart_count == 5

    # real failures still exhaust the budget
    def always_fail(restart_count, ckpt_dir):
        raise RuntimeError("boom")

    agent2 = DSElasticAgent(WorkerSpec(fn=always_fail, max_restarts=2,
                                       monitor_interval=0.01))
    with pytest.raises(RuntimeError):
        agent2.run()
    assert agent2.failure_count == 3  # 2 retries + the give-up attempt


def test_coordinator_port_skips_bound_ports():
    """Each round publishes a BIND-TESTED coordinator endpoint through the
    store: a hung coordinator from an earlier round still bound on a port
    is skipped, never collided with (round-3 advisor).  The configured
    coordinator_port stays the base of the scan window so firewalled
    deployments keep a predictable range."""
    import socket as _socket

    srv = RendezvousServer()
    hog = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    try:
        c = RendezvousClient(srv.endpoint)
        r = ElasticRendezvous(c, "solo", min_nodes=1, settle_s=0.05)
        # simulate a hung coordinator occupying the base port
        hog.bind(("", r.coordinator_port))
        hog.listen(1)
        _, _, _, coord0 = r.next_round()
        p0 = int(coord0.rsplit(":", 1)[1])
        assert p0 != r.coordinator_port  # bound port skipped
        assert p0 >= r.coordinator_port  # window stays firewall-friendly
        assert c.get("rdzv/round/0/coord") == coord0  # published via store
        r.bump_round("test")
        _, _, _, coord1 = r.next_round()
        assert c.get("rdzv/round/1/coord") == coord1
    finally:
        hog.close()
        srv.shutdown()


# ---------------------------------------------------------------------------
# multi-agent gang restart (real processes)
# ---------------------------------------------------------------------------

_WORKER = textwrap.dedent("""
    import os, sys, time
    log = os.environ["T_LOG"]
    rank = os.environ.get("PROCESS_ID", "?")
    world = os.environ.get("NUM_PROCESSES", "?")
    restart = os.environ.get("DS_ELASTIC_RESTART_COUNT", "?")
    with open(log, "a") as f:
        f.write(f"start rank={rank} world={world} restart={restart}\\n")
    if rank == "1" and restart == "0":
        time.sleep(0.3)
        sys.exit(1)  # simulated worker crash on node 1, first attempt
    time.sleep(%(run_s)s)
    with open(log, "a") as f:
        f.write(f"done rank={rank} world={world} restart={restart}\\n")
""")

_AGENT = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, %(repo)r)
    from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent,
                                                        WorkerSpec)
    spec = WorkerSpec(cmd=[sys.executable, os.environ["T_WORKER"]],
                      max_restarts=4, monitor_interval=0.05,
                      heartbeat_ttl=%(ttl)s)
    DSElasticAgent(spec).run()
""")


def _spawn_agent(tmp_path, endpoint, node_id, worker_py, log,
                 min_nodes, ttl=5.0, run_s=1.0):
    env = dict(os.environ)
    # CPU-only subprocess: without this the axon sitecustomize registers
    # the tunneled TPU backend in the agent — a dead tunnel then hangs
    # the interpreter at import (same guard as tests/unit/multiprocess)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({
        "DS_RDZV_ENDPOINT": endpoint,
        "DS_ELASTIC_NODE_ID": node_id,
        "DS_ELASTIC_MIN_NODES": str(min_nodes),
        "T_WORKER": worker_py,
        "T_LOG": log,
        "JAX_PLATFORMS": "cpu",
    })
    return subprocess.Popen(
        [sys.executable, "-c",
         _AGENT % {"repo": _REPO, "ttl": ttl}], env=env)


@pytest.mark.slow
def test_gang_restart_on_worker_failure(tmp_path):
    """Worker dies on node 1 → BOTH nodes' workers restart and both
    complete at world=2 on the next round."""
    srv = RendezvousServer()
    worker_py = str(tmp_path / "worker.py")
    log = str(tmp_path / "log.txt")
    with open(worker_py, "w") as f:
        # run long enough that node 0's first attempt is still in flight
        # when node 1's crash bumps the round (teardown, not completion)
        f.write(_WORKER % {"run_s": 3.0})
    try:
        agents = [_spawn_agent(tmp_path, srv.endpoint, f"n{i}", worker_py,
                               log, min_nodes=2) for i in range(2)]
        for a in agents:
            assert a.wait(timeout=60) == 0
        lines = open(log).read().splitlines()
        done = [l for l in lines if l.startswith("done")]
        assert len(done) == 2
        # both completions happened in the SECOND attempt at world=2
        assert all("world=2" in l and "restart=1" in l for l in done), lines
        # node 0's first attempt was torn down by the round bump (no done
        # line with restart=0)
        assert not any(l.startswith("done") and "restart=0" in l
                       for l in lines)
    finally:
        for a in agents:
            if a.poll() is None:
                a.kill()
        srv.shutdown()


@pytest.mark.slow
def test_survivor_reforms_after_node_loss(tmp_path):
    """An agent killed HARD (node loss) → the survivor's heartbeat check
    bumps the round and it completes alone at world=1."""
    srv = RendezvousServer()
    worker_py = str(tmp_path / "worker.py")
    log = str(tmp_path / "log.txt")
    # long-running worker so the kill lands mid-attempt; no crash logic
    with open(worker_py, "w") as f:
        f.write(textwrap.dedent("""
            import os, time
            log = os.environ["T_LOG"]
            rank = os.environ.get("PROCESS_ID", "?")
            world = os.environ.get("NUM_PROCESSES", "?")
            restart = os.environ.get("DS_ELASTIC_RESTART_COUNT", "?")
            with open(log, "a") as f:
                f.write(f"start rank={rank} world={world} restart={restart}\\n")
            time.sleep(float(os.environ.get("T_RUN_S", "2.0")))
            with open(log, "a") as f:
                f.write(f"done rank={rank} world={world} restart={restart}\\n")
        """))
    try:
        os.environ["T_RUN_S"] = "4.0"
        a0 = _spawn_agent(tmp_path, srv.endpoint, "n0", worker_py, log,
                          min_nodes=1, ttl=1.0)
        a1 = _spawn_agent(tmp_path, srv.endpoint, "n1", worker_py, log,
                          min_nodes=1, ttl=1.0)
        time.sleep(2.0)  # both mid-attempt at world=2
        a1.send_signal(signal.SIGKILL)  # node loss — no goodbye
        a1.wait(timeout=10)
        assert a0.wait(timeout=60) == 0
        lines = open(log).read().splitlines()
        # the survivor finished a later attempt at world=1
        assert any(l.startswith("done") and "world=1" in l
                   for l in lines), lines
    finally:
        os.environ.pop("T_RUN_S", None)
        for a in (a0, a1):
            if a.poll() is None:
                a.kill()
        srv.shutdown()


@pytest.mark.slow
def test_scale_up_new_node_triggers_reformation(tmp_path):
    """A node joining a RUNNING (sealed) round bumps it: the running agent
    restarts its worker and both complete at world=2 (torch-elastic's
    scale-up semantics)."""
    srv = RendezvousServer()
    worker_py = str(tmp_path / "worker.py")
    log = str(tmp_path / "log.txt")
    with open(worker_py, "w") as f:
        f.write(textwrap.dedent("""
            import os, time
            log = os.environ["T_LOG"]
            rank = os.environ.get("PROCESS_ID", "?")
            world = os.environ.get("NUM_PROCESSES", "?")
            restart = os.environ.get("DS_ELASTIC_RESTART_COUNT", "?")
            with open(log, "a") as f:
                f.write(f"start rank={rank} world={world} restart={restart}\\n")
            time.sleep(2.0)
            with open(log, "a") as f:
                f.write(f"done rank={rank} world={world} restart={restart}\\n")
        """))
    try:
        a0 = _spawn_agent(tmp_path, srv.endpoint, "n0", worker_py, log,
                          min_nodes=1)
        time.sleep(1.0)  # n0's round 0 is sealed and running
        a1 = _spawn_agent(tmp_path, srv.endpoint, "n1", worker_py, log,
                          min_nodes=1)  # same job config; join → bump
        assert a0.wait(timeout=60) == 0
        assert a1.wait(timeout=60) == 0
        lines = open(log).read().splitlines()
        done2 = [l for l in lines if l.startswith("done") and "world=2" in l]
        assert len(done2) == 2, lines
    finally:
        for a in (a0, a1):
            if a.poll() is None:
                a.kill()
        srv.shutdown()


def test_heartbeat_payload_ages_and_straggler_stats():
    """ISSUE 2: heartbeats can carry the watchdog's liveness payload;
    peer_heartbeat_ages feeds debug bundles, and rank 0 folds payloads
    into straggler-skew gauges."""
    from deepspeed_tpu.telemetry import get_telemetry, parse_prometheus_text

    hub = get_telemetry()
    hub.reset()
    hub.configure(enabled=True, jsonl=False, prometheus=False)
    srv = RendezvousServer()
    try:
        c = RendezvousClient(srv.endpoint)
        r = ElasticRendezvous(c, "a", min_nodes=1, settle_s=0.05)
        r.next_round()
        r.heartbeat({"step": 10, "step_time_ewma_ms": 120.0})
        # two peers that joined elsewhere published their own payloads
        c.set("rdzv/hbinfo/b", {"step": 4, "step_time_ewma_ms": 360.0})
        c.set("rdzv/hbinfo/c", {"step": 9, "step_time_ewma_ms": 130.0})

        ages = r.peer_heartbeat_ages(["a", "b"])
        assert ages["a"]["age_s"] is not None and ages["a"]["age_s"] < 60
        assert ages["a"]["info"]["step"] == 10
        assert ages["b"]["age_s"] is None  # b never wrote a heartbeat
        assert ages["b"]["left"] is False

        stats = r.publish_straggler_stats(["a", "b", "c"])
        assert stats["step_skew"] == 6.0            # 10 - 4
        assert stats["ewma_ratio"] == pytest.approx(360.0 / 130.0)
        parsed = parse_prometheus_text(hub.prometheus_text())
        assert parsed["elastic_straggler_step_skew"] == 6.0
        assert parsed["elastic_straggler_ewma_ratio"] == pytest.approx(
            360.0 / 130.0, rel=1e-6)
    finally:
        srv.shutdown()
        hub.reset()


def test_agent_records_stale_peer_counter():
    """Satellite (ISSUE 2): stale-peer detection at the agent level bumps
    a telemetry counter before tearing the attempt down."""
    from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent,
                                                        WorkerSpec)
    from deepspeed_tpu.telemetry import get_telemetry

    hub = get_telemetry()
    hub.reset()
    hub.configure(enabled=True, jsonl=False, prometheus=False)
    try:
        agent = DSElasticAgent(WorkerSpec(fn=lambda *a: 0))
        agent._record_stale_peers(["b", "c"])
        counter = hub.registry.counter("elastic/agent_stale_peer_events")
        assert counter.value == 2
    finally:
        hub.reset()


def test_client_retries_transient_errors_with_backoff(monkeypatch):
    """Satellite (ISSUE 3): a transient connect/read failure (store
    restart, ECONNRESET, EINTR) is retried with bounded backoff instead
    of killing the caller — a debug-bundle collector sweep must survive
    one reset.  The retry budget is bounded: a store that is GONE still
    fails, with the last error chained."""
    import socket as socket_mod

    from deepspeed_tpu.elasticity import rendezvous as rdzv_mod

    srv = RendezvousServer()
    try:
        real_connect = socket_mod.create_connection
        fails = {"n": 0}

        def flaky(addr, timeout=None):
            if fails["n"] < 2:
                fails["n"] += 1
                raise ConnectionResetError("transient reset")
            return real_connect(addr, timeout=timeout)

        monkeypatch.setattr(rdzv_mod.socket, "create_connection", flaky)
        c = RendezvousClient(srv.endpoint, retries=3, backoff_s=0.001)
        c.set("k", {"v": 1})          # survived two resets
        assert c.get("k") == {"v": 1}
        assert fails["n"] == 2

        def always_down(addr, timeout=None):
            raise ConnectionResetError("store is gone")

        monkeypatch.setattr(rdzv_mod.socket, "create_connection",
                            always_down)
        c2 = RendezvousClient(srv.endpoint, retries=2, backoff_s=0.001)
        with pytest.raises(ConnectionError, match="after 3 attempts"):
            c2.get("k")
    finally:
        srv.shutdown()
