"""Seed-coverage for ``profiling/flops_profiler`` (ISSUE 5 satellite):
the cost-analysis path (MFU math) and the unknown-device peak fallback
had no tests at all."""

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.profiling.flops_profiler.profiler import (
    DEFAULT_PEAK_FLOPS, PEAK_BF16_BY_KIND, FlopsProfiler,
    get_model_profile, peak_flops_per_chip)


def test_peak_flops_unknown_device_falls_back_to_backend():
    # the CPU test backend's device_kind matches no TPU entry, so the
    # helper must fall back to the backend table, never 0 or a crash
    peak = peak_flops_per_chip()
    assert peak == DEFAULT_PEAK_FLOPS[jax.default_backend()]


def test_peak_flops_kind_table_is_ordered_most_specific_first():
    kinds = [k for k, _ in PEAK_BF16_BY_KIND]
    # "v5p"/"v5e" must match before a bare "v5 lite" substring scan;
    # every entry is distinct and the peaks are positive
    assert len(set(kinds)) == len(kinds)
    assert all(p > 0 for _, p in PEAK_BF16_BY_KIND)


def test_profile_fn_cost_analysis_and_mfu_math():
    def f(a, b):
        return a @ b

    a = jnp.ones((64, 64), jnp.float32)
    prof = FlopsProfiler()
    result = prof.profile_fn(f, a, a, runs=2)
    # a 64^3 matmul is 2*64^3 = 524288 flops (XLA counts fma as 2)
    assert result["flops"] == pytest.approx(2 * 64 ** 3, rel=0.5)
    assert result["latency_s"] > 0
    # MFU consistency: mfu == achieved / (peak * device_count)
    expect_mfu = (result["achieved_flops_per_s"]
                  / (peak_flops_per_chip() * jax.device_count()))
    assert result["mfu"] == pytest.approx(expect_mfu)
    assert result["backend"] == jax.default_backend()


def test_profile_fn_reference_hook_surface():
    prof = FlopsProfiler()
    prof.profile_fn(lambda x: x * 2, jnp.ones((8,)), runs=1)
    assert prof.get_total_flops() >= 0
    assert "FLOPs" in prof.get_total_flops(as_string=True)
    assert prof.get_total_duration() > 0
    prof.end_profile()
    assert prof.profile == {}


def test_get_model_profile_standalone_fn(tmp_path):
    out = tmp_path / "profile.txt"
    flops, macs, params = get_model_profile(
        fn=lambda a: a @ a, args=(jnp.ones((16, 16)),),
        print_profile=True, as_string=False, output_file=str(out))
    assert flops > 0 and macs == flops / 2
    assert params == 16 * 16
    assert out.read_text()  # the reference-style table was written


def test_get_model_profile_as_string_form():
    flops_s, macs_s, params_s = get_model_profile(
        fn=lambda a: a @ a, args=(jnp.ones((16, 16)),),
        print_profile=False, as_string=True)
    assert "FLOPs" in flops_s and "MACs" in macs_s
