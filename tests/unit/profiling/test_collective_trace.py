"""Seed-coverage for ``profiling/collective_trace`` + the new
execution-order census feed (ISSUE 5 satellite + ROADMAP item)."""

import gzip
import json
import os

from deepspeed_tpu.profiling.collective_trace import (feed_exec_census,
                                                      parse_trace,
                                                      parse_trace_events,
                                                      profile_collectives)
from deepspeed_tpu.telemetry.collective_ledger import (CollectiveLedger,
                                                       find_first_divergence)


def _write_trace(tmp_path, events, name="t.trace.json.gz"):
    os.makedirs(str(tmp_path), exist_ok=True)
    p = os.path.join(str(tmp_path), name)
    with gzip.open(p, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


DEVICE_META = {"ph": "M", "name": "process_name", "pid": 7,
               "args": {"name": "/device:TPU:0"}}
PY_META = {"ph": "M", "name": "process_name", "pid": 9,
           "args": {"name": "/host:python"}}


def _ev(name, ts, dur, pid=7):
    return {"ph": "X", "pid": pid, "name": name, "ts": ts, "dur": dur}


def test_parse_trace_empty_dir_returns_empty(tmp_path):
    assert parse_trace(str(tmp_path)) == {}
    assert parse_trace_events(str(tmp_path)) == []


def test_profile_collectives_empty_trace_fallback(tmp_path, caplog):
    # no collectives in the fn -> empty table + the one-shot warning,
    # never an exception (the tunneled-chip path)
    import jax.numpy as jnp

    table = profile_collectives(lambda x: x + 1, jnp.ones((4,)), iters=1,
                                trace_dir=str(tmp_path / "trace"))
    assert isinstance(table, dict)


def test_parse_trace_aggregates_device_lanes_only(tmp_path):
    trace = _write_trace(tmp_path, [
        DEVICE_META, PY_META,
        _ev("all-reduce.1", 100, 10),
        _ev("all-reduce.1", 200, 30),
        _ev("fusion.7", 150, 5),              # not a collective
        _ev("all-reduce.1", 50, 99, pid=9),   # python lane: excluded
    ])
    table = parse_trace(trace)
    assert set(table) == {"all-reduce.1"}
    assert table["all-reduce.1"]["count"] == 2
    assert table["all-reduce.1"]["total_us"] == 40.0
    assert table["all-reduce.1"]["mean_us"] == 20.0


def test_parse_trace_events_ordered_by_timestamp(tmp_path):
    trace = _write_trace(tmp_path, [
        DEVICE_META,
        _ev("reduce-scatter.2", 300, 8),
        _ev("all-gather.1", 100, 4),
        _ev("all-reduce.3", 200, 6),
    ])
    events = parse_trace_events(trace)
    assert [e["name"] for e in events] == [
        "all-gather.1", "all-reduce.3", "reduce-scatter.2"]
    assert [e["ts_us"] for e in events] == sorted(
        e["ts_us"] for e in events)


def test_feed_exec_census_ordered_and_cross_rank_comparable(tmp_path):
    # two "ranks" run the same program: same collective EXECUTION order,
    # different timings — the exec chains must agree anyway
    events = [DEVICE_META,
              _ev("all-gather.1", 100, 4),
              _ev("all-reduce.3", 200, 6),
              _ev("reduce-scatter.2", 300, 8)]
    t_a = _write_trace(tmp_path / "a", events)
    slower = [DEVICE_META,
              _ev("all-gather.1", 1100, 40),
              _ev("all-reduce.3", 1900, 60),
              _ev("reduce-scatter.2", 2700, 80)]
    t_b = _write_trace(tmp_path / "b", slower)
    led_a = CollectiveLedger(enabled=True)
    led_b = CollectiveLedger(enabled=True)
    assert feed_exec_census(t_a, ledger=led_a) == 3
    assert feed_exec_census(t_b, ledger=led_b) == 3
    # ordered: seq strictly increasing, timestamps non-decreasing
    tail_a = led_a.exec_tail()
    assert [e["seq"] for e in tail_a] == [1, 2, 3]
    ts = [e["ts_us"] for e in tail_a]
    assert ts == sorted(ts)
    assert all(e["src"] == "exec_trace" for e in tail_a)
    # cross-rank comparable: identical op sequence -> identical chain
    assert led_a.exec_tail_hash == led_b.exec_tail_hash
    # a rank that executed a DIFFERENT order forks the chain
    led_c = CollectiveLedger(enabled=True)
    reordered = [DEVICE_META,
                 _ev("all-reduce.3", 100, 6),
                 _ev("all-gather.1", 200, 4),
                 _ev("reduce-scatter.2", 300, 8)]
    feed_exec_census(_write_trace(tmp_path / "c", reordered),
                     ledger=led_c)
    assert led_c.exec_tail_hash != led_a.exec_tail_hash


def test_feed_exec_census_dedupes_device_lanes(tmp_path):
    # an 8-shard single-process mesh shows the same program on every
    # lane; only ONE lane must be replayed
    meta2 = {"ph": "M", "name": "process_name", "pid": 8,
             "args": {"name": "/device:TPU:1"}}
    trace = _write_trace(tmp_path, [
        DEVICE_META, meta2,
        _ev("all-reduce.1", 100, 4, pid=7),
        _ev("all-reduce.1", 101, 4, pid=8),
    ])
    led = CollectiveLedger(enabled=True)
    assert feed_exec_census(trace, ledger=led) == 1


def test_feed_exec_census_empty_trace_is_zero(tmp_path):
    led = CollectiveLedger(enabled=True)
    assert feed_exec_census(str(tmp_path), ledger=led) == 0
    assert led.exec_seq == 0


def test_find_first_divergence_over_trace_fed_exec_tails(tmp_path):
    # ISSUE 20 satellite: the offline desync analysis runs unchanged
    # over EXEC tails harvested from profiler ring dirs — three "ranks"
    # replay their captured device lanes, one executed a different
    # second collective
    good = [DEVICE_META,
            _ev("all-gather.1", 100, 4),
            _ev("all-reduce.3", 200, 6),
            _ev("reduce-scatter.2", 300, 8)]
    bad = [DEVICE_META,
           _ev("all-gather.1", 100, 4),
           _ev("collective-permute.9", 200, 6),  # wrong op at seq 2
           _ev("reduce-scatter.2", 300, 8)]
    tails = {}
    for node, events in (("pn0", good), ("pn1", bad), ("pn2", good)):
        led = CollectiveLedger(enabled=True)
        assert feed_exec_census(_write_trace(tmp_path / node, events),
                                ledger=led) == 3
        tails[node] = led.snapshot()["exec_tail"]
    report = find_first_divergence(tails)
    assert report["desync"] is True
    assert report["first_mismatch"]["seq"] == 2
    assert report["first_mismatch"]["divergent_ranks"] == ["pn1"]
    assert report["first_mismatch"]["signatures"]["pn1"] == \
        "collective-permute.9:0"
    assert report["lagging_rank"] is None  # all at seq 3
    assert report["overlap"] == [1, 3]


def test_trace_fed_exec_lane_never_forks_census_chain(tmp_path):
    # two ranks whose LIVE census chains agree must keep agreeing even
    # when only one of them feeds a profiler trace into the exec lane —
    # the lanes are hash-isolated by construction
    led_a = CollectiveLedger(enabled=True)
    led_b = CollectiveLedger(enabled=True)
    for led in (led_a, led_b):
        led.record("all_reduce", 4096)
        led.record("psum", 128)
    trace = _write_trace(tmp_path, [DEVICE_META,
                                    _ev("all-reduce.1", 100, 4),
                                    _ev("all-gather.2", 200, 4)])
    assert feed_exec_census(trace, ledger=led_a) == 2
    assert led_a.tail_hash == led_b.tail_hash      # census chain intact
    assert led_a.seq == led_b.seq == 2
    assert led_a.exec_seq == 2 and led_b.exec_seq == 0
    assert led_a.exec_tail_hash != led_b.exec_tail_hash
    # and the divergence analysis over the CENSUS tails stays clean
    report = find_first_divergence({"a": led_a.tail(), "b": led_b.tail()})
    assert report["desync"] is False
    assert report["first_mismatch"] is None


def test_exec_lane_rides_ledger_snapshot(tmp_path):
    led = CollectiveLedger(enabled=True)
    led.record("psum", 1024)  # census lane
    led.record_exec("all-reduce.1", 0, dur_us=12.5, ts_us=100.0,
                    source="exec_trace")
    snap = led.snapshot()
    assert snap["seq"] == 1
    assert snap["exec_seq"] == 1
    assert snap["exec_tail"][0]["op"] == "all-reduce.1"
    assert snap["exec_tail"][0]["dur_us"] == 12.5
    # exec entries never touch the census chain
    led2 = CollectiveLedger(enabled=True)
    led2.record("psum", 1024)
    assert led2.tail_hash == led.tail_hash
