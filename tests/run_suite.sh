#!/bin/bash
# Full-suite runner: one fresh pytest process per shard.
#
# Why sharded: a single-process run of all ~260 tests reliably dies with
# a SIGABRT inside the XLA CPU runtime after ~240 heavy jit tests.
# Root-caused via an LD_PRELOAD SIGABRT backtrace (no gdb in the image):
#   absl LogMessage::Fail <- xla::internal::AwaitAndLogIfStuck
#   (rendezvous.cc) <- cpu::AllReduceThunk::Execute <- Eigen WorkerLoop
# i.e. a CPU-collective RENDEZVOUS TIMEOUT: late in a long run the 8
# virtual devices' collective participants stop being co-scheduled on
# the shared Eigen pool, the all-reduce rendezvous never completes, and
# XLA LOG(FATAL)s.  Sharding gives each slice a fresh XLA client/pool,
# which sidesteps the starvation entirely (and is how CI tiers anyway).
#
# Usage: tests/run_suite.sh [extra pytest args...]
set -u
cd "$(dirname "$0")/.."

SHARDS=(
  "tests/unit/inference"
  "tests/unit/launcher tests/unit/models"
  "tests/unit/moe tests/unit/ops tests/unit/parallel"
  "tests/unit/runtime --ignore=tests/unit/runtime/test_infinity.py --ignore=tests/unit/runtime/test_infinity_sp.py --ignore=tests/unit/runtime/test_infinity_opt_fp16.py --ignore=tests/unit/runtime/test_pipe_engine.py"
  "tests/unit/runtime/test_infinity.py"
  "tests/unit/runtime/test_infinity_sp.py"
  "tests/unit/runtime/test_infinity_opt_fp16.py"
  "tests/unit/runtime/test_pipe_engine.py"
  "tests/unit/monitor"
  "tests/unit/telemetry"
  "tests/unit/test_comm.py tests/unit/test_elastic_rendezvous.py tests/unit/test_mesh.py"
  "tests/unit/multiprocess"
  "tests/unit/test_feature_round2.py tests/unit/test_feature_subsystems.py"
)

total_pass=0
fail=0
for shard in "${SHARDS[@]}"; do
  echo "=== shard: $shard"
  log=$(mktemp)
  python -m pytest $shard -q "$@" >"$log" 2>&1
  rc=$?  # the real exit code — a silent SIGABRT has no text to grep
  tail -2 "$log"
  n=$(grep -oE '[0-9]+ passed' "$log" | grep -oE '[0-9]+' | head -1)
  total_pass=$((total_pass + ${n:-0}))
  if [ $rc -ne 0 ]; then
    echo "=== shard FAILED (exit $rc)"
    fail=1
  fi
  rm -f "$log"
done
# Operator-CLI smoke (ISSUE 3): a freshly generated debug bundle must
# summarize cleanly through `python -m deepspeed_tpu.telemetry`.
echo "=== CLI smoke: telemetry summary"
smoke_dir=$(mktemp -d)
bundle=$(python - "$smoke_dir" <<'PYEOF'
import sys
from deepspeed_tpu.telemetry import FlightRecorder

fr = FlightRecorder(output_path=sys.argv[1])
fr.annotate("cli_smoke", {"ok": True})
fr.record_step({"step": 1, "step_time_ms": 1.0, "loss": 0.5})
print(fr.dump("run_suite CLI smoke"))
PYEOF
)
bundle=$(echo "$bundle" | tail -1)
if python -m deepspeed_tpu.telemetry summary "$bundle" >/dev/null; then
  echo "=== CLI smoke passed"
else
  echo "=== CLI smoke FAILED"
  fail=1
fi
rm -rf "$smoke_dir"

echo "=== total passed: $total_pass; fail=$fail"
exit $fail
