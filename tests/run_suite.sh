#!/bin/bash
# Full-suite runner: one fresh pytest process per shard.
#
# Why sharded: a single-process run of all ~260 tests reliably dies with
# a SIGABRT inside the XLA CPU runtime after ~240 heavy jit tests.
# Root-caused via an LD_PRELOAD SIGABRT backtrace (no gdb in the image):
#   absl LogMessage::Fail <- xla::internal::AwaitAndLogIfStuck
#   (rendezvous.cc) <- cpu::AllReduceThunk::Execute <- Eigen WorkerLoop
# i.e. a CPU-collective RENDEZVOUS TIMEOUT: late in a long run the 8
# virtual devices' collective participants stop being co-scheduled on
# the shared Eigen pool, the all-reduce rendezvous never completes, and
# XLA LOG(FATAL)s.  Sharding gives each slice a fresh XLA client/pool,
# which sidesteps the starvation entirely (and is how CI tiers anyway).
#
# Usage: tests/run_suite.sh [extra pytest args...]
set -u
cd "$(dirname "$0")/.."

SHARDS=(
  "tests/unit/inference"
  "tests/unit/launcher tests/unit/models"
  "tests/unit/moe tests/unit/ops tests/unit/parallel"
  "tests/unit/runtime --ignore=tests/unit/runtime/test_infinity.py --ignore=tests/unit/runtime/test_infinity_sp.py --ignore=tests/unit/runtime/test_infinity_opt_fp16.py --ignore=tests/unit/runtime/test_pipe_engine.py"
  "tests/unit/runtime/test_infinity.py"
  "tests/unit/runtime/test_infinity_sp.py"
  "tests/unit/runtime/test_infinity_opt_fp16.py"
  "tests/unit/runtime/test_pipe_engine.py"
  "tests/unit/monitor"
  "tests/unit/analysis"
  "tests/unit/telemetry --ignore=tests/unit/telemetry/test_memory_ledger.py --ignore=tests/unit/telemetry/test_memory_oom.py --ignore=tests/unit/telemetry/test_memory_health.py --ignore=tests/unit/telemetry/test_memory_cli.py --ignore=tests/unit/telemetry/test_memory_watchdog.py --ignore=tests/unit/telemetry/test_numerics_stats.py --ignore=tests/unit/telemetry/test_numerics_engine.py --ignore=tests/unit/telemetry/test_numerics_cli.py"
  "tests/unit/telemetry/test_memory_ledger.py tests/unit/telemetry/test_memory_oom.py tests/unit/telemetry/test_memory_health.py tests/unit/telemetry/test_memory_cli.py tests/unit/telemetry/test_memory_watchdog.py"
  "tests/unit/telemetry/test_numerics_stats.py tests/unit/telemetry/test_numerics_engine.py tests/unit/telemetry/test_numerics_cli.py"
  "tests/unit/resilience"
  "tests/unit/elasticity"
  "tests/unit/serving"
  "tests/unit/tuning"
  "tests/unit/perf"
  "tests/unit/profiling"
  "tests/unit/anatomy"
  "tests/unit/test_comm.py tests/unit/test_elastic_rendezvous.py tests/unit/test_mesh.py tests/unit/test_overlap.py"
  "tests/unit/multiprocess --ignore=tests/unit/multiprocess/test_chaos_control_plane.py --ignore=tests/unit/multiprocess/test_serving_network.py --ignore=tests/unit/multiprocess/test_autoscale.py"
  "tests/unit/multiprocess/test_chaos_control_plane.py -m chaos"
  "tests/unit/multiprocess/test_serving_network.py -m chaos"
  "tests/unit/multiprocess/test_autoscale.py -m chaos"
  "tests/unit/test_feature_round2.py tests/unit/test_feature_subsystems.py"
)

total_pass=0
fail=0
for shard in "${SHARDS[@]}"; do
  echo "=== shard: $shard"
  log=$(mktemp)
  python -m pytest $shard -q "$@" >"$log" 2>&1
  rc=$?  # the real exit code — a silent SIGABRT has no text to grep
  tail -2 "$log"
  n=$(grep -oE '[0-9]+ passed' "$log" | grep -oE '[0-9]+' | head -1)
  total_pass=$((total_pass + ${n:-0}))
  if [ $rc -ne 0 ]; then
    echo "=== shard FAILED (exit $rc)"
    fail=1
  fi
  rm -f "$log"
done
# Operator-CLI smoke (ISSUE 3): a freshly generated debug bundle must
# summarize cleanly through `python -m deepspeed_tpu.telemetry`.
echo "=== CLI smoke: telemetry summary"
smoke_dir=$(mktemp -d)
bundle=$(python - "$smoke_dir" <<'PYEOF'
import sys
from deepspeed_tpu.telemetry import FlightRecorder

fr = FlightRecorder(output_path=sys.argv[1])
fr.annotate("cli_smoke", {"ok": True})
fr.record_step({"step": 1, "step_time_ms": 1.0, "loss": 0.5})
print(fr.dump("run_suite CLI smoke"))
PYEOF
)
bundle=$(echo "$bundle" | tail -1)
if python -m deepspeed_tpu.telemetry summary "$bundle" >/dev/null; then
  echo "=== CLI smoke passed"
else
  echo "=== CLI smoke FAILED"
  fail=1
fi
rm -rf "$smoke_dir"

# Live-cluster-view smoke (ISSUE 13): three in-process "hosts" publish
# their registry snapshots through a rendezvous store; `telemetry top
# --once` (the real module CLI, in a subprocess) must exit 0 and render
# every live node from the rollup — no bundles collected.
echo "=== CLI smoke: telemetry top --once"
if python - <<'PYEOF'
import subprocess
import sys

from deepspeed_tpu.elasticity.rendezvous import (RendezvousClient,
                                                 RendezvousServer)
from deepspeed_tpu.telemetry import (StepRecord, configure_step_stream,
                                     get_telemetry, push_node_telemetry)

srv = RendezvousServer()
try:
    c = RendezvousClient(srv.endpoint)
    tel = get_telemetry()
    tel.configure(enabled=True, jsonl=False, prometheus=False)
    configure_step_stream(enabled=True)
    for node, step in (("host-a", 4), ("host-b", 6), ("host-c", 5)):
        tel.record_step(StepRecord(
            step=step, step_time_ms=12.0, device_fenced=True,
            samples_per_sec=1.0, tokens_per_sec=100.0, loss=0.5,
            grad_norm=0.0, lr=0.1, loss_scale=1.0, overflow=False,
            skipped_steps=0, comm_bytes=0, comm_ops=0))
        push_node_telemetry(c, node)
        c.hb(f"rdzv/hb/{node}")
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.telemetry", "top", "--once",
         "--endpoint", srv.endpoint, "--peers", "host-a,host-b,host-c"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    for node in ("host-a", "host-b", "host-c"):
        assert node in out.stdout, out.stdout
    assert "LIVE" in out.stdout, out.stdout
finally:
    srv.shutdown()
print("top --once rendered all 3 hosts")
PYEOF
then
  echo "=== top smoke passed"
else
  echo "=== top smoke FAILED"
  fail=1
fi

# Fault-injection smoke (ISSUE 4): an env-var fault must drive the WHOLE
# recovery loop — NaN injected, rollback taken, recovery counter moves.
echo "=== fault-injection smoke: env-driven NaN -> rollback"
smoke_dir=$(mktemp -d)
if DS_FAULTS="nan_loss@3" JAX_PLATFORMS=cpu python - "$smoke_dir" <<'PYEOF'
import sys

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as dst
from deepspeed_tpu.parallel import MeshLayout
from deepspeed_tpu.utils import groups

out = sys.argv[1]
mesh = groups.initialize_mesh(MeshLayout.infer(1, dp=1))
rng = np.random.default_rng(0)
params = {"w": jnp.asarray(rng.normal(size=(8, 1)).astype(np.float32))}
cfg = {"train_micro_batch_size_per_gpu": 4,
       "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
       "steps_per_print": 0,
       "telemetry": {"enabled": True, "output_path": out, "job_name": "smoke",
                     "flight_recorder": {"install_handlers": False}},
       "resilience": {"enabled": True, "snapshot_interval": 1,
                      "snapshot_dir": out + "/snaps", "flush_engine": "sync",
                      "backoff_base_s": 0.0}}
engine, *_ = dst.initialize(model=lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2),
                            model_parameters=params, config=cfg, mesh=mesh)
i = 0
while engine.global_steps < 5:
    x = jnp.asarray(np.random.default_rng(i).normal(size=(4, 8)).astype(np.float32))
    engine.train_step((x, jnp.zeros((4, 1), jnp.float32)))
    i += 1
from deepspeed_tpu.telemetry import get_telemetry, parse_prometheus_text

parsed = parse_prometheus_text(get_telemetry().prometheus_text())
assert parsed["resilience_faults_injected_total"] >= 1, parsed
assert parsed["resilience_rollbacks_total"] >= 1, parsed
assert float(engine.last_metrics["loss"]) == float(engine.last_metrics["loss"])  # finite again
print("fault smoke: rollback recovered, counters:",
      {k: v for k, v in parsed.items() if k.startswith("resilience")})
PYEOF
then
  echo "=== fault smoke passed"
else
  echo "=== fault smoke FAILED"
  fail=1
fi
# the snapshot CLI must read the smoke run's artifacts cleanly — and
# the offline reshard pre-check (ISSUE 10) must answer "can I resume
# this on 3 hosts?" without starting an engine (exit 0: the smoke run's
# full-coverage 1-device snapshot reshards onto any world)
if python -m deepspeed_tpu.resilience ls "$smoke_dir/snaps" >/dev/null \
   && python -m deepspeed_tpu.resilience verify "$smoke_dir/snaps" >/dev/null \
   && python -m deepspeed_tpu.resilience verify "$smoke_dir/snaps" \
        --target-mesh 3 >/dev/null \
   && python -m deepspeed_tpu.resilience faults \
        | grep -q "sigstop_hang"; then
  echo "=== resilience CLI smoke passed"
else
  echo "=== resilience CLI smoke FAILED"
  fail=1
fi
rm -rf "$smoke_dir"

# Memory-plane CLI smoke (ISSUE 7): a ledger-carrying bundle must `mem
# show` cleanly and `mem diff` against itself must exit 0 (and a grown
# pool must verdict-exit 3 — the scriptable leak gate).
echo "=== mem CLI smoke: show / diff exit codes"
smoke_dir=$(mktemp -d)
mem_ok=1
bundles=$(python - "$smoke_dir" <<'PYEOF'
import sys
from deepspeed_tpu.telemetry import FlightRecorder
from deepspeed_tpu.telemetry.memory import get_memory_ledger

led = get_memory_ledger()
led.configure(enabled=True)
led.register("params", "p", 2 << 30)
fr = FlightRecorder(output_path=sys.argv[1])
fr.register_context("memory", led.snapshot)
a = fr.dump("mem smoke A")
led.register("snapshot", "t0", 4 << 30, space="host")
b = fr.dump("mem smoke B")
print(a)
print(b)
PYEOF
)
bundle_a=$(echo "$bundles" | tail -2 | head -1)
bundle_b=$(echo "$bundles" | tail -1)
python -m deepspeed_tpu.telemetry mem show "$bundle_a" >/dev/null || mem_ok=0
python -m deepspeed_tpu.telemetry mem diff "$bundle_a" "$bundle_a" \
    >/dev/null || mem_ok=0
python -m deepspeed_tpu.telemetry mem diff "$bundle_a" "$bundle_b" >/dev/null
[ $? -eq 3 ] || mem_ok=0
if [ $mem_ok -eq 1 ]; then
  echo "=== mem CLI smoke passed"
else
  echo "=== mem CLI smoke FAILED"
  fail=1
fi
rm -rf "$smoke_dir"

# Serving CLI smoke (ISSUE 8): the dry-run bench (real scheduler +
# prefix cache + front-end on synthetic replicas, zero device work)
# must emit the gated serving metrics cleanly.
echo "=== serving CLI smoke: bench --dry-run"
serving_line=$(JAX_PLATFORMS=cpu python -m deepspeed_tpu.serving bench \
    --dry-run --interactive 4 --background 2 2>/dev/null | tail -1)
if echo "$serving_line" | python -c '
import json, sys

line = json.loads(sys.stdin.read())
for key in ("serving_p99_ttft_ms", "prefix_hit_rate",
            "tok_s_interactive", "tok_s_background"):
    assert key in line, key
assert line["requests_completed"] == line["requests_submitted"] == 6, line
'; then
  echo "=== serving CLI smoke passed"
else
  echo "=== serving CLI smoke FAILED"
  fail=1
fi

# MoE expert-parallel smoke (ISSUE 19): the dry-run moe bench must
# train the tiny Mixtral proxy with the expert axis > 1 on the forced
# 8-device CPU mesh and emit the three gated metrics — expert params
# verifiably sharded (bytes frac == 1/ep) and ep losses matching ep=1.
echo "=== moe CLI smoke: bench --dry-run"
moe_line=$(XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python -m deepspeed_tpu.moe bench --dry-run \
    2>/dev/null | tail -1)
if echo "$moe_line" | python -c '
import json, sys

line = json.loads(sys.stdin.read())
for key in ("moe_ep_tokens_per_sec", "moe_dispatch_speedup",
            "moe_drop_rate"):
    assert key in line, key
assert line["ep"] > 1, line
assert abs(line["moe_expert_bytes_frac"] - 1.0 / line["ep"]) < 1e-6, line
assert abs(line["moe_ep_final_loss"] - line["moe_ep1_final_loss"]) \
    <= 3e-3 * abs(line["moe_ep1_final_loss"]), line
'; then
  echo "=== moe CLI smoke passed"
else
  echo "=== moe CLI smoke FAILED"
  fail=1
fi

# Front-door CLI smoke (ISSUE 14): `serve --dry-run` must boot the
# HTTP/SSE front door over synthetic replicas, answer its own health
# probe, and shut down cleanly — one parseable JSON line, exit 0.
echo "=== front-door CLI smoke: serve --dry-run"
frontdoor_line=$(JAX_PLATFORMS=cpu python -m deepspeed_tpu.serving serve \
    --dry-run 2>/dev/null | tail -1)
if echo "$frontdoor_line" | python -c '
import json, sys

line = json.loads(sys.stdin.read())
assert line["ok"] is True, line
assert line["healthz"]["healthy_replicas"] >= 1, line
'; then
  echo "=== front-door smoke passed"
else
  echo "=== front-door smoke FAILED"
  fail=1
fi

# Request-trace CLI smoke (ISSUE 15): a dry-run request pushed through
# the rollup transport must assemble into a timeline (`serving trace
# <id>` exit 0); an unknown id must exit 3, not crash.
echo "=== serving trace smoke: assembled timeline / unknown id"
trace_ok=1
JAX_PLATFORMS=cpu python - <<'PYEOF' || trace_ok=0
import subprocess
import sys

from deepspeed_tpu.elasticity.rendezvous import (RendezvousClient,
                                                 RendezvousServer)
from deepspeed_tpu.inference.v2 import KVCacheConfig
from deepspeed_tpu.serving import (Replica, ServingFrontend,
                                   SyntheticEngine, get_request_log)
from deepspeed_tpu.telemetry import get_telemetry, push_node_telemetry

srv = RendezvousServer()
try:
    c = RendezvousClient(srv.endpoint)
    get_telemetry().configure(enabled=True, jsonl=False, prometheus=False)
    get_request_log().reset()
    cc = KVCacheConfig(num_blocks=64, block_size=16, max_seq_len=256)
    fe = ServingFrontend([Replica(SyntheticEngine(cc), 0)])
    h = fe.submit([1, 2, 3, 4], max_new_tokens=6,
                  trace_id="smoke-trace-01")
    fe.run_until_idle()
    assert h.status == "done", h.status
    push_node_telemetry(c, "door")
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.serving", "trace",
         "smoke-trace-01", "--endpoint", srv.endpoint],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "smoke-trace-01" in out.stdout, out.stdout
    assert "admitted" in out.stdout, out.stdout
    unknown = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.serving", "trace",
         "no-such-trace", "--endpoint", srv.endpoint],
        capture_output=True, text=True, timeout=120)
    assert unknown.returncode == 3, (unknown.returncode,
                                     unknown.stdout + unknown.stderr)
finally:
    srv.shutdown()
print("serving trace smoke: timeline assembled, unknown id exits 3")
PYEOF
if [ $trace_ok -eq 1 ]; then
  echo "=== serving trace smoke passed"
else
  echo "=== serving trace smoke FAILED"
  fail=1
fi

# Replay smoke (ISSUE 16): `serving bench --replay` must re-issue the
# checked-in diurnal access log against an ephemeral real fleet and
# emit a parseable fidelity report carrying the sentinel-gated keys
# (including the SLO burn figure the perf baseline gates).
echo "=== serving replay smoke: bench --replay (diurnal fixture)"
replay_line=$(JAX_PLATFORMS=cpu python -m deepspeed_tpu.serving bench \
    --replay tests/fixtures/serving/diurnal_access.log --speed 20 \
    --max-requests 40 2>/dev/null | tail -1)
if echo "$replay_line" | python -c '
import json, sys

line = json.loads(sys.stdin.read())
assert line["replayed"] == 40, line
assert not line["aborted"], line
for key in ("recorded", "achieved", "diff", "within_tolerance",
            "serving_net_qps_sustained", "serving_slo_burn_rate_p99"):
    assert key in line, key
assert line["achieved"]["failed"] == 0, line["achieved"]
'; then
  echo "=== serving replay smoke passed"
else
  echo "=== serving replay smoke FAILED"
  fail=1
fi

# Step-anatomy CLI smoke (ISSUE 17): a dry-run capture (tiny probe,
# one fenced step, real profiler session) must classify its own trace
# and `anatomy show` must render the bucket table + roofline join.
echo "=== anatomy CLI smoke: capture --dry-run / show"
smoke_dir=$(mktemp -d)
anatomy_ok=1
JAX_PLATFORMS=cpu python -m deepspeed_tpu.telemetry anatomy capture \
    --dry-run --out "$smoke_dir/anat" >/dev/null || anatomy_ok=0
python -m deepspeed_tpu.telemetry anatomy show "$smoke_dir/anat" \
    | grep -q "comm_fraction" || anatomy_ok=0
if [ $anatomy_ok -eq 1 ]; then
  echo "=== anatomy CLI smoke passed"
else
  echo "=== anatomy CLI smoke FAILED"
  fail=1
fi
rm -rf "$smoke_dir"

# Fleet-profiler smoke (ISSUE 20): ONE `telemetry profile capture`
# against a real 2-process CPU gang on the production path must merge
# both ranks' device lanes into cluster_trace.json and write the
# measured-vs-modeled calibration report — the operator loop end to end.
echo "=== fleet profiler smoke: telemetry profile capture (2-proc gang)"
smoke_dir=$(mktemp -d)
if JAX_PLATFORMS=cpu python - "$smoke_dir" <<'PYEOF'
import json
import os
import signal
import subprocess
import sys

from deepspeed_tpu.elasticity.rendezvous import RendezvousServer

out = sys.argv[1]
repo = os.getcwd()
worker = os.path.join(repo, "tests/unit/multiprocess/worker_profiler_gang.py")
srv = RendezvousServer()
procs = []
try:
    for node in ("sm0", "sm1"):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update({"DS_RDZV_ENDPOINT": srv.endpoint,
                    "DS_ELASTIC_NODE_ID": node,
                    "DS_CALIBRATION_PATH": f"{out}/cal_{node}.json",
                    "T_REPO": repo, "T_OUT": out, "T_DEADLINE_S": "120",
                    "JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": repo + os.pathsep
                    + env.get("PYTHONPATH", "")})
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env,
            stdout=open(f"{out}/{node}.log", "w"),
            stderr=subprocess.STDOUT, start_new_session=True))
    cli = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.telemetry", "profile",
         "capture", "--endpoint", srv.endpoint, "--steps", "2",
         "--lead", "2", "--nodes", "sm0,sm1",
         "--out", f"{out}/archive", "--timeout", "150"],
        env={**os.environ, "DS_CALIBRATION_PATH": f"{out}/cal_cli.json",
             "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=240)
    assert cli.returncode == 0, cli.stdout + cli.stderr
    trace = json.load(open(f"{out}/archive/cluster_trace.json"))
    hosts = trace["metadata"]["hosts"]
    for node in ("sm0", "sm1"):
        assert hosts[f"{node} (device)"]["events"] > 0, hosts
    rep = json.load(open(f"{out}/archive/calibration_report.json"))
    for node in ("sm0", "sm1"):
        assert rep["nodes"][node]["measured_step_ms"] > 0, rep
    assert "factors[" in cli.stdout, cli.stdout
finally:
    for p in procs:
        try:
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    srv.shutdown()
print("fleet profiler smoke: both lanes merged, roofline calibrated")
PYEOF
then
  echo "=== fleet profiler smoke passed"
else
  echo "=== fleet profiler smoke FAILED"
  fail=1
fi
rm -rf "$smoke_dir"

# Perf-sentinel smoke (ISSUE 5): baseline-then-check on the same run
# must exit 0; a forced-regression fixture must exit 3.
echo "=== perf sentinel smoke: baseline / check exit codes"
smoke_dir=$(mktemp -d)
cat > "$smoke_dir/run.json" <<'EOF'
{"metric": "llama_110m_train_tokens_per_sec", "value": 35000.0,
 "unit": "tokens/sec/chip", "mfu": 0.42, "step_time_p50_ms": 120.0,
 "compile_time_s": 30.0, "goodput": 0.95}
EOF
cat > "$smoke_dir/regressed.json" <<'EOF'
{"metric": "llama_110m_train_tokens_per_sec", "value": 24000.0,
 "unit": "tokens/sec/chip", "mfu": 0.42, "step_time_p50_ms": 240.0,
 "compile_time_s": 30.0, "goodput": 0.95}
EOF
perf_ok=1
python -m deepspeed_tpu.telemetry perf baseline "$smoke_dir/run.json" \
    --out "$smoke_dir/base.json" >/dev/null || perf_ok=0
python -m deepspeed_tpu.telemetry perf check "$smoke_dir/run.json" \
    --baseline "$smoke_dir/base.json" >/dev/null || perf_ok=0
python -m deepspeed_tpu.telemetry perf check "$smoke_dir/regressed.json" \
    --baseline "$smoke_dir/base.json" >/dev/null
[ $? -eq 3 ] || perf_ok=0
if [ $perf_ok -eq 1 ]; then
  echo "=== perf sentinel smoke passed"
else
  echo "=== perf sentinel smoke FAILED"
  fail=1
fi
rm -rf "$smoke_dir"

# Tuning CLI smoke (ISSUE 9): the deterministic synthetic search must
# find the planted optimum, round-trip through show, and apply its
# overrides onto a base ds_config (the whole search → store → apply
# loop on CPU, no device work).
echo "=== tuning CLI smoke: search / show / apply round-trip"
smoke_dir=$(mktemp -d)
tuning_ok=1
tstore="$smoke_dir/store.json"
python -m deepspeed_tpu.tuning search --synthetic --store "$tstore" \
    >"$smoke_dir/search.json" || tuning_ok=0
tkey=$(python -c '
import json, sys

doc = json.load(open(sys.argv[1]))
assert doc["best"]["train_micro_batch_size_per_gpu"] == 8, doc["best"]
assert doc["best"]["zero_optimization.stage"] == 3, doc["best"]
print(doc["key"])
' "$smoke_dir/search.json") || tuning_ok=0
python -m deepspeed_tpu.tuning show --store "$tstore" --key "$tkey" \
    >/dev/null || tuning_ok=0
echo '{"optimizer": {"type": "AdamW"}}' > "$smoke_dir/ds_config.json"
python -m deepspeed_tpu.tuning apply --store "$tstore" --key "$tkey" \
    --config "$smoke_dir/ds_config.json" | python -c '
import json, sys

merged = json.load(sys.stdin)
assert merged["train_micro_batch_size_per_gpu"] == 8, merged
assert merged["zero_optimization"]["stage"] == 3, merged
assert merged["optimizer"]["type"] == "AdamW", merged
' || tuning_ok=0
# unknown key must be the structural-error exit, not a crash
python -m deepspeed_tpu.tuning show --store "$tstore" --key "no|such|key|x" \
    >/dev/null 2>&1
[ $? -eq 2 ] || tuning_ok=0
if [ $tuning_ok -eq 1 ]; then
  echo "=== tuning CLI smoke passed"
else
  echo "=== tuning CLI smoke FAILED"
  fail=1
fi
rm -rf "$smoke_dir"

# Static-analysis gate (ISSUE 6): dslint must run clean against the
# checked-in baseline — any NEW finding (untracked jit, raw collective,
# recompile hazard, host sync, silent except) fails the suite with the
# same exit-3 convention as the perf sentinel.
echo "=== dslint gate: analysis lint"
if python -m deepspeed_tpu.analysis lint; then
  echo "=== dslint gate passed"
else
  echo "=== dslint gate FAILED (new findings — fix, suppress, or baseline)"
  fail=1
fi
# Thread-safety smoke, UNscoped: the baseline already absorbs the
# reviewed findings (each with a written justification), and the audit
# demonstrably covers worker threads outside telemetry/resilience too
# (the swap_tensor _OptPipeline entry) — anything new gates.
echo "=== dslint races smoke"
if python -m deepspeed_tpu.analysis races; then
  echo "=== dslint races smoke passed"
else
  echo "=== dslint races smoke FAILED"
  fail=1
fi

echo "=== total passed: $total_pass; fail=$fail"
exit $fail
