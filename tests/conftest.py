"""Test harness: a virtual 8-device CPU mesh in one process.

The reference's keystone fixture (``tests/unit/common.py:DistributedTest`` [K])
forks N processes over localhost NCCL.  The TPU-native equivalent is
``--xla_force_host_platform_device_count=8`` — real mesh, real XLA collectives,
single process (SURVEY §4).
"""

import os

# XLA_FLAGS must be set before the CPU backend is created. The axon
# sitecustomize imports jax at interpreter start with JAX_PLATFORMS=axon, so
# the platform override must go through jax.config, not the env var.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE a persistent XLA compilation cache was tried here (8x faster warm
# reruns) and REVERTED: an interrupted run leaves entries that abort the
# whole process on load (`Fatal Python error: Aborted` inside the XLA CPU
# client) — a poisoned cache turns every later suite run red with no
# Python-level recovery.  bench.py still uses one, with a dirty-run
# sentinel that wipes the dir after any unclean exit.

import pytest  # noqa: E402


def pytest_collection_finish(session):
    """A single process cannot survive the whole suite: ~290 jit-heavy
    tests reliably SIGABRT late in the run (XLA-CPU collective rendezvous
    timeout — root cause documented in tests/run_suite.sh).  Warn anyone
    who launched the full suite un-sharded so the eventual crash isn't a
    mystery."""
    if len(session.items) > 150:
        import warnings

        warnings.warn(
            f"collected {len(session.items)} tests in ONE process — runs "
            "this large can die in a late XLA-CPU SIGABRT (known runtime "
            "issue, see tests/run_suite.sh). Use tests/run_suite.sh for "
            "the full suite, or -m 'not slow' for the smoke tier.",
            stacklevel=1)


@pytest.fixture(autouse=True)
def _reset_groups():
    from deepspeed_tpu.utils import groups

    groups.reset_mesh()
    yield
    groups.reset_mesh()


@pytest.fixture
def mesh8():
    from deepspeed_tpu.parallel import MeshLayout
    from deepspeed_tpu.utils import groups

    layout = MeshLayout.infer(8, dp=8)
    return groups.initialize_mesh(layout)


def require_devices(n: int):
    if jax.device_count() < n:
        pytest.skip(f"needs {n} devices, have {jax.device_count()}")
