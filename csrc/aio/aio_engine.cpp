// Async file I/O engine — the ZeRO-Infinity NVMe tier.
//
// Role parity with the reference csrc/aio/ [K] (deepspeed_aio_thread.cpp,
// deepspeed_aio_common.cpp, py_lib bindings): an aio_handle with a
// worker-thread pool draining a submission queue of pread/pwrite ops
// against O_DIRECT block files, with wait/drain semantics the swap layer
// builds on (aio_handle(block_size, queue_depth, single_submit,
// overlap_events, thread_count) ctor keys [L ACC-DC:1187-1194]).
//
// O_DIRECT is the defining property (as in the reference): NVMe-tier
// traffic bypasses the page cache, so host memory stays
// O(buffer_count × layer) instead of the kernel caching the whole
// dataset.  User buffers are arbitrary-aligned; each worker owns one
// 4 KiB-aligned bounce buffer and the aligned body of every transfer goes
// O_DIRECT while the (<4 KiB) unaligned tail goes through a plain fd —
// the same split the reference's aligned/unaligned io paths make.
// Filesystems that reject O_DIRECT (tmpfs) degrade to buffered I/O;
// ds_aio_stats reports the byte split so callers/tests can tell.
//
// Config keys honored (reference semantics, thread-pool adaptation):
//   block_size     transfer granularity (rounded up to 4 KiB)
//   queue_depth    max in-flight ops — submit blocks past it (backpressure)
//   single_submit  true: one op stays one queue entry; false (default):
//                  large ops split into block_size sub-ops so several
//                  workers overlap one transfer
//   overlap_events true (default): submit returns immediately; false:
//                  every submit drains before returning
//
// TPU-first adaptation: std::thread pool + p{read,write} with a C ABI for
// ctypes.  (io_uring/libaio would pin this to specific kernels; the pool
// saturates TPU-VM NVMe with queue_depth×thread_count in-flight ops, and
// the interface leaves room to swap the backend.)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr int64_t kAlign = 4096;  // logical block alignment for O_DIRECT

inline int64_t align_down(int64_t x) { return x & ~(kAlign - 1); }
inline int64_t align_up(int64_t x) { return (x + kAlign - 1) & ~(kAlign - 1); }

struct Op {
  enum Kind { READ, WRITE } kind;
  void* buf;
  int64_t nbytes;
  std::string path;
  int64_t offset;
  bool trunc = false;  // WRITE: ftruncate file to offset+nbytes afterwards
};

struct Handle {
  int64_t block_size;
  int queue_depth;
  int thread_count;
  bool single_submit;
  bool overlap_events;
  std::vector<std::thread> workers;
  std::deque<Op> queue;
  std::mutex mu;
  std::condition_variable cv_submit;
  std::condition_variable cv_done;
  std::atomic<int64_t> inflight{0};
  std::atomic<int64_t> errors{0};
  std::atomic<int64_t> bytes_direct{0};
  std::atomic<int64_t> bytes_buffered{0};
  std::atomic<int64_t> read_retries{0};
  bool shutdown = false;

  void worker() {
    // one aligned bounce buffer per worker, reused for every O_DIRECT op
    void* bounce = nullptr;
    int64_t bounce_cap = 0;
    for (;;) {
      Op op;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_submit.wait(lk, [&] { return shutdown || !queue.empty(); });
        if (shutdown && queue.empty()) break;
        op = queue.front();
        queue.pop_front();
      }
      if (run_one(op, &bounce, &bounce_cap) != 0) errors.fetch_add(1);
      {
        // decrement+notify under the mutex: a lock-free notify can fire
        // between the waiter's predicate check and its sleep (lost wakeup)
        std::lock_guard<std::mutex> lk(mu);
        inflight.fetch_sub(1);
        cv_done.notify_all();  // wait() AND queue_depth backpressure
      }
    }
    std::free(bounce);
  }

  int ensure_bounce(void** bounce, int64_t* cap, int64_t need) {
    if (*cap >= need) return 0;
    std::free(*bounce);
    *bounce = nullptr;
    if (posix_memalign(bounce, kAlign, need) != 0) {
      *cap = 0;
      return -1;
    }
    *cap = need;
    return 0;
  }

  // Transfer [offset, offset+nbytes) of the file through an O_DIRECT fd
  // and a bounce buffer.  Requires offset aligned; nbytes arbitrary (reads
  // may overshoot the request into the bounce buffer — never into `p`).
  int direct_body(int fd, Op::Kind kind, char* p, int64_t nbytes,
                  int64_t offset, void* bounce) {
    int64_t remaining = nbytes;
    int64_t off = offset;
    int64_t chunk = align_up(block_size > 0 ? block_size : (1 << 20));
    while (remaining > 0) {
      int64_t want = remaining < chunk ? remaining : chunk;
      if (kind == Op::READ) {
        // read whole aligned blocks; copy out just the requested bytes.
        // Short transfers are legal (signal/kernel split) — retry from
        // the returned count as long as O_DIRECT alignment holds.
        int64_t need = align_up(want);
        int64_t done = 0;
        while (done < want) {
          ssize_t got = ::pread(fd, (char*)bounce + done, need - done,
                                off + done);
          if (got <= 0) return -1;
          done += got;
          // continuing from an unaligned position would break O_DIRECT;
          // legal only when the request is already satisfied (short final
          // read at an unaligned EOF — buffered tails make those normal)
          if (done < want && done % kAlign) return -1;
        }
        std::memcpy(p, bounce, want);
      } else {
        if (want % kAlign) return -1;  // caller routes tails elsewhere
        std::memcpy(bounce, p, want);
        int64_t done = 0;
        while (done < want) {
          ssize_t put = ::pwrite(fd, (char*)bounce + done, want - done,
                                 off + done);
          if (put <= 0 || put % kAlign) return -1;
          done += put;
        }
      }
      p += want;
      off += want;
      remaining -= want;
    }
    bytes_direct.fetch_add(nbytes);
    return 0;
  }

  // Plain buffered transfer (fallback + unaligned tails).
  int buffered_body(int fd, Op::Kind kind, char* p, int64_t nbytes,
                    int64_t offset) {
    int64_t remaining = nbytes;
    int64_t off = offset;
    int64_t chunk = block_size > 0 ? block_size : (1 << 20);
    while (remaining > 0) {
      int64_t n = remaining < chunk ? remaining : chunk;
      ssize_t done = (kind == Op::READ) ? ::pread(fd, p, n, off)
                                        : ::pwrite(fd, p, n, off);
      if (done <= 0) return -1;
      p += done;
      off += done;
      remaining -= done;
    }
    bytes_buffered.fetch_add(nbytes);
    return 0;
  }

  int run_one(const Op& op, void** bounce, int64_t* bounce_cap) {
    int base = (op.kind == Op::READ) ? O_RDONLY : (O_WRONLY | O_CREAT);
    char* p = (char*)op.buf;
    int rc = 0;

    // O_DIRECT path: aligned offset required (the swapper always starts
    // at 0 / block multiples); aligned BODY via the bounce buffer, then a
    // buffered (<4 KiB) tail.  Writes need the aligned body to be a block
    // multiple; reads may overshoot into the bounce buffer, so the whole
    // length can go direct when the file is long enough.
    int dfd = -1;
    if (op.offset % kAlign == 0) dfd = ::open(op.path.c_str(), base | O_DIRECT, 0644);
    if (dfd >= 0) {
      int64_t chunk = align_up(block_size > 0 ? block_size : (1 << 20));
      if (ensure_bounce(bounce, bounce_cap, chunk) != 0) {
        ::close(dfd);
        return -1;
      }
      int64_t body = align_down(op.nbytes);
      int64_t tail = op.nbytes - body;
      if (op.kind == Op::READ) {
        // only overshoot-read when the file extends past the request
        // (aligned files written by this engine always do)
        struct stat st;
        if (::fstat(dfd, &st) == 0 &&
            st.st_size >= op.offset + align_up(op.nbytes)) {
          body = op.nbytes;
          tail = 0;
        }
      }
      if (body > 0)
        rc = direct_body(dfd, op.kind, p, body, op.offset, *bounce);
      ::close(dfd);
      if (rc != 0 && op.kind == Op::READ) {
        // the overshoot decision was taken at open time; a concurrent
        // whole-file rewrite to the exact logical size (dropping only the
        // alignment overshoot) between fstat and the final pread makes
        // the direct read come up short.  Reads are idempotent — retry
        // the whole request buffered.  A file shrunk below
        // offset+nbytes still fails (buffered_body errors at EOF): the
        // requested bytes genuinely don't exist.  read_retries makes the
        // degradation observable: a direct-path regression (EIO,
        // alignment bug) that this retry would otherwise mask shows up
        // as a climbing counter in ds_aio_stats.
        read_retries.fetch_add(1);
        int rfd = ::open(op.path.c_str(), base, 0644);
        if (rfd < 0) return -1;
        rc = buffered_body(rfd, op.kind, p, op.nbytes, op.offset);
        ::close(rfd);
        return rc;
      }
      if (rc == 0 && tail > 0) {
        int tfd = ::open(op.path.c_str(), base, 0644);
        if (tfd < 0) return -1;
        rc = buffered_body(tfd, op.kind, p + body, tail, op.offset + body);
        ::close(tfd);
      }
    } else {
      // O_DIRECT unavailable (tmpfs, unaligned offset): buffered fallback
      int fd = ::open(op.path.c_str(), base, 0644);
      if (fd < 0) return -1;
      rc = buffered_body(fd, op.kind, p, op.nbytes, op.offset);
      ::close(fd);
    }

    if (rc == 0 && op.kind == Op::WRITE && op.trunc) {
      // whole-file rewrite: drop stale tail bytes from a previous larger
      // shard at the same path.  O_DIRECT writes rounded the file up to
      // block multiples only in the buffered-tail-free case; the truncate
      // also restores the true logical size.
      if (::truncate(op.path.c_str(), op.offset + op.nbytes) != 0) rc = -1;
    }
    return rc;
  }
};

}  // namespace

extern "C" {

void* ds_aio_new(int block_size, int queue_depth, int single_submit,
                 int overlap_events, int thread_count) {
  Handle* h = new Handle();
  h->block_size = block_size;
  h->queue_depth = queue_depth > 0 ? queue_depth : 32;
  h->thread_count = thread_count > 0 ? thread_count : 1;
  h->single_submit = single_submit != 0;
  h->overlap_events = overlap_events != 0;
  for (int i = 0; i < h->thread_count; ++i)
    h->workers.emplace_back([h] { h->worker(); });
  return h;
}

void ds_aio_free(void* hp) {
  Handle* h = (Handle*)hp;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->shutdown = true;
  }
  h->cv_submit.notify_all();
  for (auto& t : h->workers) t.join();
  delete h;
}

static void enqueue(Handle* h, Op op) {
  {
    // queue_depth backpressure: the submitter blocks while the engine has
    // queue_depth ops in flight (the reference's AIO context depth)
    std::unique_lock<std::mutex> lk(h->mu);
    h->cv_done.wait(
        lk, [&] { return h->inflight.load() < h->queue_depth; });
    h->inflight.fetch_add(1);
    h->queue.push_back(std::move(op));
  }
  h->cv_submit.notify_one();
}

static void submit(Handle* h, Op op) {
  // single_submit=false (default): split large ops into block_size
  // sub-ops so several workers overlap one transfer — the thread-pool
  // analogue of batched io_submit.  WRITE splits pre-size the file once
  // so sub-writes never race an implicit extend.
  int64_t chunk = h->block_size > 0 ? align_up(h->block_size) : 0;
  bool split = !h->single_submit && chunk > 0 && op.nbytes > chunk &&
               h->thread_count > 1;
  if (split && op.kind == Op::WRITE) {
    int fd = ::open(op.path.c_str(), O_WRONLY | O_CREAT, 0644);
    if (fd < 0) {
      split = false;
    } else {
      if (op.trunc) (void)!::ftruncate(fd, op.offset + op.nbytes);
      ::close(fd);
    }
  }
  if (split) {
    op.trunc = false;  // pre-sized above; sub-writes must not truncate
    for (int64_t off = 0; off < op.nbytes; off += chunk) {
      Op sub = op;
      sub.buf = (char*)op.buf + off;
      sub.offset = op.offset + off;
      sub.nbytes = (op.nbytes - off) < chunk ? (op.nbytes - off) : chunk;
      enqueue(h, std::move(sub));
    }
  } else {
    enqueue(h, std::move(op));
  }
  if (!h->overlap_events) {
    // overlap_events=false: synchronous submits (drain before returning)
    std::unique_lock<std::mutex> lk(h->mu);
    h->cv_done.wait(lk, [&] { return h->inflight.load() == 0; });
  }
}

// async submit; pair with ds_aio_wait
void ds_aio_pread(void* hp, void* buf, int64_t nbytes, const char* path,
                  int64_t offset) {
  submit((Handle*)hp, Op{Op::READ, buf, nbytes, path, offset});
}

void ds_aio_pwrite(void* hp, const void* buf, int64_t nbytes, const char* path,
                   int64_t offset) {
  submit((Handle*)hp, Op{Op::WRITE, (void*)buf, nbytes, path, offset});
}

// write + ftruncate(offset+nbytes): for whole-file shard rewrites
void ds_aio_pwrite_trunc(void* hp, const void* buf, int64_t nbytes,
                         const char* path, int64_t offset) {
  submit((Handle*)hp, Op{Op::WRITE, (void*)buf, nbytes, path, offset, true});
}

// Block until every submitted op completes; returns count of failed ops
// since the last wait (and resets the error counter).
int64_t ds_aio_wait(void* hp) {
  Handle* h = (Handle*)hp;
  std::unique_lock<std::mutex> lk(h->mu);
  h->cv_done.wait(lk, [&] { return h->inflight.load() == 0; });
  return h->errors.exchange(0);
}

int64_t ds_aio_inflight(void* hp) { return ((Handle*)hp)->inflight.load(); }

// Bytes moved through the O_DIRECT path vs the buffered path since handle
// creation — lets callers (and the falsifying test) verify the page cache
// is actually being bypassed.
void ds_aio_stats(void* hp, int64_t* direct_bytes, int64_t* buffered_bytes) {
  Handle* h = (Handle*)hp;
  if (direct_bytes) *direct_bytes = h->bytes_direct.load();
  if (buffered_bytes) *buffered_bytes = h->bytes_buffered.load();
}

// Direct reads that degraded to the buffered fallback (shrink race, or a
// masked direct-path failure) — should stay ~0 in healthy operation.
int64_t ds_aio_read_retries(void* hp) {
  return ((Handle*)hp)->read_retries.load();
}

}  // extern "C"
