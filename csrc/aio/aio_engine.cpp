// Async file I/O engine — the ZeRO-Infinity NVMe tier.
//
// Role parity with the reference csrc/aio/ [K] (deepspeed_aio_thread.cpp,
// py_lib bindings): an aio_handle with a worker-thread pool draining a
// submission queue of pread/pwrite ops against O_DIRECT-friendly block
// files, with wait/drain semantics the swap layer builds on
// (aio_handle(block_size, queue_depth, single_submit, overlap_events,
// thread_count) ctor keys [L ACC-DC:1187-1194]).
//
// TPU-first adaptation: plain pthread/std::thread pool + pread/pwrite with a
// C ABI for ctypes. (io_uring/libaio would pin this to specific kernels; the
// thread-pool engine saturates TPU-VM NVMe with queue_depth×thread_count
// in-flight ops, and the interface leaves room to swap the backend.)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct Op {
  enum Kind { READ, WRITE } kind;
  void* buf;
  int64_t nbytes;
  std::string path;
  int64_t offset;
  bool trunc = false;  // WRITE: ftruncate file to offset+nbytes afterwards
};

struct Handle {
  int block_size;
  int queue_depth;
  int thread_count;
  std::vector<std::thread> workers;
  std::deque<Op> queue;
  std::mutex mu;
  std::condition_variable cv_submit;
  std::condition_variable cv_done;
  std::atomic<int64_t> inflight{0};
  std::atomic<int64_t> errors{0};
  bool shutdown = false;

  void worker() {
    for (;;) {
      Op op;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_submit.wait(lk, [&] { return shutdown || !queue.empty(); });
        if (shutdown && queue.empty()) return;
        op = queue.front();
        queue.pop_front();
      }
      if (run_one(op) != 0) errors.fetch_add(1);
      {
        // decrement+notify under the mutex: a lock-free notify can fire
        // between the waiter's predicate check and its sleep (lost wakeup)
        std::lock_guard<std::mutex> lk(mu);
        if (inflight.fetch_sub(1) == 1) cv_done.notify_all();
      }
    }
  }

  int run_one(const Op& op) {
    int flags = (op.kind == Op::READ) ? O_RDONLY : (O_WRONLY | O_CREAT);
    int fd = ::open(op.path.c_str(), flags, 0644);
    if (fd < 0) return -1;
    char* p = (char*)op.buf;
    int64_t remaining = op.nbytes;
    int64_t off = op.offset;
    int64_t chunk = block_size > 0 ? (int64_t)block_size : (1 << 20);
    int rc = 0;
    while (remaining > 0) {
      int64_t n = remaining < chunk ? remaining : chunk;
      ssize_t done = (op.kind == Op::READ) ? ::pread(fd, p, n, off)
                                           : ::pwrite(fd, p, n, off);
      if (done <= 0) {
        rc = -1;
        break;
      }
      p += done;
      off += done;
      remaining -= done;
    }
    if (rc == 0 && op.kind == Op::WRITE && op.trunc) {
      // whole-file rewrite: drop stale tail bytes from a previous larger
      // shard at the same path
      if (::ftruncate(fd, op.offset + op.nbytes) != 0) rc = -1;
    }
    ::close(fd);
    return rc;
  }
};

}  // namespace

extern "C" {

void* ds_aio_new(int block_size, int queue_depth, int single_submit,
                 int overlap_events, int thread_count) {
  (void)single_submit;
  (void)overlap_events;
  Handle* h = new Handle();
  h->block_size = block_size;
  h->queue_depth = queue_depth > 0 ? queue_depth : 32;
  h->thread_count = thread_count > 0 ? thread_count : 1;
  for (int i = 0; i < h->thread_count; ++i)
    h->workers.emplace_back([h] { h->worker(); });
  return h;
}

void ds_aio_free(void* hp) {
  Handle* h = (Handle*)hp;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->shutdown = true;
  }
  h->cv_submit.notify_all();
  for (auto& t : h->workers) t.join();
  delete h;
}

static void submit(Handle* h, Op op) {
  h->inflight.fetch_add(1);
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->queue.push_back(std::move(op));
  }
  h->cv_submit.notify_one();
}

// async submit; pair with ds_aio_wait
void ds_aio_pread(void* hp, void* buf, int64_t nbytes, const char* path,
                  int64_t offset) {
  submit((Handle*)hp, Op{Op::READ, buf, nbytes, path, offset});
}

void ds_aio_pwrite(void* hp, const void* buf, int64_t nbytes, const char* path,
                   int64_t offset) {
  submit((Handle*)hp, Op{Op::WRITE, (void*)buf, nbytes, path, offset});
}

// write + ftruncate(offset+nbytes): for whole-file shard rewrites
void ds_aio_pwrite_trunc(void* hp, const void* buf, int64_t nbytes,
                         const char* path, int64_t offset) {
  submit((Handle*)hp, Op{Op::WRITE, (void*)buf, nbytes, path, offset, true});
}

// Block until every submitted op completes; returns count of failed ops
// since the last wait (and resets the error counter).
int64_t ds_aio_wait(void* hp) {
  Handle* h = (Handle*)hp;
  std::unique_lock<std::mutex> lk(h->mu);
  h->cv_done.wait(lk, [&] { return h->inflight.load() == 0; });
  return h->errors.exchange(0);
}

int64_t ds_aio_inflight(void* hp) { return ((Handle*)hp)->inflight.load(); }

}  // extern "C"
