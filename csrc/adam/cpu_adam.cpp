// CPU Adam/AdamW — host-side optimizer for ZeRO-Offload.
//
// Role parity with the reference csrc/adam/cpu_adam{,_impl}.cpp [K]:
// vectorized Adam over fp32 master shards resident in host RAM, so the
// device (TPU) only holds compute params; states never touch HBM.
//
// TPU-first adaptation: no torch/CUDA coupling — plain C ABI consumed via
// ctypes; OpenMP across chunks; auto-vectorizable inner loop (gcc emits
// AVX2/AVX-512 or NEON per -march). A bf16 emit path writes the updated
// params directly in the wire format the device expects, saving one host
// cast pass.

#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// One fused Adam(W) step over a contiguous fp32 shard.
// adamw_mode: 1 → decoupled weight decay (AdamW), 0 → L2-into-grad Adam.
// bias_correction: 1 → standard Adam bias correction using `step` (1-based).
void ds_adam_step(float* params, const float* grads, float* exp_avg,
                  float* exp_avg_sq, int64_t n, int step, float lr,
                  float beta1, float beta2, float eps, float weight_decay,
                  int adamw_mode, int bias_correction) {
  const float bc1 = bias_correction ? 1.0f - std::pow(beta1, (float)step) : 1.0f;
  const float bc2 = bias_correction ? 1.0f - std::pow(beta2, (float)step) : 1.0f;
  const float step_size = lr / bc1;
  const float bc2_sqrt = std::sqrt(bc2);

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (!adamw_mode && weight_decay != 0.0f) g += weight_decay * p;
    float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
    float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) / bc2_sqrt + eps;
    // decoupled decay uses plain lr (NOT bias-corrected step_size)
    if (adamw_mode && weight_decay != 0.0f) p *= (1.0f - lr * weight_decay);
    params[i] = p - step_size * (m / denom);
  }
}

// Same step, but also emit the updated params as bf16 (round-to-nearest-even)
// into `out_bf16` — the copy the device consumes.
void ds_adam_step_bf16(float* params, const float* grads, float* exp_avg,
                       float* exp_avg_sq, uint16_t* out_bf16, int64_t n,
                       int step, float lr, float beta1, float beta2, float eps,
                       float weight_decay, int adamw_mode, int bias_correction) {
  ds_adam_step(params, grads, exp_avg, exp_avg_sq, n, step, lr, beta1, beta2,
               eps, weight_decay, adamw_mode, bias_correction);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits;
    std::memcpy(&bits, &params[i], sizeof(bits));
    uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
    bits += rounding;
    out_bf16[i] = (uint16_t)(bits >> 16);
  }
}

// Adagrad (reference csrc/adagrad/cpu_adagrad.cpp [K]).
void ds_adagrad_step(float* params, const float* grads, float* exp_avg_sq,
                     int64_t n, int /*step*/, float lr, float eps,
                     float weight_decay) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    if (weight_decay != 0.0f) g += weight_decay * params[i];
    float v = exp_avg_sq[i] + g * g;
    exp_avg_sq[i] = v;
    params[i] -= lr * g / (std::sqrt(v) + eps);
  }
}

// Lion (reference csrc/lion/cpu_lion.cpp [K]).
void ds_lion_step(float* params, const float* grads, float* exp_avg,
                  int64_t n, int /*step*/, float lr, float beta1, float beta2,
                  float weight_decay) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    float m = exp_avg[i];
    float c = beta1 * m + (1.0f - beta1) * g;
    float update = (c > 0.0f) - (c < 0.0f);  // sign
    if (weight_decay != 0.0f) p -= lr * weight_decay * p;
    params[i] = p - lr * update;
    exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
  }
}

int ds_cpu_adam_num_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
