"""Benchmark: flagship Llama training throughput + MFU on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline metric: training tokens/sec on the SAME ~110M-param Llama config as
round 1 (bf16, flash attention, fused single-program step) so ``vs_baseline``
is a true round-over-round ratio against the recorded round-1 number
(BENCH_r01.json: 35367.7 tok/s; BASELINE.json ``published`` is {} — there is
no driver-verified reference number, see BASELINE.md provenance warning).

Extras in the same JSON line:
- ``mfu``               — achieved model FLOP/s over the chip's bf16 peak,
                          FLOPs taken from XLA ``cost_analysis()`` of the
                          compiled train step (post-fusion truth).
- ``variants``          — {name: tokens/sec} for a max-fitting ZeRO-3 + remat
                          config (sized from live HBM stats) and a
                          CPU-offload-optimizer config (target: >=0.8x
                          on-device per VERDICT round-1 item 3).
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# round-1 recorded headline (BENCH_r01.json) — the cross-round baseline
R01_TOKENS_PER_SEC = 35367.7

def peak_flops_per_chip() -> float:
    # single source of truth for the per-kind peak table
    from deepspeed_tpu.profiling.flops_profiler.profiler import (
        peak_flops_per_chip as _peak)

    return _peak()


def hbm_bytes() -> int:
    try:
        stats = jax.devices()[0].memory_stats() or {}
        return int(stats.get("bytes_limit", 0))
    except Exception:
        return 0


def build_engine(cfg, batch, zero_stage=0, offload=False, bf16=True):
    import deepspeed_tpu
    from deepspeed_tpu.models import LlamaModel
    from deepspeed_tpu.parallel import MeshLayout
    from deepspeed_tpu.utils import groups

    layout = MeshLayout.infer(1, dp=1)
    mesh = groups.initialize_mesh(layout)
    model = LlamaModel(cfg, mesh=mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    zero: dict = {"stage": zero_stage}
    if offload:
        zero["offload_optimizer"] = {"device": "cpu"}
    ds_config = {
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": zero,
        "bf16": {"enabled": bf16},
        "steps_per_print": 0,
    }
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds_config, mesh=mesh)
    return engine


def _sync(metrics) -> float:
    """True device barrier.  On the tunneled axon platform
    ``jax.block_until_ready`` returns immediately; fetching a scalar result
    is a real fence, and the last step's metrics depend on every enqueued
    step through the state chain."""
    return float(metrics["loss"])


def measure(engine, batch, seq, vocab, steps, segments=3,
            budget_s: float = 120.0):
    """Median-of-segments tokens/sec with a wall-clock budget: a slow
    config (e.g. offload over a tunneled chip) degrades to fewer steps
    instead of hanging the driver's bench run."""
    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, vocab, size=(batch, seq)))
    data = {"input_ids": ids}
    _sync(engine.train_step(data))  # compile + warmup
    # probe one step to right-size the per-segment step count
    t0 = time.perf_counter()
    _sync(engine.train_step(data))
    per_step = max(time.perf_counter() - t0, 1e-4)
    steps = max(1, min(steps, int(budget_s / (segments * per_step))))
    rates = []
    for _ in range(segments):
        t0 = time.perf_counter()
        for _ in range(steps):
            m = engine.train_step(data)
        _sync(m)
        rates.append(batch * seq * steps / (time.perf_counter() - t0))
    return sorted(rates)[len(rates) // 2]


def step_flops(engine, batch, seq, vocab, cfg) -> float:
    """MODEL FLOPs per step — the analytic 6N + attention formula (the MFU
    convention: remat recompute and optimizer math don't count, so neither
    XLA cost_analysis (counts recompute) nor hardware counters apply)."""
    n_params = sum(int(x.size) for x in jax.tree.leaves(engine.state.params))
    per_tok = 6 * n_params + 12 * cfg.num_layers * seq * cfg.hidden_size
    return float(per_tok * batch * seq)


def selfcheck(block_q: int = 512, block_k: int = 512) -> None:
    """On-chip kernel numerics gate (VERDICT round-2 item 7): every Pallas
    kernel family runs ON THE REAL CHIP against its jnp reference and must
    match within tolerance.  Raises AssertionError on any mismatch — the
    round-1 VMEM-overflow decode bug is exactly the class this catches
    (interpret-mode CPU tests can't).  ``block_q/block_k`` exist so a test
    can prove a broken block size fails the gate."""
    from deepspeed_tpu.ops.pallas.decode_attention import (_reference_decode,
                                                           decode_attention)
    from deepspeed_tpu.ops.pallas.flash_attention import (
        _reference_attention, flash_attention)
    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_decode_attention, paged_decode_reference)
    from deepspeed_tpu.ops.pallas.quantizer import (_ref_quantize,
                                                    dequantize_int8,
                                                    quantize_int8)

    rng = np.random.RandomState(0)
    checks = []

    # flash fwd + bwd (f32 so tolerance is meaningful on one chip)
    B, S, h, d = 2, 1024, 4, 64
    q = jnp.asarray(rng.randn(B, S, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, h, d).astype(np.float32))
    def rel_err(got, want):
        return (float(jnp.max(jnp.abs(got - want)))
                / (float(jnp.max(jnp.abs(want))) + 1e-6))

    # tolerance note: on TPU the default matmul precision runs fp32 inputs
    # through bf16 passes, so kernel-vs-reference differ by accumulation
    # noise ~1e-2 relative even when both are correct; real indexing/VMEM
    # bugs produce O(1) relative error (or NaN), so 2e-2 discriminates.
    TOL = 2e-2
    for window in (None, 200):
        got = flash_attention(q, k, v, causal=True, block_q=block_q,
                              block_k=block_k, window=window)
        want = _reference_attention(q, k, v, causal=True, window=window)
        checks.append((f"flash_fwd(window={window})", rel_err(got, want), TOL))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=block_q, block_k=block_k) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True) ** 2)

    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_got, g_want):
        checks.append((f"flash_bwd_d{name}", rel_err(a, b), TOL))

    # decode over padded caches
    B, Smax, kv_h, hq = 4, 512, 2, 4
    qd = jnp.asarray(rng.randn(B, hq, d).astype(np.float32))
    kc = jnp.asarray(rng.randn(B, Smax, kv_h, d).astype(np.float32))
    vc = jnp.asarray(rng.randn(B, Smax, kv_h, d).astype(np.float32))
    lengths = jnp.asarray(np.array([5, 100, 256, 512], np.int32))
    got = decode_attention(qd, kc, vc, lengths, block_k=min(block_k, 128))
    want = _reference_decode(qd, kc, vc, lengths)
    checks.append(("decode", rel_err(got, want), TOL))

    # paged decode through a shuffled block table
    bs, max_blocks, num_pool = 16, 8, 64
    perm = rng.permutation(np.arange(1, num_pool))[:B * max_blocks]
    tables = jnp.asarray(perm.reshape(B, max_blocks).astype(np.int32))
    k_pool = jnp.asarray(rng.randn(num_pool, bs, kv_h, d).astype(np.float32))
    v_pool = jnp.asarray(rng.randn(num_pool, bs, kv_h, d).astype(np.float32))
    plens = jnp.asarray(np.array([3, 40, 90, 128], np.int32))
    got = paged_decode_attention(qd, k_pool, v_pool, tables, plens)
    want = paged_decode_reference(qd, k_pool, v_pool, tables, plens)
    checks.append(("paged", rel_err(got, want), TOL))

    # block-sparse attention vs its dense-masked anchor
    from deepspeed_tpu.ops.pallas.block_sparse_attention import (
        block_sparse_attention)
    from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                    sparse_attention)

    hq = 4
    qs = jnp.asarray(rng.randn(1, 1024, hq, d).astype(np.float32))
    ks = jnp.asarray(rng.randn(1, 1024, hq, d).astype(np.float32))
    vs = jnp.asarray(rng.randn(1, 1024, hq, d).astype(np.float32))
    bb = BigBirdSparsityConfig(num_heads=hq, block=16,
                               different_layout_per_head=True)
    got = block_sparse_attention(qs, ks, vs, bb)
    want = sparse_attention(qs, ks, vs, bb, impl="dense")
    checks.append(("block_sparse", rel_err(got, want), TOL))

    # int8 quantizer round trip
    x = jnp.asarray(rng.randn(512, 256).astype(np.float32))
    qx, s = quantize_int8(x)
    qr, sr = _ref_quantize(np.asarray(x))
    checks.append(("quantizer_codes",
                   float(jnp.max(jnp.abs(qx.astype(jnp.int32)
                                         - jnp.asarray(qr, jnp.int32)))), 1.0))
    deq_err = float(jnp.max(jnp.abs(dequantize_int8(qx, s) - x)))
    # |err| <= scale/2 per row; scales are max|row|/127
    bound = float(jnp.max(jnp.abs(x))) / 127.0
    checks.append(("quantizer_roundtrip", deq_err, bound * 1.01))

    bad = [(n, e, t) for n, e, t in checks if not (e <= t and np.isfinite(e))]
    if bad:
        raise AssertionError(f"kernel selfcheck FAILED: {bad}")


def main() -> None:
    from deepspeed_tpu.models import LlamaConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    extras: dict = {}

    if "--selfcheck" in sys.argv:
        selfcheck()
        print(json.dumps({"kernels_verified": True}))
        return

    if not on_tpu:  # CPU fallback so the bench always emits a line
        cfg = LlamaConfig.tiny(num_layers=2)
        engine = build_engine(cfg, 4, bf16=False)
        tps = measure(engine, 4, 128, cfg.vocab_size, steps=3, segments=1)
        print(json.dumps({
            "metric": "llama_tiny_cpu_train_tokens_per_sec",
            "value": round(tps, 1), "unit": "tokens/sec/chip",
            "vs_baseline": 1.0}))
        return

    # -- kernel numerics gate: runs BEFORE the headline -------------------
    try:
        selfcheck()
        extras["kernels_verified"] = True
    except AssertionError as e:
        extras["kernels_verified"] = False
        extras["kernels_error"] = str(e)[:300]

    # -- headline: identical config to round 1 (comparable across rounds) --
    cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                      intermediate_size=2048, num_layers=12,
                      num_heads=12, num_kv_heads=12, max_seq_len=2048,
                      dtype=jnp.bfloat16, attn_impl="flash")
    batch, seq = 8, 2048
    engine = build_engine(cfg, batch)
    tps = measure(engine, batch, seq, cfg.vocab_size, steps=20)
    flops = step_flops(engine, batch, seq, cfg.vocab_size, cfg)
    peak = peak_flops_per_chip()
    mfu = (flops * tps / (batch * seq)) / peak
    extras["mfu"] = round(mfu, 4)
    extras["device_kind"] = jax.devices()[0].device_kind
    del engine
    gc.collect()  # engine sits in a jit-closure reference cycle; free HBM now

    # -- variant: max-fitting ZeRO-3 + remat, sized from live HBM ----------
    # shape choice is MFU-tuned: wide-short beats narrow-deep on the MXU
    # (measured on v5e: h2048/L10 = 48% MFU vs h1024/L24 = 31% at equal
    # fit) — the BASELINE.md north star is MFU, so the max-fitting config
    # maximizes it, not parameter count
    try:
        hbm = hbm_bytes()
        if hbm >= 80e9:      # ~3.5B for 95G chips (56G Adam states + acts)
            big = LlamaConfig(vocab_size=32000, hidden_size=4096,
                              intermediate_size=11008, num_layers=16,
                              num_heads=32, num_kv_heads=32, max_seq_len=2048,
                              dtype=jnp.bfloat16, attn_impl="flash",
                              remat=True)
            bbatch = 4
        elif hbm >= 30e9:    # ~1.2B for 32G chips (~19G states)
            big = LlamaConfig(vocab_size=32000, hidden_size=2048,
                              intermediate_size=5504, num_layers=24,
                              num_heads=16, num_kv_heads=16, max_seq_len=2048,
                              dtype=jnp.bfloat16, attn_impl="flash",
                              remat=True)
            bbatch = 4
        else:                # 637M wide-short fits 16G chips with states+acts
            big = LlamaConfig(vocab_size=32000, hidden_size=2048,
                              intermediate_size=5504, num_layers=10,
                              num_heads=16, num_kv_heads=16, max_seq_len=2048,
                              dtype=jnp.bfloat16, attn_impl="flash",
                              remat=True)
            bbatch = 4
        eng = build_engine(big, bbatch, zero_stage=3)
        btps = measure(eng, bbatch, seq, big.vocab_size, steps=10)
        bflops = step_flops(eng, bbatch, seq, big.vocab_size, big)
        extras["variants"] = {
            "zero3_remat_large_tokens_per_sec": round(btps, 1),
            "zero3_remat_large_mfu": round(
                (bflops * btps / (bbatch * seq)) / peak, 4),
        }
        del eng
        gc.collect()
    except Exception as e:  # a variant must never kill the headline line
        extras["variants"] = {"zero3_remat_large_error": str(e)[:200]}

    # -- variant: inference v2 ragged serving throughput -------------------
    # NOTE: on the tunneled chip every decode step pays a network round
    # trip for sampling, so this measures the serving LOOP, not the chip;
    # it is tracked round-over-round for relative movement.
    try:
        from deepspeed_tpu.inference.v2 import KVCacheConfig, build_engine_v2
        from deepspeed_tpu.models import LlamaModel
        from deepspeed_tpu.parallel import MeshLayout
        from deepspeed_tpu.utils import groups

        groups.reset_mesh()
        groups.initialize_mesh(MeshLayout.infer(1, dp=1))
        smodel = LlamaModel(cfg)  # same 110M config, mesh-less
        sparams = smodel.init_params(jax.random.PRNGKey(0))
        v2 = build_engine_v2(
            smodel, sparams,
            cache_config=KVCacheConfig(num_blocks=512, block_size=16,
                                       max_seq_len=1024),
            max_batch_slots=8, prefill_chunk=128)
        prng = np.random.RandomState(1)
        prompts = [prng.randint(1, cfg.vocab_size, size=n).tolist()
                   for n in (40, 100, 200, 350, 64, 128, 500, 80)]
        v2.generate(prompts[:2], max_new_tokens=4)  # compile both programs
        v2.generate(prompts, max_new_tokens=32)
        extras.setdefault("variants", {})[
            "inference_v2_ragged_tokens_per_sec"] = round(
                v2.last_throughput, 1)
        del v2
        gc.collect()
    except Exception as e:
        extras.setdefault("variants", {})[
            "inference_v2_error"] = str(e)[:200]

    # -- variant: block-sparse kernel speedup vs dense-masked (S=4096) ----
    try:
        from deepspeed_tpu.ops.pallas.block_sparse_attention import (
            block_sparse_attention)
        from deepspeed_tpu.ops.sparse_attention import (
            BigBirdSparsityConfig, sparse_attention)

        rng = np.random.RandomState(0)
        Sb, hb, db = 4096, 8, 64
        qs = jnp.asarray(rng.randn(1, Sb, hb, db)).astype(jnp.bfloat16)
        ks = jnp.asarray(rng.randn(1, Sb, hb, db)).astype(jnp.bfloat16)
        vs = jnp.asarray(rng.randn(1, Sb, hb, db)).astype(jnp.bfloat16)
        bb = BigBirdSparsityConfig(num_heads=hb, block=16,
                                   num_random_blocks=2,
                                   num_sliding_window_blocks=5,
                                   num_global_blocks=1)

        def _bench_attn(f, n=20):
            o = f(qs, ks, vs)
            float(jnp.sum(o.astype(jnp.float32)))  # compile + fence
            t0 = time.perf_counter()
            for _ in range(n):
                o = f(qs, ks, vs)
            float(jnp.sum(o.astype(jnp.float32)))  # real fence (tunnel)
            return (time.perf_counter() - t0) / n

        t_dense = _bench_attn(jax.jit(
            lambda q, k, v: sparse_attention(q, k, v, bb, impl="dense")))
        t_sparse = _bench_attn(jax.jit(
            lambda q, k, v: block_sparse_attention(q, k, v, bb)))
        extras.setdefault("variants", {})["block_sparse_speedup_s4096"] = \
            round(t_dense / t_sparse, 2)
    except Exception as e:
        extras.setdefault("variants", {})[
            "block_sparse_error"] = str(e)[:200]

    # -- variant: CPU-offload optimizer (target >=0.8x on-device) ----------
    try:
        eng = build_engine(cfg, batch, zero_stage=2, offload=True)
        otps = measure(eng, batch, seq, cfg.vocab_size, steps=3,
                       segments=1, budget_s=45.0)
        extras.setdefault("variants", {})[
            "offload_cpu_tokens_per_sec"] = round(otps, 1)
        extras["variants"]["offload_vs_ondevice"] = round(otps / tps, 3)
        del eng
    except Exception as e:
        extras.setdefault("variants", {})[
            "offload_cpu_error"] = str(e)[:200]

    # -- ZeRO-Infinity capacity: peak params/chip the tiering can hold -----
    # CAPACITY math, not a measured training run: on this tunneled chip a
    # layer-streaming step would move every layer's params over the
    # network (minutes/step), so the honest number here is what the
    # cpu/nvme tiers can back: fp32 master + Adam moments (12 B/param)
    # stream from host/NVMe, bf16 residence is O(2 layers).  The suite's
    # test_infinity.py exercises the actual streaming path.
    try:
        import shutil

        with open("/proc/meminfo") as f:
            info = {ln.split(":")[0]: int(ln.split()[1]) for ln in f}
        host_free = info.get("MemAvailable", 0) * 1024
        # a tmpfs /tmp IS host RAM — counting it again would double-count
        with open("/proc/mounts") as f:
            tmp_is_tmpfs = any(
                ln.split()[1] == "/tmp" and ln.split()[0] == "tmpfs"
                for ln in f)
        nvme_free = 0 if tmp_is_tmpfs else shutil.disk_usage("/tmp").free
        # conservative: keep 20% headroom on each tier
        capacity = int(0.8 * (host_free + nvme_free) / 12)
        extras.setdefault("variants", {})[
            "infinity_peak_params_per_chip"] = capacity
    except Exception:
        pass

    # history file for local tracking (the cross-round ratio uses R01)
    hist = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench_baseline.json")
    try:
        with open(hist, "w") as f:
            json.dump({"tokens_per_sec": tps, "mfu": extras["mfu"]}, f)
    except Exception:
        pass

    print(json.dumps({
        "metric": "llama_110m_train_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps / R01_TOKENS_PER_SEC, 3),
        **extras,
    }))


if __name__ == "__main__":
    main()
