"""Benchmark: flagship Llama training throughput + MFU on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline metric: training tokens/sec on the SAME ~110M-param Llama config as
round 1 (bf16, flash attention, fused single-program step) so ``vs_baseline``
is a true round-over-round ratio against the recorded round-1 number
(BENCH_r01.json: 35367.7 tok/s; BASELINE.json ``published`` is {} — there is
no driver-verified reference number, see BASELINE.md provenance warning).

Extras in the same JSON line:
- ``kernels_verified``  — the on-chip Pallas selfcheck gate ran and passed
                          (``--selfcheck`` runs it standalone).
- ``mfu``               — achieved model FLOP/s over the chip's bf16 peak
                          (analytic 6N + attention FLOPs; remat recompute
                          and optimizer math excluded per MFU convention).
- ``peak_hbm_bytes``    — HBM high-water of the headline run
                          (``memory_stats().peak_bytes_in_use``); gated
                          by ``telemetry perf check`` (lower is better,
                          10% tolerance + 64 MiB absolute floor).
- ``hbm_headroom_frac`` — 1 - peak/limit: how much HBM the headline
                          config leaves free (higher is better; the
                          autotuning search budget).
- ``tuned_config_source`` — which best-known-config store entry the tuned
                          run applied (``<store path>::<key>``; "none" on
                          a store miss, "error: ..." when the tuned run
                          died).  The headline itself NEVER changes config
                          (cross-round comparability); the tuned run is a
                          separate engine build from the store entry.
- ``tuned_mfu``         — MFU of the tuned run; gated by ``telemetry perf
                          check`` so a bad promotion or stale seed gates
                          like a code regression.  ``tuned_vs_default_
                          mfu_delta`` is the same number minus the
                          headline ``mfu``.
- ``environment_failure`` — present (true) ONLY on no-data error lines
                          (device probe failed): tells ``perf check``
                          to SKIP with the reason instead of gating.
- ``flash_speedup_s{2048,8192,32768}`` — Pallas flash attention
                          (fwd+bwd, causal) vs the XLA reference ladder
                          rung at that seq length (dense masked ref to
                          8k, chunked online-softmax scan at 32k).
                          Gated; the dispatch contract is >= 1.0 at
                          every benched length.
- ``block_sparse_speedup_s4096`` — block-sparse kernel vs its own dense
                          fallback at 4k; with choose_impl's crossover
                          auto-dispatch a sub-1.0 value is a dispatch
                          bug.  Gated (was variants-only before r05).
- ``fused_adam_hbm_gbps`` — the one-pass fused Adam kernel's effective
                          HBM GB/s over the same 7-floats/param
                          accounting as ``optax_adam_hbm_gbps``
                          (variants).  Gated; acceptance is fused >
                          optax.
- ``overlap_hiding_frac`` — share of the all-gather's serialized cost
                          the chunked-ppermute ring buries under the
                          matmul it feeds (variants.overlap carries the
                          raw timings).  Gated.
- ``variants``          — driver-ladder configs (BASELINE.md): BERT-large
                          ZeRO-2, llama3-8B-shaped ZeRO-3 slice, Mixtral
                          MoE on inference v2; plus the shape-tuned MFU
                          ceiling, v2 ragged serving, the block-sparse
                          kernel speedup, and the ZeRO-Offload loopback
                          ratio + overlap breakdown.
- ``tunnel``            — measured link between this host and the chip
                          (~100 ms RTT, ~5-12 MB/s here).  Offload over
                          this link measures the LINK, not the
                          architecture (440 MB/step / 5 MB/s = 90 s no
                          matter how well the pipeline overlaps) — hence
                          the loopback variant: the same engine code on
                          the CPU backend, where host<->device moves at
                          memcpy speed, is the architecture number.
"""

from __future__ import annotations

import functools
import gc
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# Persistent compilation cache: the bench compiles ~10 distinct programs
# and on this setup each compile is a serialized remote round trip (~9 min
# of the wall was compile in round 3 measurements).  The cache makes every
# rerun — including the driver's — start warm.  A dirty-run sentinel
# guards against poisoning: an interrupted run can leave entries that
# ABORT the process on load, so if the previous run didn't exit cleanly
# the whole dir is wiped (one cold run beats a permanently red bench).
_CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache", "jax_bench")
_SENTINEL = os.path.join(_CACHE_DIR, ".bench_in_progress")


def _mark_cache_clean() -> None:
    try:
        os.remove(_SENTINEL)
    except OSError:
        pass


def _setup_compile_cache() -> None:
    """Called from main() (and at import by the loopback subprocess) — NOT
    unconditionally at import: the test suite imports this module for
    selfcheck(), and a test process managing the sentinel would wipe or
    orphan the driver's warm cache (an aborted test run once left the
    sentinel behind, forcing the next driver run cold)."""
    try:
        import atexit
        import shutil

        # the loopback subprocess (DS_BENCH_SUBPROCESS=1) shares the cache
        # but must not wipe it or clear the parent's sentinel
        if not os.environ.get("DS_BENCH_SUBPROCESS"):
            if os.path.exists(_SENTINEL):
                shutil.rmtree(_CACHE_DIR, ignore_errors=True)
            os.makedirs(_CACHE_DIR, exist_ok=True)
            with open(_SENTINEL, "w") as _f:
                _f.write(str(os.getpid()))
            # atexit covers sys.exit and normal teardown; a kill mid-run
            # leaves the sentinel and the NEXT run starts cold on a fresh
            # dir
            atexit.register(_mark_cache_clean)
        os.makedirs(_CACHE_DIR, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:
        pass


if os.environ.get("DS_BENCH_SUBPROCESS"):
    _setup_compile_cache()

# round-1 recorded headline (BENCH_r01.json) — the cross-round baseline
R01_TOKENS_PER_SEC = 35367.7

def peak_flops_per_chip() -> float:
    # single source of truth for the per-kind peak table
    from deepspeed_tpu.profiling.flops_profiler.profiler import (
        peak_flops_per_chip as _peak)

    return _peak()


def hbm_bytes() -> int:
    try:
        stats = jax.devices()[0].memory_stats() or {}
        return int(stats.get("bytes_limit", 0))
    except Exception:
        return 0


def free_hbm() -> None:
    """Collect + clear jit caches so a variant's HBM comes back even after
    an exception mid-build (an OOM'd variant must not poison the rest of
    the bench).  Callers must ``del`` their own references first — passing
    them here could never drop the caller's binding."""
    gc.collect()
    try:
        jax.clear_caches()
    except Exception:
        pass


def build_engine(cfg, batch, zero_stage=0, offload=False, bf16=True,
                 model_cls=None, gas=1, ds_extra=None):
    import deepspeed_tpu
    from deepspeed_tpu.models import LlamaModel
    from deepspeed_tpu.parallel import MeshLayout
    from deepspeed_tpu.utils import groups

    ds_extra = dict(ds_extra or {})
    ker = dict(ds_extra.get("kernels") or {})
    if ker.get("flash_attention") and hasattr(cfg, "attn_impl"):
        # the kernels.flash_attention config knob routes model attention
        # through the Pallas kernel family (same contract initialize()'s
        # tuned model_overrides use)
        import dataclasses as _dc

        repl = {"attn_impl": "flash"}
        if hasattr(cfg, "flash_block_q"):
            repl["flash_block_q"] = int(ker.get("flash_block_q", 0) or 0)
            repl["flash_block_k"] = int(ker.get("flash_block_k", 0) or 0)
        cfg = _dc.replace(cfg, **repl)

    layout = MeshLayout.infer(1, dp=1)
    mesh = groups.initialize_mesh(layout)
    model = (model_cls or LlamaModel)(cfg, mesh=mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    zero: dict = {"stage": zero_stage}
    if offload:
        zero["offload_optimizer"] = {"device": "cpu"}
    ds_config = {
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": zero,
        "bf16": {"enabled": bf16},
        "steps_per_print": 0,
        # engine-side StepRecords are THE measured numbers (ISSUE 1: bench
        # reports what the engine logged, so artifacts and telemetry can
        # never disagree); in-memory only — no file exporters in a bench
        "telemetry": {"enabled": True, "jsonl": False, "prometheus": False},
        # bench engines pin their exact config: a promoted store entry
        # must not silently shift the headline across rounds (the tuned
        # variant applies its store entry's overrides explicitly)
        "tuning": {"auto_apply": False},
    }
    ds_config.update(ds_extra)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds_config, mesh=mesh)
    return engine


def _sync(metrics) -> float:
    """True device barrier.  On the tunneled axon platform
    ``jax.block_until_ready`` returns immediately; fetching a scalar result
    is a real fence, and the last step's metrics depend on every enqueued
    step through the state chain."""
    return float(metrics["loss"])


def measure(engine, batch, seq, vocab, steps, segments=3,
            budget_s: float = 120.0, data=None):
    """Median-of-segments tokens/sec with a wall-clock budget: a slow
    config (e.g. offload over a tunneled chip) degrades to fewer steps
    instead of hanging the driver's bench run."""
    if data is None:
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, vocab, size=(batch, seq)))
        data = {"input_ids": ids}
    _sync(engine.train_step(data))  # compile + warmup
    # probe one step to right-size the per-segment step count
    t0 = time.perf_counter()
    _sync(engine.train_step(data))
    per_step = max(time.perf_counter() - t0, 1e-4)
    steps = max(1, min(steps, int(budget_s / (segments * per_step))))
    rates = []
    records = getattr(engine, "step_records", None)
    for _ in range(segments):
        # step-id marker, not a length index: the deque's maxlen eviction
        # would freeze a length-based cursor once it wraps
        mark = records[-1].step if records else 0
        t0 = time.perf_counter()
        for _ in range(steps):
            m = engine.train_step(data)
        _sync(m)
        wall = time.perf_counter() - t0
        segment = ([r for r in records if r.step > mark and r.device_fenced]
                   if records is not None else [])
        if segment:
            # the engine's OWN device-fenced StepRecords are the measured
            # numbers — the bench just aggregates them, so the emitted
            # metric line and the engine telemetry cannot disagree.
            # Cross-check against wall: record assembly/export overhead
            # is real run cost, so if the per-step device sum diverges
            # from wall by >5% the (cross-round-comparable, conservative)
            # wall number wins.
            dev_s = sum(r.step_time_ms for r in segment) / 1e3
            denom = dev_s if abs(wall - dev_s) <= 0.05 * wall else wall
            rates.append(batch * seq * len(segment) / max(denom, 1e-9))
        else:  # engine without telemetry: fall back to wall clock
            rates.append(batch * seq * steps / wall)
    return sorted(rates)[len(rates) // 2]


def _perf_extras(engine) -> dict:
    """Perf-sentinel fields for the BENCH line (telemetry/perf):
    step-time p50 from the engine's own device-fenced StepRecords,
    cumulative compile seconds from the compile tracker, and the run's
    goodput fraction — the metrics `telemetry perf check` gates on."""
    out: dict = {}
    try:
        recs = [r for r in getattr(engine, "step_records", [])
                if r.device_fenced]
        if recs:
            times = sorted(r.step_time_ms for r in recs)
            out["step_time_p50_ms"] = round(times[len(times) // 2], 2)
        from deepspeed_tpu.telemetry.perf import (get_compile_tracker,
                                                  get_goodput_ledger)

        trk = get_compile_tracker()
        if trk.enabled and trk.events_total:
            out["compile_time_s"] = round(trk.time_ms_total / 1e3, 3)
            out["compile_events"] = trk.events_total
            out["recompile_events"] = trk.recompiles_total
        gp = get_goodput_ledger()
        if gp.enabled and gp.total_seconds() > 0:
            out["goodput"] = round(gp.goodput(), 4)
        # memory plane (telemetry/memory): HBM high-water + headroom in
        # the baseline, so `telemetry perf check` gates memory
        # regressions the same way it gates throughput
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = int(stats.get("peak_bytes_in_use", 0) or 0)
        limit = int(stats.get("bytes_limit", 0) or 0)
        if peak:
            out["peak_hbm_bytes"] = peak
        if peak and limit:
            out["hbm_headroom_frac"] = round(1.0 - peak / limit, 4)
    except Exception as e:
        out["perf_extras_error"] = str(e)[:120]
    return out


def step_flops(engine, batch, seq, vocab, cfg) -> float:
    """MODEL FLOPs per step — the analytic 6N + attention formula (the MFU
    convention: remat recompute and optimizer math don't count, so neither
    XLA cost_analysis (counts recompute) nor hardware counters apply)."""
    n_params = sum(int(x.size) for x in jax.tree.leaves(engine.state.params))
    per_tok = 6 * n_params + 12 * cfg.num_layers * seq * cfg.hidden_size
    return float(per_tok * batch * seq)


def selfcheck(block_q: int = 512, block_k: int = 512) -> None:
    """On-chip kernel numerics gate (VERDICT round-2 item 7): every Pallas
    kernel family runs ON THE REAL CHIP against its jnp reference and must
    match within tolerance.  Raises AssertionError on any mismatch — the
    round-1 VMEM-overflow decode bug is exactly the class this catches
    (interpret-mode CPU tests can't).  ``block_q/block_k`` exist so a test
    can prove a broken block size fails the gate."""
    from deepspeed_tpu.ops.pallas.decode_attention import (_reference_decode,
                                                           decode_attention)
    from deepspeed_tpu.ops.pallas.flash_attention import (
        _reference_attention, flash_attention)
    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_decode_attention, paged_decode_reference)
    from deepspeed_tpu.ops.pallas.quantizer import (_ref_quantize,
                                                    dequantize_int8,
                                                    quantize_int8)

    rng = np.random.RandomState(0)
    checks = []

    # flash fwd + bwd (f32 so tolerance is meaningful on one chip)
    B, S, h, d = 2, 1024, 4, 64
    q = jnp.asarray(rng.randn(B, S, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, h, d).astype(np.float32))
    def rel_err(got, want):
        return (float(jnp.max(jnp.abs(got - want)))
                / (float(jnp.max(jnp.abs(want))) + 1e-6))

    # tolerance note: on TPU the default matmul precision runs fp32 inputs
    # through bf16 passes, so kernel-vs-reference differ by accumulation
    # noise ~1e-2 relative even when both are correct; real indexing/VMEM
    # bugs produce O(1) relative error (or NaN), so 2e-2 discriminates.
    TOL = 2e-2
    for window in (None, 200):
        got = flash_attention(q, k, v, causal=True, block_q=block_q,
                              block_k=block_k, window=window)
        want = _reference_attention(q, k, v, causal=True, window=window)
        checks.append((f"flash_fwd(window={window})", rel_err(got, want), TOL))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=block_q, block_k=block_k) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True) ** 2)

    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_got, g_want):
        checks.append((f"flash_bwd_d{name}", rel_err(a, b), TOL))

    # decode over padded caches
    B, Smax, kv_h, hq = 4, 512, 2, 4
    qd = jnp.asarray(rng.randn(B, hq, d).astype(np.float32))
    kc = jnp.asarray(rng.randn(B, Smax, kv_h, d).astype(np.float32))
    vc = jnp.asarray(rng.randn(B, Smax, kv_h, d).astype(np.float32))
    lengths = jnp.asarray(np.array([5, 100, 256, 512], np.int32))
    got = decode_attention(qd, kc, vc, lengths, block_k=min(block_k, 128))
    want = _reference_decode(qd, kc, vc, lengths)
    checks.append(("decode", rel_err(got, want), TOL))

    # paged decode through a shuffled block table
    bs, max_blocks, num_pool = 16, 8, 64
    perm = rng.permutation(np.arange(1, num_pool))[:B * max_blocks]
    tables = jnp.asarray(perm.reshape(B, max_blocks).astype(np.int32))
    k_pool = jnp.asarray(rng.randn(num_pool, bs, kv_h, d).astype(np.float32))
    v_pool = jnp.asarray(rng.randn(num_pool, bs, kv_h, d).astype(np.float32))
    plens = jnp.asarray(np.array([3, 40, 90, 128], np.int32))
    got = paged_decode_attention(qd, k_pool, v_pool, tables, plens)
    want = paged_decode_reference(qd, k_pool, v_pool, tables, plens)
    checks.append(("paged", rel_err(got, want), TOL))

    # block-sparse attention vs its dense-masked anchor
    from deepspeed_tpu.ops.pallas.block_sparse_attention import (
        block_sparse_attention)
    from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                    sparse_attention)

    hq = 4
    qs = jnp.asarray(rng.randn(1, 1024, hq, d).astype(np.float32))
    ks = jnp.asarray(rng.randn(1, 1024, hq, d).astype(np.float32))
    vs = jnp.asarray(rng.randn(1, 1024, hq, d).astype(np.float32))
    bb = BigBirdSparsityConfig(num_heads=hq, block=16,
                               different_layout_per_head=True)
    got = block_sparse_attention(qs, ks, vs, bb)
    want = sparse_attention(qs, ks, vs, bb, impl="dense")
    checks.append(("block_sparse", rel_err(got, want), TOL))

    # block-sparse backward (local-window layout → the sparse vjp path)
    from deepspeed_tpu.ops.sparse_attention import BSLongformerSparsityConfig

    lw = BSLongformerSparsityConfig(num_heads=hq, block=16,
                                    num_sliding_window_blocks=3,
                                    global_block_indices=())

    def loss_sparse(q, k, v):
        return jnp.sum(block_sparse_attention(
            q, k, v, lw, block_q=128, block_k=128).astype(jnp.float32) ** 2)

    def loss_dense_lw(q, k, v):
        return jnp.sum(sparse_attention(
            q, k, v, lw, impl="dense").astype(jnp.float32) ** 2)

    gs_ = jax.grad(loss_sparse, argnums=(0, 1, 2))(qs, ks, vs)
    gd_ = jax.grad(loss_dense_lw, argnums=(0, 1, 2))(qs, ks, vs)
    for nm, a, b in zip("qkv", gs_, gd_):
        checks.append((f"block_sparse_bwd_d{nm}", rel_err(a, b), TOL))

    # int8 quantizer round trip
    x = jnp.asarray(rng.randn(512, 256).astype(np.float32))
    qx, s = quantize_int8(x)
    qr, sr = _ref_quantize(np.asarray(x))
    checks.append(("quantizer_codes",
                   float(jnp.max(jnp.abs(qx.astype(jnp.int32)
                                         - jnp.asarray(qr, jnp.int32)))), 1.0))
    deq_err = float(jnp.max(jnp.abs(dequantize_int8(qx, s) - x)))
    # |err| <= scale/2 per row; scales are max|row|/127
    bound = float(jnp.max(jnp.abs(x))) / 127.0
    checks.append(("quantizer_roundtrip", deq_err, bound * 1.01))

    bad = [(n, e, t) for n, e, t in checks if not (e <= t and np.isfinite(e))]
    if bad:
        raise AssertionError(f"kernel selfcheck FAILED: {bad}")


_T0 = time.time()

#: bench-wide wall budget: once exceeded, remaining variants SKIP (the
#: except path records it) so the driver always gets the complete JSON
#: line — a cold compile cache costs ~10 min for everything; the budget
#: bounds the emit at ~8 (warm runs finish everything in ~3.5).
_BUDGET_S = float(os.environ.get("DS_BENCH_BUDGET_S", "780"))


class _BudgetExceeded(RuntimeError):
    pass


def _budget_check() -> None:
    spent = time.time() - _T0
    if spent > _BUDGET_S:
        raise _BudgetExceeded(
            f"skipped: bench budget exceeded ({spent:.0f}s > {_BUDGET_S:.0f}s"
            f" — cold compile cache; warm reruns cover this variant)")


def _mark(name: str) -> None:
    """Section progress to stderr (driver logs) — finding the slow stage
    of a 10-minute bench without rerunning it piecewise."""
    print(f"[bench +{time.time() - _T0:7.1f}s] {name}", file=sys.stderr,
          flush=True)



def serve_v2_throughput(model, prompts, max_new: int, *,
                        cache_blocks: int = 512, max_seq_len: int = 1024,
                        decode_burst: int = 32) -> float:
    """Shared v2 serving measurement: build the ragged engine, warm up
    BOTH compiled programs (prefill batch + the full decode burst — an
    unwarmed burst would compile inside the measured run), then time one
    ragged generate."""
    from deepspeed_tpu.inference.v2 import KVCacheConfig, build_engine_v2
    from deepspeed_tpu.parallel import MeshLayout
    from deepspeed_tpu.utils import groups

    groups.reset_mesh()
    groups.initialize_mesh(MeshLayout.infer(1, dp=1))
    params = model.init_params(jax.random.PRNGKey(0))
    eng = build_engine_v2(
        model, params,
        cache_config=KVCacheConfig(num_blocks=cache_blocks, block_size=16,
                                   max_seq_len=max_seq_len),
        max_batch_slots=8, prefill_chunk=128, prefill_batch=4,
        decode_burst=decode_burst)
    # warm EVERY program the timed run will hit: both decode shapes AND
    # every prefill page-bucket the prompt mix reaches (bucketed prefill
    # compiles per power-of-two depth — a mid-run compile would land in
    # the measured window)
    eng.generate(prompts, max_new_tokens=max_new)
    eng.generate(prompts, max_new_tokens=max_new)
    tps = eng.last_throughput
    del eng, params
    free_hbm()
    return tps


def _bench_llama8b_infinity(batch: int = 2, seq: int = 2048) -> dict:
    """Full-depth Llama-3-8B ZeRO-Infinity measurement (see call site)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import LlamaConfig, LlamaModel
    from deepspeed_tpu.ops.op_builder import CPUAdamBuilder

    if not CPUAdamBuilder.is_compatible():
        raise RuntimeError("no g++ toolchain for the fused C++ Adam")
    L = 32
    per_layer = (4096 * 4096 * 2 + 2 * 4096 * 1024 + 3 * 4096 * 14336
                 + 2 * 4096)
    with open("/proc/meminfo") as f:
        avail = {ln.split(":")[0]: int(ln.split()[1])
                 for ln in f}["MemAvailable"] * 1024
    # planes 14 B/param + fp16 source 2 B/param + 8G slack
    while L > 4 and avail < L * per_layer * 16 + 8e9:
        L -= 4  # degrade on small-RAM hosts; reported in the result
    cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                      intermediate_size=14336, num_layers=L,
                      num_heads=32, num_kv_heads=8, max_seq_len=seq,
                      rope_theta=500000.0, dtype=jnp.bfloat16,
                      attn_impl="flash", remat=True, loss_tiles=8,
                      tie_embeddings=False)
    model = LlamaModel(cfg)  # single-chip streaming (mesh=None)

    # host-side param synthesis: throughput doesn't depend on values (the
    # MXU runs dense matmuls regardless), so the trunk is fp32 zeros —
    # calloc'd virtual pages, no RAM touched until the planes read them,
    # and no fp16 casts (numpy fp16 paths run ~170 MB/s, which would put
    # minutes into seeding an 8B tree).  jax init of an 8B tree would OOM
    # the 16G chip and crawl on host PRNG.
    shapes = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    def synth(sd):
        n = int(np.prod(sd.shape))
        if n <= (1 << 26):  # resident leaves get real values (loss sanity)
            return (rng.random(n, dtype=np.float32) * 0.02).reshape(sd.shape)
        return np.zeros(sd.shape, np.float32)

    params = jax.tree.map(synth, shapes)
    _mark("8b: params synthesized")
    ds = {"train_micro_batch_size_per_gpu": batch,
          "gradient_accumulation_steps": 1,
          "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
          "zero_optimization": {"stage": 3,
                                "offload_param": {"device": "cpu"}},
          "bf16": {"enabled": True}, "steps_per_print": 0}
    # Plane seeding bypass: copying 43 GB of zeros through numpy's
    # single-core bf16 cast costs ~8 minutes and changes NOTHING the
    # bench measures (the trunk is zeros either way; planes are
    # zero-initialized).  The planes stay allocated at full depth and
    # every h2d/d2h moves real bytes; only the redundant zero-copy is
    # skipped.  The REAL fill path is exercised by test_infinity.py.
    from deepspeed_tpu.runtime.swap_tensor import (
        partitioned_param_swapper as _pps)

    _orig_fill = _pps.PartitionedParamSwapper._fill_planes
    _pps.PartitionedParamSwapper._fill_planes = \
        lambda self, planes, tree, zero_moments=True: None
    try:
        eng, *_ = deepspeed_tpu.initialize(model=model,
                                           model_parameters=params,
                                           config=ds)
    finally:
        _pps.PartitionedParamSwapper._fill_planes = _orig_fill
    _mark("8b: engine built (planes allocated, resident placed)")
    del params
    inf = eng.infinity
    sw = inf.swapper
    n_params = inf.total_param_count()

    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, seq)))
    b = {"input_ids": ids}

    _probe_cache: dict = {}

    def block(t):
        """REAL device fence: on the tunneled axon platform
        ``block_until_ready`` returns immediately, so the only reliable
        barrier is fetching a (tiny) dependent scalar — ordered dispatch
        makes that fence every enqueued op before it."""
        leaves = [l for l in jax.tree.leaves(t) if hasattr(l, "ravel")]
        key = tuple((l.shape, str(l.dtype)) for l in leaves)
        if key not in _probe_cache:
            _probe_cache[key] = jax.jit(lambda ls: sum(
                jnp.sum(l.ravel()[:1].astype(jnp.float32)) for l in ls))
        float(_probe_cache[key](leaves))
        return t

    times: dict = {}
    # ---- embed + warmup layer 0 (compiles layer_fwd) --------------------
    block(inf._fn("embed")(inf.resident, ids))  # compile + resident cast
    t0 = time.perf_counter()
    x = block(inf._fn("embed")(inf.resident, ids))
    times["embed_s"] = time.perf_counter() - t0
    _mark("8b: embed done")
    acts = {}
    t0 = time.perf_counter()
    lp = block(sw.get_device(0))
    acts[0] = x
    x, _aux = block(inf._fn("layer_fwd")(lp, x))
    sw.release(0)
    warm_fwd = time.perf_counter() - t0  # includes h2d AND compile
    _mark(f"8b: fwd warmup {warm_fwd:.1f}s")

    # ---- measured fwd layers (steady-state, no compile) -----------------
    k_fwd = 2
    h2d, fwd = [], []
    for i in range(1, 1 + k_fwd):
        t0 = time.perf_counter()
        lp = block(sw.get_device(i))
        h2d.append(time.perf_counter() - t0)
        acts[i] = x
        t0 = time.perf_counter()
        x, _aux = block(inf._fn("layer_fwd")(lp, x))
        fwd.append(time.perf_counter() - t0)
        sw.release(i)
    times["h2d_per_layer_s"] = sorted(h2d)[len(h2d) // 2]
    times["fwd_per_layer_s"] = sorted(fwd)[len(fwd) // 2]

    # ---- head loss + grad (resident) ------------------------------------
    block(inf._fn("head_grad")(inf.resident, x, b)[0])  # compile
    t0 = time.perf_counter()
    loss, (g_res, dx) = inf._fn("head_grad")(inf.resident, x, b)
    block(loss)
    times["head_s"] = time.perf_counter() - t0
    _mark("8b: head done")
    if not np.isfinite(float(loss)):
        raise RuntimeError(f"non-finite loss {float(loss)}")

    # ---- bwd: warmup (compile) + one measured layer ---------------------
    i = 1 + k_fwd - 1  # deepest measured layer, acts stashed
    t0 = time.perf_counter()
    lp = block(sw.get_device(i))
    dx2, dlp = inf._fn("layer_bwd")(lp, acts[i], dx)
    block(dx2)
    sw.release(i)
    warm_bwd = time.perf_counter() - t0
    _mark(f"8b: bwd warmup {warm_bwd:.1f}s")
    bwd_times = []
    dprev = dx2
    for j in range(i - 1, max(i - 3, -1), -1):
        lp = block(sw.get_device(j))  # h2d timed in fwd
        t0 = time.perf_counter()
        dprev, dlp = inf._fn("layer_bwd")(lp, acts[j], dprev)
        block(dprev)
        bwd_times.append(time.perf_counter() - t0)
        sw.release(j)
    times["bwd_per_layer_s"] = sorted(bwd_times)[len(bwd_times) // 2]
    # grad d2h timed as an explicit host fetch, then the fused C++ Adam
    # gets the ALREADY-FETCHED numpy tree so its timing is host-only
    # (np.asarray on the device tree again would re-pay the link)
    t0 = time.perf_counter()
    g_host = jax.tree.map(np.asarray, dlp)
    times["grad_d2h_per_layer_s"] = time.perf_counter() - t0
    sw.begin_step()
    sw.step_layer(i, g_host, lr=1e-4)  # first touch faults in m/v planes
    t0 = time.perf_counter()
    sw.step_layer(i, g_host, lr=1e-4)  # steady-state host Adam
    times["host_adam_per_layer_s"] = time.perf_counter() - t0
    times["d2h_adam_per_layer_s"] = (times["grad_d2h_per_layer_s"]
                                     + times["host_adam_per_layer_s"])

    # ---- pipelined update: the REAL overlapped bwd phase ----------------
    # (reference pipelined_optimizer_swapper role, VERDICT r4 item 2):
    # replay two full bwd+update layers through the production path —
    # h2d, vjp, then step_layer_async handing d2h+C++ Adam to the worker
    # while the next layer's h2d/vjp proceed.  The measured wall clock IS
    # the per-layer cost of the pipelined backward phase; the serial
    # composition of the same phases is the number it beats.
    k_pipe = 2
    assert sw._pipe is not None, "pipelined swapper must be the default"
    sw.drain_updates()
    t0 = time.perf_counter()
    dp_ = dx2
    for j in range(i, i - k_pipe, -1):
        lp_j = sw.get_device(j)
        dp_, dlp_j = inf._fn("layer_bwd")(lp_j, acts[j], dp_)
        sw.step_layer_async(j, dlp_j, lr=1e-4)
        sw.release(j)
    block(dp_)
    sw.drain_updates()
    pipe_wall = time.perf_counter() - t0
    serial_sum = k_pipe * (times["h2d_per_layer_s"]
                           + times["bwd_per_layer_s"]
                           + times["d2h_adam_per_layer_s"])
    times["pipelined_bwd_layer_s"] = pipe_wall / k_pipe
    times["serial_bwd_layer_s"] = serial_sum / k_pipe
    overlap_win = serial_sum / pipe_wall if pipe_wall > 0 else 1.0

    # ---- compose the full step ------------------------------------------
    # backward phase composes at the MEASURED pipelined per-layer cost
    # (d2h + host Adam overlap h2d + vjp of the next layer); forward is
    # unchanged (no update work to hide there)
    proj = (times["embed_s"] + times["head_s"]
            + L * (times["h2d_per_layer_s"] + times["fwd_per_layer_s"])
            + L * times["pipelined_bwd_layer_s"])
    result = {"layers": L, "params": int(n_params), "batch": batch,
              "seq": seq, "phases": {k: round(v, 3)
                                     for k, v in times.items()},
              "warmup_fwd_s": round(warm_fwd, 2),
              "warmup_bwd_s": round(warm_bwd, 2),
              "optimizer_overlap": {
                  "pipelined_bwd_layer_s": round(pipe_wall / k_pipe, 3),
                  "serial_bwd_layer_s": round(serial_sum / k_pipe, 3),
                  "overlap_win": round(overlap_win, 3),
                  "host_cores": os.cpu_count()}}
    peak = peak_flops_per_chip()
    remaining = _BUDGET_S - (time.time() - _T0)
    if proj < min(remaining - 30, 180):
        # the link can carry a real step — run the engine's actual
        # train_step end to end and use the measured number
        _sync(eng.train_step(b))  # warm (fills any remaining compiles)
        t0 = time.perf_counter()
        _sync(eng.train_step(b))
        step_s = time.perf_counter() - t0
        result["projected"] = False
    else:
        step_s = proj
        result["projected"] = True
        result["projection_note"] = (
            "host<->device link cannot carry a full streamed step inside "
            "the bench budget; step_s composes per-layer phases measured "
            "on the real chip at full depth (streaming is layer-linear; "
            "each phase includes one ~0.1s fence round-trip, so the "
            "composition is conservative).  The backward phase uses the "
            "MEASURED pipelined per-layer wall clock (worker-thread d2h+"
            "Adam overlapping the next layer's h2d+vjp), not the serial "
            "phase sum — see optimizer_overlap")
    tps = batch * seq / step_s
    result["step_s"] = round(step_s, 2)
    result["tokens_per_sec"] = round(tps, 3)
    result["mfu"] = round(6.0 * n_params * tps / peak, 5)
    # compute-only view: what the same step costs with the link excluded —
    # the upper bound a locally-attached host (PCIe/DMA) approaches.
    # With the pipelined optimizer the host Adam overlaps the device
    # backward, so the bwd phase costs max(vjp, adam) per layer, not the
    # sum; this box has os.cpu_count() core(s) for the OpenMP Adam, while
    # a TPU-VM host has ~100+ — host_adam/cores drops below the vjp time
    # there and the step becomes fwd+bwd-bound (the reference's
    # pipelined_optimizer_swapper steady state)
    compute_s = (times["embed_s"] + times["head_s"]
                 + L * (times["fwd_per_layer_s"]
                        + max(times["bwd_per_layer_s"],
                              times["host_adam_per_layer_s"])))
    result["compute_only_tokens_per_sec"] = round(batch * seq / compute_s, 1)
    result["compute_only_mfu"] = round(
        6.0 * n_params * (batch * seq / compute_s) / peak, 4)
    # the same law with the Adam spread over a TPU-VM-class host (96
    # cores): what THIS code does on real hardware, stated as arithmetic
    adam96 = times["host_adam_per_layer_s"] * os.cpu_count() / 96.0
    c96 = (times["embed_s"] + times["head_s"]
           + L * (times["fwd_per_layer_s"]
                  + max(times["bwd_per_layer_s"], adam96)))
    result["compute_only_96core_tokens_per_sec"] = round(
        batch * seq / c96, 1)
    result["compute_only_96core_mfu"] = round(
        6.0 * n_params * (batch * seq / c96) / peak, 4)
    del eng, inf, sw, acts
    free_hbm()
    return result


def _bench_offload_overlap_synthetic() -> dict:
    """Overlap proof where the LINK IS NOT the bottleneck (VERDICT r4
    item 7): device compute (real TPU matmul chains, async dispatch) vs
    the host fused C++ Adam (production ``_OptPipeline`` worker), with
    grads already host-resident so zero tunnel bytes move.  Serial = the
    two phases back to back (device fenced, then L sync updates);
    pipelined = the production ``step_layer_async`` interleaving — the
    wall clock approaches max(Σdev, Σadam) instead of the sum.  Sized so
    T_dev ≈ T_adam per layer (the regime where overlap matters most)."""
    from deepspeed_tpu.ops.op_builder import CPUAdamBuilder
    from deepspeed_tpu.runtime.swap_tensor.partitioned_param_swapper import (
        PartitionedParamSwapper)

    if not CPUAdamBuilder.is_compatible():
        raise RuntimeError("no g++ toolchain for the fused C++ Adam")
    L, n = 10, 6_000_000
    mk = lambda pipe: PartitionedParamSwapper(
        [{"w": np.zeros((n,), np.float32)} for _ in range(L)],
        wire_dtype=jnp.bfloat16, adam_hparams={"lr": 1e-3}, pipeline=pipe)
    g_host = {"w": (np.random.RandomState(0).rand(n) * 1e-3
                    ).astype(np.float32)}
    x = jnp.ones((1024, 1024), jnp.bfloat16)

    def fence(y):
        float(jnp.sum(y.ravel()[:1].astype(jnp.float32)))

    # calibrate: one layer's sync host Adam, then a device chain of
    # similar cost (K matmuls; 1024^3 MACs ≈ 11us each at peak — scale up)
    sw_s = mk(False)
    sw_s.begin_step()
    sw_s.step_layer(0, g_host)  # warm (faults planes in)
    t0 = time.perf_counter()
    sw_s.step_layer(0, g_host)
    t_adam = time.perf_counter() - t0

    def devchain(x, K):
        def body(c, _):
            return (c @ c) * jnp.bfloat16(1e-3) + c, None
        return jax.lax.scan(body, x, None, length=K)[0]

    # rtt-free calibration: difference two chain lengths (a single fenced
    # call is dominated by the ~100ms tunnel round trip, which would size
    # the chain to ~zero real compute)
    def timed(K, reps=3):
        f = jax.jit(functools.partial(devchain, K=K))
        fence(f(x))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fence(f(x))
            ts.append(time.perf_counter() - t0)
        return min(ts)
    per_mm = max((timed(512) - timed(64)) / 448, 2e-6)
    K = max(32, int(t_adam / per_mm))
    dc = jax.jit(functools.partial(devchain, K=K))
    fence(dc(x))
    t_dev = K * per_mm

    # serial: all device work (one fence), then L sync updates
    t0 = time.perf_counter()
    y = x
    for _ in range(L):
        y = dc(y)
    fence(y)
    for i in range(L):
        sw_s.step_layer(i, g_host)
    serial = time.perf_counter() - t0

    # pipelined: production async path — worker Adam behind device chains
    sw_p = mk(True)
    sw_p.begin_step()
    sw_p.step_layer_async(0, g_host)  # warm worker path
    sw_p.drain_updates()
    t0 = time.perf_counter()
    y = x
    for i in range(L):
        y = dc(y)
        sw_p.step_layer_async(i, g_host)
    fence(y)
    sw_p.drain_updates()
    piped = time.perf_counter() - t0
    win = serial / piped if piped > 0 else 1.0
    del sw_s, sw_p
    return {"layers": L, "plane_params": n,
            "t_adam_layer_s": round(t_adam, 4),
            "t_dev_layer_s": round(t_dev, 4),
            "serial_s": round(serial, 4), "pipelined_s": round(piped, 4),
            "overlap_win": round(win, 3)}


def _bench_infinity_sp_miniature() -> dict:
    """Ladder config 5's COMPOSITION, miniature, on the real chip: Llama
    trunk + Ulysses SP machinery (mesh-routed attention, SP dataloader
    adapter, sequence-tiled loss) + ZeRO-Infinity layer streaming, all in
    ONE run (VERDICT r4 item 1).

    One physical chip means the seq axis is size 1 — the all-to-all is a
    no-op here (``sp1_no_op: true`` in the result says so) — but every
    composed code path executes end-to-end on TPU: the streamed per-layer
    programs are the SAME jits the fake-8 dp2×sp2(×tp2) equality tests
    (tests/unit/runtime/test_infinity_sp.py) and the ``infinity_sp``
    dryrun layout prove correct at sp>1."""
    import deepspeed_tpu
    from deepspeed_tpu.models import LlamaConfig, LlamaModel
    from deepspeed_tpu.ops.op_builder import CPUAdamBuilder
    from deepspeed_tpu.parallel import MeshLayout
    from deepspeed_tpu.runtime.sequence_parallel.ulysses_sp import (
        UlyssesSPDataLoaderAdapter)
    from deepspeed_tpu.utils import groups

    if not CPUAdamBuilder.is_compatible():
        raise RuntimeError("no g++ toolchain for the fused C++ Adam")
    groups.reset_mesh()
    mesh = groups.initialize_mesh(MeshLayout.infer(1, sp=1))
    batch, seq = 4, 1024
    cfg = LlamaConfig(vocab_size=2048, hidden_size=256,
                      intermediate_size=688, num_layers=3, num_heads=8,
                      num_kv_heads=4, max_seq_len=seq, dtype=jnp.bfloat16,
                      attn_impl="flash", loss_tiles=4)
    model = LlamaModel(cfg, mesh=mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    ds = {"train_micro_batch_size_per_gpu": batch,
          "gradient_accumulation_steps": 1,
          "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
          "zero_optimization": {"stage": 3,
                                "offload_param": {"device": "cpu"}},
          "bf16": {"enabled": True}, "steps_per_print": 0}
    eng, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                       config=ds, mesh=mesh)
    assert eng.infinity is not None

    ids = np.random.RandomState(0).randint(0, cfg.vocab_size,
                                           size=(batch, seq))
    loader = UlyssesSPDataLoaderAdapter(
        [{"input_ids": jnp.asarray(ids)}] * 4)
    batches = list(loader)
    eng.train_step(batches[0])  # warm every per-layer program
    t0 = time.perf_counter()
    steps = 2
    for k in range(steps):
        m = eng.train_step(batches[(k + 1) % len(batches)])
    loss = float(m["loss"])  # fences the streamed tail
    dt = (time.perf_counter() - t0) / steps
    assert np.isfinite(loss)
    n_params = eng.infinity.total_param_count()
    del eng, params, batches, loader
    free_hbm()
    return {"tokens_per_sec": round(batch * seq / dt, 1),
            "step_s": round(dt, 3), "loss": round(loss, 4),
            "params": n_params, "layers": cfg.num_layers,
            "sp1_no_op": True, "loss_tiles": cfg.loss_tiles}


def _probe_devices_or_die(timeout_s: float = 180.0):
    """Fail FAST with an honest JSON line if the chip is unreachable.

    The tunneled axon backend hangs ``jax.devices()`` indefinitely when
    the tunnel is down (observed twice on 2026-07-31) — a hung bench
    gives the driver NOTHING, while an error line at least records why.
    The probe runs in a daemon thread; on timeout the main thread emits
    the one-line JSON contract with an ``error`` field and exits."""
    import threading

    box: dict = {}

    def probe():
        try:
            box["devices"] = jax.devices()
        except Exception as e:  # surfaced below
            box["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in box:
        return box["devices"]
    msg = box.get("error", f"jax.devices() unresponsive after "
                           f"{timeout_s:.0f}s (TPU tunnel down?)")
    try:
        # latch the verdict so nothing else in teardown walks into the
        # same hang (telemetry/memory device-unresponsive gate)
        from deepspeed_tpu.telemetry.memory import mark_device_unresponsive

        mark_device_unresponsive(msg)
    except Exception:
        pass  # the JSON line below must go out regardless
    # "environment_failure" marks a NO-DATA artifact (the r05 dead
    # tunnel): `telemetry perf check` SKIPS it with this reason instead
    # of silently passing or erroring on an empty run
    if "--selfcheck" in sys.argv:
        # keep the selfcheck output contract
        print(json.dumps({"kernels_verified": False, "error": msg,
                          "environment_failure": True}))
    else:
        print(json.dumps({"metric": "llama_110m_train_tokens_per_sec",
                          "value": 0.0, "unit": "tokens/sec/chip",
                          "vs_baseline": 0.0, "error": msg,
                          "environment_failure": True}))
    sys.stdout.flush()
    try:
        # os._exit skips atexit: clear the dirty-run sentinel ourselves or
        # the NEXT run wipes the warm compile cache for a run that never
        # compiled anything
        _mark_cache_clean()
    except Exception:
        pass
    os._exit(3)


def _emit_crash_line(e: BaseException, reason: str = "bench unhandled "
                     "exception") -> str:
    """Crash path of the one-JSON-line contract (ISSUE 2): dump a flight-
    recorder debug bundle and record its path in the BENCH artifact so a
    dead bench leaves the operator a post-mortem, not just an exit code.
    Returns the bundle path ("" if even the dump failed)."""
    import traceback

    from deepspeed_tpu.telemetry import get_flight_recorder

    path = ""
    try:
        path = get_flight_recorder().dump(
            f"{reason}: {type(e).__name__}: {e}",
            extra={"traceback": traceback.format_exc()})
    except Exception:
        pass  # the JSON line below must go out regardless
    print(json.dumps({
        "metric": "llama_110m_train_tokens_per_sec",
        "value": 0.0, "unit": "tokens/sec/chip", "vs_baseline": 0.0,
        "error": f"{type(e).__name__}: {e}"[:300],
        "debug_bundle": path,
    }))
    sys.stdout.flush()
    return path


def main() -> None:
    try:
        _main()
    except SystemExit:
        raise
    except KeyboardInterrupt:
        raise
    except BaseException as e:
        _emit_crash_line(e)
        sys.exit(4)


def _main() -> None:
    from deepspeed_tpu.models import LlamaConfig

    _setup_compile_cache()

    on_tpu = _probe_devices_or_die()[0].platform == "tpu"
    extras: dict = {}

    if "--selfcheck" in sys.argv:
        selfcheck()
        print(json.dumps({"kernels_verified": True}))
        return

    if not on_tpu:  # CPU fallback so the bench always emits a line
        from deepspeed_tpu.tuning import tuned_config_source

        cfg = LlamaConfig.tiny(num_layers=2)
        engine = build_engine(cfg, 4, bf16=False)
        tps = measure(engine, 4, 128, cfg.vocab_size, steps=3, segments=1)
        print(json.dumps({
            "metric": "llama_tiny_cpu_train_tokens_per_sec",
            "value": round(tps, 1), "unit": "tokens/sec/chip",
            "vs_baseline": 1.0,
            # bench engines never auto-apply (config pinned above), so
            # this is "none" here — the artifact still always answers
            # "was this run tuned, and from which store entry"
            "tuned_config_source": tuned_config_source(),
            **_perf_extras(engine)}))
        return

    _mark("selfcheck")
    # -- kernel numerics gate: runs BEFORE the headline -------------------
    try:
        selfcheck()
        extras["kernels_verified"] = True
    except AssertionError as e:
        extras["kernels_verified"] = False
        extras["kernels_error"] = str(e)[:300]

    _mark("headline")
    # -- headline: identical config to round 1 (comparable across rounds) --
    cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                      intermediate_size=2048, num_layers=12,
                      num_heads=12, num_kv_heads=12, max_seq_len=2048,
                      dtype=jnp.bfloat16, attn_impl="flash")
    batch, seq = 8, 2048
    engine = build_engine(cfg, batch)
    flops = step_flops(engine, batch, seq, cfg.vocab_size, cfg)
    engine.flops_per_step = flops  # StepRecords then carry TFLOPS/MFU too
    tps = measure(engine, batch, seq, cfg.vocab_size, steps=20)
    peak = peak_flops_per_chip()
    mfu = (flops * tps / (batch * seq)) / peak
    extras["mfu"] = round(mfu, 4)
    extras["device_kind"] = jax.devices()[0].device_kind
    extras.update(_perf_extras(engine))
    del engine
    free_hbm()  # engine sits in a jit-closure reference cycle

    _mark("tuned")
    # -- tuned: the best-known-config run (tuning/ — ISSUE 9) --------------
    # The headline above stays the round-1 config for cross-round
    # comparability; THIS run is what the store says the same model should
    # do on this chip — the seeded v5-lite entry (or whatever a search
    # promoted since).  ``tuned_mfu`` is a gated perf metric, so a bad
    # promotion or a stale seed shows up in `telemetry perf check`
    # exactly like a code regression, never as a hand-asserted number.
    try:
        _budget_check()
        import dataclasses

        from deepspeed_tpu.models import LlamaModel
        from deepspeed_tpu.parallel import MeshLayout
        from deepspeed_tpu.tuning import BestConfigStore, resolve_store_path
        from deepspeed_tpu.tuning.store import (current_device_kind,
                                                mesh_signature,
                                                model_fingerprint)
        from deepspeed_tpu.utils import groups

        fp = model_fingerprint(jax.eval_shape(
            LlamaModel(cfg).init_params, jax.random.PRNGKey(0)))
        tmesh = groups.initialize_mesh(MeshLayout.infer(1, dp=1))
        store = BestConfigStore(resolve_store_path())
        hit = store.lookup(fp, mesh_signature(tmesh), current_device_kind(),
                           promoted_only=True)
        if hit is None:
            extras["tuned_config_source"] = "none"
        else:
            key, entry = hit
            ov = entry.get("overrides", {})
            known = {f.name for f in dataclasses.fields(cfg)}
            tcfg = dataclasses.replace(
                cfg, **{k: v for k, v in entry.get(
                    "model_overrides", {}).items() if k in known})
            tmb = int(ov.get("train_micro_batch_size_per_gpu", batch))
            tgas = int(ov.get("gradient_accumulation_steps", 1))
            tstage = int(ov.get("zero_optimization.stage", 0))
            toff = str(ov.get("zero_optimization.offload_optimizer.device",
                              "none")) == "cpu"
            teng = build_engine(tcfg, tmb, zero_stage=tstage, offload=toff,
                                gas=tgas)
            # the engine steps on the GLOBAL batch (gas microbatches of
            # tmb rows) — feeding only tmb rows would silently measure
            # micro-batch tmb/gas, a config the store never claimed
            tglobal = tmb * tgas
            tflops = step_flops(teng, tglobal, seq, tcfg.vocab_size, tcfg)
            teng.flops_per_step = tflops
            ttps = measure(teng, tglobal, seq, tcfg.vocab_size, steps=10)
            tmfu = (tflops * ttps / (tglobal * seq)) / peak
            extras["tuned_config_source"] = f"{store.source_of(key)}::{key}"
            extras["tuned_mfu"] = round(tmfu, 4)
            extras["tuned_tokens_per_sec"] = round(ttps, 1)
            extras["tuned_vs_default_mfu_delta"] = round(tmfu - mfu, 4)
            if entry.get("stale_jax"):
                extras["tuned_stale_jax"] = entry["stale_jax"]
            del teng
            free_hbm()
    except Exception as e:  # the tuned run must never kill the headline line
        free_hbm()
        extras["tuned_config_source"] = "error: " + str(e)[:160]

    _mark("shape_tuned")
    # -- variant: max-fitting ZeRO-3 + remat, sized from live HBM ----------
    # shape choice is MFU-tuned: wide-short beats narrow-deep on the MXU
    # (measured on v5e: h2048/L10 = 48% MFU vs h1024/L24 = 31% at equal
    # fit) — the BASELINE.md north star is MFU, so the max-fitting config
    # maximizes it, not parameter count
    try:
        _budget_check()
        hbm = hbm_bytes()
        if hbm >= 80e9:      # ~3.5B for 95G chips (56G Adam states + acts)
            big = LlamaConfig(vocab_size=32000, hidden_size=4096,
                              intermediate_size=11008, num_layers=16,
                              num_heads=32, num_kv_heads=32, max_seq_len=2048,
                              dtype=jnp.bfloat16, attn_impl="flash",
                              remat=True)
            bbatch = 4
        elif hbm >= 30e9:    # ~1.2B for 32G chips (~19G states)
            big = LlamaConfig(vocab_size=32000, hidden_size=2048,
                              intermediate_size=5504, num_layers=24,
                              num_heads=16, num_kv_heads=16, max_seq_len=2048,
                              dtype=jnp.bfloat16, attn_impl="flash",
                              remat=True)
            bbatch = 4
        else:                # 637M wide-short fits 16G chips with states+acts
            big = LlamaConfig(vocab_size=32000, hidden_size=2048,
                              intermediate_size=5504, num_layers=10,
                              num_heads=16, num_kv_heads=16, max_seq_len=2048,
                              dtype=jnp.bfloat16, attn_impl="flash",
                              remat=True)
            bbatch = 4
        eng = build_engine(big, bbatch, zero_stage=3)
        btps = measure(eng, bbatch, seq, big.vocab_size, steps=10)
        bflops = step_flops(eng, bbatch, seq, big.vocab_size, big)
        # "shape_tuned": this config's aspect ratio was picked to maximize
        # MFU (VERDICT r2 weak #2) — the driver-ladder configs below are
        # the representative numbers; this one is the chip's ceiling
        extras["variants"] = {
            "zero3_remat_shape_tuned_tokens_per_sec": round(btps, 1),
            "zero3_remat_shape_tuned_mfu": round(
                (bflops * btps / (bbatch * seq)) / peak, 4),
        }
        del eng
        free_hbm()
    except Exception as e:  # a variant must never kill the headline line
        free_hbm()
        extras["variants"] = {"zero3_remat_shape_tuned_error": str(e)[:200]}

    _mark("bert_zero2")
    # -- driver ladder (BASELINE.md): BERT-large ZeRO-2 ---------------------
    try:
        _budget_check()
        from deepspeed_tpu.models.bert import BertConfig, BertModel

        bcfg = BertConfig.bert_large()  # true BERT-large, 335M
        bb, bs = 32, 512
        rng0 = np.random.RandomState(0)
        ids = jnp.asarray(rng0.randint(0, bcfg.vocab_size, size=(bb, bs)))
        labels = np.full((bb, bs), -100)
        mask_pos = rng0.rand(bb, bs) < 0.15  # MLM-style 15% masking
        labels[mask_pos] = np.asarray(ids)[mask_pos]
        bdata = {"input_ids": ids, "labels": jnp.asarray(labels)}
        eng = build_engine(bcfg, bb, zero_stage=2, model_cls=BertModel)
        btps = measure(eng, bb, bs, bcfg.vocab_size, steps=10,
                       budget_s=60.0, data=bdata)
        bflp = step_flops(eng, bb, bs, bcfg.vocab_size, bcfg)
        extras["variants"]["bert_large_zero2_tokens_per_sec"] = round(btps, 1)
        extras["variants"]["bert_zero2_mfu"] = round(
            (bflp * btps / (bb * bs)) / peak, 4)
        del eng, bdata, ids
        free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})["bert_zero2_error"] = str(e)[:200]

    _mark("mixtral_v2")
    # -- driver ladder: Mixtral-shaped MoE serving on inference v2 ----------
    try:
        _budget_check()
        from deepspeed_tpu.models import MixtralConfig, MixtralModel

        # Mixtral aspect ratios (8 experts, top-2, GQA) scaled to the chip
        mcfg = MixtralConfig(vocab_size=32000, hidden_size=1024,
                             intermediate_size=3584, num_layers=8,
                             num_heads=16, num_kv_heads=8, max_seq_len=2048,
                             num_experts=8, top_k=2, dtype=jnp.bfloat16)
        prng = np.random.RandomState(2)
        mprompts = [prng.randint(1, mcfg.vocab_size, size=n).tolist()
                    for n in (40, 100, 200, 64, 128, 80, 300, 50)]
        extras["variants"]["mixtral_proxy_v2_tokens_per_sec"] = round(
            serve_v2_throughput(MixtralModel(mcfg), mprompts, 97), 1)
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})[
            "mixtral_v2_error"] = str(e)[:200]

    _mark("moe_ep")
    # -- variant: expert-parallel training plane (ISSUE 19) ----------------
    # The Mixtral proxy TRAINED through the config-driven ep path (expert
    # mesh axis > 1 when the chip count allows; ep=1 reference alongside)
    # plus the index-form-vs-dense dispatch micro-bench.  Three figures go
    # top-level into the gated PERF_METRICS: moe_ep_tokens_per_sec,
    # moe_dispatch_speedup, moe_drop_rate.
    try:
        _budget_check()
        from deepspeed_tpu.moe.bench import run_moe_ep_bench

        mo = run_moe_ep_bench(dry_run=False, steps=4, warmup=2)
        extras.setdefault("variants", {})["moe_ep"] = mo
        for key in ("moe_ep_tokens_per_sec", "moe_dispatch_speedup",
                    "moe_drop_rate"):
            extras[key] = mo[key]
        free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})["moe_ep_error"] = str(e)[:200]

    _mark("llama_v2")
    # -- variant: inference v2 ragged serving throughput -------------------
    # NOTE: over the tunnel each dispatch pays ~100 ms RTT — bursts
    # amortize it; tracked round-over-round for relative movement.
    try:
        _budget_check()
        from deepspeed_tpu.models import LlamaModel

        prng = np.random.RandomState(1)
        prompts = [prng.randint(1, cfg.vocab_size, size=n).tolist()
                   for n in (40, 100, 200, 350, 64, 128, 500, 80)]
        extras.setdefault("variants", {})[
            "inference_v2_ragged_tokens_per_sec"] = round(
                serve_v2_throughput(LlamaModel(cfg), prompts, 97), 1)
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})[
            "inference_v2_error"] = str(e)[:200]

    _mark("serving")
    # -- variant: serving plane — SLO front-end + prefix cache over a real
    # engine replica.  Mixed-class workload with a shared 256-token header:
    # interactive p99 TTFT, prefix hit rate, and per-class tok/s land in
    # the gated baseline (`telemetry perf check` fails on regression).
    fe = None
    try:
        _budget_check()
        from deepspeed_tpu.inference.v2 import KVCacheConfig
        from deepspeed_tpu.models import LlamaModel
        from deepspeed_tpu.serving import (ServingParams,
                                           build_serving_frontend)
        from deepspeed_tpu.serving.cli import run_workload

        svcfg = LlamaConfig(vocab_size=8192, hidden_size=512,
                            intermediate_size=1408, num_layers=4,
                            num_heads=8, num_kv_heads=8, max_seq_len=1024,
                            dtype=jnp.bfloat16)
        fe = build_serving_frontend(
            LlamaModel(svcfg), replicas=1,
            cache_config=KVCacheConfig(num_blocks=512, block_size=16,
                                       max_seq_len=1024),
            max_batch_slots=8, prefill_chunk=128, prefill_batch=2,
            decode_burst=8,
            serving_params=ServingParams(interactive_reserve_frac=0.1))
        # warm both compiled programs + the prefill buckets OUTSIDE the
        # measured window (mid-run compile would land in the TTFT tail)
        run_workload(fe, time.monotonic, n_interactive=2, n_background=1,
                     header_len=256, interactive_new=8, background_new=16,
                     warm_rounds=2, seed=7)
        sv = run_workload(fe, time.monotonic, n_interactive=8,
                          n_background=4, header_len=256,
                          interactive_new=16, background_new=64, seed=0)
        extras["serving_p99_ttft_ms"] = sv["serving_p99_ttft_ms"]
        extras["prefix_hit_rate"] = sv["prefix_hit_rate"]
        extras["tok_s_interactive"] = sv["tok_s_interactive"]
        extras["tok_s_background"] = sv["tok_s_background"]
        extras.setdefault("variants", {})["serving"] = sv
    except Exception as e:
        extras.setdefault("variants", {})["serving_error"] = str(e)[:200]
    finally:
        if fe is not None:
            # detach the flight-recorder context provider — it holds the
            # front-end (and its engine + KV pool) alive otherwise, on
            # the error path too
            fe.close()
            fe = None
        free_hbm()

    _mark("serving_network")
    # -- variant: NETWORK serving plane — a real HTTP/SSE front door over
    # 2 replica worker PROCESSES (synthetic engines: this measures the
    # serving STACK — sockets, SSE writes, router RPCs, process hops —
    # not model math, so the numbers are stable across devices).
    # Sustained mixed-class QPS with shared tenant headers; p99 TTFT,
    # sustained QPS and the cross-tenant prefix hit rate land in the
    # gated baseline (`telemetry perf check` fails on regression).
    net_door = None
    net_fleet = []
    try:
        _budget_check()
        from deepspeed_tpu.launcher.serving_fleet import (
            launch_worker_fleet, shutdown_fleet)
        from deepspeed_tpu.serving import (FrontDoor, FrontDoorParams,
                                           NetworkFrontend, NetworkParams,
                                           ReplicaEndpoint)
        from deepspeed_tpu.serving.cli import run_network_workload

        net_fleet = launch_worker_fleet(2)
        net_eps = [ReplicaEndpoint(w.id, w.endpoint, role=w.role)
                   for w in net_fleet]
        net_door = FrontDoor(NetworkFrontend(net_eps, net=NetworkParams()),
                             params=FrontDoorParams())
        net_door.start()
        # warm the sockets + tenant headers outside the measured window
        run_network_workload(net_door.host, net_door.port,
                             duration_s=1.0, seed=7)
        nsv = run_network_workload(net_door.host, net_door.port,
                                   duration_s=4.0, seed=0)
        extras["serving_net_p99_ttft_ms"] = nsv["serving_net_p99_ttft_ms"]
        extras["serving_net_qps_sustained"] = \
            nsv["serving_net_qps_sustained"]
        extras["serving_net_prefix_hit_rate"] = \
            nsv["serving_net_prefix_hit_rate"]
        extras.setdefault("variants", {})["serving_network"] = nsv
    except Exception as e:
        extras.setdefault("variants", {})[
            "serving_network_error"] = str(e)[:200]
    finally:
        if net_door is not None:
            net_door.shutdown()
        if net_fleet:
            from deepspeed_tpu.launcher.serving_fleet import shutdown_fleet

            shutdown_fleet(net_fleet)
        free_hbm()

    _mark("block_sparse")
    # -- variant: block-sparse kernel speedup vs dense-masked (S=4096) ----
    try:
        _budget_check()
        from deepspeed_tpu.ops.pallas.block_sparse_attention import (
            block_sparse_attention)
        from deepspeed_tpu.ops.sparse_attention import (
            BigBirdSparsityConfig, sparse_attention)

        rng = np.random.RandomState(0)
        Sb, hb, db = 4096, 8, 64
        qs = jnp.asarray(rng.randn(1, Sb, hb, db)).astype(jnp.bfloat16)
        ks = jnp.asarray(rng.randn(1, Sb, hb, db)).astype(jnp.bfloat16)
        vs = jnp.asarray(rng.randn(1, Sb, hb, db)).astype(jnp.bfloat16)
        bb = BigBirdSparsityConfig(num_heads=hb, block=16,
                                   num_random_blocks=2,
                                   num_sliding_window_blocks=5,
                                   num_global_blocks=1)

        def _bench_attn(f, n=5, reps=10):
            # amortize dispatch: the tunnel's ~5ms per-call floor would
            # otherwise swamp sub-ms kernel differences — chain `reps`
            # applications inside ONE program via lax.scan (output feeds
            # back as v, so steps can't be elided)
            def chained(q, k, v):
                def body(c, _):
                    return (c[0], c[1], f(c[0], c[1], c[2]).astype(
                        c[2].dtype)), None
                (q_, k_, v_), _ = jax.lax.scan(body, (q, k, v), None,
                                               length=reps)
                return v_
            g = jax.jit(chained)
            o = g(qs, ks, vs)
            float(jnp.sum(o.astype(jnp.float32)))  # compile + fence
            t0 = time.perf_counter()
            for _ in range(n):
                o = g(qs, ks, vs)
            float(jnp.sum(o.astype(jnp.float32)))  # real fence (tunnel)
            return (time.perf_counter() - t0) / (n * reps)

        t_dense = _bench_attn(jax.jit(
            lambda q, k, v: sparse_attention(q, k, v, bb, impl="dense")))
        t_sparse = _bench_attn(jax.jit(
            lambda q, k, v: block_sparse_attention(q, k, v, bb)))
        extras.setdefault("variants", {})["block_sparse_speedup_s4096"] = \
            round(t_dense / t_sparse, 2)
        # top-level: gated by telemetry perf check (PERF_METRICS) — with
        # choose_impl's crossover auto-dispatch a sub-1.0 value is a
        # dispatch regression, not a tuning note
        extras["block_sparse_speedup_s4096"] = round(t_dense / t_sparse, 2)
        # long-context comparison — the block-sparse kernels' real value
        # is where dense S² attention stops being viable.  Baseline is
        # dense causal FLASH (what you'd run without sparse support) at
        # S=8192 with a representative 64-cell BigBird; the gather kernel
        # also runs S=32k+ where both dense paths cannot.  (The cb=16
        # config above coarsens near-dense at kernel granularity and
        # auto-dispatch correctly picks the dense path — speedup ~1.0.)
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

        S8 = 8192
        q8 = jnp.asarray(rng.randn(1, S8, hb, db)).astype(jnp.bfloat16)
        k8 = jnp.asarray(rng.randn(1, S8, hb, db)).astype(jnp.bfloat16)
        v8 = jnp.asarray(rng.randn(1, S8, hb, db)).astype(jnp.bfloat16)
        bb64 = BigBirdSparsityConfig(num_heads=hb, block=64,
                                     num_random_blocks=1,
                                     num_sliding_window_blocks=3,
                                     num_global_blocks=1)

        def _bench_attn8(f, n=4, reps=10):
            def chained(q, k, v):
                def body(c, _):
                    return (c[0], c[1], f(c[0], c[1], c[2]).astype(
                        c[2].dtype)), None
                (a, b, v_), _ = jax.lax.scan(body, (q, k, v), None,
                                             length=reps)
                return v_
            g = jax.jit(chained)
            float(jnp.sum(g(q8, k8, v8).astype(jnp.float32)))
            t0 = time.perf_counter()
            for _ in range(n):
                o = g(q8, k8, v8)
            float(jnp.sum(o.astype(jnp.float32)))
            return (time.perf_counter() - t0) / (n * reps)

        t_flash8 = _bench_attn8(
            lambda q, k, v: flash_attention(q, k, v, True))
        t_sparse8 = _bench_attn8(
            lambda q, k, v: block_sparse_attention(q, k, v, bb64,
                                                   causal=True))
        extras["variants"]["block_sparse_vs_flash_s8192"] = \
            round(t_flash8 / t_sparse8, 2)
        del qs, ks, vs, q8, k8, v8
        free_hbm()

        # ---- TRAINING (fwd+bwd) — the Pallas flat-tile backward ------
        # (VERDICT r4 items 3+4): grad-vs-grad against the dense masked
        # vjp at S=4096, and a live-fraction sweep vs dense-causal FLASH
        # at S=8192 (what you'd run without sparse support).  Sweep
        # documents the crossover: wins scale as ~1/(1.4·live).
        def _bench_grad(f, q_, k_, v_, n=3, reps=6):
            # differentiate w.r.t. ALL of q/k/v and fold every grad into
            # the carry — a dq-only grad lets XLA dead-code-eliminate the
            # dk/dv backward kernels and the "training" number would be
            # fwd+dq only
            def chained(q, k, v):
                def body(c, _):
                    gq, gk, gv = jax.grad(
                        lambda a, b2, c2: jnp.sum(
                            f(a, b2, c2).astype(jnp.float32) ** 2),
                        argnums=(0, 1, 2))(*c)
                    return (c[0] * 0.5 + gq.astype(c[0].dtype) * 1e-6,
                            c[1] * 0.5 + gk.astype(c[1].dtype) * 1e-6,
                            c[2] * 0.5 + gv.astype(c[2].dtype) * 1e-6), None
                (q_2, _, _), _ = jax.lax.scan(body, (q, k, v), None,
                                              length=reps)
                return q_2
            g = jax.jit(chained)
            o = g(q_, k_, v_)
            float(jnp.sum(o[0, 0, 0, :1].astype(jnp.float32)))
            t0 = time.perf_counter()
            for _ in range(n):
                o = g(q_, k_, v_)
            float(jnp.sum(o[0, 0, 0, :1].astype(jnp.float32)))
            return (time.perf_counter() - t0) / (n * reps)

        from deepspeed_tpu.ops.sparse_attention import sparse_attention \
            as _sa

        B4, h4 = 2, 16
        q4 = jnp.asarray(rng.randn(B4, Sb, h4, db)).astype(jnp.bfloat16)
        k4 = jnp.asarray(rng.randn(B4, Sb, h4, db)).astype(jnp.bfloat16)
        v4 = jnp.asarray(rng.randn(B4, Sb, h4, db)).astype(jnp.bfloat16)
        bb128 = BigBirdSparsityConfig(num_heads=h4, block=128)
        ts_ = _bench_grad(lambda q, k, v: block_sparse_attention(
            q, k, v, bb128, causal=True), q4, k4, v4)
        td_ = _bench_grad(lambda q, k, v: _sa(
            q, k, v, bb128, impl="dense", causal=True), q4, k4, v4)
        extras["variants"]["block_sparse_train_speedup_s4096"] = \
            round(td_ / ts_, 2)
        del q4, k4, v4
        free_hbm()

        sweep = {}
        qs8 = jnp.asarray(rng.randn(1, S8, h4, db)).astype(jnp.bfloat16)
        ks8 = jnp.asarray(rng.randn(1, S8, h4, db)).astype(jnp.bfloat16)
        vs8 = jnp.asarray(rng.randn(1, S8, h4, db)).astype(jnp.bfloat16)
        t_fl8 = _bench_grad(lambda q, k, v: flash_attention(q, k, v, True),
                            qs8, ks8, vs8)
        from deepspeed_tpu.ops.pallas.block_sparse_attention import (
            _live_fraction, _norm_layout, _plan)

        for win in (3, 7, 15):
            _budget_check()
            cfg_w = BigBirdSparsityConfig(
                num_heads=h4, block=128, num_global_blocks=1,
                num_random_blocks=1, num_sliding_window_blocks=win)
            lay_w = _norm_layout(cfg_w.make_layout(S8), h4)
            _, cnt_w, _ = _plan(lay_w, S8, 128, 128, 128, True)
            lf = _live_fraction(cnt_w, S8, 128, 128, True)
            t_w = _bench_grad(lambda q, k, v, c=cfg_w:
                              block_sparse_attention(q, k, v, c,
                                                     causal=True),
                              qs8, ks8, vs8)
            sweep[f"win{win}"] = {"live": round(float(lf), 3),
                                  "vs_flash": round(t_fl8 / t_w, 2)}
        extras["variants"]["block_sparse_train_sweep_s8192"] = sweep
        del qs8, ks8, vs8
        free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})[
            "block_sparse_error"] = str(e)[:200]

    _mark("flash_sweep")
    # -- variant: flash attention vs the XLA reference ladder, 2k–32k -----
    # (ISSUE 12 acceptance: the Pallas path must be >= 1.0x at EVERY
    # benched seq length, not just break even at 8k.)  Train-shaped
    # fwd+bwd timing; baseline is what the dispatch would run WITHOUT
    # the kernel: the dense masked reference where its O(S^2) logits fit
    # (2k/8k), the chunked online-softmax lax.scan beyond (32k).
    try:
        _budget_check()
        from deepspeed_tpu.ops.pallas.flash_attention import (
            _reference_attention, flash_attention)

        def _xla_chunked_attention(q, k, v, blk=512):
            """Best non-Pallas XLA form at long S: online-softmax scan
            over k-chunks (causal), O(S·blk) transients."""
            B, S, h, d = q.shape
            scale = 1.0 / np.sqrt(d)
            qt = (q.astype(jnp.float32) * scale).transpose(0, 2, 1, 3)
            kt = k.astype(jnp.float32).transpose(0, 2, 1, 3)
            vt = v.astype(jnp.float32).transpose(0, 2, 1, 3)
            nk = S // blk
            kc = kt.reshape(B, h, nk, blk, d).transpose(2, 0, 1, 3, 4)
            vc = vt.reshape(B, h, nk, blk, d).transpose(2, 0, 1, 3, 4)
            q_pos = jnp.arange(S)[:, None]

            def body(carry, chunk):
                m, l, acc = carry
                ki, kb, vb = chunk
                s = jnp.einsum("bhqd,bhkd->bhqk", qt, kb)
                k_pos = ki * blk + jnp.arange(blk)[None, :]
                s = jnp.where(q_pos >= k_pos, s, -1e30)
                m_new = jnp.maximum(m, jnp.max(s, axis=-1))
                p = jnp.exp(s - m_new[..., None])
                alpha = jnp.exp(m - m_new)
                l_new = l * alpha + jnp.sum(p, axis=-1)
                acc_new = (acc * alpha[..., None]
                           + jnp.einsum("bhqk,bhkd->bhqd", p, vb))
                return (m_new, l_new, acc_new), None

            m0 = jnp.full((B, h, S), -jnp.inf, jnp.float32)
            l0 = jnp.zeros((B, h, S), jnp.float32)
            a0 = jnp.zeros((B, h, S, d), jnp.float32)
            (m, l, acc), _ = jax.lax.scan(
                body, (m0, l0, a0), (jnp.arange(nk), kc, vc))
            out = acc / l[..., None]
            return out.transpose(0, 2, 1, 3).astype(q.dtype)

        def _bench_grad_fs(f, q_, k_, v_, n=3, reps=4):
            # self-contained copy of the block-sparse section's fwd+bwd
            # timer (that section failing must not take this gate down):
            # all of dq/dk/dv fold into the carry so no backward kernel
            # is dead-code-eliminated
            def chained(q, k, v):
                def body(c, _):
                    gq, gk, gv = jax.grad(
                        lambda a, b2, c2: jnp.sum(
                            f(a, b2, c2).astype(jnp.float32) ** 2),
                        argnums=(0, 1, 2))(*c)
                    return (c[0] * 0.5 + gq.astype(c[0].dtype) * 1e-6,
                            c[1] * 0.5 + gk.astype(c[1].dtype) * 1e-6,
                            c[2] * 0.5 + gv.astype(c[2].dtype) * 1e-6), None
                (q_2, _, _), _ = jax.lax.scan(body, (q, k, v), None,
                                              length=reps)
                return q_2
            g = jax.jit(chained)
            o = g(q_, k_, v_)
            float(jnp.sum(o[0, 0, 0, :1].astype(jnp.float32)))
            t0 = time.perf_counter()
            for _ in range(n):
                o = g(q_, k_, v_)
            float(jnp.sum(o[0, 0, 0, :1].astype(jnp.float32)))
            return (time.perf_counter() - t0) / (n * reps)

        rngf = np.random.RandomState(0)
        hf, df = 8, 64
        for Sf, Bf in ((2048, 4), (8192, 1), (32768, 1)):
            _budget_check()
            qf = jnp.asarray(rngf.randn(Bf, Sf, hf, df)).astype(
                jnp.bfloat16)
            kf = jnp.asarray(rngf.randn(Bf, Sf, hf, df)).astype(
                jnp.bfloat16)
            vf = jnp.asarray(rngf.randn(Bf, Sf, hf, df)).astype(
                jnp.bfloat16)
            if Sf <= 8192:
                baseline = lambda q, k, v: _reference_attention(
                    q, k, v, True)
            else:
                baseline = _xla_chunked_attention
            t_ref = _bench_grad_fs(baseline, qf, kf, vf)
            t_fl = _bench_grad_fs(
                lambda q, k, v: flash_attention(q, k, v, True),
                qf, kf, vf)
            key = f"flash_speedup_s{Sf}"
            extras[key] = round(t_ref / t_fl, 2)
            extras.setdefault("variants", {})[key] = extras[key]
            del qf, kf, vf
            free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})["flash_sweep_error"] = \
            str(e)[:200]

    _mark("overlap")
    # -- variant: collective-compute overlap hiding fraction --------------
    # Ring-decomposed all-gather matmul (comm/overlap.py) vs the
    # monolithic gather-then-matmul: hiding_frac = the share of the
    # collective's serialized cost the ring buries under compute.
    try:
        _budget_check()
        from jax.sharding import Mesh, PartitionSpec as Psp

        from deepspeed_tpu.comm import overlap as _ovl
        from deepspeed_tpu.comm.comm import all_gather_in_graph
        from deepspeed_tpu.utils.jax_compat import shard_map as _shmap

        devs = jax.devices()
        if len(devs) >= 2:
            omesh = Mesh(np.array(devs), ("data",))
            M, K, N = 4096, 2048, 2048
            xo = jnp.asarray(np.random.RandomState(0).randn(
                M, K)).astype(jnp.bfloat16)
            wo = jnp.asarray(np.random.RandomState(1).randn(
                K, N)).astype(jnp.bfloat16)

            def _time_fn(fn, *args, n=8):
                o = fn(*args)
                float(jnp.sum(o[:1, :1].astype(jnp.float32)))
                t0 = time.perf_counter()
                for _ in range(n):
                    o = fn(*args)
                float(jnp.sum(o[:1, :1].astype(jnp.float32)))
                return (time.perf_counter() - t0) / n

            serial = jax.jit(_shmap(
                lambda x, w: jnp.dot(
                    all_gather_in_graph(x, "data", axis=0, tiled=True),
                    w, preferred_element_type=jnp.bfloat16),
                mesh=omesh, in_specs=(Psp("data"), Psp()),
                out_specs=Psp(), check_vma=False))
            ring = jax.jit(_shmap(
                lambda x, w: _ovl.all_gather_matmul(x, w, "data",
                                                    chunks=4),
                mesh=omesh, in_specs=(Psp("data"), Psp()),
                out_specs=Psp(), check_vma=False))
            mm_only = jax.jit(lambda x, w: jnp.dot(
                x, w, preferred_element_type=jnp.bfloat16))

            t_serial = _time_fn(serial, xo, wo)
            t_ring = _time_fn(ring, xo, wo)
            t_mm = _time_fn(mm_only, xo, wo)
            coll = max(t_serial - t_mm, 1e-9)
            hiding = max(0.0, min(1.0, (t_serial - t_ring) / coll))
            extras["overlap_hiding_frac"] = round(hiding, 3)
            extras.setdefault("variants", {})["overlap"] = {
                "t_serial_ms": round(t_serial * 1e3, 3),
                "t_ring_ms": round(t_ring * 1e3, 3),
                "t_matmul_ms": round(t_mm * 1e3, 3),
                "hiding_frac": round(hiding, 3),
                "chunks": 4,
            }
            del xo, wo
            free_hbm()
        else:
            extras.setdefault("variants", {})["overlap"] = {
                "skipped": "single device — no collective to hide"}
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})["overlap_error"] = str(e)[:200]

    _mark("anatomy")
    # -- variant: step anatomy — trace-measured comm/compute split --------
    # One shared profiler session over a few fenced steps of the ring
    # all_gather_matmul (2+ devices; plain matmul fallback on one),
    # classified into compute / exposed-collective / overlapped /
    # host-sync buckets.  comm_fraction is sentinel-gated (lower is
    # better); the MEASURED overlap hiding backfills the analytic
    # overlap number when the ring variant couldn't run.
    try:
        _budget_check()
        from deepspeed_tpu.telemetry.anatomy import (capture_step_anatomy,
                                                     get_cost_ledger)

        devs = jax.devices()
        if len(devs) >= 2:
            from jax.sharding import Mesh, PartitionSpec as Psp

            from deepspeed_tpu.comm import overlap as _ovl
            from deepspeed_tpu.utils.jax_compat import shard_map as _shmap

            amesh = Mesh(np.array(devs), ("data",))
            afn = jax.jit(_shmap(
                lambda x, w: _ovl.all_gather_matmul(x, w, "data",
                                                    chunks=4),
                mesh=amesh, in_specs=(Psp("data"), Psp()),
                out_specs=Psp(), check_vma=False))
        else:
            afn = jax.jit(lambda x, w: jnp.dot(
                x, w, preferred_element_type=jnp.bfloat16))
        xa = jnp.asarray(np.random.RandomState(2).randn(
            2048, 2048)).astype(jnp.bfloat16)
        wa = jnp.asarray(np.random.RandomState(3).randn(
            2048, 2048)).astype(jnp.bfloat16)
        try:  # roofline join needs costs for the captured program
            get_cost_ledger().harvest("bench/anatomy_probe", 0,
                                      afn.lower(xa, wa).compile())
        except Exception:
            pass
        asum = capture_step_anatomy(afn, xa, wa, steps=3,
                                    site="bench/anatomy_probe")
        extras["comm_fraction"] = float(asum["comm_fraction"])
        if (asum.get("overlap_hiding_frac") is not None
                and "overlap_hiding_frac" not in extras):
            extras["overlap_hiding_frac"] = round(
                float(asum["overlap_hiding_frac"]), 3)
        roof = (asum.get("roofline") or [{}])[0]
        extras.setdefault("variants", {})["anatomy"] = {
            "window_us": asum.get("window_us"),
            "compute_us": asum.get("compute_us"),
            "coll_exposed_us": asum.get("coll_exposed_us"),
            "coll_overlapped_us": asum.get("coll_overlapped_us"),
            "host_sync_us": asum.get("host_sync_us"),
            "comm_fraction": asum.get("comm_fraction"),
            "overlap_hiding_frac": asum.get("overlap_hiding_frac"),
            "attributed_frac": asum.get("attributed_frac"),
            "roofline_verdict": roof.get("verdict"),
            "roofline_headroom": roof.get("headroom"),
            "devices": len(devs),
        }
        del xa, wa
        free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})["anatomy_error"] = str(e)[:200]

    _mark("numerics")
    # -- variant: numerics probe overhead ---------------------------------
    # The plane's contract (ISSUE 18) is that the sampled probes-on step
    # variant costs (nearly) nothing: 8 scalars per probe folded into the
    # step's own output pytree, no host callbacks.  Measured here as the
    # fenced step-time delta of a probed value_and_grad vs the identical
    # un-probed program, and sentinel-gated (lower, 5% abs floor) so a
    # probe that starts forcing a host sync or breaking a fusion shows
    # up in the trajectory.
    try:
        _budget_check()
        from deepspeed_tpu.telemetry import numerics as _num

        NH, NB, NL = 512, 256, 4
        rs = np.random.RandomState(5)
        np_ = {f"w{i}": jnp.asarray(rs.randn(NH, NH) * 0.05).astype(
            jnp.bfloat16) for i in range(NL)}
        nx = jnp.asarray(rs.randn(NB, NH)).astype(jnp.bfloat16)

        def _nloss(p, x):
            h = x
            for i in range(NL):
                h = _num.probe(f"h{i}", jnp.tanh(h @ p[f"w{i}"]))
            return jnp.sum(jnp.square(h.astype(jnp.float32)))

        def _nstep_base(p, x):
            return jax.value_and_grad(_nloss)(p, x)

        def _nstep_probed(p, x):
            def lf(pp):
                mark = _num.scan_mark()
                loss = _nloss(pp, x)
                return loss, (_num.scan_drain(mark) or {})

            return jax.value_and_grad(lf, has_aux=True)(p)

        f_base = jax.jit(_nstep_base)
        f_prob = jax.jit(_nstep_probed)

        def _ntime(fn, probed, iters=20, reps=3):
            times = []
            for _ in range(reps + 1):  # first rep is the warmup/compile
                if probed:
                    coll = _num.Collector(probes=True, moe=False,
                                          tag="bench")
                    with _num.collecting(coll):
                        t0 = time.perf_counter()
                        for _i in range(iters):
                            out = fn(np_, nx)
                        jax.block_until_ready(out)
                        times.append(time.perf_counter() - t0)
                else:
                    t0 = time.perf_counter()
                    for _i in range(iters):
                        out = fn(np_, nx)
                    jax.block_until_ready(out)
                    times.append(time.perf_counter() - t0)
            return sorted(times[1:])[len(times[1:]) // 2]

        t_off = _ntime(f_base, probed=False)
        t_on = _ntime(f_prob, probed=True)
        frac = max(0.0, (t_on - t_off) / max(t_off, 1e-9))
        extras["numerics_overhead_frac"] = round(frac, 4)
        extras.setdefault("variants", {})["numerics"] = {
            "base_s_per_20": round(t_off, 5),
            "probed_s_per_20": round(t_on, 5),
            "overhead_frac": round(frac, 4),
            "probes": NL,
        }
        del np_, nx
        free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})["numerics_error"] = str(e)[:200]

    _mark("profiler")
    # -- variant: fleet profiler duty-cycle overhead -----------------------
    # ISSUE 20's continuous mode ("always-on capture with a bounded
    # overhead budget") only earns its keep if the budget holds: the same
    # fenced step loop timed with the duty-cycled ProfilerPlane arming
    # real jax.profiler windows (capture + parse + census + calibration)
    # vs with no plane at all.  profiler_overhead_pct is sentinel-gated
    # (lower, 5pt abs floor).
    try:
        _budget_check()
        import shutil as _sh
        import tempfile as _tmp

        from deepspeed_tpu.telemetry.profiler import ProfilerPlane
        from deepspeed_tpu.telemetry.profiler.calibration import (
            default_calibration_path, get_calibration_store)

        PH, PB = 512, 256
        rs = np.random.RandomState(7)
        pw = jnp.asarray(rs.randn(PH, PH) * 0.05).astype(jnp.bfloat16)
        px = jnp.asarray(rs.randn(PB, PH)).astype(jnp.bfloat16)
        pfn = jax.jit(lambda w, x: jnp.sum(jnp.square(
            jnp.tanh(x @ w).astype(jnp.float32))))
        float(pfn(pw, px))  # warm the compile out of both timings

        def _ptime(plane, iters=60):
            t0 = time.perf_counter()
            out = None
            for i in range(iters):
                if plane is not None:
                    plane.on_step(i)
                out = pfn(pw, px)
            jax.block_until_ready(out)
            if plane is not None:
                plane.on_step(iters)  # close a still-open window
            return time.perf_counter() - t0

        t_off = min(_ptime(None), _ptime(None))
        pdir = _tmp.mkdtemp(prefix="bench_profiler_")
        # duty captures calibrate too — point the factor store at a
        # throwaway so the bench doesn't pollute the user's cache
        get_calibration_store(os.path.join(pdir, "calibration.json"))
        plane = ProfilerPlane("bench-duty", out_dir=pdir, ring=2,
                              duty_cycle_pct=10.0, duty_period_steps=20)
        plane.enable_duty_cycle()
        t_on = min(_ptime(plane), _ptime(plane))
        pct = max(0.0, (t_on - t_off) / max(t_off, 1e-9) * 100.0)
        extras["profiler_overhead_pct"] = round(pct, 2)
        extras.setdefault("variants", {})["profiler"] = {
            "base_s_per_60": round(t_off, 5),
            "duty_s_per_60": round(t_on, 5),
            "overhead_pct": round(pct, 2),
            "captures": plane._captures,
            "duty_cycle_pct": plane.duty_cycle_pct,
        }
        get_calibration_store(default_calibration_path())
        _sh.rmtree(pdir, ignore_errors=True)
        del pw, px
        free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})["profiler_error"] = str(e)[:200]

    _mark("tunnel")
    # -- tunnel characterization ------------------------------------------
    # On this axon setup the chip sits behind a network tunnel.  Measured
    # here and reported so offload numbers are read against the LINK, not
    # the architecture: at ~5 MB/s every host<->device byte costs ~200x a
    # local PCIe link, which no overlap schedule can hide.
    try:
        dev = jax.devices()[0]
        float(jax.device_put(jnp.float32(1.0), dev) + 1)
        t0 = time.perf_counter()
        for _ in range(5):
            float(jax.device_put(jnp.float32(1.0), dev) + 1)
        rtt_ms = (time.perf_counter() - t0) / 5 * 1e3
        a = np.random.RandomState(0).randn(4 * 1024 * 1024).astype(np.float32)
        # warm the transfer + sum-fence programs so a cold compile doesn't
        # masquerade as link bandwidth
        float(jnp.sum(jax.device_put(a, dev)))
        t0 = time.perf_counter()
        xd = jax.device_put(a, dev)
        float(jnp.sum(xd))
        h2d = 16.0 / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        np.asarray(xd)
        d2h = 16.0 / (time.perf_counter() - t0)
        extras["tunnel"] = {"rtt_ms": round(rtt_ms, 1),
                            "h2d_mbps": round(h2d, 1),
                            "d2h_mbps": round(d2h, 1)}
        del a, xd
        free_hbm()
    except Exception:
        pass

    _mark("offload_loopback")
    # -- variant: ZeRO-Offload ARCHITECTURE ratio (loopback link) ----------
    # r02 measured offload over the tunnel at 0.004x on-device — that
    # number is the 5 MB/s link, not the bucket pipeline (440 MB/step / 5
    # MB/s = 90 s no matter how well d2h/Adam/h2d overlap).  The honest
    # architecture measurement runs the SAME engine code on the CPU
    # backend, where host<->"device" moves at memcpy speed (a PCIe-class
    # stand-in): that ratio is what a TPU-VM with a local chip would see.
    # The overlap breakdown (d2h wait / C++ Adam / h2d dispatch vs total)
    # is reported alongside so the pipelining itself is visible.
    try:
        _budget_check()
        import subprocess

        repo = os.path.dirname(os.path.abspath(__file__))
        code = (
            "import os, sys, json\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "os.environ['DS_BENCH_SUBPROCESS'] = '1'\n"
            f"sys.path.insert(0, {repo!r})\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import jax.numpy as jnp\n"
            "import bench\n"
            "from deepspeed_tpu.models import LlamaConfig\n"
            "from deepspeed_tpu.utils import groups\n"
            "cfg = LlamaConfig(vocab_size=8192, hidden_size=512,\n"
            "                  intermediate_size=1408, num_layers=6,\n"
            "                  num_heads=8, num_kv_heads=8, max_seq_len=512,\n"
            "                  dtype=jnp.bfloat16, attn_impl='xla',\n"
            "                  remat=False)\n"
            "res = {}\n"
            "for name, off in (('ondevice', False), ('offload', True)):\n"
            "    groups.reset_mesh()\n"
            "    eng = bench.build_engine(cfg, 4, zero_stage=2, offload=off)\n"
            "    tps = bench.measure(eng, 4, 512, cfg.vocab_size, steps=5,\n"
            "                        segments=1, budget_s=25.0)\n"
            "    res[name] = tps\n"
            "    if off and getattr(eng, 'offload_opt', None) is not None:\n"
            "        res['timings'] = {k: round(v, 4) for k, v in\n"
            "                          eng.offload_opt.last_timings.items()}\n"
            "print('LOOPBACK' + json.dumps(res))\n")
        proc = subprocess.run([sys.executable, "-c", code], timeout=240,
                              capture_output=True, text=True)
        line = next(l for l in proc.stdout.splitlines()
                    if l.startswith("LOOPBACK"))
        res = json.loads(line[len("LOOPBACK"):])
        extras.setdefault("variants", {})
        extras["variants"]["offload_loopback_tokens_per_sec"] = round(
            res["offload"], 1)
        extras["variants"]["offload_vs_ondevice_loopback"] = round(
            res["offload"] / res["ondevice"], 3)
        if "timings" in res:
            t = res["timings"]
            serial = (t.get("d2h_wait_s", 0) + t.get("host_opt_s", 0)
                      + t.get("h2d_dispatch_s", 0))
            extras["variants"]["offload_overlap"] = {
                **t, "serial_sum_s": round(serial, 4)}
    except Exception as e:
        extras.setdefault("variants", {})[
            "offload_loopback_error"] = str(e)[:200]

    _mark("offload_overlap_synthetic")
    # -- overlap machinery proof with the link excluded (VERDICT r4 #7) --
    try:
        _budget_check()
        extras.setdefault("variants", {})["offload_overlap_synthetic"] = \
            _bench_offload_overlap_synthetic()
        free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})[
            "offload_overlap_synthetic_error"] = str(e)[:200]

    _mark("llama8b_proxy")
    # -- driver ladder: llama3-8B-shaped slice, ZeRO-3 on device -----------
    # 8B-true per-layer shape (h4096/i14336/GQA-8); L and vocab scale the
    # slice to what fp32 Adam states fit on this chip's HBM.  The offload
    # version of this config is link-bound on the tunnel (see "tunnel");
    # the loopback variant above carries the offload architecture number.
    try:
        _budget_check()
        hbm = hbm_bytes() or 16e9
        if hbm >= 80e9:
            attempts = [(24, 32000, 2)]
        elif hbm >= 30e9:
            attempts = [(8, 32000, 2)]
        else:  # 16G: fp32 Adam states cap the slice ~0.6B params
            attempts = [(2, 16384, 2), (1, 16384, 2)]
        last_err = None
        for L8, v8, b8 in attempts:
            try:
                l8cfg = LlamaConfig(vocab_size=v8, hidden_size=4096,
                                    intermediate_size=14336, num_layers=L8,
                                    num_heads=32, num_kv_heads=8,
                                    max_seq_len=2048, rope_theta=500000.0,
                                    dtype=jnp.bfloat16, attn_impl="flash",
                                    remat=True, loss_tiles=8,
                                    tie_embeddings=False)
                eng = build_engine(l8cfg, b8, zero_stage=3)
                otps = measure(eng, b8, 2048, l8cfg.vocab_size, steps=5,
                               segments=1, budget_s=45.0)
                oflops = step_flops(eng, b8, 2048, l8cfg.vocab_size, l8cfg)
                extras["variants"]["llama8b_proxy_zero3_tokens_per_sec"] = \
                    round(otps, 1)
                extras["variants"]["llama8b_proxy_zero3_mfu"] = round(
                    (oflops * otps / (b8 * 2048)) / peak, 4)
                extras["variants"]["llama8b_proxy_layers"] = L8
                del eng
                free_hbm()
                last_err = None
                break
            except Exception as e:
                eng = None  # drop the failed attempt's engine before retry
                free_hbm()
                last_err = e
        if last_err is not None:
            raise last_err
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})[
            "llama8b_proxy_error"] = str(e)[:200]

    _mark("llama8b_infinity_full_depth")
    # -- north star: Llama-3-8B shapes at the REAL layer count (32) via
    # ZeRO-Infinity layer streaming (VERDICT r3 item 2).  The full trunk's
    # host planes (fp32 master + Adam moments + bf16 wire ≈ 14 B/param)
    # are ACTUALLY allocated and seeded — this is the real model, not a
    # 2-layer slice — and the phases of the real streamed step (wire h2d,
    # layer fwd, vjp, grad d2h + fused C++ Adam) are measured with the
    # engine's own compiled fns on the chip.  When the host↔device link
    # can carry a full step inside the budget the engine's real
    # train_step is timed; behind a slow tunnel the honest number is the
    # per-layer measured phases composed over all 32 layers (streaming is
    # layer-linear BY DESIGN — O(2 layers) device residency), reported
    # with projected=true + the link stats that explain it.
    # (vocab 32000 keeps the RESIDENT embed/head optimizer states inside
    # a 16G chip's HBM; every trunk shape is 8B-true.)
    try:
        _budget_check()
        extras.setdefault("variants", {})["llama8b_infinity"] = \
            _bench_llama8b_infinity()
        v = extras["variants"]["llama8b_infinity"]
        extras["variants"]["llama8b_infinity_mfu"] = v.get("mfu")
        extras["variants"]["llama8b_infinity_tokens_per_sec"] = \
            v.get("tokens_per_sec")
        extras["variants"]["llama8b_infinity_params"] = v.get("params")
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})[
            "llama8b_infinity_error"] = str(e)[:300]

    _mark("infinity_sp_miniature")
    # -- ladder config 5's composition (Infinity × Ulysses SP) on-chip ----
    try:
        _budget_check()
        extras.setdefault("variants", {})["llama_infinity_sp"] = \
            _bench_infinity_sp_miniature()
        extras["variants"]["llama_infinity_sp_tokens_per_sec"] = \
            extras["variants"]["llama_infinity_sp"]["tokens_per_sec"]
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})[
            "llama_infinity_sp_error"] = str(e)[:300]

    _mark("resnet_cifar")
    # -- driver ladder config 1: CIFAR ResNet-56, ZeRO-0 -------------------
    try:
        _budget_check()
        from deepspeed_tpu.models.resnet import ResNetConfig, ResNetModel

        rcfg = ResNetConfig.resnet56(dtype=jnp.bfloat16)
        rb = 128
        rng0 = np.random.RandomState(0)
        rdata = {
            "images": jnp.asarray(rng0.randn(
                rb, rcfg.image_size, rcfg.image_size, 3).astype(np.float32)),
            "labels": jnp.asarray(rng0.randint(0, rcfg.num_classes,
                                               size=(rb,))),
        }
        eng = build_engine(rcfg, rb, zero_stage=0, model_cls=ResNetModel)
        # measure() counts batch*seq tokens; seq=1 makes that images/sec,
        # with its median-of-segments noise rejection and budget logic
        ips = measure(eng, rb, 1, rcfg.num_classes, steps=20,
                      budget_s=45.0, data=rdata)
        extras["variants"]["resnet56_cifar_images_per_sec"] = round(ips, 1)
        del eng, rdata
        free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})[
            "resnet_cifar_error"] = str(e)[:200]

    _mark("fused_adam_probe")
    # -- SURVEY row 30 evidence: a hand-fused Pallas Adam only matters if
    # XLA leaves update bandwidth on the table.  The probe times an
    # isolated optax adamw step over a 13.75M-param plane and reports
    # achieved HBM GB/s (7 fp32 passes/param) — read against the chip's
    # ~820 GB/s peak, it bounds what a custom kernel could win on a
    # component that is ~2%% of a training step.
    try:
        _budget_check()
        import optax

        n = 110_000_000 // 8  # one shard-sized param plane
        p = jnp.zeros((n,), jnp.float32)
        g = jnp.ones((n,), jnp.float32) * 1e-3
        tx = optax.adamw(1e-4)
        state = tx.init(p)

        @jax.jit
        def opt_step(p, g, state):
            u, state = tx.update(g, state, p)
            return optax.apply_updates(p, u), state

        p2, state = opt_step(p, g, state)  # compile
        float(jnp.sum(p2))
        # 200 chained steps between fences: the ~100 ms tunnel fence
        # amortizes to 0.5 ms/step, so the number reflects the kernel
        t0 = time.perf_counter()
        for _ in range(200):
            p2, state = opt_step(p2, g, state)
        float(jnp.sum(p2))
        dt = (time.perf_counter() - t0) / 200
        # bytes moved: p r/w + g r + m r/w + v r/w = 7 floats/param
        gbps = 7 * 4 * n / dt / 1e9
        extras["variants"]["optax_adam_hbm_gbps"] = round(gbps, 1)

        # the one-pass fused kernel over the SAME plane + byte accounting
        # (ops/pallas/fused_optimizer.py): one read of g + one r/w of
        # p/m/v, no materialized updates tree — the effective GB/s over
        # the identical 7-floats/param logical traffic is the gated
        # fused_adam_hbm_gbps (acceptance: > optax_adam_hbm_gbps)
        from deepspeed_tpu.ops.pallas.fused_optimizer import (
            FusedAdamConfig, apply_fused_adam)

        fcfg = FusedAdamConfig(weight_decay=0.01, decoupled_wd=True)
        fstate = tx.init(p)

        @jax.jit
        def fused_step(p, g, state):
            return apply_fused_adam(state, p, g, 1e-4, 1.0, fcfg)

        p3, fstate = fused_step(p, g, fstate)  # compile
        float(jnp.sum(p3))
        t0 = time.perf_counter()
        for _ in range(200):
            p3, fstate = fused_step(p3, g, fstate)
        float(jnp.sum(p3))
        fdt = (time.perf_counter() - t0) / 200
        fgbps = 7 * 4 * n / fdt / 1e9
        extras["fused_adam_hbm_gbps"] = round(fgbps, 1)
        extras["variants"]["fused_adam_hbm_gbps"] = round(fgbps, 1)
        extras["variants"]["fused_vs_optax_adam"] = round(fgbps / gbps, 2)
        del p, g, p2, p3, state, fstate
        free_hbm()
    except Exception as e:
        free_hbm()
        extras.setdefault("variants", {})[
            "fused_adam_probe_error"] = str(e)[:200]

    _mark("infinity")
    # -- ZeRO-Infinity capacity: peak params/chip the tiering can hold -----
    # CAPACITY math, not a measured training run: on this tunneled chip a
    # layer-streaming step would move every layer's params over the
    # network (minutes/step), so the honest number here is what the
    # cpu/nvme tiers can back: fp32 master + Adam moments (12 B/param)
    # stream from host/NVMe, bf16 residence is O(2 layers).  The suite's
    # test_infinity.py exercises the actual streaming path.
    try:
        import shutil

        with open("/proc/meminfo") as f:
            info = {ln.split(":")[0]: int(ln.split()[1]) for ln in f}
        host_free = info.get("MemAvailable", 0) * 1024
        # a tmpfs /tmp IS host RAM — counting it again would double-count
        with open("/proc/mounts") as f:
            tmp_is_tmpfs = any(
                ln.split()[1] == "/tmp" and ln.split()[0] == "tmpfs"
                for ln in f)
        nvme_free = 0 if tmp_is_tmpfs else shutil.disk_usage("/tmp").free
        # conservative: keep 20% headroom on each tier
        capacity = int(0.8 * (host_free + nvme_free) / 12)
        extras.setdefault("variants", {})[
            "infinity_peak_params_per_chip"] = capacity
    except Exception:
        pass

    # perf baseline for local tracking + the regression sentinel (the
    # cross-round ratio uses R01; `python -m deepspeed_tpu.telemetry
    # perf check --baseline .bench_baseline.json` gates later runs)
    hist = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench_baseline.json")
    try:
        from deepspeed_tpu.telemetry.perf import save_baseline

        save_baseline(hist, {"metric": "llama_110m_train_tokens_per_sec",
                             "value": tps, **extras},
                      source="bench.py headline")
    except Exception:
        try:  # the sentinel must never cost the bench its artifact line
            with open(hist, "w") as f:
                json.dump({"tokens_per_sec": tps, "mfu": extras["mfu"]}, f)
        except Exception:
            pass

    print(json.dumps({
        "metric": "llama_110m_train_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps / R01_TOKENS_PER_SEC, 3),
        **extras,
    }))


if __name__ == "__main__":
    main()
