"""Benchmark: flagship Llama training throughput on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The metric is training tokens/sec on a ~110M-param Llama (bf16, remat,
fused single-program step).  ``vs_baseline`` is the ratio against the
model-flops-derived reference rate the DeepSpeed papers imply for the same
scale (BASELINE.json has no driver-verified numbers — ``published`` is {} —
so the ratio is reported against this script's own first recorded run when
available, else 1.0).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    import deepspeed_tpu
    from deepspeed_tpu.models import LlamaConfig, LlamaModel
    from deepspeed_tpu.parallel import MeshLayout
    from deepspeed_tpu.utils import groups

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_layers=12,
                          num_heads=12, num_kv_heads=12, max_seq_len=2048,
                          dtype=jnp.bfloat16, attn_impl="flash")
        batch, seq, steps = 8, 2048, 20
    else:  # CPU fallback so the bench always emits a line
        cfg = LlamaConfig.tiny(num_layers=2)
        batch, seq, steps = 4, 128, 3

    layout = MeshLayout.infer(1, dp=1)
    mesh = groups.initialize_mesh(layout)
    model = LlamaModel(cfg, mesh=mesh)
    params = model.init_params(jax.random.PRNGKey(0))

    ds_config = {
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 0},
        "bf16": {"enabled": bool(on_tpu)},
    }
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds_config, mesh=mesh)

    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, seq)))
    batch_d = {"input_ids": ids}

    engine.train_step(batch_d)  # compile + warmup
    jax.block_until_ready(engine.state.params)

    # median of 3 segments: robust to the tunneled chip's throughput noise
    # without inflating the number the way a max would
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            engine.train_step(batch_d)
        jax.block_until_ready(engine.state.params)
        rates.append(batch * seq * steps / (time.perf_counter() - t0))
    tokens_per_sec = sorted(rates)[1]

    # persist the first TPU run as this bench's own baseline
    vs_baseline = 1.0
    baseline_file = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".bench_baseline.json")
    if on_tpu:
        try:
            if os.path.exists(baseline_file):
                with open(baseline_file) as f:
                    vs_baseline = tokens_per_sec / float(
                        json.load(f)["tokens_per_sec"])
            else:
                with open(baseline_file, "w") as f:
                    json.dump({"tokens_per_sec": tokens_per_sec}, f)
        except Exception:
            pass

    print(json.dumps({
        "metric": "llama_110m_train_tokens_per_sec"
        if on_tpu else "llama_tiny_cpu_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
