"""Wall-clock and throughput timers.

Capability parity with the reference's ``deepspeed/utils/timer.py`` [K]:
``SynchronizedWallClockTimer`` (named timers; on GPU the reference uses CUDA
events — here synchronization is ``jax.block_until_ready`` on a token array)
and ``ThroughputTimer`` (samples/sec + TFLOPS given a per-step FLOP count).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional


def _sync() -> None:
    """Drain all outstanding device work so host wall-clock is meaningful."""
    try:
        import jax

        # effects_barrier waits for all dispatched computations on all devices.
        jax.effects_barrier()
    except Exception as e:  # timers must never kill the step they time
        from .logging import debug_once

        debug_once("timer/sync", f"timer device sync failed ({e!r}); "
                                 f"timings may reflect dispatch, not device")


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self._start: Optional[float] = None
        self._elapsed = 0.0
        self._count = 0

    def start(self, sync: bool = False) -> None:
        if sync:
            _sync()
        self._start = time.perf_counter()

    def stop(self, sync: bool = False) -> None:
        if self._start is None:
            return
        if sync:
            _sync()
        self._elapsed += time.perf_counter() - self._start
        self._count += 1
        self._start = None

    def reset(self) -> None:
        self._start = None
        self._elapsed = 0.0
        self._count = 0

    def elapsed(self, reset: bool = True) -> float:
        value = self._elapsed
        if reset:
            self.reset()
        return value

    def mean(self) -> float:
        return self._elapsed / max(self._count, 1)


class SynchronizedWallClockTimer:
    """Named-timer registry. ``timer(name).start()/stop()``; ``log([names])``."""

    def __init__(self) -> None:
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    @contextmanager
    def record(self, name: str, sync: bool = False):
        t = self(name)
        t.start(sync=sync)
        try:
            yield t
        finally:
            t.stop(sync=sync)

    def log(self, names: Optional[List[str]] = None, reset: bool = True,
            log_fn: Optional[Callable[[str], Any]] = None) -> str:
        names = names or list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                parts.append(f"{name}: {self.timers[name].elapsed(reset=reset) * 1000:.2f}ms")
        msg = " | ".join(parts)
        if log_fn is None:
            from .logging import log_dist

            log_dist(f"time: {msg}")
        else:
            log_fn(msg)
        return msg


class ThroughputTimer:
    """Tracks samples/sec, tokens/sec and TFLOPS across steps.

    ``batch_size`` is the global train batch; ``flops_per_step`` (optional) is
    the model FLOPs for one optimizer step (fwd+bwd), used for TFLOPS/MFU.
    """

    def __init__(self, batch_size: int, seq_length: int = 0,
                 flops_per_step: float = 0.0, start_step: int = 2):
        self.batch_size = batch_size
        self.seq_length = seq_length
        self.flops_per_step = flops_per_step
        self.start_step = start_step  # skip compile/warmup steps
        self.step_count = 0
        self.total_time = 0.0
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, sync: bool = True) -> None:
        if self._t0 is None:
            return
        if sync:
            _sync()
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.step_count += 1
        if self.step_count > self.start_step:
            self.total_time += dt

    @property
    def counted_steps(self) -> int:
        return max(self.step_count - self.start_step, 0)

    def avg_step_time(self) -> float:
        return self.total_time / max(self.counted_steps, 1)

    def samples_per_sec(self) -> float:
        if self.total_time == 0:
            return 0.0
        return self.counted_steps * self.batch_size / self.total_time

    def tokens_per_sec(self) -> float:
        return self.samples_per_sec() * self.seq_length

    def tflops(self) -> float:
        if self.total_time == 0 or not self.flops_per_step:
            return 0.0
        return self.counted_steps * self.flops_per_step / self.total_time / 1e12
