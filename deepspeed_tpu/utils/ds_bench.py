"""``ds_bench`` — collective micro-benchmark CLI.

Reference: ``bin/ds_bench`` [K] (thin shim over
``DeepSpeedExamples/benchmarks/communication``): time
all_reduce/all_gather/all_to_all/broadcast over a size sweep and print
busbw/algbw — the tool operators use to validate a fabric before training.

TPU-first: collectives are jitted ``jax.lax`` ops over the global mesh;
timings come from compiled-program replay with a scalar-fetch fence
(``block_until_ready`` is unreliable on tunneled platforms).  Works on a
real slice or on a forced virtual CPU mesh (``--force_cpu_devices N``).
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List
from .jax_compat import shard_map as _shard_map


def _bench_collective(op: str, n_elems: int, trials: int, mesh) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = tuple(mesh.axis_names)
    world = int(mesh.devices.size)
    # per-shard width rounded to a multiple of world so tiled all_to_all's
    # divisibility holds on any device count; report the ACTUAL bytes moved
    m = max(n_elems // world, world)
    m -= m % world
    n_elems = world * m
    x = jnp.ones((world, m), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))

    # the comm verbs wrap the same lax collectives and feed the census —
    # a fabric-validation run should appear in the ledger like any other
    from ..comm.comm import (all_gather_in_graph, all_to_all_in_graph,
                             psum)

    def body(v):
        if op == "all_reduce":
            return psum(v, axis)
        if op == "all_gather":
            return all_gather_in_graph(v, axis, tiled=False)
        if op == "all_to_all":
            # local shard is [1, m]: exchange m/world-sized chunks
            return all_to_all_in_graph(v, axis, split_axis=1,
                                       concat_axis=0, tiled=True)
        if op == "broadcast":
            return psum(jnp.where(
                jax.lax.axis_index(axis[0]) == 0, v, jnp.zeros_like(v)),
                axis)
        raise ValueError(op)

    fn = jax.jit(_shard_map(body, mesh=mesh, in_specs=P(axis),
                               out_specs=P() if op == "all_reduce"
                               else P(axis),
                               check_vma=False))
    out = fn(x)
    float(jnp.sum(out))  # compile + fence
    t0 = time.perf_counter()
    for _ in range(trials):
        out = fn(x)
    float(jnp.sum(out))
    dt = (time.perf_counter() - t0) / trials
    nbytes = n_elems * 4
    # ring busbw convention: allreduce moves 2(n-1)/n of the payload
    factor = 2 * (world - 1) / world if op == "all_reduce" else \
        (world - 1) / world
    return {"op": op, "bytes": nbytes, "time_us": dt * 1e6,
            "algbw_GBps": nbytes / dt / 1e9,
            "busbw_GBps": nbytes * factor / dt / 1e9}


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(prog="ds_bench")
    parser.add_argument("--op", default="all_reduce",
                        choices=["all_reduce", "all_gather", "all_to_all",
                                 "broadcast", "all"])
    parser.add_argument("--minsize", type=int, default=1 << 14)
    parser.add_argument("--maxsize", type=int, default=1 << 22)
    parser.add_argument("--trials", type=int, default=10)
    parser.add_argument("--force_cpu_devices", type=int, default=0,
                        help="virtual CPU mesh size (testing without TPUs)")
    args = parser.parse_args(argv)

    if args.force_cpu_devices:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count="
              f"{args.force_cpu_devices}")
        import jax

        try:
            import jax.extend.backend as jeb

            jeb.clear_backends()
        except (ImportError, AttributeError, RuntimeError):
            pass  # older jax without clear_backends — flags still apply
                  # to the first real backend build
        jax.config.update("jax_platforms", "cpu")
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(jax.devices(), ("data",))
    ops = (["all_reduce", "all_gather", "all_to_all", "broadcast"]
           if args.op == "all" else [args.op])
    print(f"ds_bench: {len(jax.devices())} x "
          f"{jax.devices()[0].device_kind}")
    print(f"{'op':>12} {'bytes':>12} {'time(us)':>10} {'algbw':>10} "
          f"{'busbw':>10}")
    for op in ops:
        n = args.minsize
        while n <= args.maxsize:
            r = _bench_collective(op, n, args.trials, mesh)
            print(f"{r['op']:>12} {r['bytes']:>12} {r['time_us']:>10.1f} "
                  f"{r['algbw_GBps']:>9.2f}G {r['busbw_GBps']:>9.2f}G")
            n *= 4
    return 0


if __name__ == "__main__":
    sys.exit(main())
