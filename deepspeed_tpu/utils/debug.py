"""Debug / sanitizer mode.

Reference: the closest surfaces are ``deepspeed.comm`` async-op debug
checks, NaN/Inf grad screening (``check_grad_overflow``), and ``DS_DEBUG``
env logging [K] (SURVEY §5.2 — no TSAN/ASAN integration exists upstream).

TPU story per SURVEY §5.2's plan: XLA programs are race-free; the risk
surface is host↔device async (offload streams, async checkpointing) and
silent NaN propagation.  Debug mode therefore:

* forces a REAL device fence after every ``train_step`` (a scalar fetch —
  on tunneled platforms ``block_until_ready`` can be a no-op, a metrics
  fetch is not), so failures surface at the step that caused them;
* enables ``jax_debug_nans`` (XLA re-runs the failing op un-jitted and
  points at it) and raises on non-finite loss.

Activated by ``configure(...)`` or env ``DS_DEBUG=1`` at import.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from .logging import log_dist

_FORCE_SYNC = False
_NAN_CHECK = False


def configure(force_sync: Optional[bool] = None,
              nan_check: Optional[bool] = None) -> None:
    """Turn sanitizer behaviors on/off (both default ON when called)."""
    global _FORCE_SYNC, _NAN_CHECK
    if force_sync is None and nan_check is None:
        force_sync = nan_check = True
    if force_sync is not None:
        _FORCE_SYNC = bool(force_sync)
    if nan_check is not None:
        _NAN_CHECK = bool(nan_check)
        jax.config.update("jax_debug_nans", _NAN_CHECK)
    log_dist(f"debug mode: force_sync={_FORCE_SYNC} nan_check={_NAN_CHECK}")


def enabled() -> bool:
    return _FORCE_SYNC or _NAN_CHECK


def check_step(metrics) -> None:
    """Called by the engine after each train_step when debug mode is on."""
    if not (_FORCE_SYNC or _NAN_CHECK):
        return
    loss = float(metrics["loss"])  # real fence: drains the dispatch queue
    if _NAN_CHECK:
        import math

        if not math.isfinite(loss):
            raise FloatingPointError(
                f"non-finite loss {loss} (debug nan_check); enable "
                "jax_debug_nans tracebacks by re-running the step un-jitted")


if os.environ.get("DS_DEBUG", "") not in ("", "0", "false"):
    configure()
