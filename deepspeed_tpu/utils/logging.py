"""Rank-aware logging.

Capability parity with the reference's ``deepspeed/utils/logging.py`` [K]:
``logger`` (module-level, level settable externally), ``log_dist`` (log only on
selected ranks), plus ``should_log_rank0``.  On TPU the "rank" is the JAX
process index (one process per TPU-VM host), not a per-chip rank: inside a
single process all local chips share one Python logger.
"""

from __future__ import annotations

import logging
import os
import sys

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


def create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(logging.Formatter(LOG_FORMAT, datefmt="%Y-%m-%d %H:%M:%S"))
        lg.addHandler(handler)
        lg.propagate = False
    env_level = os.environ.get("DS_TPU_LOG_LEVEL")
    if env_level is not None:
        level = int(env_level) if env_level.isdigit() else env_level.upper()
    lg.setLevel(level)
    return lg


logger = create_logger()


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def should_log_rank0() -> bool:
    return _process_index() == 0


def log_dist(message: str, ranks: list[int] | None = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (default: rank 0 only).

    ``ranks=[-1]`` logs on every process. Mirrors the reference ``log_dist``.
    """
    my_rank = _process_index()
    ranks = ranks if ranks is not None else [0]
    if -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def set_log_level(level: int | str) -> None:
    logger.setLevel(level)


#: keys already logged by the once-helpers — process-wide, so a failure
#: that fires every step (a broken telemetry exporter, a flaky fence)
#: says so exactly once instead of either flooding or staying silent
_logged_once: set[str] = set()


def _log_once(level: int, key: str, message: str) -> None:
    if key in _logged_once:
        return
    _logged_once.add(key)
    logger.log(level, message)


def debug_once(key: str, message: str) -> None:
    """Log ``message`` at DEBUG the first time ``key`` is seen.

    The sanctioned body for best-effort ``except Exception`` blocks
    (dslint's ``bare-except`` rule): failure paths that must never
    escalate (telemetry export, diagnostics collection) still leave one
    trace of the first breakage instead of swallowing it forever."""
    _log_once(logging.DEBUG, key, message)


def warn_once(key: str, message: str) -> None:
    """Like :func:`debug_once` at WARNING — for fallbacks an operator
    should hear about even without debug logging switched on."""
    _log_once(logging.WARNING, key, message)
