"""``ds_ssh`` — run a command on every hostfile host.

Reference: ``bin/ds_ssh`` [K]: parallel-ssh a shell command across the
hostfile (ops convenience for pod management).
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from typing import List

from ..launcher.runner import DLTS_HOSTFILE, parse_hostfile


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(prog="ds_ssh")
    parser.add_argument("--hostfile", "-f", default=DLTS_HOSTFILE)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("need a command")
    hosts = list(parse_hostfile(args.hostfile))
    procs = {h: subprocess.Popen(["ssh", h] + args.command,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
             for h in hosts}
    rc = 0
    for h, p in procs.items():
        out, _ = p.communicate()
        print(f"----- {h} (rc={p.returncode})")
        sys.stdout.write(out.decode(errors="replace"))
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
