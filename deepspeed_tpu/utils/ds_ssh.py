"""``ds_ssh`` — run a command on every hostfile host.

Reference: ``bin/ds_ssh`` [K]: parallel-ssh a shell command across the
hostfile (ops convenience for pod management).

One hung host must not block the whole fan-out (ISSUE 11 satellite):
each host gets a per-host ``--timeout``; a host that blows it is
killed, reported with ``rc=timeout``, and listed explicitly in the
summary line — the command still returns nonzero so scripts notice.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import time
from typing import List

from ..launcher.runner import DLTS_HOSTFILE, parse_hostfile

#: rc reported for a host that exceeded the per-host timeout (the
#: shell convention for "timed out", distinct from any ssh rc)
TIMEOUT_RC = 124


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(prog="ds_ssh")
    parser.add_argument("--hostfile", "-f", default=DLTS_HOSTFILE)
    parser.add_argument("--timeout", "-t", type=float, default=120.0,
                        help="per-host timeout in seconds; a host that "
                             "exceeds it is killed and reported as "
                             "timed out (<= 0 waits forever)")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("need a command")
    hosts = list(parse_hostfile(args.hostfile))
    procs = {h: subprocess.Popen(["ssh", h] + args.command,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
             for h in hosts}
    rc = 0
    timed_out: List[str] = []
    # ONE shared deadline from spawn: the processes all run in
    # parallel, so a pod of uniformly-hung hosts must cost ~one
    # timeout total, not hosts x timeout sequentially
    deadline = time.monotonic() + args.timeout if args.timeout > 0 \
        else None
    for h, p in procs.items():
        try:
            remaining = None
            if deadline is not None:
                remaining = max(deadline - time.monotonic(), 0.1)
            out, _ = p.communicate(timeout=remaining)
            host_rc = p.returncode
        except subprocess.TimeoutExpired:
            # kill + reap: a wedged ssh must not leak, and the next
            # host's communicate() must not inherit the stall
            p.kill()
            out, _ = p.communicate()
            host_rc = TIMEOUT_RC
            timed_out.append(h)
        print(f"----- {h} (rc={'timeout' if h in timed_out else host_rc})")
        sys.stdout.write((out or b"").decode(errors="replace"))
        rc = rc or host_rc
    if timed_out:
        print(f"----- TIMED OUT after {args.timeout:.0f}s: "
              f"{', '.join(timed_out)}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
