"""set_z3_leaf_modules — ZeRO-3 gather-granularity hints.

Reference [L ACC-DC:1538]: marks MoE blocks so ZeRO-3 gathers the whole
block at once (the hook prefetcher can't see through data-dependent expert
routing).  Under GSPMD there IS no gather state machine — XLA schedules
all-gathers from the dataflow graph, routing included — so the hint has no
work to do; it is kept for API/config parity and records the request.
"""

from __future__ import annotations

from typing import Any, List

from .logging import logger

_LEAF_MODULES: List[Any] = []


def set_z3_leaf_modules(model: Any, leaf_module_classes: List[Any],
                        raise_if_not_found: bool = True) -> List[Any]:
    _LEAF_MODULES.extend(leaf_module_classes)
    logger.info(
        f"set_z3_leaf_modules({[getattr(c, '__name__', c) for c in leaf_module_classes]}): "
        "no-op on TPU — GSPMD schedules gathers from dataflow, MoE included")
    return []


def get_z3_leaf_modules(model: Any = None) -> List[Any]:
    return list(_LEAF_MODULES)


def z3_leaf_module(model: Any) -> bool:
    return False
