"""NUMA / CPU-affinity helpers for the host-side optimizer.

Reference: ``deepspeed/utils/numa.py`` [K]: parses the NUMA topology and
pins launcher worker processes to cores so CPU-Adam's OpenMP threads
don't migrate across sockets (ZeRO-Offload throughput on multi-socket
hosts).  Same role here for the C++ host optimizer
(``csrc/adam/cpu_adam.cpp``, OpenMP).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from .logging import logger


def get_numa_nodes() -> Dict[int, List[int]]:
    """{numa_node: [cpu, ...]} from sysfs; single node 0 when absent."""
    base = "/sys/devices/system/node"
    nodes: Dict[int, List[int]] = {}
    if os.path.isdir(base):
        for entry in sorted(os.listdir(base)):
            if not entry.startswith("node"):
                continue
            try:
                nid = int(entry[4:])
            except ValueError:
                continue
            cpus: List[int] = []
            cpulist = os.path.join(base, entry, "cpulist")
            if os.path.exists(cpulist):
                with open(cpulist) as f:
                    for part in f.read().strip().split(","):
                        if "-" in part:
                            a, b = part.split("-")
                            cpus.extend(range(int(a), int(b) + 1))
                        elif part:
                            cpus.append(int(part))
            nodes[nid] = cpus
    if not nodes:
        nodes[0] = list(range(os.cpu_count() or 1))
    return nodes


def pin_to_numa_node(node: Optional[int] = None,
                     local_rank: int = 0) -> List[int]:
    """Affinity-pin this process to one NUMA node's cores (round-robin by
    ``local_rank`` when ``node`` is None).  Returns the core list; also
    sizes OMP threads to the allocation so CPU-Adam doesn't oversubscribe."""
    nodes = get_numa_nodes()
    if node is None:
        node = sorted(nodes)[local_rank % len(nodes)]
    cores = nodes.get(node) or nodes[sorted(nodes)[0]]
    try:
        os.sched_setaffinity(0, cores)
        os.environ.setdefault("OMP_NUM_THREADS", str(len(cores)))
        logger.info(f"pinned to NUMA node {node}: {len(cores)} cores")
    except (AttributeError, OSError) as e:  # non-linux / containers
        logger.warning(f"NUMA pinning unavailable: {e}")
    return cores
