from .logging import log_dist, logger, set_log_level
from .timer import SynchronizedWallClockTimer, ThroughputTimer

__all__ = ["logger", "log_dist", "set_log_level",
           "SynchronizedWallClockTimer", "ThroughputTimer"]
