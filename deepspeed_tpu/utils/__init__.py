from .init_on_device import OnDevice
from .logging import log_dist, logger, set_log_level
from .memory import memory_status, see_memory_usage
from .nvtx import instrument_w_nvtx
from .timer import SynchronizedWallClockTimer, ThroughputTimer
from .z3_leaf_module import (get_z3_leaf_modules, set_z3_leaf_modules,
                             z3_leaf_module)

__all__ = ["logger", "log_dist", "set_log_level",
           "SynchronizedWallClockTimer", "ThroughputTimer", "OnDevice",
           "set_z3_leaf_modules", "get_z3_leaf_modules", "z3_leaf_module",
           "see_memory_usage", "memory_status", "instrument_w_nvtx"]
