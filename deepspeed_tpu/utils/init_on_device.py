"""OnDevice — abstract ("meta") model construction.

Reference: ``deepspeed/utils/init_on_device.py`` [K] — ``OnDevice(dtype,
device="meta")`` builds torch modules without allocating storage.  JAX has
this natively as ``jax.eval_shape``; the context exposes it under the
reference name.
"""

from __future__ import annotations

from typing import Any, Callable

import jax


class OnDevice:
    def __init__(self, dtype: Any = None, device: str = "meta",
                 enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled

    def __enter__(self) -> "OnDevice":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def abstract(self, init_fn: Callable[..., Any], *args) -> Any:
        """ShapeDtypeStruct pytree — zero bytes allocated."""
        if not self.enabled:
            return init_fn(*args)
        return jax.eval_shape(init_fn, *args)
