"""Version-portable wrappers over jax APIs that moved between releases.

The codebase is written against the jax >= 0.9 surface (``jax.shard_map``
with ``axis_names=``/``check_vma=``); older installs (0.4.x) carry the
same capability as ``jax.experimental.shard_map.shard_map`` with the
inverse knobs (``auto=`` lists the axes that STAY automatic instead of
``axis_names=`` listing the manual ones, and replication checking is
``check_rep=``).  Import ``shard_map`` from here everywhere so one
translation covers both.
"""

from __future__ import annotations

from typing import Any, Optional, Set

import jax

if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs,
                  axis_names: Optional[Set[Any]] = None,
                  check_vma: bool = False):
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(f, **kw)

else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs,
                  axis_names: Optional[Set[Any]] = None,
                  check_vma: bool = False):
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - set(axis_names)
            if auto:
                kw["auto"] = auto
        return _shard_map(f, **kw)


def partial_manual_shard_map_ok() -> bool:
    """Whether this jax/jaxlib can compile PARTIAL-manual ``shard_map``
    (manual over a subset of axes) when some AUTO axis has size > 1.
    jaxlib 0.4.x CHECK-fails in the SPMD partitioner on that combination
    (``spmd_partitioner.cc: target.IsManualSubgroup() ==
    sharding().IsManualSubgroup()``) — an uncatchable process abort, so
    tests exercising those paths (Ulysses/ring SP, 1F1B pipeline + dp)
    must skip rather than crash the suite.  Size-1 auto axes are fine
    everywhere."""
    return hasattr(jax, "shard_map")


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` (size of a named mesh axis at the current
    trace point) for releases that predate it: a psum of 1 over the axis
    is statically evaluated to the same number."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    # a psum of the literal 1 is an axis-SIZE query the partitioner folds
    # to a constant, not data movement — and this shim sits BELOW comm/
    # in the import graph, so it cannot route through the comm verbs
    return jax.lax.psum(1, axis_name)  # dslint: disable=raw-collective


def abstract_mesh_or_none():
    """The context AbstractMesh (inside ``jax.set_mesh``/``shard_map``
    scopes) on jax >= 0.7; None on releases without the concept — callers
    fall back to their concrete mesh."""
    try:
        return jax.sharding.get_abstract_mesh()
    except AttributeError:
        return None


def current_manual_axes() -> Set[Any]:
    """Mesh axes that are MANUAL at the current trace point (we are inside
    a ``shard_map`` over them).  jax >= 0.7 exposes this on the abstract
    mesh; 0.4.x carries the same information in the axis environment."""
    try:
        am = jax.sharding.get_abstract_mesh()
    except AttributeError:
        am = None
    if am is not None:
        return set(getattr(am, "manual_axes", ()) or ())
    try:
        from jax._src.core import get_axis_env

        return set(get_axis_env().axis_sizes)
    except Exception:
        return set()


def live_arrays():
    """``jax.live_arrays()`` — every live array the client tracks —
    across releases; ``[]`` when the introspection API is absent (the
    memory plane then reports device/host stats only)."""
    try:
        return list(jax.live_arrays())
    except Exception as e:  # API drift across jax releases
        from .logging import debug_once

        debug_once("jax_compat/live_arrays",
                   f"jax.live_arrays unavailable ({e!r})")
        return []


def ckpt_metadata_tree(loader, path):
    """Orbax moved checkpoint metadata between releases: newer
    StandardCheckpointer returns an object with ``.item_metadata.tree``,
    older ones hand back the tree (dict) directly."""
    meta = loader.metadata(path)
    im = getattr(meta, "item_metadata", None)
    if im is not None:
        return im.tree
    tree = getattr(meta, "tree", None)
    if tree is not None:
        return tree
    return meta
