"""Global registry of the active mesh and parallel-group handles.

Capability parity with the reference ``deepspeed/utils/groups.py`` [K] (the
DP/TP/PP/EP/SP process-group registry; verified public names
``_get_sequence_parallel_group/_world_size/_rank`` at ACC:2492-2496 [L]).

On TPU a "process group" is a (mesh, axis-names) pair: collectives along the
group are expressed as PartitionSpecs or ``shard_map`` axis names instead of
rank lists.  ``MeshAxisGroup`` carries enough for both the in-graph use (axis
names) and host-side bookkeeping (sizes, per-process rank).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh

from ..parallel.mesh import (AXIS_DATA, AXIS_EXPERT, AXIS_PIPE, AXIS_SEQ,
                             AXIS_TENSOR, DP_AXES, MeshLayout, build_mesh)


@dataclasses.dataclass(frozen=True)
class MeshAxisGroup:
    """A parallel group = one or more named mesh axes."""

    mesh: Mesh
    axes: Tuple[str, ...]

    @property
    def size(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.axes]))

    def axis_name(self) -> Union[str, Tuple[str, ...]]:
        """The axis-name payload for jax.lax collectives inside shard_map."""
        return self.axes if len(self.axes) > 1 else self.axes[0]

    def rank_of_process(self) -> int:
        """Best-effort group rank of *this process* (multihost: derived from
        the first local device's mesh coordinate). In single-process mode with
        N local devices this is always 0; in-graph code should use
        ``jax.lax.axis_index`` instead."""
        local = jax.local_devices()[0]
        idx = np.argwhere(self.mesh.devices == local)
        if idx.size == 0:
            return 0
        coord = idx[0]
        rank = 0
        for a in self.axes:
            i = self.mesh.axis_names.index(a)
            rank = rank * self.mesh.shape[a] + int(coord[i])
        return rank


class _GroupRegistry:
    def __init__(self) -> None:
        self.mesh: Optional[Mesh] = None
        self.layout: Optional[MeshLayout] = None

    def initialize(self, layout: Optional[MeshLayout] = None,
                   mesh: Optional[Mesh] = None) -> Mesh:
        if mesh is None:
            mesh = build_mesh(layout)
        self.mesh = mesh
        self.layout = layout or MeshLayout(
            pp=mesh.shape[AXIS_PIPE], ep=mesh.shape[AXIS_EXPERT],
            dp=mesh.shape[AXIS_DATA], sp=mesh.shape[AXIS_SEQ],
            tp=mesh.shape[AXIS_TENSOR])
        return mesh

    def reset(self) -> None:
        self.mesh = None
        self.layout = None

    def require_mesh(self) -> Mesh:
        if self.mesh is None:
            self.initialize()
        return self.mesh  # type: ignore[return-value]


_REGISTRY = _GroupRegistry()


def initialize_mesh(layout: Optional[MeshLayout] = None,
                    mesh: Optional[Mesh] = None) -> Mesh:
    return _REGISTRY.initialize(layout, mesh)


def reset_mesh() -> None:
    _REGISTRY.reset()


def get_mesh() -> Mesh:
    return _REGISTRY.require_mesh()


def get_layout() -> MeshLayout:
    _REGISTRY.require_mesh()
    return _REGISTRY.layout  # type: ignore[return-value]


def _group(axes: Sequence[str]) -> MeshAxisGroup:
    return MeshAxisGroup(mesh=_REGISTRY.require_mesh(), axes=tuple(axes))


# -- public group getters (reference names, minus torch.distributed objects) --

def get_data_parallel_group() -> MeshAxisGroup:
    return _group(DP_AXES)


def get_data_parallel_world_size() -> int:
    return get_data_parallel_group().size


def get_model_parallel_group() -> MeshAxisGroup:
    return _group((AXIS_TENSOR,))


def get_tensor_model_parallel_world_size() -> int:
    return get_model_parallel_group().size


def get_pipe_parallel_group() -> MeshAxisGroup:
    return _group((AXIS_PIPE,))


def get_expert_parallel_group() -> MeshAxisGroup:
    return _group((AXIS_EXPERT,))


def get_expert_parallel_world_size() -> int:
    return get_expert_parallel_group().size


# Sequence-parallel getters — the exact names accelerate/HF import [L ACC:2492].
def _get_sequence_parallel_group() -> MeshAxisGroup:
    return _group((AXIS_SEQ,))


def _get_sequence_parallel_world_size() -> int:
    return _get_sequence_parallel_group().size


def _get_sequence_parallel_rank() -> int:
    return _get_sequence_parallel_group().rank_of_process()


get_sequence_parallel_group = _get_sequence_parallel_group
get_sequence_parallel_world_size = _get_sequence_parallel_world_size
get_sequence_parallel_rank = _get_sequence_parallel_rank
