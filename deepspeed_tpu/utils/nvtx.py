"""Profiler range annotation — ``instrument_w_nvtx`` parity.

Reference: ``deepspeed/utils/nvtx.py`` [K]: decorates hot functions with
NVTX ranges for nsight.  TPU equivalent (SURVEY §5.1): ``jax.profiler``
trace annotations — the named range shows up in xprof/tensorboard traces
around both the host call and the device ops it dispatches.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax


def instrument_w_nvtx(func: Callable) -> Callable:
    """Decorator: wrap ``func`` in a named profiler range (reference name
    kept so call sites port verbatim)."""

    @functools.wraps(func)
    def wrapped(*args: Any, **kwargs: Any):
        with jax.profiler.TraceAnnotation(func.__qualname__):
            with jax.named_scope(func.__qualname__):
                return func(*args, **kwargs)

    return wrapped


def range_push(name: str):
    """Manual range begin (reference ``nvtx.range_push`` role)."""
    ann = jax.profiler.TraceAnnotation(name)
    ann.__enter__()
    return ann


def range_pop(ann) -> None:
    ann.__exit__(None, None, None)
