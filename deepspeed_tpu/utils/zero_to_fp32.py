"""zero_to_fp32 — consolidate a sharded checkpoint into plain fp32 arrays.

Reference: ``deepspeed/utils/zero_to_fp32.py`` [K] — the offline tool shipped
INTO every checkpoint dir that merges ZeRO shards into a single fp32
state_dict [L trainer.py:4218].  Orbax stores logical (unsharded) arrays, so
"consolidation" here is a restore-without-mesh + dtype cast — resumable from
ANY source mesh layout (the universal-checkpoint capability, SURVEY §5.4).
"""

from __future__ import annotations

import argparse
import os
import pickle
from typing import Any, Dict, Optional

import jax
import numpy as np
from .jax_compat import ckpt_metadata_tree


def path_key(path) -> str:
    """Canonical '/'-joined key for a pytree path (GetAttrKey / DictKey /
    SequenceKey all covered) — ONE implementation shared by every
    checkpoint-export tool so converter and loader can never disagree."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def resolve_tag(checkpoint_dir: str, tag: Optional[str]) -> str:
    """'latest' file, else newest global_step* dir (shared by every
    offline checkpoint tool)."""
    if tag is not None:
        return tag
    latest = os.path.join(checkpoint_dir, "latest")
    if os.path.exists(latest):
        with open(latest) as f:
            return f.read().strip()
    candidates = sorted(d for d in os.listdir(checkpoint_dir)
                        if d.startswith("global_step"))
    if not candidates:
        raise FileNotFoundError(
            f"no global_step* checkpoint under {checkpoint_dir}")
    return candidates[-1]


def restore_saved_state(checkpoint_dir: str, tag: Optional[str] = None):
    """Mesh-free host restore of a saved engine TrainState; returns
    (state, tag)."""
    import orbax.checkpoint as ocp

    tag = resolve_tag(checkpoint_dir, tag)
    state_path = os.path.join(checkpoint_dir, tag, "state")
    with ocp.StandardCheckpointer() as loader:
        meta = ckpt_metadata_tree(loader, state_path)
        target = jax.tree.map(
            lambda am: jax.ShapeDtypeStruct(tuple(am.shape), am.dtype), meta)
        return loader.restore(state_path, target), tag


def get_fp32_state_dict_from_zero_checkpoint(
        checkpoint_dir: str, tag: Optional[str] = None) -> Dict[str, Any]:
    """Load the params subtree of a saved engine state as host fp32 numpy,
    flattened to {'/'-joined path: array}."""
    restored, _ = restore_saved_state(checkpoint_dir, tag)
    params = restored["params"] if isinstance(restored, dict) else restored.params
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        flat[path_key(path)] = np.asarray(jax.device_get(leaf),
                                          dtype=np.float32)
    return flat


def convert_zero_checkpoint_to_fp32_state_dict(
        checkpoint_dir: str, output_file: str,
        tag: Optional[str] = None) -> None:
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    with open(output_file, "wb") as f:
        pickle.dump(sd, f)
    total = sum(v.size for v in sd.values())
    print(f"saved {len(sd)} tensors / {total:,} params to {output_file}")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--tag", default=None)
    a = p.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(a.checkpoint_dir,
                                               a.output_file, tag=a.tag)


if __name__ == "__main__":
    main()
