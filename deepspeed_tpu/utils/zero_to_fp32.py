"""zero_to_fp32 — consolidate a sharded checkpoint into plain fp32 arrays.

Reference: ``deepspeed/utils/zero_to_fp32.py`` [K] — the offline tool shipped
INTO every checkpoint dir that merges ZeRO shards into a single fp32
state_dict [L trainer.py:4218].  Orbax stores logical (unsharded) arrays, so
"consolidation" here is a restore-without-mesh + dtype cast — resumable from
ANY source mesh layout (the universal-checkpoint capability, SURVEY §5.4).
"""

from __future__ import annotations

import argparse
import os
import pickle
from typing import Any, Dict, Optional

import jax
import numpy as np


def get_fp32_state_dict_from_zero_checkpoint(
        checkpoint_dir: str, tag: Optional[str] = None) -> Dict[str, Any]:
    """Load the params subtree of a saved engine state as host fp32 numpy,
    flattened to {'/'-joined path: array}."""
    import orbax.checkpoint as ocp

    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip()
        else:
            candidates = sorted(
                d for d in os.listdir(checkpoint_dir)
                if d.startswith("global_step"))
            if not candidates:
                raise FileNotFoundError(
                    f"no global_step* checkpoint under {checkpoint_dir}")
            tag = candidates[-1]
    state_path = os.path.join(checkpoint_dir, tag, "state")
    with ocp.StandardCheckpointer() as loader:
        meta = loader.metadata(state_path).item_metadata.tree
        target = jax.tree.map(
            lambda am: jax.ShapeDtypeStruct(tuple(am.shape), am.dtype), meta)
        restored = loader.restore(state_path, target)
    params = restored["params"] if isinstance(restored, dict) else restored.params
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        flat[key] = np.asarray(jax.device_get(leaf), dtype=np.float32)
    return flat


def convert_zero_checkpoint_to_fp32_state_dict(
        checkpoint_dir: str, output_file: str,
        tag: Optional[str] = None) -> None:
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    with open(output_file, "wb") as f:
        pickle.dump(sd, f)
    total = sum(v.size for v in sd.values())
    print(f"saved {len(sd)} tensors / {total:,} params to {output_file}")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--tag", default=None)
    a = p.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(a.checkpoint_dir,
                                               a.output_file, tag=a.tag)


if __name__ == "__main__":
    main()
