"""Memory introspection — ``see_memory_usage`` parity.

Reference: ``deepspeed/runtime/utils.py:see_memory_usage(message, force)``
[K]: prints allocator stats at checkpoints in the engine lifecycle (the
single most-used debugging helper in reference issue reports).  TPU form:
per-device HBM stats from the runtime + host RSS/available from procfs.
"""

from __future__ import annotations

import os
from typing import Dict

import jax

from .logging import log_dist


def _host_memory() -> Dict[str, float]:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            info = {line.split(":")[0]: line.split()[1] for line in f}
        out["host_used_GB"] = (int(info["MemTotal"])
                               - int(info["MemAvailable"])) / 2 ** 20
        out["host_available_GB"] = int(info["MemAvailable"]) / 2 ** 20
    except (OSError, KeyError):
        pass
    try:
        with open(f"/proc/{os.getpid()}/statm") as f:
            rss_pages = int(f.read().split()[1])
        out["process_rss_GB"] = rss_pages * os.sysconf("SC_PAGE_SIZE") / 2 ** 30
    except (OSError, ValueError):
        pass
    return out


def memory_status() -> Dict[str, float]:
    """Device + host memory numbers (GB)."""
    out = _host_memory()
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        out["device_in_use_GB"] = stats.get("bytes_in_use", 0) / 2 ** 30
        out["device_limit_GB"] = stats.get("bytes_limit", 0) / 2 ** 30
        out["device_peak_GB"] = stats.get("peak_bytes_in_use", 0) / 2 ** 30
    except Exception as e:  # platforms without memory_stats (CPU, tunnels)
        from .logging import debug_once

        debug_once("memory/device_stats",
                   f"device memory_stats unavailable ({e!r}); "
                   f"reporting host memory only")
    return out


def see_memory_usage(message: str, force: bool = False) -> None:
    """Reference signature; logs device HBM + host memory at ``message``."""
    if not force:
        return
    s = memory_status()
    parts = [f"{k}={v:.2f}" for k, v in s.items()]
    log_dist(f"MEMSTATS {message} | " + " ".join(parts))
