"""Memory introspection — ``see_memory_usage`` parity.

Reference: ``deepspeed/runtime/utils.py:see_memory_usage(message, force)``
[K]: prints allocator stats at checkpoints in the engine lifecycle (the
single most-used debugging helper in reference issue reports).

Since the memory plane landed (``telemetry/memory/``) this module is a
thin veneer over the :class:`~..telemetry.memory.MemoryLedger`: BOTH
report the same numbers because both read the same account — the ledger
adds per-pool breakdowns (``pool_params_GB`` etc.) when it is enabled,
and honors the device-unresponsive latch so a dead TPU tunnel cannot
hang a memory print on a failure path.
"""

from __future__ import annotations

from typing import Dict

from .logging import log_dist


def memory_status() -> Dict[str, float]:
    """Device + host memory numbers (GB), via the memory ledger (plus
    per-pool ``pool_*_GB`` fields when the ledger is enabled)."""
    from ..telemetry.memory import get_memory_ledger

    return get_memory_ledger().status()


def see_memory_usage(message: str, force: bool = False) -> None:
    """Reference signature; logs device HBM + host memory at ``message``."""
    if not force:
        return
    s = memory_status()
    parts = [f"{k}={v:.2f}" for k, v in s.items()]
    log_dist(f"MEMSTATS {message} | " + " ".join(parts))
