"""ds_to_universal — convert a saved engine checkpoint to the UNIVERSAL
per-parameter layout.

Reference: ``deepspeed/checkpoint/ds_to_universal.py`` [K] (SURVEY §5.4) —
the shipped CLI that merges a parallelism-specific ZeRO checkpoint into one
directory per parameter holding canonical fp32 weights + optimizer moments,
loadable at ANY parallelism layout.

TPU-native mechanics: orbax already stores logical (unsharded) arrays, so
the conversion is a restore-without-mesh walk of the saved TrainState that
writes, per parameter path::

    <out>/zero/<param/path>/fp32.npy         fp32 master weight
    <out>/zero/<param/path>/exp_avg.npy      Adam first moment (when found)
    <out>/zero/<param/path>/exp_avg_sq.npy   Adam second moment (when found)
    <out>/universal_metadata.json            step + per-param shapes/dtypes

and ``load_universal_checkpoint`` (runtime/checkpointing.py) re-assembles
an engine's TrainState from those files under ANY mesh — each array lands
via ``jax.device_put`` onto the target state's shardings.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np


from .zero_to_fp32 import path_key as _path_key
from .zero_to_fp32 import restore_saved_state as _restore_state


def convert(checkpoint_dir: str, output_dir: str,
            tag: Optional[str] = None) -> Dict[str, Any]:
    """Write the universal layout; returns the metadata dict."""
    state, tag = _restore_state(checkpoint_dir, tag)
    params = state["params"] if isinstance(state, dict) else state.params
    opt_state = (state.get("opt_state") if isinstance(state, dict)
                 else getattr(state, "opt_state", None))
    step = state.get("step", 0) if isinstance(state, dict) else \
        getattr(state, "step", 0)

    flat_params = {
        _path_key(p): np.asarray(jax.device_get(l), np.float32)
        for p, l in jax.tree_util.tree_flatten_with_path(params)[0]}

    # Adam moments: optax's ScaleByAdamState mirrors the param tree under
    # leaves whose path contains 'mu' / 'nu'.  Match by path SUFFIX — the
    # optax chain prefix (tuple indices, state names) varies by config.
    moments: Dict[str, Dict[str, np.ndarray]] = {"mu": {}, "nu": {}}
    if opt_state is not None:
        for p, l in jax.tree_util.tree_flatten_with_path(opt_state)[0]:
            key = _path_key(p)
            parts = key.split("/")
            for field, name in (("mu", "mu"), ("nu", "nu")):
                if name in parts:
                    suffix = "/".join(parts[parts.index(name) + 1:])
                    if suffix in flat_params and np.shape(l) == np.shape(
                            flat_params[suffix]):
                        moments[field][suffix] = np.asarray(
                            jax.device_get(l), np.float32)

    zero_dir = os.path.join(output_dir, "zero")
    meta: Dict[str, Any] = {"step": int(np.asarray(step)),
                            "source_tag": tag, "params": {}}
    for key, arr in flat_params.items():
        pdir = os.path.join(zero_dir, key)
        os.makedirs(pdir, exist_ok=True)
        np.save(os.path.join(pdir, "fp32.npy"), arr)
        entry = {"shape": list(arr.shape), "has_moments": False}
        if key in moments["mu"] and key in moments["nu"]:
            np.save(os.path.join(pdir, "exp_avg.npy"), moments["mu"][key])
            np.save(os.path.join(pdir, "exp_avg_sq.npy"),
                    moments["nu"][key])
            entry["has_moments"] = True
        meta["params"][key] = entry
    os.makedirs(output_dir, exist_ok=True)
    with open(os.path.join(output_dir, "universal_metadata.json"),
              "w") as f:
        json.dump(meta, f, indent=2)
    return meta


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ds_to_universal",
        description="Convert a saved checkpoint to the universal "
                    "per-parameter fp32 layout")
    ap.add_argument("--input_folder", required=True)
    ap.add_argument("--output_folder", required=True)
    ap.add_argument("--tag", default=None)
    args = ap.parse_args(argv)
    meta = convert(args.input_folder, args.output_folder, args.tag)
    print(f"ds_to_universal: wrote {len(meta['params'])} params "
          f"(step {meta['step']}) to {args.output_folder}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
