"""``ds_io`` / ``ds_nvme_tune`` — AIO engine throughput benchmark.

Reference: ``bin/ds_io`` + ``bin/ds_nvme_tune`` [K]: sweep the async-I/O
engine's (block_size, queue_depth, threads) space against a target volume
and report read/write GB/s — how operators pick the ``aio`` config block
for ZeRO-Infinity NVMe offload.

Drives this repo's C++ engine (``csrc/aio/aio_engine.cpp`` via
``ops.aio.aio_handle``) against a scratch file.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List

import numpy as np


def _bench(path: str, nbytes: int, block_size: int, queue_depth: int,
           threads: int, trials: int) -> dict:
    from ..ops.aio import aio_handle

    handle = aio_handle(block_size=block_size, queue_depth=queue_depth,
                        single_submit=False, overlap_events=True,
                        thread_count=threads)
    buf = np.random.bytes(nbytes)
    arr = np.frombuffer(buf, np.uint8)

    t0 = time.perf_counter()
    for _ in range(trials):
        handle.sync_pwrite(arr, path)
    w = nbytes * trials / (time.perf_counter() - t0)

    out = np.empty(nbytes, np.uint8)
    t0 = time.perf_counter()
    for _ in range(trials):
        handle.sync_pread(out, path)
    r = nbytes * trials / (time.perf_counter() - t0)
    # AIO failures are async error COUNTS, not exceptions — verify the
    # round trip actually moved the bytes before reporting throughput
    if not (np.array_equal(out[:4096], arr[:4096])
            and np.array_equal(out[-4096:], arr[-4096:])):
        raise IOError(f"read-back mismatch on {path} (async I/O failed)")
    return {"write_GBps": w / 1e9, "read_GBps": r / 1e9}


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(prog="ds_io")
    parser.add_argument("--path", default="/tmp/ds_io_scratch.bin")
    parser.add_argument("--mb", type=int, default=64,
                        help="payload size in MiB")
    parser.add_argument("--trials", type=int, default=3)
    parser.add_argument("--sweep", action="store_true",
                        help="sweep block_size x queue_depth x threads "
                             "(ds_nvme_tune role)")
    parser.add_argument("--block_size", type=int, default=1 << 20)
    parser.add_argument("--queue_depth", type=int, default=8)
    parser.add_argument("--threads", type=int, default=4)
    args = parser.parse_args(argv)
    # the ds_nvme_tune alias IS the sweep (reference bin/ds_nvme_tune role)
    if "ds_nvme_tune" in os.path.basename(sys.argv[0] or ""):
        args.sweep = True

    nbytes = args.mb << 20
    combos = ([(bs, qd, th)
               for bs in (1 << 18, 1 << 20, 1 << 22)
               for qd in (4, 16)
               for th in (2, 8)]
              if args.sweep else
              [(args.block_size, args.queue_depth, args.threads)])
    print(f"{'block':>10} {'depth':>6} {'thr':>4} {'write':>10} {'read':>10}")
    best = None
    for bs, qd, th in combos:
        try:
            r = _bench(args.path, nbytes, bs, qd, th, args.trials)
        except Exception as e:
            print(f"{bs:>10} {qd:>6} {th:>4}  FAIL {e}")
            continue
        print(f"{bs:>10} {qd:>6} {th:>4} {r['write_GBps']:>9.2f}G "
              f"{r['read_GBps']:>9.2f}G")
        score = r["write_GBps"] + r["read_GBps"]
        if best is None or score > best[0]:
            best = (score, bs, qd, th)
    if best and args.sweep:
        print(f"best: block_size={best[1]} queue_depth={best[2]} "
              f"thread_count={best[3]}  → aio config block")
    try:
        os.unlink(args.path)
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
