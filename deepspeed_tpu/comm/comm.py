"""Communication backend — DeepSpeed-verb API over XLA collectives.

Capability parity with the reference ``deepspeed/comm/comm.py`` [K]: the
module-level verbs (``all_reduce``, ``all_gather``, ``reduce_scatter``,
``all_to_all_single``, ``broadcast``, ``barrier``, ``init_distributed``,
``get_rank``/``get_world_size``) plus the ``comms_logger`` timing wrapper that
the reference installs around every collective.

Design (TPU-first, NOT a NCCL translation):

* **In-graph collectives** (``psum``/``all_gather``/``psum_scatter``/
  ``all_to_all``/``ppermute``) are the real data plane.  They are thin named
  wrappers over ``jax.lax`` usable inside ``shard_map``; the wrapper exists so
  the comms logger can count/annotate them and so group handles
  (:class:`~deepspeed_tpu.utils.groups.MeshAxisGroup`) can be passed instead
  of raw axis names.  Inside ``jit`` XLA schedules and overlaps these on ICI —
  there is no bucketing/stream machinery to port because GSPMD owns it.

* **Eager verbs** mirror the reference's host-called API for code that is not
  inside a jitted step (checkpoint consolidation, debugging, tests).  They jit
  a ``shard_map`` of the matching lax collective over the group's mesh on the
  fly (cached per shape/dtype/group).

* **Control plane**: ``init_distributed`` maps to ``jax.distributed.initialize``
  (multi-host rendezvous — the NCCL/TCP-store equivalent); ``barrier`` uses a
  tiny device all-reduce, falling back to ``multihost_utils.sync_global_devices``.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from ..utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils import groups as groups_mod
from ..utils.groups import MeshAxisGroup
from ..utils.logging import logger

AxisName = Union[str, Tuple[str, ...]]

# ---------------------------------------------------------------------------
# ReduceOp — mirror of the reference's torch.distributed.ReduceOp surface.
# ---------------------------------------------------------------------------


class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


# ---------------------------------------------------------------------------
# comms logger (reference: deepspeed/comm/comm.py comms_logger + utils)
# ---------------------------------------------------------------------------


class CommsLogger:
    """Counts collective calls and (eager path) wall time per op name.

    Three surfaces, mirroring what can honestly be measured where:

    * eager verbs record at *execution* time (count/bytes/seconds real);
    * in-graph wrappers always record a *trace-time* census (structural
      collectives per compiled program — XLA runs without Python);
    * with ``exec_counts=True``, in-graph wrappers ALSO attach an
      effectful host callback that fires on every EXECUTION of the
      compiled program — ``exec_summary()`` counts scale with runs (a
      trace-time census cannot).  Counts are per LOCAL DEVICE SHARD per
      run (an 8-device mesh bumps a collective 8× per step; multi-host,
      each process counts its own shards) — ``exec_summary(per_step=
      True)`` normalizes by ``jax.local_device_count()``.  Opt-in: each
      callback is a device→host hop, meaningful overhead on
      remote/tunneled platforms — a diagnostics switch, like the
      reference's comms_logger.  Per-collective DEVICE timing still
      comes from ``profiling/collective_trace.py``.
    """

    def __init__(self) -> None:
        self.enabled = False
        self.verbose = False
        self.exec_counts = False
        self.stats: dict[str, dict[str, float]] = {}
        self.exec_stats: dict[str, dict[str, float]] = {}
        #: optional CollectiveLedger (telemetry/collective_ledger.py) fed
        #: INDEPENDENTLY of `enabled` — desync forensics must not depend
        #: on the stats logger being switched on.  Attached via
        #: telemetry.collective_ledger.attach_collective_ledger().
        self.ledger = None
        import threading

        self._exec_lock = threading.Lock()

    def configure(self, enabled: bool = True, verbose: bool = False,
                  exec_counts: bool = False) -> None:
        self.enabled = enabled
        self.verbose = verbose
        self.exec_counts = exec_counts

    def record(self, name: str, nbytes: int, seconds: float = 0.0) -> None:
        led = self.ledger
        if led is not None:
            # call-site order is deterministic per host (identical
            # programs issue identical sequences), which is what makes
            # cross-rank ledger comparison meaningful
            led.record(name, nbytes, source="census")
        if not self.enabled:
            return
        entry = self.stats.setdefault(name, {"count": 0, "bytes": 0, "seconds": 0.0})
        entry["count"] += 1
        entry["bytes"] += nbytes
        entry["seconds"] += seconds
        if self.verbose:
            logger.info(f"comm: {name} bytes={nbytes} time={seconds * 1e3:.3f}ms")

    def record_exec(self, name: str, nbytes: int) -> None:
        # gate at EXECUTION time too: probes baked into already-compiled
        # programs must stop counting the moment the logger is disabled.
        # Locked: unordered debug callbacks may fire concurrently from
        # several device shards, and += is not atomic.
        led = self.ledger
        if led is not None and getattr(led, "exec_feed", False):
            # opt-in: execution probes fire from UNORDERED device
            # callbacks, so their interleaving is not comparable across
            # ranks — they land in the ledger's separate EXEC lane
            # (per-host sequence forensics), never in the census chain
            # the live desync detection hashes
            led.record_exec(name, nbytes, source="exec_probe")
        if not (self.enabled and self.exec_counts):
            return
        with self._exec_lock:
            entry = self.exec_stats.setdefault(name,
                                               {"count": 0, "bytes": 0})
            entry["count"] += 1
            entry["bytes"] += nbytes

    def attach_exec_probe(self, name: str, x) -> None:
        """Called from in-graph wrappers at trace time: plant an effectful
        callback that bumps ``exec_stats`` on every EXECUTION of the
        compiled program (jax.debug.callback is an effect, so it is
        neither DCE'd nor cached away).

        The enable decision is baked in at TRACE time: programs compiled
        while ``exec_counts`` was off carry no probe and are not
        retrofitted when it is later enabled (only the disable direction
        is dynamic, via the exec-time gate in :meth:`record_exec`).
        Configure ``exec_counts=True`` before first compile of anything
        you want counted — planting callbacks unconditionally would tax
        every program with a device→host hop even when diagnostics are
        off, the wrong default on tunneled platforms."""
        if not (self.enabled and self.exec_counts):
            return
        nbytes = _nbytes(x)
        jax.debug.callback(
            functools.partial(self.record_exec, name, nbytes))

    def summary(self) -> dict[str, dict[str, float]]:
        return self.stats

    def total_bytes(self) -> int:
        """Cumulative bytes over every call-site record (eager timing +
        trace-time census); the engine's StepRecord carries this so BENCH
        artifacts and the telemetry registry report one number.  Execution-
        probe bytes are a separate measure — see :meth:`exec_summary`."""
        return int(sum(e.get("bytes", 0) for e in self.stats.values()))

    def total_ops(self) -> int:
        return int(sum(e.get("count", 0) for e in self.stats.values()))

    #: class-wide: log the first effects_barrier failure only — the
    #: fallback (stale-by-one counts) is benign, but silence hid real
    #: backend breakage behind a bare `except: pass` for two rounds
    _barrier_logged = False

    def _flush_effects(self, where: str) -> None:
        """Flush in-flight debug callbacks; on failure keep the fallback
        (counts may lag by the in-flight runs) but say so ONCE at debug
        level instead of swallowing the exception bare."""
        try:
            jax.effects_barrier()
        except Exception as e:
            if not CommsLogger._barrier_logged:
                CommsLogger._barrier_logged = True
                logger.debug(
                    f"comms_logger: jax.effects_barrier() failed in {where} "
                    f"({e!r}); execution counts may lag in-flight runs")

    def exec_summary(self, per_step: bool = False
                     ) -> dict[str, dict[str, float]]:
        """Per-execution stats.  Raw counts are per LOCAL DEVICE SHARD per
        run (see class docstring); ``per_step=True`` returns a normalized
        copy — counts/bytes divided by ``jax.local_device_count()`` — so
        callers stop hand-dividing (the engine's StepRecord comm-exec
        fields use this path)."""
        # debug callbacks are asynchronous; flush in-flight effects so
        # the summary reflects every completed run
        self._flush_effects("exec_summary")
        if not per_step:
            return self.exec_stats
        n = max(1, jax.local_device_count())
        with self._exec_lock:
            snap = {name: dict(e) for name, e in self.exec_stats.items()}
        return {name: {k: v / n for k, v in e.items()}
                for name, e in snap.items()}

    def exec_totals(self, per_step: bool = False) -> Tuple[float, float]:
        """(ops, bytes) summed over every probed collective; normalized
        per local device shard when ``per_step``."""
        summary = self.exec_summary(per_step=per_step)
        ops = sum(e.get("count", 0) for e in summary.values())
        nbytes = sum(e.get("bytes", 0) for e in summary.values())
        return ops, nbytes

    def reset(self) -> None:
        self.stats = {}
        # flush in-flight callbacks first, or counts from PRE-reset
        # runs would land in the fresh dict after the swap
        self._flush_effects("reset")
        with self._exec_lock:
            # same lock the execution probes take: a concurrent callback
            # must not land its increment in an abandoned dict
            self.exec_stats = {}


comms_logger = CommsLogger()


def _nbytes(x: Any) -> int:
    try:
        return int(np.prod(np.shape(x))) * jnp.dtype(jnp.result_type(x)).itemsize
    except Exception:
        return 0


def _axis(group: Union[MeshAxisGroup, AxisName, None]) -> AxisName:
    if group is None:
        return groups_mod.get_data_parallel_group().axis_name()
    if isinstance(group, MeshAxisGroup):
        return group.axis_name()
    return group


# ---------------------------------------------------------------------------
# In-graph collectives — call these inside shard_map/jit.
# ---------------------------------------------------------------------------


def psum(x, group: Union[MeshAxisGroup, AxisName, None] = None):
    axis = _axis(group)
    comms_logger.record("psum", _nbytes(x))
    comms_logger.attach_exec_probe("psum", x)
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, group: Union[MeshAxisGroup, AxisName, None] = None):
    axis = _axis(group)
    comms_logger.record("pmean", _nbytes(x))
    comms_logger.attach_exec_probe("pmean", x)
    return jax.lax.pmean(x, axis_name=axis)


def pmax(x, group=None):
    comms_logger.record("pmax", _nbytes(x))
    comms_logger.attach_exec_probe("pmax", x)
    return jax.lax.pmax(x, axis_name=_axis(group))


def all_gather_in_graph(x, group=None, axis: int = 0, tiled: bool = True):
    comms_logger.record("all_gather", _nbytes(x))
    comms_logger.attach_exec_probe("all_gather", x)
    return jax.lax.all_gather(x, axis_name=_axis(group), axis=axis, tiled=tiled)


def reduce_scatter_in_graph(x, group=None, scatter_dimension: int = 0, tiled: bool = True):
    comms_logger.record("reduce_scatter", _nbytes(x))
    comms_logger.attach_exec_probe("reduce_scatter", x)
    return jax.lax.psum_scatter(
        x, axis_name=_axis(group), scatter_dimension=scatter_dimension, tiled=tiled)


def all_to_all_in_graph(x, group=None, split_axis: int = 0, concat_axis: int = 0,
                        tiled: bool = True):
    """Ulysses/MoE workhorse — first-class on ICI."""
    comms_logger.record("all_to_all", _nbytes(x))
    comms_logger.attach_exec_probe("all_to_all", x)
    return jax.lax.all_to_all(
        x, axis_name=_axis(group), split_axis=split_axis,
        concat_axis=concat_axis, tiled=tiled)


def ppermute(x, perm: Sequence[Tuple[int, int]], group=None):
    """Pipeline P2P: send/recv pairs as a collective-permute (ICI-native)."""
    comms_logger.record("ppermute", _nbytes(x))
    comms_logger.attach_exec_probe("ppermute", x)
    return jax.lax.ppermute(x, axis_name=_axis(group), perm=list(perm))


def axis_index(group=None):
    return jax.lax.axis_index(_axis(group))


# ---------------------------------------------------------------------------
# Eager verbs — the reference's host-called API shape.
# ---------------------------------------------------------------------------


def _group_or_dp(group) -> MeshAxisGroup:
    if isinstance(group, MeshAxisGroup):
        return group
    if group is None:
        return groups_mod.get_data_parallel_group()
    if isinstance(group, str):
        return MeshAxisGroup(mesh=groups_mod.get_mesh(), axes=(group,))
    return MeshAxisGroup(mesh=groups_mod.get_mesh(), axes=tuple(group))


@functools.lru_cache(maxsize=256)
def _eager_collective(kind: str, mesh: Mesh, axes: Tuple[str, ...],
                      shape: Tuple[int, ...], dtype: Any, extra: Any = None):
    """Build+cache a jitted shard_map collective over `axes` of `mesh`.

    The input is treated as sharded on its leading dim over `axes` (gather /
    reduce_scatter / all_to_all).  ``all_reduce`` shards the leading dim when
    it divides the group size; otherwise (scalars, odd shapes — e.g. the
    reference's loss averaging) it falls back to replicated semantics: the
    value is taken to be each rank's identical local tensor, so SUM returns
    value × group_size, matching ``torch.distributed.all_reduce`` of a
    replicated value."""
    axis_name = axes if len(axes) > 1 else axes[0]
    group_size = int(np.prod([mesh.shape[a] for a in axes]))
    sharded = PartitionSpec(axes)
    replicated = PartitionSpec()

    if kind == "all_reduce":
        op = extra
        divisible = len(shape) > 0 and shape[0] % group_size == 0
        spec = sharded if divisible else replicated

        def fn(x):
            if op == ReduceOp.SUM:
                return jax.lax.psum(x, axis_name)
            if op == ReduceOp.AVG:
                return jax.lax.pmean(x, axis_name)
            if op == ReduceOp.MAX:
                return jax.lax.pmax(x, axis_name)
            if op == ReduceOp.MIN:
                return jax.lax.pmin(x, axis_name)
            if op == ReduceOp.PROD:
                gathered = jax.lax.all_gather(x, axis_name, axis=0)
                return jnp.prod(gathered, axis=0)
            raise ValueError(f"unsupported reduce op {op}")

        return jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec,),
                                 out_specs=spec, check_vma=False))
    if kind == "all_gather":
        def fn(x):
            return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)

        return jax.jit(shard_map(fn, mesh=mesh, in_specs=(sharded,),
                                 out_specs=replicated, check_vma=False))
    if kind == "reduce_scatter":
        def fn(x):
            return jax.lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)

        return jax.jit(shard_map(fn, mesh=mesh, in_specs=(replicated,),
                                 out_specs=sharded, check_vma=False))
    if kind == "all_to_all":
        # torch all_to_all_single semantics: global leading dim indexes the
        # rank; each rank's local row is split into |group| chunks along the
        # next dim, chunk j goes to rank j. Globally: out[i, j·k:(j+1)·k] =
        # in[j, i·k:(i+1)·k].
        def fn(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=1,
                                      tiled=True)

        return jax.jit(shard_map(fn, mesh=mesh, in_specs=(sharded,),
                                 out_specs=sharded, check_vma=False))
    raise ValueError(kind)


def _timed(name: str, fn, x):
    t0 = time.perf_counter()
    out = fn(x)
    if comms_logger.enabled:
        # block_until_ready is a no-op on tunneled platforms (axon) — a
        # ONE-element fetch (device-side index, then host transfer of a
        # scalar) is the reliable execution fence
        jax.block_until_ready(out)
        leaf = jax.tree.leaves(out)[0]
        np.asarray(leaf[(0,) * getattr(leaf, "ndim", 0)])
        comms_logger.record(name, _nbytes(x), time.perf_counter() - t0)
    elif comms_logger.ledger is not None:
        # stats logger off: record() is a stats no-op but still feeds the
        # collective ledger (desync forensics must see eager verbs too);
        # no fence — timing is only honest when the logger is on.  Guarded
        # so the everything-off default stays zero-cost per call.
        comms_logger.record(name, _nbytes(x))
    return out


def all_reduce(tensor, op: str = ReduceOp.SUM, group=None):
    """Eager all-reduce across the group; returns the reduced array
    (functional — JAX arrays are immutable, unlike the reference's in-place)."""
    g = _group_or_dp(group)
    x = jnp.asarray(tensor)
    fn = _eager_collective("all_reduce", g.mesh, g.axes, x.shape,
                           jnp.result_type(x), op)
    return _timed("all_reduce", fn, x)


def all_gather(tensor, group=None):
    """Gather leading-dim shards across the group → replicated concat."""
    g = _group_or_dp(group)
    x = jnp.asarray(tensor)
    fn = _eager_collective("all_gather", g.mesh, g.axes, x.shape, jnp.result_type(x))
    return _timed("all_gather", fn, x)


# reference name: all_gather_into_tensor
all_gather_into_tensor = all_gather


def reduce_scatter(tensor, group=None):
    """Reduce a replicated tensor and scatter leading-dim shards."""
    g = _group_or_dp(group)
    x = jnp.asarray(tensor)
    fn = _eager_collective("reduce_scatter", g.mesh, g.axes, x.shape, jnp.result_type(x))
    return _timed("reduce_scatter", fn, x)


reduce_scatter_tensor = reduce_scatter


def all_to_all_single(tensor, group=None):
    g = _group_or_dp(group)
    x = jnp.asarray(tensor)
    fn = _eager_collective("all_to_all", g.mesh, g.axes, x.shape, jnp.result_type(x))
    return _timed("all_to_all_single", fn, x)


def broadcast(tensor, src: int = 0, group=None):
    """Replicate ``tensor``'s value from group-rank ``src`` to every rank.

    In single-controller JAX a host value is already consistent across the
    mesh; for multihost process-level broadcast we use multihost_utils."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(
            jnp.asarray(tensor), is_source=jax.process_index() == src)
    return jnp.asarray(tensor)


def barrier(group=None) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("deepspeed_tpu.comm.barrier")
    else:
        jax.effects_barrier()


#: per-tag monotonic round counters for monitored_barrier (each call on
#: the same tag is a fresh store key, so re-used tags never cross-talk)
_mon_barrier_seq: Dict[str, int] = {}
_mon_barrier_lock = threading.Lock()

#: the last monitored_barrier timeout, registered as flight-recorder
#: context ``monitored_barrier`` on first failure — the watchdog's hang
#: bundle then NAMES the ranks that never arrived
_mon_barrier_failure: Optional[Dict[str, Any]] = None


def _note_barrier_failure(doc: Dict[str, Any]) -> None:
    global _mon_barrier_failure
    first = _mon_barrier_failure is None
    _mon_barrier_failure = doc
    if first:
        try:
            from ..telemetry.flight_recorder import get_flight_recorder

            get_flight_recorder().register_context(
                "monitored_barrier", lambda: _mon_barrier_failure)
        except Exception as e:
            from ..utils.logging import debug_once

            debug_once("comm/mon_barrier_fr",
                       f"flight-recorder barrier context failed ({e!r})")


def monitored_barrier(group=None, timeout: float = 30.0,
                      tag: str = "default",
                      world: Optional[int] = None,
                      rank: Optional[int] = None,
                      store: Optional[Any] = None) -> None:
    """Barrier that, on timeout, NAMES the ranks that failed to arrive.

    The reference ``monitored_barrier`` is the debugging barrier: a hang
    inside a plain barrier says nothing; this one raises with the exact
    missing rank set.  With a rendezvous store (``store`` arg or
    ``DS_RDZV_ENDPOINT``), every rank appends its id under a per-round
    key and polls until all ``world`` ranks arrived — the timeout error
    lists whoever didn't make it, the collective ledger records the
    round either way, and the failure doc rides the watchdog's next
    flight-recorder bundle as context ``monitored_barrier``.  Without a
    store, multi-process falls back to ``sync_global_devices`` under a
    watchdog thread (a timeout is still detected, but the missing set is
    unknowable).  ``world``/``rank`` override process discovery for
    tests and out-of-band gangs."""
    world = int(world if world is not None else jax.process_count())
    rank = int(rank if rank is not None else jax.process_index())
    with _mon_barrier_lock:
        seq = _mon_barrier_seq.get(tag, 0) + 1
        _mon_barrier_seq[tag] = seq

    def _ledger(op: str) -> None:
        try:
            from ..telemetry.collective_ledger import get_collective_ledger

            get_collective_ledger().record(op, 0, source="barrier")
        except Exception as e:
            from ..utils.logging import debug_once

            debug_once("comm/mon_barrier_ledger",
                       f"barrier ledger record failed ({e!r})")

    if world <= 1 and store is None:
        jax.effects_barrier()
        _ledger(f"monitored_barrier:{tag}#{seq}")
        return

    if store is None:
        endpoint = os.environ.get("DS_RDZV_ENDPOINT")
        if endpoint:
            from ..elasticity.rendezvous import RendezvousClient

            store = RendezvousClient(endpoint)

    if store is not None:
        key = f"barrier/{tag}/{seq}"
        arrived = set(int(r) for r in store.append(key, rank))
        deadline = time.monotonic() + float(timeout)
        while len(arrived) < world and time.monotonic() < deadline:
            time.sleep(min(0.05, timeout / 20.0))
            got = store.get(key)
            if isinstance(got, list):
                arrived = set(int(r) for r in got)
        if len(arrived) >= world:
            _ledger(f"monitored_barrier:{tag}#{seq}")
            return
        missing = sorted(set(range(world)) - arrived)
        doc = {"tag": tag, "round": seq, "timeout_s": float(timeout),
               "world": world, "rank": rank,
               "arrived": sorted(arrived), "missing": missing,
               "ts": time.time()}
        _note_barrier_failure(doc)
        _ledger(f"monitored_barrier_timeout:{tag}#{seq}:"
                f"missing={','.join(map(str, missing))}")
        raise RuntimeError(
            f"monitored_barrier({tag!r} round {seq}) timed out after "
            f"{timeout}s: ranks {missing} never arrived "
            f"({len(arrived)}/{world} present)")

    # no store: the arrival set is unknowable — run the device barrier
    # under a watchdog thread so a hang still becomes a named timeout
    from jax.experimental import multihost_utils

    done = threading.Event()
    err: List[BaseException] = []

    def _sync() -> None:
        try:
            multihost_utils.sync_global_devices(
                f"deepspeed_tpu.comm.monitored_barrier:{tag}#{seq}")
        except BaseException as e:  # surfaced on the caller thread
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_sync, daemon=True,
                         name=f"ds-monitored-barrier-{tag}")
    t.start()
    if not done.wait(float(timeout)):
        doc = {"tag": tag, "round": seq, "timeout_s": float(timeout),
               "world": world, "rank": rank, "arrived": None,
               "missing": None, "ts": time.time()}
        _note_barrier_failure(doc)
        _ledger(f"monitored_barrier_timeout:{tag}#{seq}:missing=unknown")
        raise RuntimeError(
            f"monitored_barrier({tag!r} round {seq}) timed out after "
            f"{timeout}s (no rendezvous store — set DS_RDZV_ENDPOINT "
            f"to learn WHICH ranks were missing)")
    if err:
        raise err[0]
    _ledger(f"monitored_barrier:{tag}#{seq}")


# ---------------------------------------------------------------------------
# init / rank queries (reference: init_distributed + launcher env discovery)
# ---------------------------------------------------------------------------

_initialized = False


def is_initialized() -> bool:
    return _initialized


def init_distributed(dist_backend: str = "xla",
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout: Optional[int] = None,
                     auto_mpi_discovery: bool = True) -> None:
    """Multi-host rendezvous. Single-process (one TPU VM or local dev) is a
    no-op: all local chips are already visible to this controller.

    Env discovery mirrors the reference launcher contract: honors
    ``COORDINATOR_ADDRESS``/``MASTER_ADDR:MASTER_PORT``, ``WORLD_SIZE`` (as
    process count), ``RANK``.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if coordinator_address is None and os.environ.get("MASTER_ADDR"):
        coordinator_address = (f"{os.environ['MASTER_ADDR']}:"
                               f"{os.environ.get('MASTER_PORT', '12355')}")
    num_processes = num_processes or int(os.environ.get("WORLD_SIZE", "0")) or None
    process_id = process_id if process_id is not None else (
        int(os.environ["RANK"]) if "RANK" in os.environ else None)
    if coordinator_address and num_processes and num_processes > 1:
        try:
            # CPU backend: cross-process collectives need gloo (the test
            # substrate for multi-controller runs; TPU rides ICI/DCN and
            # ignores this).  Must be set before the backend exists.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception as e:
            # backend already up or knob absent — TPU path
            from ..utils.logging import debug_once

            debug_once("comm/gloo_knob",
                       f"jax_cpu_collectives_implementation not set "
                       f"({e!r}); TPU path or backend already built")
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _initialized = True


def get_rank(group=None) -> int:
    """Global rank of this controller within [0, get_world_size()).

    JAX is single-controller-per-host: one process drives many chips, so a
    per-chip rank does not exist on the host side.  We return the global id
    of the first local device — rank 0 on the lead host, a contiguous range
    start elsewhere — which keeps ``rank == 0`` gating (the dominant use)
    and ``0 <= rank < world_size`` correct.  In-graph code wanting a true
    per-shard rank must use :func:`axis_index`.
    """
    if group is None:
        return int(jax.local_devices()[0].id)
    return _group_or_dp(group).rank_of_process()


def get_world_size(group=None) -> int:
    if group is None:
        return jax.device_count()
    return _group_or_dp(group).size


def get_local_rank() -> int:
    return 0  # single controller per host; local chips are not separate ranks


def new_group(axes: Sequence[str]) -> MeshAxisGroup:
    """A 'new group' is just a named view over mesh axes — zero-cost."""
    return MeshAxisGroup(mesh=groups_mod.get_mesh(), axes=tuple(axes))
