"""Collective–compute overlap — chunked ring decompositions of
all-gather / reduce-scatter that XLA can hide behind the matmuls they
feed.

SNIPPETS.md [1]'s GSPMD pattern hands XLA the collectives automatically,
but a monolithic ``all-gather`` on the tensor or DP axis SERIALIZES
against the matmul that consumes it: nothing computes until the last
byte lands.  Decomposed into a ``ppermute`` ring at chunk granularity,
every step's transfer is independent of every other step's compute, so
the scheduler runs chunk *i*'s matmul while chunk *i+1* is in flight —
the classic Megatron/TE overlapped-GEMM recipe, built TPU-side from the
ICI-native collective-permute.

Everything routes through the :mod:`deepspeed_tpu.comm.comm` verbs
(``dist.ppermute`` / ``dist.axis_index``), so the CollectiveLedger
census sees every ring hop and the desync detector can compare them
across ranks — a raw ``jax.lax.ppermute`` here would be invisible to
forensics (and ``dslint``'s raw-collective rule rejects it).

All functions run INSIDE ``shard_map`` over manual mesh axes:

* :func:`ring_all_gather` — chunked AG (ZeRO-3 param gather).
* :func:`ring_reduce_scatter` — chunked RS (ZeRO-3 grad reduce).
* :func:`all_gather_matmul` — AG ∘ matmul with per-step compute
  (``[m_loc, K] @ [K, N] → [W·m_loc, N]``), the latency-hidden form.
* :func:`matmul_reduce_scatter` — matmul ∘ RS, the mirrored epilogue.

``chunks`` (the ``kernels.overlap_chunks`` tuning dimension) splits each
shard into that many ring payloads: more chunks → finer pipelining but
more per-hop latency; the PR-9 search plane owns the pick per (model,
mesh, device_kind).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jax_compat import axis_size as _axis_size
from . import comm as dist

AxisName = Union[str, Tuple[str, ...]]


def _axes_tuple(axes: AxisName) -> Tuple[str, ...]:
    return axes if isinstance(axes, tuple) else (axes,)


def _world(axes: AxisName) -> int:
    w = 1
    for a in _axes_tuple(axes):
        w *= int(_axis_size(a))
    return w


def _linear_index(axes: AxisName):
    """Row-major linear index over (possibly several) manual axes —
    matches how ``PartitionSpec((a, b))`` linearizes shards."""
    idx = jnp.int32(0)
    for a in _axes_tuple(axes):
        idx = idx * int(_axis_size(a)) + dist.axis_index(a)
    return idx


def _ring_perm(world: int) -> list:
    return [(i, (i + 1) % world) for i in range(world)]


def _split_chunks(x, chunks: int, axis: int):
    if chunks <= 1:
        return [x]
    n = x.shape[axis]
    if n % chunks:
        raise ValueError(
            f"overlap chunks={chunks} must divide the shard dim {n} "
            f"(axis {axis}) — pick a divisor (kernels.overlap_chunks)")
    return [jax.lax.slice_in_dim(x, c * (n // chunks), (c + 1) * (n // chunks),
                                 axis=axis) for c in range(chunks)]


def ring_all_gather(x, axes: AxisName, axis: int = 0, chunks: int = 1):
    """Chunked ring all-gather of ``x`` (this rank's shard) over manual
    ``axes`` → the concatenation ordered by rank along ``axis``.

    Equivalent to ``lax.all_gather(tiled=True)`` but emitted as W−1
    ``ppermute`` hops per chunk, so a consumer of shard *r* can start
    the moment hop |me−r| lands instead of after the full gather."""
    world = _world(axes)
    if world == 1:
        return x
    me = _linear_index(axes)
    perm = _ring_perm(world)
    shard = x.shape[axis]
    out_shape = list(x.shape)
    out_shape[axis] = shard * world
    pieces = _split_chunks(x, chunks, axis)
    sub = shard // len(pieces)
    out = jnp.zeros(tuple(out_shape), x.dtype)
    for ci, piece in enumerate(pieces):
        buf = piece
        for step in range(world):
            src = (me - step) % world          # whose shard buf holds now
            start = src * shard + ci * sub
            out = jax.lax.dynamic_update_slice_in_dim(out, buf, start,
                                                      axis=axis)
            if step + 1 < world:
                buf = dist.ppermute(buf, perm, axes)
    return out


def ring_reduce_scatter(x, axes: AxisName, axis: int = 0,
                        chunks: int = 1):
    """Chunked ring reduce-scatter: every rank holds a full partial ``x``;
    returns this rank's SUM-reduced shard along ``axis`` (the
    ``lax.psum_scatter(tiled=True)`` contract)."""
    world = _world(axes)
    if world == 1:
        return x
    me = _linear_index(axes)
    perm = _ring_perm(world)
    n = x.shape[axis]
    if n % world:
        raise ValueError(f"reduce_scatter dim {n} not divisible by "
                         f"group size {world}")
    shard = n // world

    def block(b, ci=0, sub=None, nsub=1):
        start = b * shard + ci * (shard // nsub)
        size = shard // nsub
        return jax.lax.dynamic_slice_in_dim(x, start, size, axis=axis)

    outs = []
    for ci in range(max(chunks, 1)):
        nsub = max(chunks, 1)
        if shard % nsub:
            raise ValueError(
                f"overlap chunks={chunks} must divide the output shard "
                f"dim {shard} (kernels.overlap_chunks)")
        # start at block (me + W - 1); after W-1 add-and-forward hops the
        # accumulator sitting at rank me covers block me with every
        # rank's contribution
        acc = block((me + world - 1) % world, ci, None, nsub)
        for step in range(1, world):
            acc = dist.ppermute(acc, perm, axes)
            acc = acc + block((me + world - 1 - step) % world, ci, None,
                              nsub)
        outs.append(acc)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=axis)


def all_gather_matmul(x, w, axes: AxisName, chunks: int = 1,
                      precision=None):
    """Latency-hidden ``all_gather(x) @ w``: ``x [m_loc, K]`` is this
    rank's row shard, ``w [K, N]`` is resident — each ring step matmuls
    the chunk it holds while the next hop is in flight, writing its rows
    of the ``[W·m_loc, N]`` result.  Output rows are ordered by rank
    (the ``all_gather(tiled=True) @ w`` contract)."""
    world = _world(axes)
    if world == 1:
        return jnp.dot(x, w, precision=precision,
                       preferred_element_type=x.dtype)
    me = _linear_index(axes)
    perm = _ring_perm(world)
    m_loc = x.shape[0]
    out = jnp.zeros((m_loc * world, w.shape[1]),
                    jnp.result_type(x.dtype, w.dtype))
    pieces = _split_chunks(x, chunks, 0)
    sub = m_loc // len(pieces)
    for ci, piece in enumerate(pieces):
        buf = piece
        for step in range(world):
            src = (me - step) % world
            y = jnp.dot(buf, w, precision=precision,
                        preferred_element_type=out.dtype)
            out = jax.lax.dynamic_update_slice_in_dim(
                out, y, src * m_loc + ci * sub, axis=0)
            if step + 1 < world:
                buf = dist.ppermute(buf, perm, axes)
    return out


def matmul_reduce_scatter(x, w, axes: AxisName, chunks: int = 1,
                          precision=None):
    """Latency-hidden ``psum_scatter(x @ w)``: ``x [m, K_loc]`` carries
    this rank's K shard (a partial product), output is this rank's row
    shard of the reduced ``[m, N]``.  The per-block matmul runs INSIDE
    the ring loop — block *b*'s dot is independent of block *b−1*'s hop,
    so the scheduler overlaps them (a single monolithic dot before the
    scatter would serialize)."""
    world = _world(axes)
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    if world == 1:
        return jnp.dot(x, w, precision=precision,
                       preferred_element_type=out_dtype)
    me = _linear_index(axes)
    perm = _ring_perm(world)
    m = x.shape[0]
    if m % world:
        raise ValueError(f"matmul_reduce_scatter rows {m} not divisible "
                         f"by group size {world}")
    shard = m // world
    nsub = max(int(chunks), 1)
    if shard % nsub:
        raise ValueError(
            f"overlap chunks={chunks} must divide the output shard dim "
            f"{shard} (kernels.overlap_chunks)")
    sub = shard // nsub

    def partial_y(b, ci):
        rows = jax.lax.dynamic_slice_in_dim(x, b * shard + ci * sub, sub,
                                            axis=0)
        return jnp.dot(rows, w, precision=precision,
                       preferred_element_type=out_dtype)

    outs = []
    for ci in range(nsub):
        acc = partial_y((me + world - 1) % world, ci)
        for step in range(1, world):
            acc = dist.ppermute(acc, perm, axes)
            acc = acc + partial_y((me + world - 1 - step) % world, ci)
        outs.append(acc)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


def staging_bytes(shape: Sequence[int], dtype: Any, chunks: int) -> int:
    """Bytes of ring staging buffers a decomposed collective keeps in
    flight (one chunk payload + the assembled output slot) — what the
    engine registers under the ledger's ``collective_scratch`` pool so
    ``peak_hbm_bytes`` gating and OOM forensics name the ring."""
    total = int(np.prod(list(shape))) * jnp.dtype(dtype).itemsize
    return total // max(int(chunks), 1)
