from .comm import (ReduceOp, all_gather, all_gather_in_graph,
                   all_gather_into_tensor, all_reduce, all_to_all_in_graph,
                   all_to_all_single, axis_index, barrier, broadcast,
                   comms_logger, get_local_rank, get_rank, get_world_size,
                   init_distributed, is_initialized, monitored_barrier,
                   new_group, pmax, pmean, ppermute, psum, reduce_scatter,
                   reduce_scatter_in_graph, reduce_scatter_tensor)

__all__ = [
    "ReduceOp", "all_gather", "all_gather_in_graph", "all_gather_into_tensor",
    "all_reduce", "all_to_all_in_graph", "all_to_all_single", "axis_index",
    "barrier", "broadcast", "comms_logger", "get_local_rank", "get_rank",
    "get_world_size", "init_distributed", "is_initialized",
    "monitored_barrier", "new_group", "pmax", "pmean", "ppermute", "psum",
    "reduce_scatter", "reduce_scatter_in_graph", "reduce_scatter_tensor",
]
