"""Block skip lattice + block-size tables — shared by every attention kernel.

The causal triangle and the sliding window are BLOCK-structured masks:
at kernel-block granularity they define a boolean ``[nq, nk]`` lattice of
live tiles.  Before this module, :mod:`flash_attention` derived its
causal k-loop bounds inline and :mod:`block_sparse_attention` tril'd its
layout inline — two skip implementations that could (and did) drift.
Now there is ONE lattice:

* :func:`live_lattice` — the host-side ``[nq, nk]`` live-tile grid for
  (causal, window); block-sparse intersects its ``SparsityConfig``
  layout with it (:func:`apply_lattice`), flash walks it directly.
* :func:`kv_block_bounds` / :func:`q_block_bounds` — the traced
  contiguous [lo, hi) loop bounds the RESIDENT kernels use (causal and
  window lattices are banded, so a contiguous range is exact).
* :func:`plan_q_live` / :func:`plan_k_live` — padded live-index plans
  (row-major / column-major) that drive the STREAMED kernels' scalar-
  prefetched gather ``index_map``s, the same machinery as the
  block-sparse gather forward.
* :func:`tile_keep` — the in-kernel ``[bq, bk]`` token mask for one
  tile (causal edge + window band + segment equality), shared by the
  flash forward, both flash backwards, and the block-sparse tile update
  so masking cannot drift between passes.

Block-size selection (:func:`auto_flash_blocks`) is seq-length-aware:
the 512-everywhere default that made flash merely break even at 8k
(BENCH_r04) loses VMEM headroom to the resident K/V planes as S grows —
the table steps tiles down where the measured crossover sits.
"""

from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: PER-PLANE element bound (S·d of K, same for V) for VMEM-resident
#: kernels; K+V together occupy up to 2x this.  2M elems/plane = 8 MiB
#: bf16 — inside a v5e core's VMEM alongside q/acc scratch (one bound
#: for flash AND block-sparse so their dispatch cannot disagree about
#: what "fits").
RESIDENT_VMEM_ELEMS = 2 * 1024 * 1024


def resident_fits(S: int, d: int) -> bool:
    """Whether a head's K/V planes fit the resident-kernel VMEM budget."""
    return S * d <= RESIDENT_VMEM_ELEMS


# ---------------------------------------------------------------------------
# block-size tables
# ---------------------------------------------------------------------------

#: (min_S·d_elems_exclusive → (block_q, block_k)) forward table,
#: measured on v5e at d=64/bf16: 512-tiles win on MXU occupancy up to 8k
#: (·64); past that the fp32 q/score/acc tiles compete with the resident
#: K/V planes — whose footprint is S·d, which is why the key is ELEMENTS
#: not raw S (a d=128 model hits the pressure point at half the S) —
#: and the scheduler stops double-buffering; smaller q tiles restore the
#: pipeline.  ``auto_flash_blocks`` walks this largest-bound-first.
_FWD_BLOCKS: Tuple[Tuple[int, Tuple[int, int]], ...] = (
    (16384 * 64, (256, 256)),   # S·d > 1M elems
    (8192 * 64, (256, 512)),    # 512k < S·d <= 1M
    (0, (512, 512)),            # S·d <= 512k
)

#: backward table: the dkv pass holds q/do/lse/Δ resident (O(S·d)) on
#: top of what the forward holds, so tiles cap earlier — the PR-5-era
#: guard was exactly ``S·d > 4096·64 → cap 256``, preserved here as the
#: 262k boundary.
_BWD_BLOCKS: Tuple[Tuple[int, Tuple[int, int]], ...] = (
    (8192 * 64, (128, 256)),    # S·d > 512k
    (4096 * 64, (256, 256)),    # 262k < S·d <= 512k
    (0, (512, 512)),            # S·d <= 262k
)


def fit_block(b: int, S: int) -> int:
    """Largest block <= ``b`` that divides S and keeps the (8, 128)
    sublane tiling legal (shared by forward/backward eligibility so the
    two dispatch sites cannot drift)."""
    b = min(b, S)
    while b >= 64 and (S % b or b % 8):
        b //= 2
    return b


def auto_flash_blocks(S: int, d: int, backward: bool = False
                      ) -> Tuple[int, int]:
    """VMEM-pressure-aware (block_q, block_k) for the flash kernels,
    keyed on S·d (the resident planes' footprint); callers pass explicit
    sizes (or the tuning plane's ``kernels.flash_block_*`` overrides) to
    bypass the table."""
    elems = S * max(d, 1)
    table = _BWD_BLOCKS if backward else _FWD_BLOCKS
    for min_elems, (bq, bk) in table:
        if elems > min_elems:  # the (0, ...) row matches any valid S·d
            return fit_block(bq, S), fit_block(bk, S)
    raise AssertionError(f"block table has no row for S·d = {elems}")


# ---------------------------------------------------------------------------
# the lattice itself
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def live_lattice(S: int, block_q: int, block_k: int, causal: bool,
                 window: Optional[int] = None) -> np.ndarray:
    """Host-side ``[nq, nk]`` bool — True where a (q-block, k-block) tile
    holds ANY unmasked (causal ∩ window) token pair.  This is the single
    source of truth for "which tiles exist": flash plans walk it,
    block-sparse intersects its layout with it."""
    nq, nk = S // block_q, S // block_k
    qi = np.arange(nq)
    kj = np.arange(nk)
    q_lo = qi[:, None] * block_q                   # first q pos of row
    q_hi = q_lo + block_q - 1                      # last q pos of row
    k_lo = kj[None, :] * block_k
    k_hi = k_lo + block_k - 1
    # a tile is live iff SOME (q, k) pair in it is unmasked; the q−k
    # values a tile can realize form the interval [q_lo−k_hi, q_hi−k_lo]
    live = np.ones((nq, nk), bool)
    if causal:
        live &= k_lo <= q_hi                       # ∃ pair with q−k ≥ 0
    if window is not None:
        live &= (q_lo - k_hi) < window             # ∃ pair with q−k < w
        if not causal:
            live &= (k_lo - q_hi) < window         # ∃ pair with k−q < w
    return live


def apply_lattice(layout: np.ndarray, causal: bool,
                  window: Optional[int] = None,
                  cb: int = 1) -> np.ndarray:
    """Intersect a ``[H, nb, nb]`` sparsity-cell layout with the causal/
    window lattice at CELL granularity — the block-sparse planner's skip
    source (replaces its inline tril).  ``window`` is TOKENS (the unit
    every other lattice function uses); ``cb`` is the cell size in
    tokens, so the cell lattice is computed over the token grid with
    cells as blocks (cb=1 keeps cells == tokens)."""
    lay = np.asarray(layout)
    H, nb, _ = lay.shape
    if not causal and window is None:
        return lay
    cb = max(int(cb), 1)
    lat = live_lattice(nb * cb, cb, cb, causal, window)
    return lay * lat[None].astype(lay.dtype)


def kv_block_bounds(qi, block_q: int, block_k: int, nk: int, causal: bool,
                    window: Optional[int] = None):
    """Traced [k0, nk_eff) k-block loop bounds for one q-block — the
    contiguous-range form of the lattice row (causal/window rows are
    banded so the range is exact).  Shared by the resident flash forward
    and its dq backward."""
    if causal:
        nk_eff = (qi * block_q + block_q + block_k - 1) // block_k
        nk_eff = jnp.minimum(nk_eff, nk)
    else:
        nk_eff = nk
    k0 = 0
    if window is not None:
        k0 = jnp.maximum(qi * block_q - (window - 1), 0) // block_k
        if not causal:
            nk_eff = jnp.minimum(
                nk_eff,
                (qi * block_q + block_q - 1 + window + block_k - 1)
                // block_k)
    return k0, nk_eff


def q_block_bounds(ki, block_q: int, block_k: int, nq: int, causal: bool,
                   window: Optional[int] = None):
    """Traced [q0, nq_eff) q-block bounds for one k-block (the dkv pass's
    transposed walk of the same lattice)."""
    q0 = (ki * block_k) // block_q if causal else 0
    nq_eff = nq
    if window is not None:
        nq_eff = jnp.minimum(
            nq, (ki * block_k + block_k - 1 + window + block_q - 1)
            // block_q)
        if not causal:
            q0 = jnp.maximum(ki * block_k - (window - 1), 0) // block_q
    return q0, nq_eff


# ---------------------------------------------------------------------------
# streamed-kernel plans (padded live-index lists over the lattice)
# ---------------------------------------------------------------------------

_PLAN_CACHE: OrderedDict = OrderedDict()
_PLAN_CACHE_MAX = 32


def _cached(key, build):
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        _PLAN_CACHE.move_to_end(key)
        return hit
    out = build()
    _PLAN_CACHE[key] = out
    while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
        _PLAN_CACHE.popitem(last=False)
    return out


def plan_q_live(S: int, block_q: int, block_k: int, causal: bool,
                window: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Row-major plan: per q-block, the list of live k-block ids —
    ``(idx [nq, L] int32, counts [nq] int32)`` with dead slots padded by
    the last live id (consecutive identical indices elide the re-DMA,
    the block-sparse gather trick).  Drives the streamed forward and the
    streamed dq backward."""
    def build():
        lat = live_lattice(S, block_q, block_k, causal, window)
        nq = lat.shape[0]
        lists = [np.nonzero(lat[qi])[0] for qi in range(nq)]
        L = max((len(l) for l in lists), default=1)
        L = max(L, 1)
        idx = np.zeros((nq, L), np.int32)
        counts = np.zeros((nq,), np.int32)
        for qi, live in enumerate(lists):
            counts[qi] = len(live)
            if len(live):
                idx[qi, :len(live)] = live
                idx[qi, len(live):] = live[-1]
        return idx, counts
    return _cached((S, block_q, block_k, causal, window, "q"), build)


def plan_k_live(S: int, block_q: int, block_k: int, causal: bool,
                window: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Column-major plan: per k-block, the live q-block ids — the
    streamed dk/dv backward's transposed walk."""
    def build():
        lat = live_lattice(S, block_q, block_k, causal, window)
        nk = lat.shape[1]
        lists = [np.nonzero(lat[:, kj])[0] for kj in range(nk)]
        L = max((len(l) for l in lists), default=1)
        L = max(L, 1)
        idx = np.zeros((nk, L), np.int32)
        counts = np.zeros((nk,), np.int32)
        for kj, live in enumerate(lists):
            counts[kj] = len(live)
            if len(live):
                idx[kj, :len(live)] = live
                idx[kj, len(live):] = live[-1]
        return idx, counts
    return _cached((S, block_q, block_k, causal, window, "k"), build)


# ---------------------------------------------------------------------------
# the in-kernel tile mask
# ---------------------------------------------------------------------------


def tile_keep(qi, kj, block_q: int, block_k: int, causal: bool,
              window: Optional[int] = None, q_seg=None, k_seg=None):
    """``[bq, bk]`` bool keep mask for tile (qi, kj): causal edge ∩
    window band ∩ segment equality.  ``q_seg [bq]`` / ``k_seg [bk]`` are
    this tile's segment-id slices (packed sequences / padding); None
    skips the segment term.  Returns None when nothing masks (the caller
    skips the where())."""
    need_pos = causal or window is not None
    keep = None
    if need_pos:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            keep = q_pos >= k_pos
        if window is not None:
            reach = ((q_pos - k_pos < window) if causal
                     else (q_pos - k_pos < window)
                     & (k_pos - q_pos < window))
            keep = reach if keep is None else keep & reach
    if q_seg is not None and k_seg is not None:
        seg = q_seg[:, None] == k_seg[None, :]
        keep = seg if keep is None else keep & seg
    return keep
