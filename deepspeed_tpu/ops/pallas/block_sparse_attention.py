"""Block-sparse attention Pallas kernel — skips dead k-blocks per head.

Role parity: the reference's Triton block-sparse kernels
(``csrc/sparse_attention`` + ``deepspeed/ops/sparse_attention`` [K],
SURVEY §2.2) execute only the key blocks a ``SparsityConfig`` layout marks
live; round 2 shipped layout semantics but ran DENSE masked attention
(VERDICT round-2 missing #4).  This kernel closes that gap the TPU way:

* Host-side planning coarsens the ``[nb, nb]`` cell layout to kernel-block
  granularity and emits, per (head, q-block), the list of LIVE k-block ids
  (scalar-prefetched to SMEM) plus each live tile's cell sub-layout.
* The kernel is the flash-attention skeleton (online softmax over a
  ``fori_loop``), but the loop runs over the live list only — work per
  q-block is O(live · block) instead of O(S) — and every tile applies its
  exact token mask, rebuilt from the cell sub-layout with two tiny 0/1
  expansion matmuls (a Mosaic-friendly ``kron``; reshape-merge lowering
  rejects the naive broadcast form).
* Fully-masked query rows produce 0 (matching the dense path's explicit
  zeroing), via ``where(l > 0, acc / l, 0)``.

Two TPU forwards, selected by shape (:func:`_select_fwd`): the
VMEM-resident kernel when a head's K/V fit VMEM (zero per-step transfer
— fastest at short/medium S), and the splash-style GATHER kernel
(:func:`_bs_gather_kernel`) beyond that bound: a (bh, q-block, live-s)
grid whose K/V ``BlockSpec`` index_map reads the scalar-prefetched live
list, so each step DMAs ONLY its live k-block — HBM traffic O(live),
VMEM O(block), sequence length unbounded.  (Round 3's dynamic-offset
``make_async_copy`` gather crashed Mosaic; a data-dependent index_map
is the supported way — the paged decode kernel gathers pages
identically.)

Backward (``custom_vjp``): a PALLAS kernel pair on TPU —
:func:`_bs_bwd_dq_kernel` walks each head's FLAT live-tile list
row-major (dq accumulates in VMEM, flushed by the data-dependent output
index_map at row boundaries), :func:`_bs_bwd_dkv_kernel` walks it
column-major (dk/dv flush at column boundaries; no scatter-add pass
exists).  Both grids are exactly the live-tile count (``_plan_flat``) —
no per-row max_live padding — so every layout, dense global rows
included, pays its true live area: measured 2.8x the dense vjp at
S=4096/bf16 BigBird cb=128 (live 0.26) on v5e.  Softmax stats ride from
the forward (lse output + saved o), the flash-backward recipe.  The jnp
forms (padded ``_sparse_bwd_tiles``, per-row-count
``_sparse_bwd_bucketed``) remain the interpret-mode backward and the
anchors the kernel numerics are tested against; mostly-live layouts at
materializable S still route to the dense masked vjp (at >0.5 live
there is no work to skip).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import lattice


# ---------------------------------------------------------------------------
# host-side planning
# ---------------------------------------------------------------------------

from collections import OrderedDict

_PLAN_CACHE: OrderedDict = OrderedDict()
_PLAN_CACHE_MAX = 16  # bounded: entries hold megabyte-scale cell tensors


def _plan(layout: np.ndarray, S: int, block_q: int, block_k: int,
          cb: int, causal: bool):
    """layout [H, nb, nb] → (idx [H, nq, max_live] int32,
    counts [H, nq] int32, cells [H, nq, max_live, qc, kc] int8)."""
    key = (layout.tobytes(), layout.shape, S, block_q, block_k, cb, causal)
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        _PLAN_CACHE.move_to_end(key)
        return hit
    H, nb, _ = layout.shape
    nq, nk = S // block_q, S // block_k
    qc, kc = block_q // cb, block_k // cb
    # the shared skip lattice (ops/pallas/lattice.py): cells the causal
    # triangle kills are dropped by the SAME rule flash uses
    lay = lattice.apply_lattice(layout.astype(np.int8), causal, cb=cb)
    lists = [[[] for _ in range(nq)] for _ in range(H)]
    for h in range(H):
        coarse = lay[h].reshape(nq, qc, nk, kc).any(axis=(1, 3))
        for qi in range(nq):
            lists[h][qi] = np.nonzero(coarse[qi])[0].tolist()
    max_live = max((len(l) for row in lists for l in row), default=1)
    max_live = max(max_live, 1)
    idx = np.zeros((H, nq, max_live), np.int32)
    counts = np.zeros((H, nq), np.int32)
    cells = np.zeros((H, nq, max_live, qc, kc), np.int8)
    for h in range(H):
        for qi in range(nq):
            live = lists[h][qi]
            counts[h, qi] = len(live)
            for s, kj in enumerate(live):
                idx[h, qi, s] = kj
                cells[h, qi, s] = lay[h, qi * qc:(qi + 1) * qc,
                                      kj * kc:(kj + 1) * kc]
            if live:
                # pad with the LAST live index: consecutive identical
                # block indices skip the re-DMA, so padded grid steps
                # cost ~nothing (they are masked by s < count anyway)
                idx[h, qi, len(live):] = live[-1]
    out = (idx, counts, cells)
    _PLAN_CACHE[key] = out
    while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
        _PLAN_CACHE.popitem(last=False)
    return out


def _plan_flat(layout: np.ndarray, S: int, block_q: int, block_k: int,
               cb: int, causal: bool, kmajor: bool = False):
    """FLAT tile list per head for the backward kernels: the (qi, kj)
    live pairs concatenated row-major (``kmajor=False``, dq pass) or
    column-major (``kmajor=True``, dk/dv pass).  Returns
    (qidx [H, T], kidx [H, T], cells [H, T, qc, kc], totals [H]) with
    T = max over heads of the true live-tile count — the grid walks
    EXACTLY the live tiles (no per-row max_live padding at all); heads
    with fewer tiles pad by repeating their last pair (DMA elided,
    compute masked by ``t < total``)."""
    key = (layout.tobytes(), layout.shape, S, block_q, block_k, cb,
           causal, "F", kmajor)
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        _PLAN_CACHE.move_to_end(key)
        return hit
    H, nb, _ = layout.shape
    nq, nk = S // block_q, S // block_k
    qc, kc = block_q // cb, block_k // cb
    lay = lattice.apply_lattice(layout.astype(np.int8), causal, cb=cb)
    pairs = []
    for h in range(H):
        coarse = lay[h].reshape(nq, qc, nk, kc).any(axis=(1, 3))
        qq, kk = np.nonzero(coarse)
        if kmajor:
            order = np.lexsort((qq, kk))
        else:
            order = np.lexsort((kk, qq))
        pairs.append((qq[order], kk[order]))
    T = max((len(p[0]) for p in pairs), default=1)
    T = max(T, 1)
    qidx = np.zeros((H, T), np.int32)
    kidx = np.zeros((H, T), np.int32)
    cells = np.zeros((H, T, qc, kc), np.int8)
    totals = np.zeros((H,), np.int32)
    for h, (qq, kk) in enumerate(pairs):
        n = len(qq)
        totals[h] = n
        if n:
            qidx[h, :n], kidx[h, :n] = qq, kk
            qidx[h, n:], kidx[h, n:] = qq[-1], kk[-1]
            for t in range(n):
                cells[h, t] = lay[h, qq[t] * qc:(qq[t] + 1) * qc,
                                  kk[t] * kc:(kk[t] + 1) * kc]
            cells[h, n:] = cells[h, n - 1]
    out = (qidx, kidx, cells, totals)
    _PLAN_CACHE[key] = out
    while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
        _PLAN_CACHE.popitem(last=False)
    return out


def _keep_tile(cell, kj, qi, *, block_q: int, block_k: int, cb: int,
               causal: bool):
    """[block_q, block_k] bool keep mask for one (qi, kj) tile from its
    cell-granular mask — shared by the forward online-softmax update and
    the backward dq/dkv kernels so masking cannot drift between passes."""
    qc, kc = block_q // cb, block_k // cb
    if qc == 1 and kc == 1:
        # kernel block == cell: a planned tile is live by construction,
        # so the mask is just causality — the SHARED lattice tile mask
        # (the rule flash uses), no kron expansion matmuls
        keep = lattice.tile_keep(qi, kj, block_q, block_k, causal)
        return keep if keep is not None else jnp.ones(
            (block_q, block_k), jnp.bool_)
    # 0/1 expansion matmuls: keep = R @ cell @ K (an in-kernel kron;
    # Mosaic rejects the naive broadcast+reshape-merge lowering)
    ri = jax.lax.broadcasted_iota(jnp.int32, (block_q, qc), 0) // cb
    rc = jax.lax.broadcasted_iota(jnp.int32, (block_q, qc), 1)
    R = (ri == rc).astype(jnp.float32)
    ki = jax.lax.broadcasted_iota(jnp.int32, (kc, block_k), 0)
    kcol = jax.lax.broadcasted_iota(jnp.int32, (kc, block_k), 1) // cb
    K = (ki == kcol).astype(jnp.float32)
    keep_f = jax.lax.dot_general(
        jax.lax.dot_general(R, cell, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32),
        K, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    keep = keep_f > 0.5
    causal_keep = lattice.tile_keep(qi, kj, block_q, block_k, causal)
    if causal_keep is not None:
        keep = keep & causal_keep
    return keep


def _tile_update(q, kblk, vblk, cell, kj, qi, m, l, acc, *,
                 block_q: int, block_k: int, cb: int, causal: bool):
    """ONE live tile's online-softmax update — shared by the resident
    (interpret) and gather (production) kernels so their numerics cannot
    drift.  ``q`` is pre-scaled fp32; returns (m', l', acc')."""
    keep = _keep_tile(cell, kj, qi, block_q=block_q, block_k=block_k,
                      cb=cb, causal=causal)
    s_mat = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
    s_mat = jnp.where(keep, s_mat, -1e30)
    m_new = jnp.maximum(m, jnp.max(s_mat, axis=-1))
    # explicit zeroing: a row whose every entry in this tile is masked
    # must not accumulate exp(-1e30 - (-1e30)) = 1 garbage
    p = jnp.where(keep, jnp.exp(s_mat - m_new[:, None]), 0.0)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[:, None] + jax.lax.dot_general(
        p, vblk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _bs_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, cells_ref, o_ref,
               lse_ref, *,
               block_q: int, block_k: int, cb: int, H: int, scale: float,
               causal: bool):
    """One grid step per (B·h, q-block); a ``fori_loop`` walks the LIVE
    k-block list, slicing each live block out of the VMEM-resident K/V.
    K/V are DMA'd once per ``bh`` (their block index is constant across
    the inner ``qi`` grid dim, so Pallas skips the re-fetch), and compute
    is O(live · block_k) per q-block instead of O(S).

    This kernel serves production traffic whenever a head's K/V fit the
    VMEM budget (see :func:`_select_fwd` — zero per-step transfer makes
    it fastest at short/medium S) and ALL interpret-mode runs.  Beyond
    the VMEM bound (S·d > ``_RESIDENT_VMEM_ELEMS`` per plane) the
    splash-style :func:`_bs_gather_kernel` takes over."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qi = pl.program_id(1)
    h_idx = jax.lax.rem(bh, H)
    qc, kc = block_q // cb, block_k // cb
    count = cnt_ref[h_idx, qi]
    d = q_ref.shape[-1]

    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(s, carry):
        m, l, acc = carry
        kj = idx_ref[h_idx, qi, s]
        kblk = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        cell = cells_ref[0, 0, s].astype(jnp.float32)  # [qc, kc]
        return _tile_update(q, kblk, vblk, cell, kj, qi, m, l, acc,
                            block_q=block_q, block_k=block_k, cb=cb,
                            causal=causal)

    m, l, acc = jax.lax.fori_loop(0, count, body, (m0, l0, acc0))
    l2 = l[:, None]
    o_ref[0] = jnp.where(l2 > 0, acc / jnp.where(l2 > 0, l2, 1.0),
                         0.0).astype(o_ref.dtype)
    # softmax stats for the kernel backward: p = exp(s - lse).  Fully
    # masked rows get +1e30 so the backward's exp underflows to exactly 0
    lse_ref[0, :, 0] = jnp.where(
        l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)), 1e30)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def _bs_gather_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, cells_ref,
                      o_ref, lse_ref, m_ref, l_ref, acc_ref, *,
                      block_q: int,
                      block_k: int, cb: int, H: int, scale: float,
                      causal: bool, max_live: int):
    """Splash-style GATHER forward: the grid walks (bh, q-block, live-s)
    and the K/V BlockSpec's scalar-prefetched ``index_map`` DMAs ONLY the
    live k-block for each step — HBM traffic is O(live · block_k) per
    q-block and VMEM holds one block, so S is unbounded by VMEM
    residency.  This is the Mosaic-safe realization of the round-3
    "splash gather" (dynamic-offset ``make_async_copy`` crashed the
    toolchain; a data-dependent ``index_map`` is exactly how the paged
    decode kernel already gathers pages, so it compiles).  Online-softmax
    state rides VMEM scratch across the s steps; padded steps (s ≥
    count) repeat the last live index so their DMA is skipped by Pallas'
    same-block elision and their compute by ``pl.when``."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qi = pl.program_id(1)
    s = pl.program_id(2)
    h_idx = jax.lax.rem(bh, H)
    count = cnt_ref[h_idx, qi]
    qc, kc = block_q // cb, block_k // cb
    d = q_ref.shape[-1]

    @pl.when(s == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(s < count)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale      # [block_q, d]
        kblk = k_ref[0].astype(jnp.float32)           # [block_k, d]
        vblk = v_ref[0].astype(jnp.float32)
        kj = idx_ref[h_idx, qi, s]
        cell = cells_ref[0, 0, 0].astype(jnp.float32)  # [qc, kc]
        m_new, l_new, acc_new = _tile_update(
            q, kblk, vblk, cell, kj, qi, m_ref[:, 0], l_ref[:, 0],
            acc_ref[...], block_q=block_q, block_k=block_k, cb=cb,
            causal=causal)
        m_ref[...] = m_new[:, None]
        l_ref[...] = l_new[:, None]
        acc_ref[...] = acc_new

    @pl.when(s == max_live - 1)
    def _finalize():
        l2 = l_ref[...]
        o_ref[0] = jnp.where(
            l2 > 0, acc_ref[...] / jnp.where(l2 > 0, l2, 1.0),
            0.0).astype(o_ref.dtype)
        m1, l1 = m_ref[:, 0], l_ref[:, 0]
        lse_ref[0, :, 0] = jnp.where(
            l1 > 0, m1 + jnp.log(jnp.where(l1 > 0, l1, 1.0)), 1e30)


def _bs_fwd_gather(q, k, v, layout_key, causal, block_q, block_k, cb,
                   interpret):
    """Forward via :func:`_bs_gather_kernel` (same contract as
    :func:`_bs_fwd`)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    layout = _layout_from_key(layout_key)
    B, S, h, d = q.shape
    H = layout.shape[0]
    idx, counts, cells = _plan(layout, S, block_q, block_k, cb, causal)
    max_live = idx.shape[2]
    nq = S // block_q
    qc, kc = block_q // cb, block_k // cb

    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    Hl = h if H == h else 1
    kern = functools.partial(_bs_gather_kernel, block_q=block_q,
                             block_k=block_k, cb=cb, H=Hl,
                             scale=1.0 / np.sqrt(d), causal=causal,
                             max_live=max_live)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * h, nq, max_live),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, s, idx, cnt: (bh, qi, 0)),
            # the splash gather: each grid step DMAs only ITS live block
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, s, idx, cnt:
                         (bh, idx[jax.lax.rem(bh, Hl), qi, s], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, s, idx, cnt:
                         (bh, idx[jax.lax.rem(bh, Hl), qi, s], 0)),
            pl.BlockSpec((1, 1, 1, qc, kc),
                         lambda bh, qi, s, idx, cnt:
                         (jax.lax.rem(bh, Hl), qi, s, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, s, idx, cnt: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qi, s, idx, cnt: (bh, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
                   jax.ShapeDtypeStruct((B * h, S, 1), jnp.float32)],
        interpret=bool(interpret),
    )(jnp.asarray(idx), jnp.asarray(counts), qr, kr, vr, jnp.asarray(cells))
    out = out.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return out, (q, k, v, out, lse)


def _dense_reference(q, k, v, layout, cb, causal):
    from ..sparse_attention import block_layout_to_token_mask

    lay = layout[0] if layout.shape[0] == 1 else layout
    mask = block_layout_to_token_mask(lay, cb, causal)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    m = mask[None] if mask.ndim == 3 else mask[None, None]
    s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    p = jnp.where(jnp.any(m, axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _norm_layout(layout: np.ndarray, h: int) -> np.ndarray:
    """→ [H, nb, nb] with H ∈ {1, num_heads} (shared layouts stay 1)."""
    layout = np.asarray(layout)
    if layout.ndim == 2:
        return layout[None]
    if layout.shape[0] != h:
        raise ValueError(f"per-head layout has {layout.shape[0]} heads, "
                         f"attention has {h}")
    return layout


#: PER-PLANE element bound (S·d of K, same for V) for the resident
#: kernel — ONE bound shared with flash (ops/pallas/lattice.py) so the
#: two kernel families cannot disagree about what "fits VMEM"
_RESIDENT_VMEM_ELEMS = lattice.RESIDENT_VMEM_ELEMS

#: measured kernel-overhead factor vs the dense fused-matmul path
#: (v5e, bf16, d=64, BigBird-style layouts): the tile loop wins when
#: ``1/(overhead · live) > 1``, and the fixed per-tile cost inflates the
#: factor at short S — which is exactly how BENCH_r04 lost at 4k
#: (``block_sparse_speedup_s4096 = 0.96``: near-dense coarsened layout
#: plus a 1.7x overhead floor).  (S_max, factor) pairs, first match.
_KERNEL_OVERHEAD_BY_S: Tuple[Tuple[int, float], ...] = (
    (2048, 2.2), (4096, 1.7), (8192, 1.4), (1 << 62, 1.3))


def _kernel_overhead(S: int) -> float:
    for cap, ov in _KERNEL_OVERHEAD_BY_S:
        if S <= cap:
            return ov
    return _KERNEL_OVERHEAD_BY_S[-1][1]


def dense_live_threshold(S: int) -> float:
    """Live fraction above which the dense masked path is expected to
    beat the tile kernel at this seq length — the CROSSOVER the
    auto-dispatch enforces, so the kernel never loses to its own
    fallback (a sub-1.0 ``block_sparse_speedup_*`` bench entry is a
    dispatch bug, not a tuning note)."""
    return min(1.0 / _kernel_overhead(S), 0.95)


def choose_impl(S: int, d: int, live_frac: float,
                interpret: bool = False) -> str:
    """The ONE forward dispatch contract: "dense" (the flash-class XLA
    fallback), "resident" (VMEM-resident tile kernel), or "gather"
    (splash-style streamed kernel).  Interpret mode always exercises a
    kernel; beyond ``_DENSE_DISPATCH_MAX_S`` the dense path's O(S²)
    logits stop being materializable regardless of live fraction."""
    if interpret:
        return ("resident" if S * d <= _RESIDENT_VMEM_ELEMS else "gather")
    if S <= _DENSE_DISPATCH_MAX_S and live_frac > dense_live_threshold(S):
        return "dense"
    if S * d <= _RESIDENT_VMEM_ELEMS:
        return "resident"
    return "gather"


def _bs_auto_block(S: int, cb: int) -> int:
    """Default kernel block for this seq length: cell-matched 128 at
    short/medium S (no live-coverage inflation, causality-only tile
    masks — measured 2.8x the dense vjp at S=4096); 256 at S≥8k where
    per-tile DMA latency starts to dominate the gather walk."""
    return max(cb, 128 if S <= 4096 else 256)


def _select_fwd(q, interpret):
    """Shape-aware forward selection (measured on v5e):

    * resident kernel — K/V DMA'd once per (batch·head) and kept in
      VMEM; zero per-step transfer cost.  Fastest whenever S·d fits the
      VMEM budget, and the only interpret-mode kernel (its fori_loop
      interprets ~max_live× faster than the gather's per-step grid).
    * gather kernel — per-step DMA of only the live k-block via the
      scalar-prefetched index_map; HBM traffic O(live), VMEM O(block).
      Takes over when K/V exceed VMEM residency (long sequences), where
      the resident kernel cannot run at all.
    """
    S, d = q.shape[1], q.shape[3]
    if interpret or S * d <= _RESIDENT_VMEM_ELEMS:
        return _bs_fwd
    return _bs_fwd_gather


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _bs_attention(q, k, v, layout_key, causal, block_q, block_k, cb,
                  interpret):
    return _select_fwd(q, interpret)(q, k, v, layout_key, causal, block_q,
                                     block_k, cb, interpret)[0]


#: key → np layout (hashable indirection for custom_vjp); bounded LRU.
#: The key embeds (bytes, shape, dtype) so an evicted entry can always be
#: reconstructed — a delayed vjp after 32+ other layouts must not KeyError.
_LAYOUTS: OrderedDict = OrderedDict()
_LAYOUTS_MAX = 32

# longest S at which the dense path's O(S^2) logits/mask are still
# materializable on v5e HBM — beyond it, forward AND backward must route
# to the sparse kernels regardless of live fraction (one constant so a
# retune cannot desynchronize the two dispatch sites)
_DENSE_DISPATCH_MAX_S = 8192


def _layout_from_key(key) -> np.ndarray:
    cached = _LAYOUTS.get(key)
    if cached is not None:
        return cached
    raw, shape, dtype = key
    return np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape)


def _bs_fwd(q, k, v, layout_key, causal, block_q, block_k, cb, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    layout = _layout_from_key(layout_key)
    B, S, h, d = q.shape
    H = layout.shape[0]
    idx, counts, cells = _plan(layout, S, block_q, block_k, cb, causal)
    max_live = idx.shape[2]
    nq = S // block_q

    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    # layout head-dim H is 1 (shared) or h; the kernel/index maps fold
    # bh into the layout's head axis (shared → always 0)
    Hl = h if H == h else 1
    kern = functools.partial(_bs_kernel, block_q=block_q, block_k=block_k,
                             cb=cb, H=Hl, scale=1.0 / np.sqrt(d),
                             causal=causal)
    qc, kc = block_q // cb, block_k // cb
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * h, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, idx, cnt: (bh, qi, 0)),
            # constant index over qi → DMA'd once per bh, then resident
            pl.BlockSpec((1, S, d), lambda bh, qi, idx, cnt: (bh, 0, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi, idx, cnt: (bh, 0, 0)),
            pl.BlockSpec((1, 1, max_live, qc, kc),
                         lambda bh, qi, idx, cnt: (bh % Hl, qi, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, idx, cnt: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qi, idx, cnt: (bh, qi, 0)),
        ],
    )
    out, lse = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
                   jax.ShapeDtypeStruct((B * h, S, 1), jnp.float32)],
        interpret=bool(interpret),
    )(jnp.asarray(idx), jnp.asarray(counts), qr, kr, vr, jnp.asarray(cells))
    out = out.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return out, (q, k, v, out, lse)


def _sparse_bwd_tiles(q, k, v, do, layout, cb, causal, block_q, block_k):
    """O(live) backward: gathered live-tile recompute (jnp, XLA fuses).

    Shapes: q/k/v/do ``[B, S, h, d]``.  The plan's padded ``idx/counts/
    cells`` arrays drive a fully vectorized gather over live tiles only —
    scores/probabilities exist as ``[B, h, nq, L, bq, bk]`` (L = max
    live), so work AND memory scale with the live count, not S².  dk/dv
    return through a scatter-add over the gathered block ids."""
    B, S, h, d = q.shape
    H = layout.shape[0]
    idx, counts, cells = _plan(layout, S, block_q, block_k, cb, causal)
    nq, L = idx.shape[1], idx.shape[2]
    nk = S // block_k
    scale = 1.0 / np.sqrt(d)
    # head-fold: layout head axis is 1 (shared) or h.  The k/v GATHER
    # needs an h-sized index; the mask tensors stay at H and broadcast —
    # expanding a shared layout's masks h-fold would cost h× the memory
    # for identical copies.
    hl = np.arange(h) % H                      # [h] → layout head index
    idx_h = jnp.asarray(idx)[hl]               # [h, nq, L] (gather index)
    idx_H = jnp.asarray(idx)                   # [H, nq, L] (mask builds)
    counts_H = jnp.asarray(counts)             # [H, nq]
    cells_H = jnp.asarray(cells)               # [H, nq, L, qc, kc]

    qt = q.transpose(0, 2, 1, 3).reshape(B, h, nq, block_q, d)
    kt = k.transpose(0, 2, 1, 3).reshape(B, h, nk, block_k, d)
    vt = v.transpose(0, 2, 1, 3).reshape(B, h, nk, block_k, d)
    dot = do.transpose(0, 2, 1, 3).reshape(B, h, nq, block_q, d)

    # gather each (h, qi)'s live k/v blocks: [B, h, nq, L, bk, d]
    harange = jnp.arange(h)[:, None, None]
    kg = kt[:, harange, idx_h]
    vg = vt[:, harange, idx_h]

    f32 = jnp.float32
    s = jnp.einsum("bhqad,bhqlkd->bhqlak", qt.astype(f32),
                   kg.astype(f32)) * scale  # [B,h,nq,L,bq,bk]

    # per-tile keep mask: cell kron + causal + live-slot gating, all at
    # the layout head size H (broadcasts over h in the where/products)
    keep = jnp.repeat(jnp.repeat(cells_H > 0, cb, axis=3),
                      cb, axis=4)  # [H, nq, L, bq, bk]
    if causal:
        q_pos = (jnp.arange(nq)[:, None] * block_q
                 + jnp.arange(block_q)[None, :])        # [nq, bq]
        k_pos = (idx_H[..., None] * block_k
                 + jnp.arange(block_k))                  # [H, nq, L, bk]
        keep = keep & (q_pos[None, :, None, :, None]
                       >= k_pos[:, :, :, None, :])
    live = (jnp.arange(L)[None, None] < counts_H[..., None])  # [H, nq, L]
    keep = keep & live[..., None, None]
    keep = keep[None]  # [1, H(bcast->h), nq, L, bq, bk]

    s = jnp.where(keep, s, -1e30)
    m = jnp.max(s, axis=(3, 5), keepdims=True)           # over (L, bk)
    p = jnp.where(keep, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=(3, 5), keepdims=True)
    l = jnp.where(l > 0, l, 1.0)
    p = p / l                                            # [B,h,nq,L,bq,bk]

    o = jnp.einsum("bhqlak,bhqlkd->bhqad", p, vg.astype(f32))
    delta = jnp.sum(dot.astype(f32) * o, axis=-1)        # [B,h,nq,bq]
    dp = jnp.einsum("bhqad,bhqlkd->bhqlak", dot.astype(f32),
                    vg.astype(f32))
    ds = p * (dp - delta[:, :, :, None, :, None])        # [B,h,nq,L,bq,bk]

    dq = jnp.einsum("bhqlak,bhqlkd->bhqad", ds, kg.astype(f32)) * scale
    dk_g = jnp.einsum("bhqlak,bhqad->bhqlkd", ds, qt.astype(f32)) * scale
    dv_g = jnp.einsum("bhqlak,bhqad->bhqlkd", p, dot.astype(f32))

    # scatter-add gathered-tile grads back to their k blocks via
    # segment-sum over flat block ids (duplicate ids across q-blocks
    # accumulate; tiny index arrays — a full-shape advanced-index
    # scatter measured pathologically slow on TPU)
    flat_ids = idx_h.reshape(h, nq * L)

    def seg(vals_h, ids_h):  # [nq*L, bk*d], [nq*L] → [nk, bk*d]
        return jax.ops.segment_sum(vals_h, ids_h, num_segments=nk)

    def seg_bh(vals_b):  # [h, nq*L, bk*d]
        return jax.vmap(seg)(vals_b, flat_ids)

    dk = jax.vmap(seg_bh)(
        dk_g.reshape(B, h, nq * L, block_k * d)).reshape(
            B, h, nk, block_k, d)
    dv = jax.vmap(seg_bh)(
        dv_g.reshape(B, h, nq * L, block_k * d)).reshape(
            B, h, nk, block_k, d)

    dq = dq.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)




def _live_fraction(counts: np.ndarray, S: int, block_q: int,
                   block_k: int, causal: bool) -> float:
    """Live kernel-block fraction of the ACHIEVABLE area — causal layouts
    are normalized by the tril'd block count (``_plan`` already trils the
    layout, so a full-grid denominator would undercount causal density by
    ~2x and miscalibrate both dispatch gates)."""
    H, nq = counts.shape
    nk = S // block_k
    if causal:
        achievable = sum(min(nk, -(-((qi + 1) * block_q) // block_k))
                         for qi in range(nq)) * H
    else:
        achievable = H * nq * nk
    return float(counts.sum()) / float(max(achievable, 1))


_BWD_BUCKET_CACHE: OrderedDict = OrderedDict()


def _bwd_buckets(layout: np.ndarray, S: int, block_q: int, block_k: int,
                 cb: int, causal: bool):
    """Host-side bucket plan for the per-row-count backward: rows (one per
    (layout-head, q-block)) grouped by their live count rounded up to a
    power of two — a dense global row lands in its own deep bucket and no
    longer pads every other row to its depth.  ≤ log2(nk)+1 buckets, so
    the compile count stays bounded."""
    ck = (layout.tobytes(), layout.shape, S, block_q, block_k, cb, causal)
    hit = _BWD_BUCKET_CACHE.get(ck)
    if hit is not None:
        _BWD_BUCKET_CACHE.move_to_end(ck)
        return hit
    idx, counts, cells = _plan(layout, S, block_q, block_k, cb, causal)
    H, nq, L = idx.shape
    buckets: dict = {}
    for hh in range(H):
        for qi in range(nq):
            c = int(counts[hh, qi])
            if c == 0:
                continue
            lb = 1
            while lb < c:
                lb *= 2
            lb = min(lb, L)
            buckets.setdefault(lb, []).append((hh, qi))
    out = []
    for lb in sorted(buckets):
        rows = np.asarray(buckets[lb], np.int32)
        out.append((lb, rows[:, 0], rows[:, 1]))
    result = (idx, counts, cells, out)
    _BWD_BUCKET_CACHE[ck] = result
    while len(_BWD_BUCKET_CACHE) > _PLAN_CACHE_MAX:
        _BWD_BUCKET_CACHE.popitem(last=False)
    return result


def _sparse_bwd_bucketed(q, k, v, do, layout, cb, causal, block_q, block_k):
    """Per-row-count O(live) backward (the round-3/4 "per-row-count"
    item): the same gathered-tile math as :func:`_sparse_bwd_tiles`, but
    rows are processed in live-count buckets, so layouts with a few dense
    global rows (BigBird/Fixed) pay for THOSE rows only instead of
    padding the whole grid to ``max_live``.  Work and memory are the true
    live area, summed over buckets."""
    B, S, h, d = q.shape
    H = layout.shape[0]
    idx, counts, cells, buckets = _bwd_buckets(layout, S, block_q, block_k,
                                               cb, causal)
    nq, L = idx.shape[1], idx.shape[2]
    nk = S // block_k
    G = h // H  # real heads per layout head (shared layout: G = h)
    scale = 1.0 / np.sqrt(d)
    f32 = jnp.float32

    # [B, G, H, n*, blk, d]: real head j = g*H + (j % H) — matches the
    # padded path's ``hl = arange(h) % H`` fold
    qt = q.transpose(0, 2, 1, 3).reshape(B, G, H, nq, block_q, d)
    kt = k.transpose(0, 2, 1, 3).reshape(B, G, H, nk, block_k, d)
    vt = v.transpose(0, 2, 1, 3).reshape(B, G, H, nk, block_k, d)
    dot = do.transpose(0, 2, 1, 3).reshape(B, G, H, nq, block_q, d)

    dq_acc = jnp.zeros((B, G, H, nq, block_q, d), f32)
    dk_flat = jnp.zeros((B, G, H * nk, block_k * d), f32)
    dv_flat = jnp.zeros((B, G, H * nk, block_k * d), f32)

    for lb, hidx, qidx in buckets:
        Rb = len(hidx)
        idx_rows = idx[hidx, qidx][:, :lb]             # np [Rb, lb]
        cnt_rows = jnp.asarray(counts[hidx, qidx])     # [Rb]
        cells_rows = cells[hidx, qidx][:, :lb]         # np [Rb, lb, qc, kc]

        q_r = qt[:, :, hidx, qidx].astype(f32)         # [B, G, Rb, bq, d]
        do_r = dot[:, :, hidx, qidx].astype(f32)
        kg = kt[:, :, hidx[:, None], idx_rows].astype(f32)  # [B,G,Rb,lb,bk,d]
        vg = vt[:, :, hidx[:, None], idx_rows].astype(f32)

        s = jnp.einsum("bgrad,bgrlkd->bgrlak", q_r, kg) * scale
        keep = jnp.repeat(jnp.repeat(jnp.asarray(cells_rows) > 0, cb,
                                     axis=2), cb, axis=3)  # [Rb,lb,bq,bk]
        if causal:
            q_pos = (qidx[:, None] * block_q
                     + np.arange(block_q)[None, :])        # np [Rb, bq]
            k_pos = (idx_rows[..., None] * block_k
                     + np.arange(block_k))                 # np [Rb, lb, bk]
            keep = keep & jnp.asarray(
                q_pos[:, None, :, None] >= k_pos[:, :, None, :])
        live = jnp.arange(lb)[None] < cnt_rows[:, None]    # [Rb, lb]
        keep = keep & live[..., None, None]
        keep = keep[None, None]                            # bcast B, G

        s = jnp.where(keep, s, -1e30)
        m = jnp.max(s, axis=(3, 5), keepdims=True)
        p = jnp.where(keep, jnp.exp(s - m), 0.0)
        l = jnp.sum(p, axis=(3, 5), keepdims=True)
        l = jnp.where(l > 0, l, 1.0)
        p = p / l

        o = jnp.einsum("bgrlak,bgrlkd->bgrad", p, vg)
        delta = jnp.sum(do_r * o, axis=-1)                 # [B, G, Rb, bq]
        dp = jnp.einsum("bgrad,bgrlkd->bgrlak", do_r, vg)
        ds = p * (dp - delta[:, :, :, None, :, None])

        dq_rows = jnp.einsum("bgrlak,bgrlkd->bgrad", ds, kg) * scale
        dk_rows = jnp.einsum("bgrlak,bgrad->bgrlkd", ds, q_r) * scale
        dv_rows = jnp.einsum("bgrlak,bgrad->bgrlkd", p, do_r)

        # rows are unique per bucket → a scatter-add never collides here;
        # ADD (not set) keeps the accumulator donation-friendly
        dq_acc = dq_acc.at[:, :, hidx, qidx].add(dq_rows)
        seg_ids = (hidx[:, None] * nk + idx_rows).reshape(-1)  # np [Rb*lb]

        def seg(vals):  # [Rb*lb, bk*d] → [H*nk, bk*d]
            return jax.ops.segment_sum(vals, jnp.asarray(seg_ids),
                                       num_segments=H * nk)

        dk_flat = dk_flat + jax.vmap(jax.vmap(seg))(
            dk_rows.reshape(B, G, Rb * lb, block_k * d))
        dv_flat = dv_flat + jax.vmap(jax.vmap(seg))(
            dv_rows.reshape(B, G, Rb * lb, block_k * d))

    dq = dq_acc.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    dk = dk_flat.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    dv = dv_flat.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bs_bwd_dq_kernel(qidx_ref, kidx_ref, tot_ref, q_ref, do_ref, k_ref,
                      v_ref, cells_ref, lse_ref, delta_ref, dq_ref,
                      acc_ref, *, block_q: int, block_k: int, cb: int,
                      H: int, scale: float, causal: bool):
    """dq pass of the Pallas block-sparse backward (reference
    ``csrc/sparse_attention`` bwd kernels, SURVEY §2.2), FLAT-tile form:
    the grid walks (bh, t) over each head's exact live-tile list
    (``_plan_flat`` row-major) — no per-row max_live padding exists, so
    every layout (dense global rows included) pays exactly its live
    area.  The OUTPUT BlockSpec is data-dependent (dq block = qidx[t]):
    Pallas keeps the block in VMEM while consecutive tiles share a row
    and flushes on the row boundary — the same same-index elision the
    gather forward uses for its K/V reads, applied to a write.  Uses
    forward-saved softmax stats: p = exp(s·scale − lse),
    ds = p ⊙ (do·Vᵀ − Δ), dq += ds·K·scale."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    t = pl.program_id(1)
    h_idx = jax.lax.rem(bh, H)
    total = tot_ref[h_idx]
    qi = qidx_ref[h_idx, t]
    prev_qi = qidx_ref[h_idx, jnp.maximum(t - 1, 0)]

    @pl.when((t == 0) | (prev_qi != qi))
    def _new_row():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(t < total)
    def _step():
        q = q_ref[0].astype(jnp.float32)            # [bq, d]
        do = do_ref[0].astype(jnp.float32)
        kblk = k_ref[0].astype(jnp.float32)         # [bk, d]
        vblk = v_ref[0].astype(jnp.float32)
        kj = kidx_ref[h_idx, t]
        cell = cells_ref[0, 0].astype(jnp.float32)
        keep = _keep_tile(cell, kj, qi, block_q=block_q, block_k=block_k,
                          cb=cb, causal=causal)
        s_mat = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        lse = lse_ref[0, :, 0]                      # [bq]
        p = jnp.where(keep, jnp.exp(s_mat - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, 0][:, None])
        acc_ref[...] += jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    # write EVERY step: Pallas flushes the VMEM block to HBM only when
    # the output index map changes (row boundary / bh boundary), so the
    # flushed value is the completed row accumulation
    dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bs_bwd_dkv_kernel(qidx_ref, kidx_ref, tot_ref, k_ref, v_ref, q_ref,
                       do_ref, cells_ref, lse_ref, delta_ref, dk_ref,
                       dv_ref, kacc_ref, vacc_ref, *, block_q: int,
                       block_k: int, cb: int, H: int, scale: float,
                       causal: bool):
    """dk/dv pass: the same flat walk in COLUMN-major order
    (``_plan_flat(kmajor=True)``) — consecutive tiles share a k-block, so
    dk/dv accumulate in VMEM scratch and flush on the column boundary
    via the data-dependent output BlockSpec.  No scatter-add exists at
    all (the jnp backward's segment-sum is replaced by the iteration
    order).  dv += pᵀ·do, dk += dsᵀ·q·scale."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    t = pl.program_id(1)
    h_idx = jax.lax.rem(bh, H)
    total = tot_ref[h_idx]
    kj = kidx_ref[h_idx, t]
    prev_kj = kidx_ref[h_idx, jnp.maximum(t - 1, 0)]

    @pl.when((t == 0) | (prev_kj != kj))
    def _new_col():
        kacc_ref[...] = jnp.zeros_like(kacc_ref)
        vacc_ref[...] = jnp.zeros_like(vacc_ref)

    @pl.when(t < total)
    def _step():
        kblk = k_ref[0].astype(jnp.float32)         # [bk, d]
        vblk = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)            # [bq, d] (gathered)
        do = do_ref[0].astype(jnp.float32)
        qi = qidx_ref[h_idx, t]
        cell = cells_ref[0, 0].astype(jnp.float32)
        keep = _keep_tile(cell, kj, qi, block_q=block_q, block_k=block_k,
                          cb=cb, causal=causal)
        s_mat = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        lse = lse_ref[0, :, 0]
        p = jnp.where(keep, jnp.exp(s_mat - lse[:, None]), 0.0)
        vacc_ref[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # pᵀ·do [bk, d]
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, 0][:, None])
        kacc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # dsᵀ·q [bk, d]

    dk_ref[0] = kacc_ref[...].astype(dk_ref.dtype)
    dv_ref[0] = vacc_ref[...].astype(dv_ref.dtype)


def _sparse_bwd_pallas(q, k, v, o, lse, do, layout, cb, causal,
                       block_q, block_k, interpret=False):
    """Full Pallas backward: dq via a row-major flat-tile walk, dk/dv via
    the column-major walk — both grids are EXACTLY the live-tile count
    (``_plan_flat``), so dense global rows cost their true depth and no
    per-row-count bucketing is needed; blocks never visited by the walk
    (fully-dead rows/columns) are zeroed by the ``counts``-mask below."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, h, d = q.shape
    H = layout.shape[0]
    qidx, kidx, cells_f, totals = _plan_flat(layout, S, block_q, block_k,
                                             cb, causal, kmajor=False)
    qidx_t, kidx_t, cells_ft, _ = _plan_flat(layout, S, block_q, block_k,
                                             cb, causal, kmajor=True)
    T = qidx.shape[1]
    nq, nk = S // block_q, S // block_k
    qc, kc = block_q // cb, block_k // cb
    Hl = h if H == h else 1
    scale = 1.0 / np.sqrt(d)

    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    dor = do.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    # Δ_i = Σ_d do_i · o_i — one cheap fused XLA pass over [B,S,h,d]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                         # [B, S, h]
    delta = delta.transpose(0, 2, 1).reshape(B * h, S, 1)

    rem = jax.lax.rem
    dq_kern = functools.partial(
        _bs_bwd_dq_kernel, block_q=block_q, block_k=block_k, cb=cb, H=Hl,
        scale=scale, causal=causal)
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B * h, T),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, qi[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, qi[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, ki[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, ki[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, 1, qc, kc),
                         lambda bh, t, qi, ki, tt:
                         (rem(bh, Hl), t, 0, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, t, qi, ki, tt:
                         (bh, qi[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, t, qi, ki, tt:
                         (bh, qi[rem(bh, Hl), t], 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, t, qi, ki, tt:
                               (bh, qi[rem(bh, Hl), t], 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        dq_kern, grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
        interpret=bool(interpret),
    )(jnp.asarray(qidx), jnp.asarray(kidx), jnp.asarray(totals),
      qr, dor, kr, vr, jnp.asarray(cells_f), lse, delta)

    dkv_kern = functools.partial(
        _bs_bwd_dkv_kernel, block_q=block_q, block_k=block_k, cb=cb, H=Hl,
        scale=scale, causal=causal)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B * h, T),
        in_specs=[
            pl.BlockSpec((1, block_k, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, ki[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, ki[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, qi[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, qi[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, 1, qc, kc),
                         lambda bh, t, qi, ki, tt:
                         (rem(bh, Hl), t, 0, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, t, qi, ki, tt:
                         (bh, qi[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, t, qi, ki, tt:
                         (bh, qi[rem(bh, Hl), t], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, ki[rem(bh, Hl), t], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, t, qi, ki, tt:
                         (bh, ki[rem(bh, Hl), t], 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
    )
    dk, dv = pl.pallas_call(
        dkv_kern, grid_spec=dkv_spec,
        out_shape=[jax.ShapeDtypeStruct((B * h, S, d), k.dtype),
                   jax.ShapeDtypeStruct((B * h, S, d), v.dtype)],
        interpret=bool(interpret),
    )(jnp.asarray(qidx_t), jnp.asarray(kidx_t), jnp.asarray(totals),
      kr, vr, qr, dor, jnp.asarray(cells_ft), lse, delta)

    # blocks the flat walks never visit (fully-dead rows/columns — e.g.
    # strictly-above-diagonal under causal) hold uninitialized memory:
    # zero them from one vectorized coarse-liveness reduction
    lay_b = lattice.apply_lattice(layout.astype(bool), causal, cb=cb)
    coarse = lay_b.reshape(H, nq, block_q // cb, nk,
                           block_k // cb).any(axis=(2, 4))  # [H, nq, nk]
    hl = np.arange(h) % H
    qmask = jnp.asarray(coarse.any(axis=2)[hl])      # [h, nq]
    kmask = jnp.asarray(coarse.any(axis=1)[hl])      # [h, nk]
    qm = qmask.reshape(1, h, nq, 1, 1)
    dq = jnp.where(
        qm, dq.reshape(B, h, nq, block_q, d), 0.0).reshape(B, h, S, d)
    km = kmask.reshape(1, h, nk, 1, 1)
    dk = jnp.where(
        km, dk.reshape(B, h, nk, block_k, d), 0.0).reshape(B, h, S, d)
    dv = jnp.where(
        km, dv.reshape(B, h, nk, block_k, d), 0.0).reshape(B, h, S, d)

    back = lambda a: a.transpose(0, 2, 1, 3)
    return (back(dq).astype(q.dtype), back(dk).astype(k.dtype),
            back(dv).astype(v.dtype))
def _bs_bwd(layout_key, causal, block_q, block_k, cb, interpret, res, do):
    """Backward dispatch.

    Production (TPU, non-interpret): the PALLAS kernel backward —
    :func:`_sparse_bwd_pallas` — which is O(live) uniformly for every
    layout (padded grid steps cost a tick, not a matmul; dense global
    rows pay their true depth via the transposed plan), fed by the
    forward-saved softmax stats.  The jnp forms
    (:func:`_sparse_bwd_tiles` padded, :func:`_sparse_bwd_bucketed`
    per-row-count) remain the interpret-mode backward (the kernel's
    per-step grid interprets orders of magnitude slower) and the
    directly-tested anchors the kernel math is locked against.  The
    dense masked vjp serves mostly-live layouts at materializable S,
    where big fused matmuls beat any tile loop."""
    q, k, v, o, lse = res
    layout = _layout_from_key(layout_key)
    S = q.shape[1]
    _, counts, _ = _plan(layout, S, block_q, block_k, cb, causal)
    live_frac = _live_fraction(counts, S, block_q, block_k, causal)
    # beyond _DENSE_DISPATCH_MAX_S the dense vjp's O(S^2) logits stop
    # being materializable, so the sparse form runs regardless of live
    # fraction (a 0.6-live S=32k layout must not OOM in backward when the
    # forward deliberately routed it to the kernel).  The live threshold
    # is the SAME crossover the forward dispatch uses (choose_impl) so
    # the two sites cannot drift.
    if (live_frac <= dense_live_threshold(S)
            or S > _DENSE_DISPATCH_MAX_S):
        if not interpret:
            return _sparse_bwd_pallas(q, k, v, o, lse, do, layout, cb,
                                      causal, block_q, block_k,
                                      interpret=False)
        _, _, _, buckets = _bwd_buckets(layout, S, block_q, block_k, cb,
                                        causal)
        if len(buckets) <= 1:
            # uniform live depth (local-window layouts): the padded form
            # IS the single bucket, with simpler indexing
            return _sparse_bwd_tiles(q, k, v, do, layout, cb, causal,
                                     block_q, block_k)
        return _sparse_bwd_bucketed(q, k, v, do, layout, cb, causal,
                                    block_q, block_k)

    def f(q, k, v):
        return _dense_reference(q, k, v, layout, cb, causal)

    _, vjp = jax.vjp(f, q, k, v)
    return vjp(do)


def _bs_vjp_fwd(q, k, v, layout_key, causal, block_q, block_k, cb,
                interpret):
    return _select_fwd(q, interpret)(q, k, v, layout_key, causal, block_q,
                                     block_k, cb, interpret)


_bs_attention.defvjp(_bs_vjp_fwd, _bs_bwd)


def block_sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           sparsity_config: Any, causal: bool = False,
                           block_q: int = 0, block_k: int = 0,
                           interpret: bool | None = None) -> jnp.ndarray:
    """[B, S, h, d] attention executing ONLY the k-blocks the config's
    layout marks live (per head when the layout is per-head).  Numerics
    match :func:`deepspeed_tpu.ops.sparse_attention.sparse_attention`
    (the dense masked path) to accumulation tolerance.

    Block-size auto-tune (measured on v5e, S=4096/bf16/BigBird cb=128):
    128-blocks match the cell granularity, so coarsening inflates no
    live coverage, the per-tile mask is causality alone, and the flat
    backward runs 2.8x the dense vjp (256-blocks: 0.9x — coarsened live
    0.26→0.51 erases the win) while the forward is within 3%.
    ``block_q``/``block_k`` 0 → :func:`_bs_auto_block` (seq-length
    aware: 128 to 4k, 256 beyond); explicit sizes still apply.

    Dispatch is :func:`choose_impl`'s crossover contract: above the
    per-seq-length live-fraction threshold the DENSE masked path is the
    faster correct implementation, and auto-dispatch takes it — the
    kernel never loses to its own fallback."""
    B, S, h, d = q.shape
    cb = sparsity_config.block
    layout = _norm_layout(sparsity_config.make_layout(S), h)
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _dense_reference(q, k, v, layout, cb, causal)
        interpret = False
    auto = _bs_auto_block(S, cb)
    block_q = min(block_q, auto) if block_q else auto
    block_k = min(block_k, auto) if block_k else auto

    def fits(b):
        return b >= cb and b % cb == 0 and S % b == 0 and b % 8 == 0

    while block_q > cb and not fits(block_q):
        block_q //= 2
    while block_k > cb and not fits(block_k):
        block_k //= 2
    if not (fits(block_q) and fits(block_k)):
        return _dense_reference(q, k, v, layout, cb, causal)

    # fine-celled layouts can coarsen to near-dense at kernel-block
    # granularity (a 256-token block is live if ANY of its 16-token cells
    # is) — when most kernel blocks are live, the dense masked path's big
    # fused matmuls beat the tile loop (measured: cb=16 BigBird at S=4096
    # coarsens to 0.92 live and dense wins 2x).  choose_impl owns the
    # crossover (per-seq-length live threshold — the r04 0.96@4k fix);
    # interpret mode always exercises a kernel (tests' tiny grids
    # coarsen dense), and past _DENSE_DISPATCH_MAX_S dense cannot run.
    _, counts, _ = _plan(layout, S, block_q, block_k, cb, causal)
    live = _live_fraction(counts, S, block_q, block_k, causal)
    if choose_impl(S, d, live, bool(interpret)) == "dense":
        return _dense_reference(q, k, v, layout, cb, causal)
    key = (layout.tobytes(), layout.shape, layout.dtype.str)
    _LAYOUTS[key] = layout
    _LAYOUTS.move_to_end(key)
    while len(_LAYOUTS) > _LAYOUTS_MAX:
        _LAYOUTS.popitem(last=False)
    return _bs_attention(q, k, v, key, causal, block_q, block_k, cb,
                         interpret)
