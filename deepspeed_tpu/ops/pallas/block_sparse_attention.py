"""Block-sparse attention Pallas kernel — skips dead k-blocks per head.

Role parity: the reference's Triton block-sparse kernels
(``csrc/sparse_attention`` + ``deepspeed/ops/sparse_attention`` [K],
SURVEY §2.2) execute only the key blocks a ``SparsityConfig`` layout marks
live; round 2 shipped layout semantics but ran DENSE masked attention
(VERDICT round-2 missing #4).  This kernel closes that gap the TPU way:

* Host-side planning coarsens the ``[nb, nb]`` cell layout to kernel-block
  granularity and emits, per (head, q-block), the list of LIVE k-block ids
  (scalar-prefetched to SMEM) plus each live tile's cell sub-layout.
* The kernel is the flash-attention skeleton (online softmax over a
  ``fori_loop``), but the loop runs over the live list only — work per
  q-block is O(live · block) instead of O(S) — and every tile applies its
  exact token mask, rebuilt from the cell sub-layout with two tiny 0/1
  expansion matmuls (a Mosaic-friendly ``kron``; reshape-merge lowering
  rejects the naive broadcast form).
* Fully-masked query rows produce 0 (matching the dense path's explicit
  zeroing), via ``where(l > 0, acc / l, 0)``.

Two TPU forwards, selected by shape (:func:`_select_fwd`): the
VMEM-resident kernel when a head's K/V fit VMEM (zero per-step transfer
— fastest at short/medium S), and the splash-style GATHER kernel
(:func:`_bs_gather_kernel`) beyond that bound: a (bh, q-block, live-s)
grid whose K/V ``BlockSpec`` index_map reads the scalar-prefetched live
list, so each step DMAs ONLY its live k-block — HBM traffic O(live),
VMEM O(block), sequence length unbounded.  (Round 3's dynamic-offset
``make_async_copy`` gather crashed Mosaic; a data-dependent index_map
is the supported way — the paged decode kernel gathers pages
identically.)

Backward (``custom_vjp``) auto-selects: an O(live) gathered-tile sparse
backward (jnp: gather live k-blocks, softmax jacobian per tile,
segment-sum scatter of dk/dv — 1.5-2.4x faster than the dense vjp for
local-window layouts on v5e at S=4096) when ``max_live*2 <= nk``, else
the dense masked vjp (a dense global row makes the padded form slower
than dense).  A per-row-count Pallas bwd kernel (the gather-forward
pattern applied to dq/dk/dv) is the remaining item.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# host-side planning
# ---------------------------------------------------------------------------

from collections import OrderedDict

_PLAN_CACHE: OrderedDict = OrderedDict()
_PLAN_CACHE_MAX = 16  # bounded: entries hold megabyte-scale cell tensors


def _plan(layout: np.ndarray, S: int, block_q: int, block_k: int,
          cb: int, causal: bool):
    """layout [H, nb, nb] → (idx [H, nq, max_live] int32,
    counts [H, nq] int32, cells [H, nq, max_live, qc, kc] int8)."""
    key = (layout.tobytes(), layout.shape, S, block_q, block_k, cb, causal)
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        _PLAN_CACHE.move_to_end(key)
        return hit
    H, nb, _ = layout.shape
    nq, nk = S // block_q, S // block_k
    qc, kc = block_q // cb, block_k // cb
    lay = layout.astype(np.int8)
    if causal:
        # cells strictly above the diagonal contribute nothing
        lay = np.stack([np.tril(l) for l in lay])
    lists = [[[] for _ in range(nq)] for _ in range(H)]
    for h in range(H):
        coarse = lay[h].reshape(nq, qc, nk, kc).any(axis=(1, 3))
        for qi in range(nq):
            lists[h][qi] = np.nonzero(coarse[qi])[0].tolist()
    max_live = max((len(l) for row in lists for l in row), default=1)
    max_live = max(max_live, 1)
    idx = np.zeros((H, nq, max_live), np.int32)
    counts = np.zeros((H, nq), np.int32)
    cells = np.zeros((H, nq, max_live, qc, kc), np.int8)
    for h in range(H):
        for qi in range(nq):
            live = lists[h][qi]
            counts[h, qi] = len(live)
            for s, kj in enumerate(live):
                idx[h, qi, s] = kj
                cells[h, qi, s] = lay[h, qi * qc:(qi + 1) * qc,
                                      kj * kc:(kj + 1) * kc]
            if live:
                # pad with the LAST live index: consecutive identical
                # block indices skip the re-DMA, so padded grid steps
                # cost ~nothing (they are masked by s < count anyway)
                idx[h, qi, len(live):] = live[-1]
    out = (idx, counts, cells)
    _PLAN_CACHE[key] = out
    while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
        _PLAN_CACHE.popitem(last=False)
    return out


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _tile_update(q, kblk, vblk, cell, kj, qi, m, l, acc, *,
                 block_q: int, block_k: int, cb: int, causal: bool):
    """ONE live tile's online-softmax update — shared by the resident
    (interpret) and gather (production) kernels so their numerics cannot
    drift.  ``q`` is pre-scaled fp32; returns (m', l', acc')."""
    qc, kc = block_q // cb, block_k // cb
    # 0/1 expansion matmuls: keep = R @ cell @ K (an in-kernel kron;
    # Mosaic rejects the naive broadcast+reshape-merge lowering)
    ri = jax.lax.broadcasted_iota(jnp.int32, (block_q, qc), 0) // cb
    rc = jax.lax.broadcasted_iota(jnp.int32, (block_q, qc), 1)
    R = (ri == rc).astype(jnp.float32)
    ki = jax.lax.broadcasted_iota(jnp.int32, (kc, block_k), 0)
    kcol = jax.lax.broadcasted_iota(jnp.int32, (kc, block_k), 1) // cb
    K = (ki == kcol).astype(jnp.float32)
    keep_f = jax.lax.dot_general(
        jax.lax.dot_general(R, cell, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32),
        K, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    keep = keep_f > 0.5
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_off = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        keep = keep & (q_pos >= kj * block_k + k_off)

    s_mat = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
    s_mat = jnp.where(keep, s_mat, -1e30)
    m_new = jnp.maximum(m, jnp.max(s_mat, axis=-1))
    # explicit zeroing: a row whose every entry in this tile is masked
    # must not accumulate exp(-1e30 - (-1e30)) = 1 garbage
    p = jnp.where(keep, jnp.exp(s_mat - m_new[:, None]), 0.0)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[:, None] + jax.lax.dot_general(
        p, vblk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _bs_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, cells_ref, o_ref, *,
               block_q: int, block_k: int, cb: int, H: int, scale: float,
               causal: bool):
    """One grid step per (B·h, q-block); a ``fori_loop`` walks the LIVE
    k-block list, slicing each live block out of the VMEM-resident K/V.
    K/V are DMA'd once per ``bh`` (their block index is constant across
    the inner ``qi`` grid dim, so Pallas skips the re-fetch), and compute
    is O(live · block_k) per q-block instead of O(S).

    This kernel serves production traffic whenever a head's K/V fit the
    VMEM budget (see :func:`_select_fwd` — zero per-step transfer makes
    it fastest at short/medium S) and ALL interpret-mode runs.  Beyond
    the VMEM bound (S·d > ``_RESIDENT_VMEM_ELEMS`` per plane) the
    splash-style :func:`_bs_gather_kernel` takes over."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qi = pl.program_id(1)
    h_idx = jax.lax.rem(bh, H)
    qc, kc = block_q // cb, block_k // cb
    count = cnt_ref[h_idx, qi]
    d = q_ref.shape[-1]

    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(s, carry):
        m, l, acc = carry
        kj = idx_ref[h_idx, qi, s]
        kblk = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        cell = cells_ref[0, 0, s].astype(jnp.float32)  # [qc, kc]
        return _tile_update(q, kblk, vblk, cell, kj, qi, m, l, acc,
                            block_q=block_q, block_k=block_k, cb=cb,
                            causal=causal)

    m, l, acc = jax.lax.fori_loop(0, count, body, (m0, l0, acc0))
    l2 = l[:, None]
    o_ref[0] = jnp.where(l2 > 0, acc / jnp.where(l2 > 0, l2, 1.0),
                         0.0).astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def _bs_gather_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, cells_ref,
                      o_ref, m_ref, l_ref, acc_ref, *, block_q: int,
                      block_k: int, cb: int, H: int, scale: float,
                      causal: bool, max_live: int):
    """Splash-style GATHER forward: the grid walks (bh, q-block, live-s)
    and the K/V BlockSpec's scalar-prefetched ``index_map`` DMAs ONLY the
    live k-block for each step — HBM traffic is O(live · block_k) per
    q-block and VMEM holds one block, so S is unbounded by VMEM
    residency.  This is the Mosaic-safe realization of the round-3
    "splash gather" (dynamic-offset ``make_async_copy`` crashed the
    toolchain; a data-dependent ``index_map`` is exactly how the paged
    decode kernel already gathers pages, so it compiles).  Online-softmax
    state rides VMEM scratch across the s steps; padded steps (s ≥
    count) repeat the last live index so their DMA is skipped by Pallas'
    same-block elision and their compute by ``pl.when``."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qi = pl.program_id(1)
    s = pl.program_id(2)
    h_idx = jax.lax.rem(bh, H)
    count = cnt_ref[h_idx, qi]
    qc, kc = block_q // cb, block_k // cb
    d = q_ref.shape[-1]

    @pl.when(s == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(s < count)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale      # [block_q, d]
        kblk = k_ref[0].astype(jnp.float32)           # [block_k, d]
        vblk = v_ref[0].astype(jnp.float32)
        kj = idx_ref[h_idx, qi, s]
        cell = cells_ref[0, 0, 0].astype(jnp.float32)  # [qc, kc]
        m_new, l_new, acc_new = _tile_update(
            q, kblk, vblk, cell, kj, qi, m_ref[:, 0], l_ref[:, 0],
            acc_ref[...], block_q=block_q, block_k=block_k, cb=cb,
            causal=causal)
        m_ref[...] = m_new[:, None]
        l_ref[...] = l_new[:, None]
        acc_ref[...] = acc_new

    @pl.when(s == max_live - 1)
    def _finalize():
        l2 = l_ref[...]
        o_ref[0] = jnp.where(
            l2 > 0, acc_ref[...] / jnp.where(l2 > 0, l2, 1.0),
            0.0).astype(o_ref.dtype)


def _bs_fwd_gather(q, k, v, layout_key, causal, block_q, block_k, cb,
                   interpret):
    """Forward via :func:`_bs_gather_kernel` (same contract as
    :func:`_bs_fwd`)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    layout = _layout_from_key(layout_key)
    B, S, h, d = q.shape
    H = layout.shape[0]
    idx, counts, cells = _plan(layout, S, block_q, block_k, cb, causal)
    max_live = idx.shape[2]
    nq = S // block_q
    qc, kc = block_q // cb, block_k // cb

    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    Hl = h if H == h else 1
    kern = functools.partial(_bs_gather_kernel, block_q=block_q,
                             block_k=block_k, cb=cb, H=Hl,
                             scale=1.0 / np.sqrt(d), causal=causal,
                             max_live=max_live)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * h, nq, max_live),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, s, idx, cnt: (bh, qi, 0)),
            # the splash gather: each grid step DMAs only ITS live block
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, s, idx, cnt:
                         (bh, idx[jax.lax.rem(bh, Hl), qi, s], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, s, idx, cnt:
                         (bh, idx[jax.lax.rem(bh, Hl), qi, s], 0)),
            pl.BlockSpec((1, 1, 1, qc, kc),
                         lambda bh, qi, s, idx, cnt:
                         (jax.lax.rem(bh, Hl), qi, s, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, s, idx, cnt: (bh, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
        interpret=bool(interpret),
    )(jnp.asarray(idx), jnp.asarray(counts), qr, kr, vr, jnp.asarray(cells))
    out = out.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return out, (q, k, v)


def _dense_reference(q, k, v, layout, cb, causal):
    from ..sparse_attention import block_layout_to_token_mask

    lay = layout[0] if layout.shape[0] == 1 else layout
    mask = block_layout_to_token_mask(lay, cb, causal)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    m = mask[None] if mask.ndim == 3 else mask[None, None]
    s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    p = jnp.where(jnp.any(m, axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _norm_layout(layout: np.ndarray, h: int) -> np.ndarray:
    """→ [H, nb, nb] with H ∈ {1, num_heads} (shared layouts stay 1)."""
    layout = np.asarray(layout)
    if layout.ndim == 2:
        return layout[None]
    if layout.shape[0] != h:
        raise ValueError(f"per-head layout has {layout.shape[0]} heads, "
                         f"attention has {h}")
    return layout


#: PER-PLANE element bound (S·d of K, same for V) for the resident
#: kernel; K+V together then occupy up to 2x this.  2M elems/plane =
#: 8 MiB/plane in bf16 — comfortably inside a v5e core's ~64 MiB VMEM
#: alongside q/acc scratch, with headroom for fp32 inputs (2x bytes)
_RESIDENT_VMEM_ELEMS = 2 * 1024 * 1024


def _select_fwd(q, interpret):
    """Shape-aware forward selection (measured on v5e):

    * resident kernel — K/V DMA'd once per (batch·head) and kept in
      VMEM; zero per-step transfer cost.  Fastest whenever S·d fits the
      VMEM budget, and the only interpret-mode kernel (its fori_loop
      interprets ~max_live× faster than the gather's per-step grid).
    * gather kernel — per-step DMA of only the live k-block via the
      scalar-prefetched index_map; HBM traffic O(live), VMEM O(block).
      Takes over when K/V exceed VMEM residency (long sequences), where
      the resident kernel cannot run at all.
    """
    S, d = q.shape[1], q.shape[3]
    if interpret or S * d <= _RESIDENT_VMEM_ELEMS:
        return _bs_fwd
    return _bs_fwd_gather


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _bs_attention(q, k, v, layout_key, causal, block_q, block_k, cb,
                  interpret):
    return _select_fwd(q, interpret)(q, k, v, layout_key, causal, block_q,
                                     block_k, cb, interpret)[0]


#: key → np layout (hashable indirection for custom_vjp); bounded LRU.
#: The key embeds (bytes, shape, dtype) so an evicted entry can always be
#: reconstructed — a delayed vjp after 32+ other layouts must not KeyError.
_LAYOUTS: OrderedDict = OrderedDict()
_LAYOUTS_MAX = 32

# longest S at which the dense path's O(S^2) logits/mask are still
# materializable on v5e HBM — beyond it, forward AND backward must route
# to the sparse kernels regardless of live fraction (one constant so a
# retune cannot desynchronize the two dispatch sites)
_DENSE_DISPATCH_MAX_S = 8192


def _layout_from_key(key) -> np.ndarray:
    cached = _LAYOUTS.get(key)
    if cached is not None:
        return cached
    raw, shape, dtype = key
    return np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape)


def _bs_fwd(q, k, v, layout_key, causal, block_q, block_k, cb, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    layout = _layout_from_key(layout_key)
    B, S, h, d = q.shape
    H = layout.shape[0]
    idx, counts, cells = _plan(layout, S, block_q, block_k, cb, causal)
    max_live = idx.shape[2]
    nq = S // block_q

    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    # layout head-dim H is 1 (shared) or h; the kernel/index maps fold
    # bh into the layout's head axis (shared → always 0)
    Hl = h if H == h else 1
    kern = functools.partial(_bs_kernel, block_q=block_q, block_k=block_k,
                             cb=cb, H=Hl, scale=1.0 / np.sqrt(d),
                             causal=causal)
    qc, kc = block_q // cb, block_k // cb
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * h, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, idx, cnt: (bh, qi, 0)),
            # constant index over qi → DMA'd once per bh, then resident
            pl.BlockSpec((1, S, d), lambda bh, qi, idx, cnt: (bh, 0, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi, idx, cnt: (bh, 0, 0)),
            pl.BlockSpec((1, 1, max_live, qc, kc),
                         lambda bh, qi, idx, cnt: (bh % Hl, qi, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, idx, cnt: (bh, qi, 0)),
    )
    out = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
        interpret=bool(interpret),
    )(jnp.asarray(idx), jnp.asarray(counts), qr, kr, vr, jnp.asarray(cells))
    out = out.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return out, (q, k, v)


def _sparse_bwd_tiles(q, k, v, do, layout, cb, causal, block_q, block_k):
    """O(live) backward: gathered live-tile recompute (jnp, XLA fuses).

    Shapes: q/k/v/do ``[B, S, h, d]``.  The plan's padded ``idx/counts/
    cells`` arrays drive a fully vectorized gather over live tiles only —
    scores/probabilities exist as ``[B, h, nq, L, bq, bk]`` (L = max
    live), so work AND memory scale with the live count, not S².  dk/dv
    return through a scatter-add over the gathered block ids."""
    B, S, h, d = q.shape
    H = layout.shape[0]
    idx, counts, cells = _plan(layout, S, block_q, block_k, cb, causal)
    nq, L = idx.shape[1], idx.shape[2]
    nk = S // block_k
    scale = 1.0 / np.sqrt(d)
    # head-fold: layout head axis is 1 (shared) or h.  The k/v GATHER
    # needs an h-sized index; the mask tensors stay at H and broadcast —
    # expanding a shared layout's masks h-fold would cost h× the memory
    # for identical copies.
    hl = np.arange(h) % H                      # [h] → layout head index
    idx_h = jnp.asarray(idx)[hl]               # [h, nq, L] (gather index)
    idx_H = jnp.asarray(idx)                   # [H, nq, L] (mask builds)
    counts_H = jnp.asarray(counts)             # [H, nq]
    cells_H = jnp.asarray(cells)               # [H, nq, L, qc, kc]

    qt = q.transpose(0, 2, 1, 3).reshape(B, h, nq, block_q, d)
    kt = k.transpose(0, 2, 1, 3).reshape(B, h, nk, block_k, d)
    vt = v.transpose(0, 2, 1, 3).reshape(B, h, nk, block_k, d)
    dot = do.transpose(0, 2, 1, 3).reshape(B, h, nq, block_q, d)

    # gather each (h, qi)'s live k/v blocks: [B, h, nq, L, bk, d]
    harange = jnp.arange(h)[:, None, None]
    kg = kt[:, harange, idx_h]
    vg = vt[:, harange, idx_h]

    f32 = jnp.float32
    s = jnp.einsum("bhqad,bhqlkd->bhqlak", qt.astype(f32),
                   kg.astype(f32)) * scale  # [B,h,nq,L,bq,bk]

    # per-tile keep mask: cell kron + causal + live-slot gating, all at
    # the layout head size H (broadcasts over h in the where/products)
    keep = jnp.repeat(jnp.repeat(cells_H > 0, cb, axis=3),
                      cb, axis=4)  # [H, nq, L, bq, bk]
    if causal:
        q_pos = (jnp.arange(nq)[:, None] * block_q
                 + jnp.arange(block_q)[None, :])        # [nq, bq]
        k_pos = (idx_H[..., None] * block_k
                 + jnp.arange(block_k))                  # [H, nq, L, bk]
        keep = keep & (q_pos[None, :, None, :, None]
                       >= k_pos[:, :, :, None, :])
    live = (jnp.arange(L)[None, None] < counts_H[..., None])  # [H, nq, L]
    keep = keep & live[..., None, None]
    keep = keep[None]  # [1, H(bcast->h), nq, L, bq, bk]

    s = jnp.where(keep, s, -1e30)
    m = jnp.max(s, axis=(3, 5), keepdims=True)           # over (L, bk)
    p = jnp.where(keep, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=(3, 5), keepdims=True)
    l = jnp.where(l > 0, l, 1.0)
    p = p / l                                            # [B,h,nq,L,bq,bk]

    o = jnp.einsum("bhqlak,bhqlkd->bhqad", p, vg.astype(f32))
    delta = jnp.sum(dot.astype(f32) * o, axis=-1)        # [B,h,nq,bq]
    dp = jnp.einsum("bhqad,bhqlkd->bhqlak", dot.astype(f32),
                    vg.astype(f32))
    ds = p * (dp - delta[:, :, :, None, :, None])        # [B,h,nq,L,bq,bk]

    dq = jnp.einsum("bhqlak,bhqlkd->bhqad", ds, kg.astype(f32)) * scale
    dk_g = jnp.einsum("bhqlak,bhqad->bhqlkd", ds, qt.astype(f32)) * scale
    dv_g = jnp.einsum("bhqlak,bhqad->bhqlkd", p, dot.astype(f32))

    # scatter-add gathered-tile grads back to their k blocks via
    # segment-sum over flat block ids (duplicate ids across q-blocks
    # accumulate; tiny index arrays — a full-shape advanced-index
    # scatter measured pathologically slow on TPU)
    flat_ids = idx_h.reshape(h, nq * L)

    def seg(vals_h, ids_h):  # [nq*L, bk*d], [nq*L] → [nk, bk*d]
        return jax.ops.segment_sum(vals_h, ids_h, num_segments=nk)

    def seg_bh(vals_b):  # [h, nq*L, bk*d]
        return jax.vmap(seg)(vals_b, flat_ids)

    dk = jax.vmap(seg_bh)(
        dk_g.reshape(B, h, nq * L, block_k * d)).reshape(
            B, h, nk, block_k, d)
    dv = jax.vmap(seg_bh)(
        dv_g.reshape(B, h, nq * L, block_k * d)).reshape(
            B, h, nk, block_k, d)

    dq = dq.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)




def _live_fraction(counts: np.ndarray, S: int, block_q: int,
                   block_k: int, causal: bool) -> float:
    """Live kernel-block fraction of the ACHIEVABLE area — causal layouts
    are normalized by the tril'd block count (``_plan`` already trils the
    layout, so a full-grid denominator would undercount causal density by
    ~2x and miscalibrate both dispatch gates)."""
    H, nq = counts.shape
    nk = S // block_k
    if causal:
        achievable = sum(min(nk, -(-((qi + 1) * block_q) // block_k))
                         for qi in range(nq)) * H
    else:
        achievable = H * nq * nk
    return float(counts.sum()) / float(max(achievable, 1))


_BWD_BUCKET_CACHE: OrderedDict = OrderedDict()


def _bwd_buckets(layout: np.ndarray, S: int, block_q: int, block_k: int,
                 cb: int, causal: bool):
    """Host-side bucket plan for the per-row-count backward: rows (one per
    (layout-head, q-block)) grouped by their live count rounded up to a
    power of two — a dense global row lands in its own deep bucket and no
    longer pads every other row to its depth.  ≤ log2(nk)+1 buckets, so
    the compile count stays bounded."""
    ck = (layout.tobytes(), layout.shape, S, block_q, block_k, cb, causal)
    hit = _BWD_BUCKET_CACHE.get(ck)
    if hit is not None:
        _BWD_BUCKET_CACHE.move_to_end(ck)
        return hit
    idx, counts, cells = _plan(layout, S, block_q, block_k, cb, causal)
    H, nq, L = idx.shape
    buckets: dict = {}
    for hh in range(H):
        for qi in range(nq):
            c = int(counts[hh, qi])
            if c == 0:
                continue
            lb = 1
            while lb < c:
                lb *= 2
            lb = min(lb, L)
            buckets.setdefault(lb, []).append((hh, qi))
    out = []
    for lb in sorted(buckets):
        rows = np.asarray(buckets[lb], np.int32)
        out.append((lb, rows[:, 0], rows[:, 1]))
    result = (idx, counts, cells, out)
    _BWD_BUCKET_CACHE[ck] = result
    while len(_BWD_BUCKET_CACHE) > _PLAN_CACHE_MAX:
        _BWD_BUCKET_CACHE.popitem(last=False)
    return result


def _sparse_bwd_bucketed(q, k, v, do, layout, cb, causal, block_q, block_k):
    """Per-row-count O(live) backward (the round-3/4 "per-row-count"
    item): the same gathered-tile math as :func:`_sparse_bwd_tiles`, but
    rows are processed in live-count buckets, so layouts with a few dense
    global rows (BigBird/Fixed) pay for THOSE rows only instead of
    padding the whole grid to ``max_live``.  Work and memory are the true
    live area, summed over buckets."""
    B, S, h, d = q.shape
    H = layout.shape[0]
    idx, counts, cells, buckets = _bwd_buckets(layout, S, block_q, block_k,
                                               cb, causal)
    nq, L = idx.shape[1], idx.shape[2]
    nk = S // block_k
    G = h // H  # real heads per layout head (shared layout: G = h)
    scale = 1.0 / np.sqrt(d)
    f32 = jnp.float32

    # [B, G, H, n*, blk, d]: real head j = g*H + (j % H) — matches the
    # padded path's ``hl = arange(h) % H`` fold
    qt = q.transpose(0, 2, 1, 3).reshape(B, G, H, nq, block_q, d)
    kt = k.transpose(0, 2, 1, 3).reshape(B, G, H, nk, block_k, d)
    vt = v.transpose(0, 2, 1, 3).reshape(B, G, H, nk, block_k, d)
    dot = do.transpose(0, 2, 1, 3).reshape(B, G, H, nq, block_q, d)

    dq_acc = jnp.zeros((B, G, H, nq, block_q, d), f32)
    dk_flat = jnp.zeros((B, G, H * nk, block_k * d), f32)
    dv_flat = jnp.zeros((B, G, H * nk, block_k * d), f32)

    for lb, hidx, qidx in buckets:
        Rb = len(hidx)
        idx_rows = idx[hidx, qidx][:, :lb]             # np [Rb, lb]
        cnt_rows = jnp.asarray(counts[hidx, qidx])     # [Rb]
        cells_rows = cells[hidx, qidx][:, :lb]         # np [Rb, lb, qc, kc]

        q_r = qt[:, :, hidx, qidx].astype(f32)         # [B, G, Rb, bq, d]
        do_r = dot[:, :, hidx, qidx].astype(f32)
        kg = kt[:, :, hidx[:, None], idx_rows].astype(f32)  # [B,G,Rb,lb,bk,d]
        vg = vt[:, :, hidx[:, None], idx_rows].astype(f32)

        s = jnp.einsum("bgrad,bgrlkd->bgrlak", q_r, kg) * scale
        keep = jnp.repeat(jnp.repeat(jnp.asarray(cells_rows) > 0, cb,
                                     axis=2), cb, axis=3)  # [Rb,lb,bq,bk]
        if causal:
            q_pos = (qidx[:, None] * block_q
                     + np.arange(block_q)[None, :])        # np [Rb, bq]
            k_pos = (idx_rows[..., None] * block_k
                     + np.arange(block_k))                 # np [Rb, lb, bk]
            keep = keep & jnp.asarray(
                q_pos[:, None, :, None] >= k_pos[:, :, None, :])
        live = jnp.arange(lb)[None] < cnt_rows[:, None]    # [Rb, lb]
        keep = keep & live[..., None, None]
        keep = keep[None, None]                            # bcast B, G

        s = jnp.where(keep, s, -1e30)
        m = jnp.max(s, axis=(3, 5), keepdims=True)
        p = jnp.where(keep, jnp.exp(s - m), 0.0)
        l = jnp.sum(p, axis=(3, 5), keepdims=True)
        l = jnp.where(l > 0, l, 1.0)
        p = p / l

        o = jnp.einsum("bgrlak,bgrlkd->bgrad", p, vg)
        delta = jnp.sum(do_r * o, axis=-1)                 # [B, G, Rb, bq]
        dp = jnp.einsum("bgrad,bgrlkd->bgrlak", do_r, vg)
        ds = p * (dp - delta[:, :, :, None, :, None])

        dq_rows = jnp.einsum("bgrlak,bgrlkd->bgrad", ds, kg) * scale
        dk_rows = jnp.einsum("bgrlak,bgrad->bgrlkd", ds, q_r) * scale
        dv_rows = jnp.einsum("bgrlak,bgrad->bgrlkd", p, do_r)

        # rows are unique per bucket → a scatter-add never collides here;
        # ADD (not set) keeps the accumulator donation-friendly
        dq_acc = dq_acc.at[:, :, hidx, qidx].add(dq_rows)
        seg_ids = (hidx[:, None] * nk + idx_rows).reshape(-1)  # np [Rb*lb]

        def seg(vals):  # [Rb*lb, bk*d] → [H*nk, bk*d]
            return jax.ops.segment_sum(vals, jnp.asarray(seg_ids),
                                       num_segments=H * nk)

        dk_flat = dk_flat + jax.vmap(jax.vmap(seg))(
            dk_rows.reshape(B, G, Rb * lb, block_k * d))
        dv_flat = dv_flat + jax.vmap(jax.vmap(seg))(
            dv_rows.reshape(B, G, Rb * lb, block_k * d))

    dq = dq_acc.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    dk = dk_flat.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    dv = dv_flat.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bs_bwd(layout_key, causal, block_q, block_k, cb, interpret, res, do):
    """Backward, auto-selected by the plan's shape.

    The gathered-tile sparse backward pads every q-block to ``max_live``
    k-blocks, so it only SAVES work when ``max_live << nk`` (local-window
    layouts).  One dense global row (BigBird/Fixed) drags ``max_live`` to
    ``nk`` and the padded form does more work than the dense vjp plus
    gather/scatter overhead (v5e, S=4096: local window L=3/nk=16 runs
    1.5-2.4x FASTER sparse; a global row making L=nk runs 0.68x) — the
    dense masked vjp was the backward there until the PER-ROW-COUNT
    bucketed form (:func:`_sparse_bwd_bucketed`) landed — rows grouped by
    live depth pay only their own work, so global rows stop taxing the
    grid.  This padded form still serves uniform-depth layouts (the
    single-bucket case, where padding is exact and the indexing simpler)
    and is the directly-tested reference for the bucketed math."""
    q, k, v = res
    layout = _layout_from_key(layout_key)
    S = q.shape[1]
    _, counts, _ = _plan(layout, S, block_q, block_k, cb, causal)
    # the bucketed backward's work is the TRUE live area (each row pays
    # its own depth), so the only reason to fall back to the dense vjp is
    # a layout that is mostly live anyway — there the gather/scatter
    # overhead buys nothing
    live_frac = _live_fraction(counts, S, block_q, block_k, causal)
    # beyond _DENSE_DISPATCH_MAX_S the dense vjp's O(S^2) logits stop
    # being materializable, so the bucketed form runs regardless of live
    # fraction (a 0.6-live S=32k layout must not OOM in backward when the
    # forward deliberately routed it to the kernel)
    if live_frac <= 0.5 or S > _DENSE_DISPATCH_MAX_S:
        _, _, _, buckets = _bwd_buckets(layout, S, block_q, block_k, cb,
                                        causal)
        if len(buckets) <= 1:
            # uniform live depth (local-window layouts): the padded form
            # IS the single bucket, with simpler indexing
            return _sparse_bwd_tiles(q, k, v, do, layout, cb, causal,
                                     block_q, block_k)
        return _sparse_bwd_bucketed(q, k, v, do, layout, cb, causal,
                                    block_q, block_k)

    def f(q, k, v):
        return _dense_reference(q, k, v, layout, cb, causal)

    _, vjp = jax.vjp(f, q, k, v)
    return vjp(do)


def _bs_vjp_fwd(q, k, v, layout_key, causal, block_q, block_k, cb,
                interpret):
    return _select_fwd(q, interpret)(q, k, v, layout_key, causal, block_q,
                                     block_k, cb, interpret)


_bs_attention.defvjp(_bs_vjp_fwd, _bs_bwd)


def block_sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           sparsity_config: Any, causal: bool = False,
                           block_q: int = 256, block_k: int = 256,
                           interpret: bool | None = None) -> jnp.ndarray:
    """[B, S, h, d] attention executing ONLY the k-blocks the config's
    layout marks live (per head when the layout is per-head).  Numerics
    match :func:`deepspeed_tpu.ops.sparse_attention.sparse_attention`
    (the dense masked path) to accumulation tolerance.

    Default 256-blocks: best measured on v5e at S=4096/bf16/BigBird
    (1.6x dense-masked; 128-blocks 1.4x — fewer loop iterations win
    until coarsening inflates live coverage)."""
    B, S, h, d = q.shape
    cb = sparsity_config.block
    layout = _norm_layout(sparsity_config.make_layout(S), h)
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _dense_reference(q, k, v, layout, cb, causal)
        interpret = False

    def fits(b):
        return b >= cb and b % cb == 0 and S % b == 0 and b % 8 == 0

    while block_q > cb and not fits(block_q):
        block_q //= 2
    while block_k > cb and not fits(block_k):
        block_k //= 2
    if not (fits(block_q) and fits(block_k)):
        return _dense_reference(q, k, v, layout, cb, causal)

    # fine-celled layouts can coarsen to near-dense at kernel-block
    # granularity (a 256-token block is live if ANY of its 16-token cells
    # is) — when most kernel blocks are live, the dense masked path's big
    # fused matmuls beat the tile loop (measured: cb=16 BigBird at S=4096
    # coarsens to 0.92 live and dense wins 2x).  Auto-dispatch exists to
    # pick the fastest correct impl, so route those to dense — but NOT
    # in interpret mode (that flag means "exercise the kernel", and the
    # kernel tests' tiny grids coarsen dense), and NOT at long S, where
    # the dense path's O(S^2) logits/mask stop being materializable.
    _, counts, _ = _plan(layout, S, block_q, block_k, cb, causal)
    if (not interpret and S <= _DENSE_DISPATCH_MAX_S
            and _live_fraction(counts, S, block_q, block_k,
                               causal) > 0.6):
        return _dense_reference(q, k, v, layout, cb, causal)
    key = (layout.tobytes(), layout.shape, layout.dtype.str)
    _LAYOUTS[key] = layout
    _LAYOUTS.move_to_end(key)
    while len(_LAYOUTS) > _LAYOUTS_MAX:
        _LAYOUTS.popitem(last=False)
    return _bs_attention(q, k, v, key, causal, block_q, block_k, cb,
                         interpret)
