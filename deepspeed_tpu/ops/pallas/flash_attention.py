"""Flash attention — Pallas TPU kernel with online softmax.

Role parity: the reference's fused attention kernels
(``csrc/transformer/`` + inference attention [K]) — here as a blocked
q-loop × online-softmax k-loop kernel that never materializes the
``[S, S]`` score matrix in HBM.

Forward is the Pallas kernel and also emits the per-row log-sum-exp so
the backward never has to re-derive softmax normalization.  Backward is a
flash-style chunked recompute: a ``lax.scan`` over k-blocks that holds at
most ``[B, h, S, block_k]`` of scores at a time (O(S·block) transient, not
O(S²)), using the standard ``delta = Σ_d do·o`` trick for the softmax
jacobian.  ``interpret=True`` (CPU testing) and the jnp reference path
keep numerics checkable everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _mask(S, T, causal, window=None):
    from ..masks import local_attention_mask

    return local_attention_mask(jnp.arange(S), jnp.arange(T),
                                causal=causal, window=window)


def _reference_attention(q, k, v, causal: bool, window=None):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal or window is not None:
        s = jnp.where(_mask(s.shape[-2], s.shape[-1], causal, window),
                      s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _reference_fwd_with_lse(q, k, v, causal: bool, window=None):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal or window is not None:
        s = jnp.where(_mask(s.shape[-2], s.shape[-1], causal, window),
                      s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B, h, S]
    p = jnp.exp(s - lse[..., None]).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v), lse


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
               block_k: int, seq_len: int, causal: bool, scale: float,
               window=None):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
    nk = seq_len // block_k

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(ki, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal or window is not None:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = q_pos >= k_pos if causal else jnp.bool_(True)
            if window is not None:
                reach = (q_pos - k_pos < window if causal
                         else jnp.abs(q_pos - k_pos) < window)
                keep = keep & reach
            s = jnp.where(keep, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # blocks strictly above the diagonal contribute nothing
        nk_eff = (qi * block_q + block_q + block_k - 1) // block_k
        nk_eff = jnp.minimum(nk_eff, nk)
    else:
        nk_eff = nk
    if window is not None:
        # sliding window: blocks entirely BEFORE the earliest reachable
        # position are skipped too — this is where flash beats the dense
        # mask for windowed (Mistral) configs: work per q block is
        # O(window), not O(S)
        k0 = jnp.maximum(qi * block_q - (window - 1), 0) // block_k
    else:
        k0 = 0
    m, l, acc = jax.lax.fori_loop(k0, nk_eff, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 512, block_k: int = 512,
                    window=None):
    """[B, S, h, d] attention; Pallas on TPU, jnp reference elsewhere.
    ``window`` = sliding-window reach (ops/masks semantics); the kernel
    skips k-blocks wholly outside the window.

    Default 512-blocks: measured 1.9x faster than 128-blocks on v5e at
    B=8/S=2048/d=64 (bigger MXU tiles, fewer grid steps; the [bq, bk]
    fp32 score tile is 1 MiB — comfortably inside VMEM)."""
    return _flash_fwd(q, k, v, causal, block_q, block_k, window)[0]


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


def _flash_call(q, k, v, causal, block_q, block_k, interpret,
                with_lse: bool = False, window=None):
    from jax.experimental import pallas as pl

    B, S, h, d = q.shape
    # shrink blocks to divisors of S that keep the (8, 128) sublane tiling
    # legal: S=1920 with 512-defaults runs the kernel at 128/128 instead
    # of the O(S^2) dense path; a non-8-aligned S (e.g. 321) can never
    # satisfy both constraints and drops to the dense reference
    def fit(b):
        b = min(b, S)
        while b >= 64 and (S % b or b % 8):
            b //= 2
        return b

    block_q, block_k = fit(block_q), fit(block_k)
    if block_q < 64 or block_k < 64:  # degenerate shapes → dense reference
        out, lse = _reference_fwd_with_lse(q, k, v, causal, window)
        return (out, lse) if with_lse else out
    # [B, S, h, d] -> [B*h, S, d]
    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)

    kernel = functools.partial(
        _fa_kernel, block_q=block_q, block_k=block_k, seq_len=S,
        causal=causal, scale=1.0 / np.sqrt(d), window=window)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * h, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            # lse as [B*h, S, 1]: trailing singleton keeps the block shape
            # legal under the (8, 128) TPU tiling rule for any block_q
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
            jax.ShapeDtypeStruct((B * h, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    out = out.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(B, h, S)  # drops the singleton
    return (out, lse) if with_lse else out


def _flash_fwd(q, k, v, causal, block_q, block_k, window=None):
    if _use_pallas():
        out, lse = _flash_call(q, k, v, causal, block_q, block_k,
                               interpret=False, with_lse=True,
                               window=window)
    else:
        out, lse = _reference_fwd_with_lse(q, k, v, causal, window)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, window, res, do):
    """Flash-style chunked backward: scan over k-blocks, O(S·block_k) live.

    Uses the saved per-row log-sum-exp (no softmax re-normalization pass)
    and ``delta_i = Σ_d do_i·o_i`` so the softmax jacobian term needs no
    cross-block reduction.
    """
    q, k, v, out, lse = res
    B, S, h, d = q.shape
    scale = 1.0 / np.sqrt(d)
    blk = min(block_k, S)
    while blk > 1 and S % blk:  # shrink to a divisor (matches _flash_call)
        blk //= 2
    if blk < 64:
        blk = S  # degenerate fall-back: one chunk (== full recompute)
    nk = S // blk

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    # delta: [B, h, S] — rowwise do·o
    delta = jnp.einsum("bqhd,bqhd->bhq", do32, out.astype(jnp.float32))

    k_chunks = k.reshape(B, nk, blk, h, d).transpose(1, 0, 2, 3, 4)
    v_chunks = v.reshape(B, nk, blk, h, d).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(S)

    def body(dq_acc, chunk):
        ki, kblk, vblk = chunk
        kb32 = kblk.astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, kb32) * scale
        if causal or window is not None:
            from ..masks import local_attention_mask

            k_pos = ki * blk + jnp.arange(blk)
            s = jnp.where(local_attention_mask(q_pos, k_pos, causal, window),
                          s, -1e30)
        p = jnp.exp(s - lse[..., None])  # [B, h, S, blk]
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do32, vblk.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, kb32) * scale
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, q32) * scale
        return dq_acc, (dk_blk, dv_blk)

    dq0 = jnp.zeros((B, S, h, d), jnp.float32)
    dq, (dk_chunks, dv_chunks) = jax.lax.scan(
        body, dq0, (jnp.arange(nk), k_chunks, v_chunks))
    dk = dk_chunks.transpose(1, 0, 2, 3, 4).reshape(B, S, h, d)
    dv = dv_chunks.transpose(1, 0, 2, 3, 4).reshape(B, S, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_interpret(q, k, v, causal: bool = True,
                              block_q: int = 64, block_k: int = 64,
                              window=None):
    """Interpreter-mode kernel run (CPU numerics testing)."""
    return _flash_call(q, k, v, causal, block_q, block_k, interpret=True,
                       window=window)
