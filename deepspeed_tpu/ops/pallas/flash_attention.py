"""Flash attention — Pallas TPU kernel with online softmax.

Role parity: the reference's fused attention kernels
(``csrc/transformer/`` + inference attention [K]) — here as a blocked
q-loop × online-softmax k-loop kernel that never materializes the
``[S, S]`` score matrix in HBM.

Forward is the Pallas kernel and also emits the per-row log-sum-exp so
the backward never has to re-derive softmax normalization.  Backward is a
flash-style chunked recompute: a ``lax.scan`` over k-blocks that holds at
most ``[B, h, S, block_k]`` of scores at a time (O(S·block) transient, not
O(S²)), using the standard ``delta = Σ_d do·o`` trick for the softmax
jacobian.  ``interpret=True`` (CPU testing) and the jnp reference path
keep numerics checkable everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _mask(S, T, causal, window=None):
    from ..masks import local_attention_mask

    return local_attention_mask(jnp.arange(S), jnp.arange(T),
                                causal=causal, window=window)


def _reference_attention(q, k, v, causal: bool, window=None):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal or window is not None:
        s = jnp.where(_mask(s.shape[-2], s.shape[-1], causal, window),
                      s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _reference_fwd_with_lse(q, k, v, causal: bool, window=None):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal or window is not None:
        s = jnp.where(_mask(s.shape[-2], s.shape[-1], causal, window),
                      s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B, h, S]
    p = jnp.exp(s - lse[..., None]).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v), lse


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
               block_k: int, seq_len: int, causal: bool, scale: float,
               window=None):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
    nk = seq_len // block_k

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(ki, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal or window is not None:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = q_pos >= k_pos if causal else jnp.bool_(True)
            if window is not None:
                reach = (q_pos - k_pos < window if causal
                         else jnp.abs(q_pos - k_pos) < window)
                keep = keep & reach
            s = jnp.where(keep, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # blocks strictly above the diagonal contribute nothing
        nk_eff = (qi * block_q + block_q + block_k - 1) // block_k
        nk_eff = jnp.minimum(nk_eff, nk)
    else:
        nk_eff = nk
    if window is not None:
        # sliding window: blocks entirely BEFORE the earliest reachable
        # position are skipped too — this is where flash beats the dense
        # mask for windowed (Mistral) configs: work per q block is
        # O(window), not O(S)
        k0 = jnp.maximum(qi * block_q - (window - 1), 0) // block_k
    else:
        k0 = 0
    m, l, acc = jax.lax.fori_loop(k0, nk_eff, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 512, block_k: int = 512,
                    window=None):
    """[B, S, h, d] attention; Pallas on TPU, jnp reference elsewhere.
    ``window`` = sliding-window reach (ops/masks semantics); the kernel
    skips k-blocks wholly outside the window.

    Default 512-blocks: measured 1.9x faster than 128-blocks on v5e at
    B=8/S=2048/d=64 (bigger MXU tiles, fewer grid steps; the [bq, bk]
    fp32 score tile is 1 MiB — comfortably inside VMEM)."""
    return _flash_fwd(q, k, v, causal, block_q, block_k, window)[0]


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


def _flash_fit_probe(b: int, S: int) -> int:
    """The block size _flash_call's ``fit`` would settle on (shared logic
    so the backward's kernel-eligibility check can't drift)."""
    b = min(b, S)
    while b >= 64 and (S % b or b % 8):
        b //= 2
    return b


def _flash_call(q, k, v, causal, block_q, block_k, interpret,
                with_lse: bool = False, window=None):
    from jax.experimental import pallas as pl

    B, S, h, d = q.shape
    # shrink blocks to divisors of S that keep the (8, 128) sublane tiling
    # legal: S=1920 with 512-defaults runs the kernel at 128/128 instead
    # of the O(S^2) dense path; a non-8-aligned S (e.g. 321) can never
    # satisfy both constraints and drops to the dense reference
    block_q = _flash_fit_probe(block_q, S)
    block_k = _flash_fit_probe(block_k, S)
    if block_q < 64 or block_k < 64:  # degenerate shapes → dense reference
        out, lse = _reference_fwd_with_lse(q, k, v, causal, window)
        return (out, lse) if with_lse else out
    # [B, S, h, d] -> [B*h, S, d]
    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)

    kernel = functools.partial(
        _fa_kernel, block_q=block_q, block_k=block_k, seq_len=S,
        causal=causal, scale=1.0 / np.sqrt(d), window=window)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * h, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            # lse as [B*h, S, 1]: trailing singleton keeps the block shape
            # legal under the (8, 128) TPU tiling rule for any block_q
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
            jax.ShapeDtypeStruct((B * h, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    out = out.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(B, h, S)  # drops the singleton
    return (out, lse) if with_lse else out


def _flash_fwd(q, k, v, causal, block_q, block_k, window=None):
    if _use_pallas():
        out, lse = _flash_call(q, k, v, causal, block_q, block_k,
                               interpret=False, with_lse=True,
                               window=window)
    else:
        out, lse = _reference_fwd_with_lse(q, k, v, causal, window)
    return out, (q, k, v, out, lse)


def _fa_bwd_dq_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref,
                      dq_ref, *, block_q: int, block_k: int, seq_len: int,
                      causal: bool, scale: float, window):
    """Pallas dq pass: grid (bh, q-block); K/V ride VMEM-resident (as in
    the forward) and the k-loop SKIPS blocks above the causal diagonal /
    outside the window — scores never touch HBM, and causal work is the
    true triangle, both of which the jnp chunked backward paid for."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    nk = seq_len // block_k
    q = q_ref[0].astype(jnp.float32)                   # [bq, d]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]                             # [bq]
    delta = delta_ref[0, :, 0]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(ki, acc):
        kblk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        keep = jnp.ones((block_q, block_k), jnp.bool_)
        if causal:
            keep = q_pos >= k_pos
        if window is not None:
            keep = keep & (q_pos - k_pos < window) & (k_pos - q_pos < window)
        p = jnp.where(keep, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return acc + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        nk_eff = (qi * block_q + block_q + block_k - 1) // block_k
        nk_eff = jnp.minimum(nk_eff, nk)
    else:
        nk_eff = nk
    k0 = 0
    if window is not None:
        k0 = jnp.maximum(qi * block_q - (window - 1), 0) // block_k
        if not causal:
            # window reaches forward too: clip k-blocks past the last
            # position any row of this q-block can see
            nk_eff = jnp.minimum(
                nk_eff,
                (qi * block_q + block_q - 1 + window + block_k - 1)
                // block_k)
    acc = jax.lax.fori_loop(
        k0, nk_eff, body, jnp.zeros((block_q, q.shape[-1]), jnp.float32))
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, *, block_q: int, block_k: int,
                       seq_len: int, causal: bool, scale: float, window):
    """Pallas dk/dv pass: grid (bh, k-block); Q/do/lse/Δ VMEM-resident,
    q-loop starts at the diagonal under causality.  dv += pᵀ·do,
    dk += dsᵀ·q·scale, accumulated in registers/VMEM — no segment-sum or
    HBM score chunks."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    nq = seq_len // block_q
    kblk = k_ref[0].astype(jnp.float32)                # [bk, d]
    vblk = v_ref[0].astype(jnp.float32)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(qi, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), 0]
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        keep = jnp.ones((block_q, block_k), jnp.bool_)
        if causal:
            keep = q_pos >= k_pos
        if window is not None:
            keep = keep & (q_pos - k_pos < window) & (k_pos - q_pos < window)
        p = jnp.where(keep, jnp.exp(s - lse[:, None]), 0.0)
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        return dk_acc, dv_acc

    q0 = (ki * block_k) // block_q if causal else 0
    nq_eff = nq
    if window is not None:
        # rows beyond the window's backward reach see nothing of this
        # k-block: clip both ends so windowed work is O(S·window), the
        # mirror of the dq pass (and the forward's k0 skip)
        nq_eff = jnp.minimum(
            nq, (ki * block_k + block_k - 1 + window + block_q - 1)
            // block_q)
        if not causal:
            q0 = jnp.maximum(ki * block_k - (window - 1), 0) // block_q
    d = kblk.shape[-1]
    dk_acc, dv_acc = jax.lax.fori_loop(
        q0, nq_eff, body, (jnp.zeros((block_k, d), jnp.float32),
                           jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, do, causal, block_q, block_k,
                      window, interpret: bool = False):
    """Kernel backward: dq + dk/dv passes with VMEM-resident scores.

    Replaces the jnp chunked scan, which materialized [B, h, S, block]
    fp32 score chunks in HBM (bandwidth-bound: ~4 such tensors per chunk)
    and computed the full S×block products even above the causal diagonal
    — measured 4x faster at B=8/S=2048/h=12/d=64 on v5e, taking the
    110M-headline attention from 7.5%% to ~30%% component efficiency."""
    from jax.experimental import pallas as pl

    B, S, h, d = q.shape
    # long S: the dkv pass holds q/do/lse/Δ VMEM-resident (O(S·d)), so
    # 512-blocks push scoped VMEM past the 16M limit at S>=8192 — cap
    # the backward blocks there (measured: no headline impact at S=2048)
    if S * d > 4096 * 64:
        block_q, block_k = min(block_q, 256), min(block_k, 256)
    block_q = _flash_fit_probe(block_q, S)
    block_k = _flash_fit_probe(block_k, S)
    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    dor = do.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    lse_r = lse.reshape(B * h, S, 1)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                            # [B, S, h]
    delta_r = delta.transpose(0, 2, 1).reshape(B * h, S, 1)
    scale = 1.0 / np.sqrt(d)

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, seq_len=S, causal=causal,
                          scale=scale, window=window),
        grid=(B * h, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
        interpret=interpret,
    )(qr, dor, kr, vr, lse_r, delta_r)

    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, seq_len=S, causal=causal,
                          scale=scale, window=window),
        grid=(B * h, S // block_k),
        in_specs=[
            pl.BlockSpec((1, S, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, S, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, S, 1), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, S, 1), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((B * h, S, d), k.dtype),
                   jax.ShapeDtypeStruct((B * h, S, d), v.dtype)],
        interpret=interpret,
    )(qr, dor, kr, vr, lse_r, delta_r)

    back = lambda a: a.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return back(dq), back(dk), back(dv)


def _flash_bwd(causal, block_q, block_k, window, res, do):
    """Backward dispatch: the Pallas kernel pair on TPU (VMEM-resident
    scores, causal-triangle work); the jnp chunked scan elsewhere.

    Uses the saved per-row log-sum-exp (no softmax re-normalization pass)
    and ``delta_i = Σ_d do_i·o_i`` so the softmax jacobian term needs no
    cross-block reduction.
    """
    q, k, v, out, lse = res
    B, S, h, d = q.shape
    if _use_pallas() and S % 64 == 0 and min(
            _flash_fit_probe(block_q, S), _flash_fit_probe(block_k, S)) >= 64:
        return _flash_bwd_pallas(q, k, v, out, lse, do, causal, block_q,
                                 block_k, window)
    scale = 1.0 / np.sqrt(d)
    blk = min(block_k, S)
    while blk > 1 and S % blk:  # shrink to a divisor (matches _flash_call)
        blk //= 2
    if blk < 64:
        blk = S  # degenerate fall-back: one chunk (== full recompute)
    nk = S // blk

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    # delta: [B, h, S] — rowwise do·o
    delta = jnp.einsum("bqhd,bqhd->bhq", do32, out.astype(jnp.float32))

    k_chunks = k.reshape(B, nk, blk, h, d).transpose(1, 0, 2, 3, 4)
    v_chunks = v.reshape(B, nk, blk, h, d).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(S)

    def body(dq_acc, chunk):
        ki, kblk, vblk = chunk
        kb32 = kblk.astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, kb32) * scale
        if causal or window is not None:
            from ..masks import local_attention_mask

            k_pos = ki * blk + jnp.arange(blk)
            s = jnp.where(local_attention_mask(q_pos, k_pos, causal, window),
                          s, -1e30)
        p = jnp.exp(s - lse[..., None])  # [B, h, S, blk]
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do32, vblk.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, kb32) * scale
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, q32) * scale
        return dq_acc, (dk_blk, dv_blk)

    dq0 = jnp.zeros((B, S, h, d), jnp.float32)
    dq, (dk_chunks, dv_chunks) = jax.lax.scan(
        body, dq0, (jnp.arange(nk), k_chunks, v_chunks))
    dk = dk_chunks.transpose(1, 0, 2, 3, 4).reshape(B, S, h, d)
    dv = dv_chunks.transpose(1, 0, 2, 3, 4).reshape(B, S, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_interpret(q, k, v, causal: bool = True,
                              block_q: int = 64, block_k: int = 64,
                              window=None):
    """Interpreter-mode kernel run (CPU numerics testing)."""
    return _flash_call(q, k, v, causal, block_q, block_k, interpret=True,
                       window=window)
