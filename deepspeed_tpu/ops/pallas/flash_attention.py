"""Flash attention — Pallas TPU kernel with online softmax.

Role parity: the reference's fused attention kernels
(``csrc/transformer/`` + inference attention [K]) — here as a blocked
q-loop × online-softmax k-loop kernel that never materializes the
``[S, S]`` score matrix in HBM.

The kernel family (dispatched by :func:`_flash_call` / :func:`_flash_bwd`):

* **resident** (fwd + dq/dkv backward): K/V (and in the dkv pass
  q/do/lse/Δ) ride VMEM whole; the k-loop walks the contiguous
  ``lattice.kv_block_bounds`` range, so causal work is the true
  triangle and windowed work is O(S·window).  Fastest while a head's
  planes fit the VMEM budget (``lattice.resident_fits``).
* **streamed** (fwd + dq/dkv backward): beyond VMEM residency the grid
  grows a live-step dimension and a scalar-prefetched ``index_map``
  DMAs ONLY each step's live block (``lattice.plan_q_live`` /
  ``plan_k_live`` — the same gather machinery as the block-sparse
  kernels, here walking the causal/window lattice).  VMEM holds one
  block; S is unbounded.

Block sizes are seq-length-aware (``lattice.auto_flash_blocks``) unless
the caller (or the tuning plane's ``kernels.flash_block_*`` dimensions)
pins them.  ``segment_ids`` masks cross-segment pairs (packed sequences
/ BERT padding) on the resident kernels and every reference path.

Forward also emits the per-row log-sum-exp so the backward never has to
re-derive softmax normalization; backward uses the standard
``delta = Σ_d do·o`` trick for the softmax jacobian.  ``interpret=True``
(CPU testing) and the jnp reference path keep numerics checkable
everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import lattice


def _mask(S, T, causal, window=None):
    from ..masks import local_attention_mask

    return local_attention_mask(jnp.arange(S), jnp.arange(T),
                                causal=causal, window=window)


def _full_mask(S, T, causal, window, segment_ids):
    """[B or 1, 1, S, T] bool combined mask (positions ∩ segments)."""
    m = _mask(S, T, causal, window)[None, None]
    if segment_ids is not None:
        seg = (segment_ids[:, None, :, None]
               == segment_ids[:, None, None, :])
        m = m & seg
    return m


def _reference_attention(q, k, v, causal: bool, window=None,
                         segment_ids=None):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal or window is not None or segment_ids is not None:
        s = jnp.where(_full_mask(s.shape[-2], s.shape[-1], causal, window,
                                 segment_ids), s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _reference_fwd_with_lse(q, k, v, causal: bool, window=None,
                            segment_ids=None):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal or window is not None or segment_ids is not None:
        s = jnp.where(_full_mask(s.shape[-2], s.shape[-1], causal, window,
                                 segment_ids), s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B, h, S]
    p = jnp.exp(s - lse[..., None]).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v), lse


# kept as the module-local name older callers/tests import; the logic
# lives in lattice.fit_block so forward/backward eligibility share it
_flash_fit_probe = lattice.fit_block


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


def _resolve_blocks(block_q, block_k, S, d, backward=False):
    """0/None → the seq-length table; explicit values are honored (then
    shrunk to legal divisors).  The backward CAPS explicit sizes at the
    table's choice — its resident passes hold extra O(S·d) planes, and a
    512-block at S≥8k pushes scoped VMEM past the limit."""
    abq, abk = lattice.auto_flash_blocks(S, d, backward=backward)
    block_q = min(block_q, abq) if (block_q and backward) else (block_q
                                                               or abq)
    block_k = min(block_k, abk) if (block_k and backward) else (block_k
                                                               or abk)
    return lattice.fit_block(block_q, S), lattice.fit_block(block_k, S)


# ---------------------------------------------------------------------------
# resident kernels
# ---------------------------------------------------------------------------


def _fa_kernel(q_ref, k_ref, v_ref, seg_ref, o_ref, lse_ref, *,
               block_q: int, block_k: int, seq_len: int, causal: bool,
               scale: float, window=None, has_seg: bool = False):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
    nk = seq_len // block_k

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    q_seg = (seg_ref[0, pl.ds(qi * block_q, block_q)] if has_seg else None)

    def body(ki, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_seg = (seg_ref[0, pl.ds(ki * block_k, block_k)] if has_seg
                 else None)
        keep = lattice.tile_keep(qi, ki, block_q, block_k, causal, window,
                                 q_seg, k_seg)
        if keep is not None:
            s = jnp.where(keep, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if has_seg:
            # a row fully masked in this tile must not accumulate the
            # exp(-1e30 − (-1e30)) = 1 garbage a pure -inf carry avoids
            p = jnp.where(keep, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    k0, nk_eff = lattice.kv_block_bounds(qi, block_q, block_k, nk, causal,
                                         window)
    m, l, acc = jax.lax.fori_loop(k0, nk_eff, body, (m0, l0, acc0))
    l2 = l[:, None]
    o_ref[0] = jnp.where(l2 > 0, acc / jnp.where(l2 > 0, l2, 1.0),
                         0.0).astype(o_ref.dtype)
    lse_ref[0] = jnp.where(l2 > 0, m[:, None] + jnp.log(
        jnp.where(l2 > 0, l2, 1.0)), 1e30)


# ---------------------------------------------------------------------------
# streamed forward (long S): gather each live k-block via the lattice plan
# ---------------------------------------------------------------------------


def _fa_stream_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref,
                      lse_ref, m_ref, l_ref, acc_ref, *, block_q: int,
                      block_k: int, causal: bool, scale: float, window,
                      max_live: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    s = pl.program_id(2)
    count = cnt_ref[qi]

    @pl.when(s == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(s < count)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale      # [bq, d]
        kblk = k_ref[0].astype(jnp.float32)           # [bk, d]
        vblk = v_ref[0].astype(jnp.float32)
        kj = idx_ref[qi, s]
        sc = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        keep = lattice.tile_keep(qi, kj, block_q, block_k, causal, window)
        if keep is not None:
            sc = jnp.where(keep, sc, -1e30)
        m, l = m_ref[:, 0], l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1))
        p = jnp.exp(sc - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new[:, None]
        l_ref[...] = l_new[:, None]
        acc_ref[...] = acc_new

    @pl.when(s == max_live - 1)
    def _finalize():
        l2 = l_ref[...]
        o_ref[0] = jnp.where(l2 > 0, acc_ref[...] / jnp.where(
            l2 > 0, l2, 1.0), 0.0).astype(o_ref.dtype)
        m1 = m_ref[...]
        lse_ref[0] = jnp.where(l2 > 0, m1 + jnp.log(
            jnp.where(l2 > 0, l2, 1.0)), 1e30)


def _flash_fwd_stream(qr, kr, vr, causal, block_q, block_k, window,
                      interpret):
    """[B*h, S, d] streamed forward over the lattice plan."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, d = qr.shape
    nq = S // block_q
    idx, counts = lattice.plan_q_live(S, block_q, block_k, causal, window)
    L = idx.shape[1]
    kern = functools.partial(_fa_stream_kernel, block_q=block_q,
                             block_k=block_k, causal=causal,
                             scale=1.0 / np.sqrt(d), window=window,
                             max_live=L)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nq, L),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, s, idx, cnt: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, s, idx, cnt: (bh, idx[qi, s], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, s, idx, cnt: (bh, idx[qi, s], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, s, idx, cnt: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qi, s, idx, cnt: (bh, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((BH, S, d), qr.dtype),
                   jax.ShapeDtypeStruct((BH, S, 1), jnp.float32)],
        interpret=bool(interpret),
    )(jnp.asarray(idx), jnp.asarray(counts), qr, kr, vr)


def _flash_call(q, k, v, causal, block_q, block_k, interpret,
                with_lse: bool = False, window=None, segment_ids=None,
                force_stream: bool = False):
    from jax.experimental import pallas as pl

    B, S, h, d = q.shape
    block_q, block_k = _resolve_blocks(block_q, block_k, S, d)
    if block_q < 64 or block_k < 64:  # degenerate shapes → dense reference
        out, lse = _reference_fwd_with_lse(q, k, v, causal, window,
                                           segment_ids)
        return (out, lse) if with_lse else out
    # [B, S, h, d] -> [B*h, S, d]
    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)

    stream = force_stream or not lattice.resident_fits(S, d)
    if stream and segment_ids is None:
        out, lse = _flash_fwd_stream(qr, kr, vr, causal, block_q, block_k,
                                     window, interpret)
        out = out.reshape(B, h, S, d).transpose(0, 2, 1, 3)
        lse = lse.reshape(B, h, S)
        return (out, lse) if with_lse else out
    # segments ride the resident kernel only (the streamed plan is a
    # pure position lattice); beyond residency they fall back dense —
    # packed long-sequence streaming is a later round
    has_seg = segment_ids is not None
    if stream and has_seg:
        out, lse = _reference_fwd_with_lse(q, k, v, causal, window,
                                           segment_ids)
        return (out, lse) if with_lse else out
    seg = (segment_ids.astype(jnp.int32) if has_seg
           else jnp.zeros((B, 1), jnp.int32))
    heads = h

    kernel = functools.partial(
        _fa_kernel, block_q=block_q, block_k=block_k, seq_len=S,
        causal=causal, scale=1.0 / np.sqrt(d), window=window,
        has_seg=has_seg)
    seg_block = (1, S) if has_seg else (1, 1)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * h, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec(seg_block,
                         lambda bh, qi: (bh // heads, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            # lse as [B*h, S, 1]: trailing singleton keeps the block shape
            # legal under the (8, 128) TPU tiling rule for any block_q
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
            jax.ShapeDtypeStruct((B * h, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, seg)
    out = out.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(B, h, S)  # drops the singleton
    return (out, lse) if with_lse else out


# ---------------------------------------------------------------------------
# resident backward kernels
# ---------------------------------------------------------------------------


def _fa_bwd_dq_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref,
                      seg_ref, dq_ref, *, block_q: int, block_k: int,
                      seq_len: int, causal: bool, scale: float, window,
                      has_seg: bool = False):
    """Pallas dq pass: grid (bh, q-block); K/V ride VMEM-resident (as in
    the forward) and the k-loop walks the lattice's contiguous live range
    — scores never touch HBM, and causal work is the true triangle."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    nk = seq_len // block_k
    q = q_ref[0].astype(jnp.float32)                   # [bq, d]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]                             # [bq]
    delta = delta_ref[0, :, 0]
    q_seg = (seg_ref[0, pl.ds(qi * block_q, block_q)] if has_seg else None)

    def body(ki, acc):
        kblk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_seg = (seg_ref[0, pl.ds(ki * block_k, block_k)] if has_seg
                 else None)
        keep = lattice.tile_keep(qi, ki, block_q, block_k, causal, window,
                                 q_seg, k_seg)
        p = jnp.exp(s - lse[:, None])
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return acc + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    k0, nk_eff = lattice.kv_block_bounds(qi, block_q, block_k, nk, causal,
                                         window)
    acc = jax.lax.fori_loop(
        k0, nk_eff, body, jnp.zeros((block_q, q.shape[-1]), jnp.float32))
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref,
                       seg_ref, dk_ref, dv_ref, *, block_q: int,
                       block_k: int, seq_len: int, causal: bool,
                       scale: float, window, has_seg: bool = False):
    """Pallas dk/dv pass: grid (bh, k-block); Q/do/lse/Δ VMEM-resident,
    q-loop walks the transposed lattice range.  dv += pᵀ·do,
    dk += dsᵀ·q·scale, accumulated in registers/VMEM — no segment-sum or
    HBM score chunks."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    nq = seq_len // block_q
    kblk = k_ref[0].astype(jnp.float32)                # [bk, d]
    vblk = v_ref[0].astype(jnp.float32)
    k_seg = (seg_ref[0, pl.ds(ki * block_k, block_k)] if has_seg else None)

    def body(qi, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), 0]
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_seg = (seg_ref[0, pl.ds(qi * block_q, block_q)] if has_seg
                 else None)
        keep = lattice.tile_keep(qi, ki, block_q, block_k, causal, window,
                                 q_seg, k_seg)
        p = jnp.exp(s - lse[:, None])
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        return dk_acc, dv_acc

    q0, nq_eff = lattice.q_block_bounds(ki, block_q, block_k, nq, causal,
                                        window)
    d = kblk.shape[-1]
    dk_acc, dv_acc = jax.lax.fori_loop(
        q0, nq_eff, body, (jnp.zeros((block_k, d), jnp.float32),
                           jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, do, causal, block_q, block_k,
                      window, interpret: bool = False, segment_ids=None):
    """Resident kernel backward: dq + dk/dv passes with VMEM-resident
    scores — measured 4x the jnp chunked scan at B=8/S=2048/h=12/d=64 on
    v5e (took the 110M-headline attention from 7.5%% to ~30%% component
    efficiency)."""
    from jax.experimental import pallas as pl

    B, S, h, d = q.shape
    block_q, block_k = _resolve_blocks(block_q, block_k, S, d,
                                       backward=True)
    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    dor = do.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    lse_r = lse.reshape(B * h, S, 1)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                            # [B, S, h]
    delta_r = delta.transpose(0, 2, 1).reshape(B * h, S, 1)
    scale = 1.0 / np.sqrt(d)
    has_seg = segment_ids is not None
    seg = (segment_ids.astype(jnp.int32) if has_seg
           else jnp.zeros((B, 1), jnp.int32))
    seg_block = (1, S) if has_seg else (1, 1)
    heads = h

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, seq_len=S, causal=causal,
                          scale=scale, window=window, has_seg=has_seg),
        grid=(B * h, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec(seg_block, lambda bh, qi: (bh // heads, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
        interpret=interpret,
    )(qr, dor, kr, vr, lse_r, delta_r, seg)

    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, seq_len=S, causal=causal,
                          scale=scale, window=window, has_seg=has_seg),
        grid=(B * h, S // block_k),
        in_specs=[
            pl.BlockSpec((1, S, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, S, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, S, 1), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, S, 1), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec(seg_block, lambda bh, ki: (bh // heads, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((B * h, S, d), k.dtype),
                   jax.ShapeDtypeStruct((B * h, S, d), v.dtype)],
        interpret=interpret,
    )(qr, dor, kr, vr, lse_r, delta_r, seg)

    back = lambda a: a.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return back(dq), back(dk), back(dv)


# ---------------------------------------------------------------------------
# streamed backward kernels (long S)
# ---------------------------------------------------------------------------


def _fa_bwd_dq_stream_kernel(idx_ref, cnt_ref, q_ref, do_ref, k_ref,
                             v_ref, lse_ref, delta_ref, dq_ref, acc_ref,
                             *, block_q: int, block_k: int, causal: bool,
                             scale: float, window):
    """Streamed dq: grid (bh, q-block, live-s); each step's K/V block is
    gathered by the prefetched lattice plan.  dq accumulates in VMEM
    scratch; the constant-over-s output index map flushes it at the
    q-row boundary (the block-sparse flat-walk write trick)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    s = pl.program_id(2)
    count = cnt_ref[qi]

    @pl.when(s == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(s < count)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        kblk = k_ref[0].astype(jnp.float32)
        vblk = v_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        kj = idx_ref[qi, s]
        sc = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        keep = lattice.tile_keep(qi, kj, block_q, block_k, causal, window)
        p = jnp.exp(sc - lse[:, None])
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        acc_ref[...] += jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _fa_bwd_dkv_stream_kernel(idx_ref, cnt_ref, q_ref, do_ref, k_ref,
                              v_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                              kacc_ref, vacc_ref, *, block_q: int,
                              block_k: int, causal: bool, scale: float,
                              window):
    """Streamed dk/dv: grid (bh, k-block, live-s) over the transposed
    plan; q/do/lse/Δ blocks gathered per step, dk/dv accumulate in
    scratch and flush at the k-column boundary."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    s = pl.program_id(2)
    count = cnt_ref[ki]

    @pl.when(s == 0)
    def _init():
        kacc_ref[...] = jnp.zeros_like(kacc_ref)
        vacc_ref[...] = jnp.zeros_like(vacc_ref)

    @pl.when(s < count)
    def _step():
        kblk = k_ref[0].astype(jnp.float32)
        vblk = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        qi = idx_ref[ki, s]
        sc = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        keep = lattice.tile_keep(qi, ki, block_q, block_k, causal, window)
        p = jnp.exp(sc - lse[:, None])
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        vacc_ref[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        kacc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    dk_ref[0] = kacc_ref[...].astype(dk_ref.dtype)
    dv_ref[0] = vacc_ref[...].astype(dv_ref.dtype)


def _flash_bwd_stream(q, k, v, out, lse, do, causal, block_q, block_k,
                      window, interpret: bool = False):
    """Streamed kernel backward — VMEM holds one tile's operands, HBM
    traffic follows the lattice's live count, S unbounded by residency."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, h, d = q.shape
    block_q, block_k = _resolve_blocks(block_q, block_k, S, d,
                                       backward=True)
    nq, nk = S // block_q, S // block_k
    qr = q.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    kr = k.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    vr = v.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    dor = do.transpose(0, 2, 1, 3).reshape(B * h, S, d)
    lse_r = lse.reshape(B * h, S, 1)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    delta_r = delta.transpose(0, 2, 1).reshape(B * h, S, 1)
    scale = 1.0 / np.sqrt(d)

    idx, counts = lattice.plan_q_live(S, block_q, block_k, causal, window)
    L = idx.shape[1]
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * h, nq, L),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, s, ix, ct: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, s, ix, ct: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, s, ix, ct: (bh, ix[qi, s], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, s, ix, ct: (bh, ix[qi, s], 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qi, s, ix, ct: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qi, s, ix, ct: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, s, ix, ct: (bh, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_stream_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          window=window),
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((B * h, S, d), q.dtype),
        interpret=bool(interpret),
    )(jnp.asarray(idx), jnp.asarray(counts), qr, dor, kr, vr, lse_r,
      delta_r)

    idx_k, counts_k = lattice.plan_k_live(S, block_q, block_k, causal,
                                          window)
    Lk = idx_k.shape[1]
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * h, nk, Lk),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, ki, s, ix, ct: (bh, ix[ki, s], 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda bh, ki, s, ix, ct: (bh, ix[ki, s], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, ki, s, ix, ct: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, ki, s, ix, ct: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, ki, s, ix, ct: (bh, ix[ki, s], 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, ki, s, ix, ct: (bh, ix[ki, s], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d),
                         lambda bh, ki, s, ix, ct: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, ki, s, ix, ct: (bh, ki, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_stream_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          window=window),
        grid_spec=dkv_spec,
        out_shape=[jax.ShapeDtypeStruct((B * h, S, d), k.dtype),
                   jax.ShapeDtypeStruct((B * h, S, d), v.dtype)],
        interpret=bool(interpret),
    )(jnp.asarray(idx_k), jnp.asarray(counts_k), qr, dor, kr, vr, lse_r,
      delta_r)

    back = lambda a: a.reshape(B, h, S, d).transpose(0, 2, 1, 3)
    return back(dq), back(dk), back(dv)


# ---------------------------------------------------------------------------
# custom_vjp wiring + public entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, seg, causal, block_q, block_k, window):
    return _flash_inner_fwd(q, k, v, seg, causal, block_q, block_k,
                            window)[0]


def _flash_inner_fwd(q, k, v, seg, causal, block_q, block_k, window):
    segment_ids = seg if seg is not None and seg.ndim == 2 \
        and seg.shape[1] == q.shape[1] else None
    if _use_pallas():
        out, lse = _flash_call(q, k, v, causal, block_q, block_k,
                               interpret=False, with_lse=True,
                               window=window, segment_ids=segment_ids)
    else:
        out, lse = _reference_fwd_with_lse(q, k, v, causal, window,
                                           segment_ids)
    return out, (q, k, v, seg, out, lse)


def _flash_inner_bwd(causal, block_q, block_k, window, res, do):
    """Backward dispatch: resident Pallas kernels while the planes fit
    VMEM, streamed kernels beyond, jnp chunked scan off-TPU.

    Uses the saved per-row log-sum-exp (no softmax re-normalization pass)
    and ``delta_i = Σ_d do_i·o_i`` so the softmax jacobian term needs no
    cross-block reduction."""
    q, k, v, seg, out, lse = res
    segment_ids = seg if seg is not None and seg.ndim == 2 \
        and seg.shape[1] == q.shape[1] else None
    B, S, h, d = q.shape
    bq, bk = _resolve_blocks(block_q, block_k, S, d, backward=True)
    dseg = np.zeros(seg.shape, dtype=jax.dtypes.float0)
    kernel_ok = _use_pallas() and S % 64 == 0 and min(bq, bk) >= 64
    # segments ride the resident kernels only (mirrors the forward)
    if kernel_ok and segment_ids is not None \
            and not lattice.resident_fits(S, d):
        kernel_ok = False
    if kernel_ok:
        if lattice.resident_fits(S, d):
            dq, dk, dv = _flash_bwd_pallas(
                q, k, v, out, lse, do, causal, block_q, block_k, window,
                segment_ids=segment_ids)
        else:
            dq, dk, dv = _flash_bwd_stream(
                q, k, v, out, lse, do, causal, block_q, block_k, window)
        return dq, dk, dv, dseg
    scale = 1.0 / np.sqrt(d)
    blk = min(bk if bk >= 1 else S, S)
    while blk > 1 and S % blk:  # shrink to a divisor (matches _flash_call)
        blk //= 2
    if blk < 64:
        blk = S  # degenerate fall-back: one chunk (== full recompute)
    nk = S // blk

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    # delta: [B, h, S] — rowwise do·o
    delta = jnp.einsum("bqhd,bqhd->bhq", do32, out.astype(jnp.float32))

    k_chunks = k.reshape(B, nk, blk, h, d).transpose(1, 0, 2, 3, 4)
    v_chunks = v.reshape(B, nk, blk, h, d).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(S)

    def body(dq_acc, chunk):
        ki, kblk, vblk = chunk
        kb32 = kblk.astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, kb32) * scale
        if causal or window is not None or segment_ids is not None:
            from ..masks import local_attention_mask

            k_pos = ki * blk + jnp.arange(blk)
            m = local_attention_mask(q_pos, k_pos, causal, window)[None,
                                                                   None]
            if segment_ids is not None:
                seg_m = (segment_ids[:, None, :, None]
                         == jax.lax.dynamic_slice_in_dim(
                             segment_ids, ki * blk, blk,
                             axis=1)[:, None, None, :])
                m = m & seg_m
            s = jnp.where(m, s, -1e30)
        p = jnp.exp(s - lse[..., None])  # [B, h, S, blk]
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do32, vblk.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, kb32) * scale
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, q32) * scale
        return dq_acc, (dk_blk, dv_blk)

    dq0 = jnp.zeros((B, S, h, d), jnp.float32)
    dq, (dk_chunks, dv_chunks) = jax.lax.scan(
        body, dq0, (jnp.arange(nk), k_chunks, v_chunks))
    dk = dk_chunks.transpose(1, 0, 2, 3, 4).reshape(B, S, h, d)
    dv = dv_chunks.transpose(1, 0, 2, 3, 4).reshape(B, S, h, d)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dseg)


_flash.defvjp(_flash_inner_fwd, _flash_inner_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 0, block_k: int = 0,
                    window=None, segment_ids=None):
    """[B, S, h, d] attention; Pallas on TPU, jnp reference elsewhere.

    ``block_q``/``block_k`` 0 → the seq-length-aware table
    (:func:`lattice.auto_flash_blocks`; forward and backward resolve
    independently).  ``window`` = sliding-window reach (ops/masks
    semantics); k-blocks wholly outside the lattice are skipped.
    ``segment_ids [B, S]`` masks cross-segment pairs (packed sequences,
    padding) on the resident kernels and all reference paths."""
    B, S = q.shape[0], q.shape[1]
    seg = (segment_ids.astype(jnp.int32) if segment_ids is not None
           else jnp.zeros((B, 1), jnp.int32))
    return _flash(q, k, v, seg, causal, int(block_q or 0),
                  int(block_k or 0), window)


def flash_attention_interpret(q, k, v, causal: bool = True,
                              block_q: int = 64, block_k: int = 64,
                              window=None, segment_ids=None,
                              stream: bool = False):
    """Interpreter-mode kernel run (CPU numerics testing); ``stream=True``
    forces the long-S gather kernels regardless of residency."""
    return _flash_call(q, k, v, causal, block_q, block_k, interpret=True,
                       window=window, segment_ids=segment_ids,
                       force_stream=stream)
