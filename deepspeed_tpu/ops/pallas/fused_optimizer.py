"""One-pass fused sharded optimizer — Pallas Adam + grad-norm kernels.

Role parity: the reference's fused CUDA Adam (``csrc/adam`` +
``ops/adam/fused_adam.py`` [K]) — multi-tensor apply collapsed into one
HBM sweep.  The optax chain the engine compiles costs 3–4 separate
sweeps over every gradient/param/moment plane per step (unscale sweep,
clip sweep, two moment updates, an ``updates`` tree materialized, then
``apply_updates``) — BENCH_r04 measured the isolated optax adamw update
at ``optax_adam_hbm_gbps = 352.9`` against the chip's ~820 GB/s peak.
The fused form is two passes total over the ZeRO shard:

1. :func:`tree_sqsum` — ONE read of the (still loss-scaled) grads
   producing the global grad-norm partial; the caller reduces it over
   the data-parallel group (comm verbs / GSPMD) and folds unscale +
   clip + overflow-zero into a single per-element multiplier.
2. :func:`fused_adam_tree` — ONE read of grads + params + moments and
   one write of params + moments: ``g·mult`` (unscale/clip applied on
   the fly), both Adam moments, bias correction, weight decay, and the
   param update, with ``input_output_aliases`` donating p/m/v in place.

Numerics mirror ``optax.scale_by_adam`` op-for-op — same formula, same
operation order.  Against the EAGER optax chain the first step from a
fresh state is bit-exact on the moments and ≤1 ulp on params; beyond
that the only divergence is XLA FMA contraction (``a·b + c`` fused into
one rounding where eager optax takes two — measured ≤1.2e-7 absolute on
params over 3 steps, and the engine's optax path is itself jitted so it
contracts the same way).  The parity tests in
``tests/unit/ops/test_fused_optimizer.py`` lock exactly this contract,
so an engine can flip ``kernels.fused_adam`` on without perturbing a
loss curve.
``interpret`` mode (CPU) lowers the same kernels through the Pallas
interpreter, keeping parity testable without a chip.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: kernel tile: (rows, 128) fp32 — rows per grid step.  64 rows × 128
#: lanes × 4 B = 32 KiB per plane per step; 7 resident planes ≈ 224 KiB,
#: comfortably double-buffered in VMEM.
_LANES = 128
_ROWS = 64
_CHUNK = _ROWS * _LANES


class FusedAdamConfig(NamedTuple):
    """Static hyperparameters (baked into the kernel at trace time)."""

    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    #: True → AdamW (decay added to the update direction, the optax
    #: ``adamw`` chain); False with weight_decay>0 → additive L2 (decay
    #: folded into the grads BEFORE the moments, the optax
    #: ``add_decayed_weights → adam`` chain)
    decoupled_wd: bool = True


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


def _pad_flat(x: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
    """Flatten to [rows, 128] fp32-tileable form, zero-padded."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    padded = -(-n // _CHUNK) * _CHUNK
    if padded != n:
        flat = jnp.concatenate(
            [flat, jnp.zeros((padded - n,), flat.dtype)])
    return flat.reshape(padded // _LANES, _LANES), n


# ---------------------------------------------------------------------------
# pass 1: grad-norm partials (one read per grad element)
# ---------------------------------------------------------------------------


def _sqsum_kernel(g_ref, out_ref):
    g = g_ref[...].astype(jnp.float32)
    out_ref[0, 0] = jnp.sum(g * g)


def leaf_sqsum(g: jnp.ndarray, interpret: Optional[bool] = None
               ) -> jnp.ndarray:
    """Σ g² of one leaf via the Pallas reduction kernel — one HBM read,
    per-tile partials summed on the host graph."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = not _use_pallas()
    rows2d, _ = _pad_flat(g)
    steps = rows2d.shape[0] // _ROWS
    partials = pl.pallas_call(
        _sqsum_kernel,
        grid=(steps,),
        in_specs=[pl.BlockSpec((_ROWS, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((steps, 1), jnp.float32),
        interpret=bool(interpret),
    )(rows2d)
    return jnp.sum(partials)


def tree_sqsum(grads: Any, interpret: Optional[bool] = None) -> jnp.ndarray:
    """Global Σ g² over a gradient tree (the grad-norm² partial for THIS
    shard; under GSPMD the sum over logical arrays already spans the
    mesh — multi-controller callers psum the result over the existing
    comm verbs)."""
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sum(jnp.stack([leaf_sqsum(g, interpret) for g in leaves]))


# ---------------------------------------------------------------------------
# pass 2: the fused update (one read of g/p/m/v, one write of p/m/v)
# ---------------------------------------------------------------------------


def _adam_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref,
                 vo_ref, *, b1: float, b2: float, eps: float, wd: float,
                 decoupled_wd: bool):
    """Mirrors ``optax.scale_by_adam``'s update op-for-op (same formula,
    same operation ORDER — the bit-parity contract).  ``sc_ref`` (SMEM)
    carries the traced scalars: [lr, mult, bc1, bc2]."""
    lr = sc_ref[0, 0]
    mult = sc_ref[0, 1]
    bc1 = sc_ref[0, 2]
    bc2 = sc_ref[0, 3]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * mult
    if wd and not decoupled_wd:
        # optax chain(add_decayed_weights, adam): decay enters the moments
        g = g + wd * p
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    m_new = (1.0 - b1) * g + b1 * m          # otu.tree_update_moment
    v_new = (1.0 - b2) * (g * g) + b2 * v    # ..._per_elem_norm
    mu_hat = m_new / bc1                     # tree_bias_correction
    nu_hat = v_new / bc2
    direction = mu_hat / (jnp.sqrt(nu_hat) + eps)
    if wd and decoupled_wd:
        # optax adamw: chain(scale_by_adam, add_decayed_weights, -lr)
        direction = direction + wd * p
    po_ref[...] = (p + (-lr) * direction).astype(po_ref.dtype)
    mo_ref[...] = m_new.astype(mo_ref.dtype)
    vo_ref[...] = v_new.astype(vo_ref.dtype)


def fused_adam_leaf(p, g, m, v, lr, mult, bc1, bc2,
                    cfg: FusedAdamConfig,
                    interpret: Optional[bool] = None):
    """One leaf through the fused kernel → (p_new, m_new, v_new)."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = not _use_pallas()
    shape, dtype = p.shape, p.dtype
    p2, n = _pad_flat(p)
    g2, _ = _pad_flat(g)
    m2, _ = _pad_flat(m)
    v2, _ = _pad_flat(v)
    steps = p2.shape[0] // _ROWS
    scalars = jnp.stack([jnp.asarray(lr, jnp.float32),
                         jnp.asarray(mult, jnp.float32),
                         jnp.asarray(bc1, jnp.float32),
                         jnp.asarray(bc2, jnp.float32)]).reshape(1, 4)
    kern = functools.partial(_adam_kernel, b1=cfg.b1, b2=cfg.b2,
                             eps=cfg.eps, wd=cfg.weight_decay,
                             decoupled_wd=cfg.decoupled_wd)
    kwargs = {}
    if not interpret:
        # donate p/m/v into their outputs — the in-place contract that
        # makes this ONE read + ONE write per element (the interpreter
        # doesn't support aliasing)
        kwargs["input_output_aliases"] = {1: 0, 3: 1, 4: 2}
    plane = lambda i: (i, 0)
    from jax.experimental.pallas import tpu as pltpu

    p_new, m_new, v_new = pl.pallas_call(
        kern,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((1, 4), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((_ROWS, _LANES), plane),
            pl.BlockSpec((_ROWS, _LANES), plane),
            pl.BlockSpec((_ROWS, _LANES), plane),
            pl.BlockSpec((_ROWS, _LANES), plane),
        ],
        out_specs=[pl.BlockSpec((_ROWS, _LANES), plane)] * 3,
        out_shape=[jax.ShapeDtypeStruct(p2.shape, dtype),
                   jax.ShapeDtypeStruct(p2.shape, m.dtype),
                   jax.ShapeDtypeStruct(p2.shape, v.dtype)],
        interpret=bool(interpret),
        **kwargs,
    )(scalars, p2, g2, m2, v2)
    unpad = lambda x2, dt: x2.reshape(-1)[:n].reshape(shape).astype(dt)
    return (unpad(p_new, dtype), unpad(m_new, m.dtype),
            unpad(v_new, v.dtype))


def fused_adam_tree(params: Any, grads: Any, mu: Any, nu: Any,
                    count_inc, lr, mult=1.0,
                    cfg: FusedAdamConfig = FusedAdamConfig(),
                    interpret: Optional[bool] = None):
    """Whole-tree fused update → (params', mu', nu').

    ``count_inc`` is the POST-increment step (optax
    ``safe_int32_increment(count)``); ``mult`` is the combined
    per-element gradient multiplier (loss-scale unscale × clip factor ×
    overflow zero) the engine folds in so no separate unscale/clip
    sweeps exist."""
    # bias corrections once per step (optax: 1 - decay**count_inc)
    cf = count_inc
    bc1 = 1.0 - jnp.asarray(cfg.b1, jnp.float32) ** cf
    bc2 = 1.0 - jnp.asarray(cfg.b2, jnp.float32) ** cf
    flat_p, treedef = jax.tree.flatten(params)
    flat_g = jax.tree.leaves(grads)
    flat_m = jax.tree.leaves(mu)
    flat_v = jax.tree.leaves(nu)
    out_p, out_m, out_v = [], [], []
    for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
        pn, mn, vn = fused_adam_leaf(p, g, m, v, lr, mult, bc1, bc2, cfg,
                                     interpret)
        out_p.append(pn)
        out_m.append(mn)
        out_v.append(vn)
    return (jax.tree.unflatten(treedef, out_p),
            jax.tree.unflatten(treedef, out_m),
            jax.tree.unflatten(treedef, out_v))


# ---------------------------------------------------------------------------
# optax-state surgery (the engine keeps optax's state LAYOUT so
# checkpoints, ZeRO sharding specs, and the non-fused path interchange)
# ---------------------------------------------------------------------------


def find_adam_state(opt_state) -> Tuple[Tuple[int, ...], Any]:
    """Locate the ``ScaleByAdamState`` inside an optax chain's state —
    recursing through nested plain tuples, since a chain-of-chains
    (``chain(add_decayed_weights, adam)``) nests the inner chain's state
    → (index path, state).  Raises with the observed layout when the
    chain carries none (the engine gates fused mode on adam-family
    optimizers, so this is a config bug worth naming)."""
    def walk(st, path):
        if hasattr(st, "mu") and hasattr(st, "nu") and hasattr(st,
                                                               "count"):
            return path, st
        if isinstance(st, tuple) and not hasattr(st, "_fields"):
            for i, sub in enumerate(st):
                hit = walk(sub, path + (i,))
                if hit is not None:
                    return hit
        return None

    hit = walk(opt_state, ())
    if hit is None:
        states = (opt_state if isinstance(opt_state, tuple)
                  else (opt_state,))
        raise ValueError(
            f"no ScaleByAdamState in optimizer state (got "
            f"{[type(s).__name__ for s in states]}) — kernels.fused_adam "
            f"requires an adam/adamw-family optimizer")
    return hit


def replace_adam_state(opt_state, path: Tuple[int, ...], new_state):
    if not path:
        return new_state
    if isinstance(opt_state, tuple) and not hasattr(opt_state, "_fields"):
        i = path[0]
        return (opt_state[:i]
                + (replace_adam_state(opt_state[i], path[1:], new_state),)
                + opt_state[i + 1:])
    return new_state


def apply_fused_adam(opt_state, params, grads, lr, mult,
                     cfg: FusedAdamConfig,
                     interpret: Optional[bool] = None):
    """The engine's step-time entry: optax-shaped ``opt_state`` in,
    (params', opt_state') out — two fused passes instead of the chain's
    3–4 sweeps.  Callers that skipped the separate unscale/clip sweeps
    pass their combined multiplier as ``mult``."""
    import optax

    path, adam = find_adam_state(opt_state)
    count_inc = optax.safe_int32_increment(adam.count)
    new_params, new_mu, new_nu = fused_adam_tree(
        params, grads, adam.mu, adam.nu, count_inc, lr, mult, cfg,
        interpret)
    new_adam = type(adam)(count=count_inc, mu=new_mu, nu=new_nu)
    new_state = replace_adam_state(opt_state, path, new_adam)

    def bump(st, p):
        # keep counter-only states (ScaleByScheduleState from a
        # schedule-built lr) marching so fused/non-fused checkpoints and
        # a mid-run fallback to the optax chain stay interchangeable
        if p == path:
            return st  # the adam state, already replaced
        if (hasattr(st, "_fields")
                and getattr(st, "_fields", ()) == ("count",)):
            return type(st)(count=optax.safe_int32_increment(st.count))
        if isinstance(st, tuple) and not hasattr(st, "_fields"):
            return tuple(bump(s, p + (i,)) for i, s in enumerate(st))
        return st

    return new_params, bump(new_state, ())


# ---------------------------------------------------------------------------
# jnp reference (the anchor the kernel parity tests lock against)
# ---------------------------------------------------------------------------


def reference_adam_tree(params, grads, mu, nu, count_inc, lr, mult=1.0,
                        cfg: FusedAdamConfig = FusedAdamConfig()):
    """Pure-jnp mirror of the kernel math (itself mirroring optax) —
    the second anchor in the three-way parity test: optax chain ==
    this == the Pallas kernel."""
    b1, b2, eps, wd = cfg.b1, cfg.b2, cfg.eps, cfg.weight_decay
    bc1 = 1.0 - jnp.asarray(b1, jnp.float32) ** count_inc
    bc2 = 1.0 - jnp.asarray(b2, jnp.float32) ** count_inc

    def leaf(p, g, m, v):
        g = g.astype(jnp.float32) * mult
        if wd and not cfg.decoupled_wd:
            g = g + wd * p
        m_new = (1.0 - b1) * g + b1 * m
        v_new = (1.0 - b2) * (g * g) + b2 * v
        direction = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if wd and cfg.decoupled_wd:
            direction = direction + wd * p
        return p + (-lr) * direction, m_new, v_new

    trees = [jax.tree.map(lambda *xs, i=i: leaf(*xs)[i], params, grads,
                          mu, nu) for i in range(3)]
    return tuple(trees)
