"""Pallas TPU kernels (reference csrc CUDA kernel roles, SURVEY §2.2):
flash attention (csrc/transformer fused attention), decode attention w/ KV
cache (csrc/transformer/inference), int8 quantizer (csrc/quantization for
ZeRO++ compressed collectives), one-pass fused Adam (csrc/adam fused
optimizer), and the shared block skip lattice every attention kernel
plans against."""

from .block_sparse_attention import block_sparse_attention
from .decode_attention import decode_attention
from .flash_attention import flash_attention
from .fused_optimizer import (FusedAdamConfig, apply_fused_adam,
                              fused_adam_tree, tree_sqsum)
from .quantizer import dequantize_int8, quantize_int8

__all__ = ["flash_attention", "decode_attention", "quantize_int8",
           "dequantize_int8", "block_sparse_attention", "FusedAdamConfig",
           "apply_fused_adam", "fused_adam_tree", "tree_sqsum"]
