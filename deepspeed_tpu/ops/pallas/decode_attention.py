"""Decode attention with KV cache — the inference-serving hot kernel.

Role parity: the reference's kernel-injection decode attention
(``csrc/transformer/inference/`` fused attention over a KV cache [K]) and
the inference-v2 ragged blocked-KV kernels.  Single-token queries attend
over a padded per-sequence cache with true lengths — the TPU-friendly
static-shape formulation of ragged batching.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _reference_decode(q, k_cache, v_cache, lengths):
    # q: [B, h, d]; caches: [B, Smax, h, d]; lengths: [B]
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhd,bkhd->bhk", q, k_cache).astype(jnp.float32) * scale
    Smax = k_cache.shape[1]
    mask = jnp.arange(Smax)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bkhd->bhd", p, v_cache)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                   s_max: int, scale: float):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    length = len_ref[b]
    q = q_ref[0].astype(jnp.float32) * scale  # [h, d]
    h, d = q.shape
    nk = s_max // block_k

    m0 = jnp.full((h,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((h,), jnp.float32)
    acc0 = jnp.zeros((h, d), jnp.float32)

    def body(ki, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(ki * block_k, block_k), :, :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block_k, block_k), :, :].astype(jnp.float32)
        # [block_k, h] scores — elementwise-multiply + d-reduce (VPU):
        # Mosaic cannot lower batched (per-head) dots, and decode is
        # memory-bound so the MXU is not the limiter here
        s = jnp.sum(kblk * q[None, :, :], axis=-1)
        pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, h), 0)
        s = jnp.where(pos < length, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=0))
        p = jnp.exp(s - m_new[None, :])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=0)
        acc_new = acc * alpha[:, None] + jnp.sum(
            p[:, :, None] * vblk, axis=0)
        return m_new, l_new, acc_new

    # only blocks below the length can contribute
    nk_eff = jnp.minimum((length + block_k - 1) // block_k, nk)
    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-9)[:, None]).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, lengths, block_k: int = 128,
                     interpret: bool | None = None):
    """q ``[B, h, d]`` one-token queries over padded caches
    ``[B, Smax, h, d]`` with per-sequence ``lengths [B]``."""
    from jax.experimental import pallas as pl

    if interpret is None:
        if jax.default_backend() != "tpu":
            return _reference_decode(q, k_cache, v_cache, lengths)
        interpret = False
    B, Smax, h, d = k_cache.shape
    block_k = min(block_k, Smax)
    if Smax % block_k:
        return _reference_decode(q, k_cache, v_cache, lengths)

    kernel = functools.partial(_decode_kernel, block_k=block_k, s_max=Smax,
                               scale=1.0 / np.sqrt(d))
    grid_spec = None
    from jax.experimental.pallas import tpu as pltpu

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, h, d), lambda b, lens: (b, 0, 0)),
                pl.BlockSpec((1, Smax, h, d), lambda b, lens: (b, 0, 0, 0)),
                pl.BlockSpec((1, Smax, h, d), lambda b, lens: (b, 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, h, d), lambda b, lens: (b, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B, h, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q, k_cache, v_cache)
    return out
