"""Decode attention with KV cache — the inference-serving hot kernel.

Role parity: the reference's kernel-injection decode attention
(``csrc/transformer/inference/`` fused attention over a KV cache [K]) and
the inference-v2 ragged blocked-KV kernels.  Single-token queries attend
over a padded per-sequence cache with true lengths — the TPU-friendly
static-shape formulation of ragged batching.

VMEM discipline: the KV sequence dimension is blocked through the *grid*
(``grid=(B, nk)``) so only one ``[block_k, h, d]`` tile of K and V is
resident at a time, with the online-softmax state (m, l, acc) carried in
VMEM scratch across the sequential inner grid axis.  Loading the whole
``[Smax, h, d]`` cache per sequence (h=32, d=128, Smax=8k, bf16 → ~64 MiB)
would blow the ~16 MiB VMEM budget and fail to lower on real hardware.
Blocks entirely beyond a sequence's true length clamp their DMA index to
the last valid block and skip compute, so ragged batches do no wasted I/O.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _reference_decode(q, k_cache, v_cache, lengths, window=None):
    # q: [B, h, d]; caches: [B, Smax, kv_h, d] with kv_h | h (GQA); lengths: [B]
    n_rep = q.shape[1] // k_cache.shape[2]
    if n_rep > 1:
        k_cache = jnp.repeat(k_cache, n_rep, axis=2)
        v_cache = jnp.repeat(v_cache, n_rep, axis=2)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhd,bkhd->bhk", q, k_cache).astype(jnp.float32) * scale
    Smax = k_cache.shape[1]
    pos = jnp.arange(Smax)[None, None, :]
    mask = pos < lengths[:, None, None]
    if window is not None:  # sliding window: only the last `window` tokens
        mask = mask & (pos >= lengths[:, None, None] - window)
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bkhd->bhd", p, v_cache)


def _num_valid_blocks(length, block_k):
    return jax.lax.div(length + block_k - 1, block_k)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                   *, block_k: int, num_blocks: int, scale: float,
                   n_rep: int):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    ki = pl.program_id(1)
    length = len_ref[b]
    nk_valid = _num_valid_blocks(length, block_k)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(ki < nk_valid)
    def _update():
        q = q_ref[0].astype(jnp.float32) * scale  # [h, d]
        h = q.shape[0]
        kblk = k_ref[0].astype(jnp.float32)  # [block_k, kv_h, d]
        vblk = v_ref[0].astype(jnp.float32)
        if n_rep > 1:  # GQA: expand KV heads in VMEM, not in the HBM cache
            kblk = jnp.repeat(kblk, n_rep, axis=1)
            vblk = jnp.repeat(vblk, n_rep, axis=1)
        # [block_k, h] scores — elementwise-multiply + d-reduce (VPU):
        # Mosaic cannot lower batched (per-head) dots, and decode is
        # memory-bound so the MXU is not the limiter here
        s = jnp.sum(kblk * q[None, :, :], axis=-1)
        pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, h), 0)
        s = jnp.where(pos < length, s, -1e30)
        m_prev = m_ref[0]  # [h]
        l_prev = l_ref[0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=0))
        p = jnp.exp(s - m_new[None, :])
        alpha = jnp.exp(m_prev - m_new)
        m_ref[0] = m_new
        l_ref[0] = l_prev * alpha + jnp.sum(p, axis=0)
        acc_ref[...] = (acc_ref[...] * alpha[:, None]
                        + jnp.sum(p[:, :, None] * vblk, axis=0))

    @pl.when(ki == num_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[0], 1e-9)[:, None]).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, lengths, block_k: int = 128,
                     interpret: bool | None = None, window=None):
    """q ``[B, h, d]`` one-token queries over padded caches
    ``[B, Smax, kv_h, d]`` (``kv_h`` divides ``h`` — GQA groups expanded
    inside the kernel) with per-sequence ``lengths [B]``.  ``window``
    (Mistral sliding window) routes to the masked reference path — the
    blocked kernel's window support (skipping pre-window blocks' DMA) is a
    serving optimization for a later round."""
    from jax.experimental import pallas as pl

    if window is not None:
        return _reference_decode(q, k_cache, v_cache, lengths, window)
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _reference_decode(q, k_cache, v_cache, lengths)
        interpret = False
    B, Smax, kv_h, d = k_cache.shape
    h = q.shape[1]
    n_rep = h // kv_h
    block_k = min(block_k, Smax)
    if Smax % block_k or h % kv_h:
        return _reference_decode(q, k_cache, v_cache, lengths)
    num_blocks = Smax // block_k

    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               num_blocks=num_blocks, scale=1.0 / np.sqrt(d),
                               n_rep=n_rep)
    from jax.experimental.pallas import tpu as pltpu

    def _kv_index(b, ki, lens):
        # Clamp out-of-range blocks onto the last valid one: the revisited
        # block's DMA is a no-op and compute is @pl.when-skipped, so ragged
        # tails cost nothing.
        nk_valid = _num_valid_blocks(lens[b], jnp.int32(block_k))
        return (b, jnp.minimum(ki, jnp.maximum(nk_valid - 1, 0)), 0, 0)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, num_blocks),
            in_specs=[
                pl.BlockSpec((1, h, d), lambda b, ki, lens: (b, 0, 0)),
                pl.BlockSpec((1, block_k, kv_h, d), _kv_index),
                pl.BlockSpec((1, block_k, kv_h, d), _kv_index),
            ],
            out_specs=pl.BlockSpec((1, h, d), lambda b, ki, lens: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((1, h), jnp.float32),      # running max m
                pltpu.VMEM((1, h), jnp.float32),      # running denom l
                pltpu.VMEM((h, d), jnp.float32),      # output accumulator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, h, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q, k_cache, v_cache)
    return out
