"""Paged decode attention — blocked-KV-cache kernel for inference v2.

Role parity: the reference FastGen ragged kernels
(``deepspeed/inference/v2/kernels/ragged_ops/`` — blocked KV cache with
linear/blocked attention over a block table [K], SURVEY §2.2 row "Inference
v2 kernels").  Sequences share one physical KV pool; a per-sequence block
table maps logical KV positions onto pool blocks, so memory is allocated in
``block_size`` pages instead of a padded ``[B, Smax]`` rectangle.

TPU-first formulation: the pool has a static shape ``[num_blocks,
block_size, kv_h, d]`` and the block table rides the kernel's scalar
prefetch, so the table lookup happens in the BlockSpec ``index_map`` —
the DMA engine fetches exactly the pages a sequence owns, one page per
sequential grid step, with the online-softmax state carried in VMEM
scratch (same discipline as ``decode_attention.py``; a page is the unit
of both allocation AND kernel tiling).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from ...utils.jax_compat import shard_map as _shard_map


def paged_decode_reference(q, k_pool, v_pool, block_tables, lengths,
                           window=None):
    """Pure-jnp reference.  ``q [B, h, d]``; pools ``[N, bs, kv_h, d]``;
    ``block_tables [B, max_blocks]``; ``lengths [B]``; ``window`` =
    sliding-window reach (only the last ``window`` cache entries)."""
    B = q.shape[0]
    _, bs, kv_h, d = k_pool.shape
    max_blocks = block_tables.shape[1]
    # gather each sequence's pages into a padded [B, max_blocks*bs, kv_h, d]
    k = k_pool[block_tables].reshape(B, max_blocks * bs, kv_h, d)
    v = v_pool[block_tables].reshape(B, max_blocks * bs, kv_h, d)
    n_rep = q.shape[1] // kv_h
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    scale = 1.0 / np.sqrt(d)
    s = jnp.einsum("bhd,bkhd->bhk", q, k).astype(jnp.float32) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, :]
    mask = pos < lengths[:, None, None]
    if window is not None:
        mask = mask & (pos >= lengths[:, None, None] - window)
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bkhd->bhd", p, v)


def _num_valid_blocks(length, block_size):
    return jax.lax.div(length + block_size - 1, block_size)


def _paged_kernel(len_ref, table_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, block_size: int, num_blocks: int,
                  scale: float, n_rep: int, window=None):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    ki = pl.program_id(1)
    length = len_ref[b]
    nk_valid = _num_valid_blocks(length, block_size)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if window is not None:
        # skip blocks wholly BEFORE the window: a fully-masked block would
        # otherwise poison the online softmax (exp(-1e30 - m) with m also
        # -1e30 is exp(0)); the boundary block always has >=1 live entry
        k0 = jnp.maximum(length - window, 0) // block_size
        in_range = (ki < nk_valid) & (ki >= k0)
    else:
        in_range = ki < nk_valid

    @pl.when(in_range)
    def _update():
        q = q_ref[0].astype(jnp.float32) * scale  # [h, d]
        h = q.shape[0]
        kblk = k_ref[0].astype(jnp.float32)  # [block_size, kv_h, d]
        vblk = v_ref[0].astype(jnp.float32)
        if n_rep > 1:  # GQA groups expand in VMEM, never in the pool
            kblk = jnp.repeat(kblk, n_rep, axis=1)
            vblk = jnp.repeat(vblk, n_rep, axis=1)
        s = jnp.sum(kblk * q[None, :, :], axis=-1)  # [block_size, h]
        pos = ki * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (block_size, h), 0)
        keep = pos < length
        if window is not None:  # sliding window: only the cache tail
            keep = keep & (pos >= length - window)
        s = jnp.where(keep, s, -1e30)
        m_prev = m_ref[0]
        l_prev = l_ref[0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=0))
        p = jnp.exp(s - m_new[None, :])
        alpha = jnp.exp(m_prev - m_new)
        m_ref[0] = m_new
        l_ref[0] = l_prev * alpha + jnp.sum(p, axis=0)
        acc_ref[...] = (acc_ref[...] * alpha[:, None]
                        + jnp.sum(p[:, :, None] * vblk, axis=0))

    @pl.when(ki == num_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[0], 1e-9)[:, None]).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, block_tables, lengths,
                           interpret: bool | None = None, window=None):
    """One-token queries ``q [B, h, d]`` over a shared paged KV pool
    ``[N, block_size, kv_h, d]`` addressed by ``block_tables [B, max_blocks]``
    with true ``lengths [B]``.  ``window`` (sliding-window attention) is
    handled natively by the kernel: out-of-window pages are skipped via the
    k0 grid start in ``_paged_kernel`` and the clamped ``_kv_index``, so no
    dead-page work is done."""
    from jax.experimental import pallas as pl

    if interpret is None:
        if jax.default_backend() != "tpu":
            return paged_decode_reference(q, k_pool, v_pool, block_tables,
                                          lengths, window)
        interpret = False
    B, h, d = q.shape
    _, block_size, kv_h, _ = k_pool.shape
    max_blocks = block_tables.shape[1]
    n_rep = h // kv_h
    if h % kv_h:
        return paged_decode_reference(q, k_pool, v_pool, block_tables,
                                      lengths, window)

    kernel = functools.partial(_paged_kernel, block_size=block_size,
                               num_blocks=max_blocks,
                               scale=1.0 / np.sqrt(d), n_rep=n_rep,
                               window=window)
    from jax.experimental.pallas import tpu as pltpu

    def _kv_index(b, ki, lens, table):
        # in-range pages resolve through the block table; out-of-range grid
        # steps clamp onto a valid page (the repeated DMA is a no-op and
        # compute is masked); with a window, pages wholly BEFORE the
        # window clamp forward onto the window's first page — their
        # compute is fully masked, and their DMA collapses to a revisit
        nk_valid = _num_valid_blocks(lens[b], jnp.int32(block_size))
        ki_c = jnp.minimum(ki, jnp.maximum(nk_valid - 1, 0))
        if window is not None:
            k0 = jnp.maximum(lens[b] - window, 0) // block_size
            ki_c = jnp.maximum(ki_c, k0)
        return (table[b, ki_c], 0, 0, 0)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, max_blocks),
            in_specs=[
                pl.BlockSpec((1, h, d), lambda b, ki, lens, table: (b, 0, 0)),
                pl.BlockSpec((1, block_size, kv_h, d), _kv_index),
                pl.BlockSpec((1, block_size, kv_h, d), _kv_index),
            ],
            out_specs=pl.BlockSpec((1, h, d),
                                   lambda b, ki, lens, table: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((1, h), jnp.float32),
                pltpu.VMEM((1, h), jnp.float32),
                pltpu.VMEM((h, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, h, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), block_tables.astype(jnp.int32),
      q, k_pool, v_pool)
    return out


def paged_decode_attention_tp(q, k_pool, v_pool, block_tables, lengths,
                              mesh, window=None):
    """TENSOR-PARALLEL paged decode: the Pallas kernel itself is not
    GSPMD-partitionable (custom call), so the partitioning is explicit —
    a ``shard_map`` over the ``tensor`` mesh axis on the HEAD dims.
    Attention heads are independent, so each TP rank runs the kernel on
    its local ``h/tp`` query heads against its local ``kv_h/tp`` pool
    slice with NO cross-rank communication; block tables and lengths are
    replicated metadata.  Requires ``tp | kv_heads`` (the serving engine
    enforces this at admission).

    Reference: the v2 inference kernels run TP-sharded the same way
    (SURVEY §2.2 inference-kernels row); this closes round 3's
    "einsum-fallback attention under TP serving" gap."""
    from ...parallel.mesh import AXIS_TENSOR

    P = jax.sharding.PartitionSpec

    def local(q_, kp, vp, bt, ln):
        return paged_decode_attention(q_, kp, vp, bt, ln, window=window)

    return _shard_map(
        local, mesh=mesh,
        in_specs=(P(None, AXIS_TENSOR, None),
                  P(None, None, AXIS_TENSOR, None),
                  P(None, None, AXIS_TENSOR, None), P(), P()),
        out_specs=P(None, AXIS_TENSOR, None),
        check_vma=False,
        axis_names={AXIS_TENSOR})(q, k_pool, v_pool,
                                  block_tables, lengths)
