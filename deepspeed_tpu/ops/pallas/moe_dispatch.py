"""Sparse MoE token dispatch/combine — the expert-parallel data plane.

Role parity: DeepSpeed's MoE dispatch is an explicit ``_AllToAll`` around a
dense einsum (``deepspeed/moe/sharded_moe.py`` [K], GShard arXiv 2006.16668);
the dense one-hot formulation costs O(T·E·C·H) FLOPs and materialises a
``[T, E, C]`` mask whose useful content is k·T entries.  This module lowers
the gating decision to INDEX form and moves tokens with gathers instead:

* dispatch: ``src_idx [E, C]`` — which token fills slot c of expert e
  (``EMPTY_SLOT`` for unfilled slots).  ``expert_in[e, c] = tokens[src]``
  is a pure row gather, O(E·C·H) traffic and exactly the dense einsum's
  result bit-for-bit (each slot has at most one contributing token, so the
  dense reduction degenerates to a copy).
* combine: ``flat_idx [T, K]`` into the flattened ``[E·C, H]`` expert
  output (``E·C`` addresses a zero pad row for dropped assignments) plus
  renormalized ``gates [T, K]`` — ``y[t] = Σ_k gates[t,k]·out[flat_idx[t,k]]``,
  O(k·T·H) instead of O(T·E·C·H).

Three rungs share these index semantics:

* ``*_reference`` — jnp ``take``-based, fully differentiable (``take``'s
  transpose is the scatter-add), GSPMD-friendly: this is what runs under an
  expert-sharded mesh, where the gather IS the all-to-all boundary.
* ``pallas_dispatch`` / ``pallas_combine`` — Pallas kernels riding
  ``PrefetchScalarGridSpec``: the index array is scalar-prefetched to SMEM
  and drives per-row dynamic-slice loads from a VMEM-resident token /
  expert-output block.  Forward-only kernels with a ``custom_vjp`` whose
  backward is the jnp reference (indices are routing decisions — integer,
  non-differentiable — so both paths share one backward).
* ``choose_dispatch_impl`` — the auto crossover: tiny T·E·C keeps the dense
  einsum (fusion beats bookkeeping), sharded meshes keep the jnp sparse
  path (``pallas_call`` does not self-partition under GSPMD), TPU +
  unsharded goes to the kernels.

Scratch accounting: the dispatch buffers ``[E, C, H]`` (+ pad rows) are
transient per-step bytes registered in the memory ledger under
``collective_scratch`` by the calling ``MOELayer``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

#: src_idx value marking an unfilled expert slot
EMPTY_SLOT = -1

#: auto crossover: dense einsum below this T·E·C volume (the [T,E,C] mask
#: is small enough that XLA's fused einsum beats gather bookkeeping)
DENSE_CROSSOVER_TEC = 1 << 16

#: fleet-profiler calibration multiplier on the crossover (ISSUE 20):
#: a measured compute factor > 1 means the device runs the dense einsum
#: slower than modeled, so the sparse path wins earlier (scale < 1)
_CROSSOVER_SCALE = 1.0


def set_crossover_scale(scale: float) -> None:
    """Scale the measured-once dense/sparse crossover by a calibration
    factor (``tuning.space.apply_calibration`` drives this from the
    persisted fleet-profiler factors).  Clamped to [0.25, 4] — a wild
    capture must not flip every dispatch decision."""
    global _CROSSOVER_SCALE
    _CROSSOVER_SCALE = min(max(float(scale), 0.25), 4.0)


def dense_crossover_tec() -> int:
    """The calibrated T·E·C crossover the auto impl compares against."""
    return max(int(DENSE_CROSSOVER_TEC * _CROSSOVER_SCALE), 1)

#: pallas combine tiles tokens in blocks of this many rows
_COMBINE_BLOCK_T = 128


# ---------------------------------------------------------------------------
# index construction (shared by every sparse rung)
# ---------------------------------------------------------------------------

def routing_to_indices(expert_idx: jnp.ndarray, slot: jnp.ndarray,
                       keep: jnp.ndarray, num_experts: int, capacity: int
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-choice routing ``(expert_idx [K,T], slot [K,T], keep [K,T])`` →
    ``(src_idx [E, C], flat_idx [T, K])``.

    ``src_idx[e, c]`` is the token id filling slot ``c`` of expert ``e``
    (``EMPTY_SLOT`` if none); ``flat_idx[t, k]`` indexes the flattened
    ``[E·C + 1, H]`` expert output, with ``E·C`` = the zero pad row for
    dropped assignments.  Kept ``(e, c)`` pairs are unique by construction
    (slot = cumulative position within the expert), so the scatter has no
    collisions.
    """
    E, C = num_experts, capacity
    K, T = expert_idx.shape
    tid = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T))
    flat_ec = jnp.where(keep, expert_idx * C + slot, E * C).astype(jnp.int32)
    src = jnp.full((E * C + 1,), EMPTY_SLOT, jnp.int32)
    src = src.at[flat_ec.reshape(-1)].set(tid.reshape(-1), mode="drop")
    src_idx = src[: E * C].reshape(E, C)
    flat_idx = flat_ec.T  # [T, K]
    return jax.lax.stop_gradient(src_idx), jax.lax.stop_gradient(flat_idx)


# ---------------------------------------------------------------------------
# jnp reference rung (differentiable; runs under GSPMD meshes)
# ---------------------------------------------------------------------------

def dispatch_reference(tokens: jnp.ndarray, src_idx: jnp.ndarray
                       ) -> jnp.ndarray:
    """``tokens [T, H]`` gathered into ``[E, C, H]`` expert buffers; empty
    slots come out zero.

    Deliberately clamp-and-mask instead of gathering from a ``[T+1, H]``
    zero-padded copy: the pad row makes the gather operand's leading dim
    indivisible by the mesh axes, and XLA's SPMD partitioner mishandles
    the unevenly-padded gather (wrong rows on non-zero shards).  Clamped
    in-bounds indices keep the operand evenly shardable.
    """
    T, H = tokens.shape
    E, C = src_idx.shape
    idx = jnp.clip(src_idx, 0, T - 1)
    out = jnp.take(tokens, idx.reshape(-1), axis=0).reshape(E, C, H)
    return out * (src_idx >= 0)[..., None].astype(tokens.dtype)


def combine_reference(expert_out: jnp.ndarray, flat_idx: jnp.ndarray,
                      gates: jnp.ndarray) -> jnp.ndarray:
    """``expert_out [E, C, H]`` + ``flat_idx/gates [T, K]`` →
    ``y [T, H] = Σ_k gates[t,k] · expert_out.flat[flat_idx[t,k]]``.

    Same clamp-and-mask scheme as :func:`dispatch_reference` (dropped
    assignments address ``E·C``, which is masked out) so the gather
    operand stays evenly shardable under GSPMD.
    """
    E, C, H = expert_out.shape
    flat = expert_out.reshape(E * C, H)
    valid = flat_idx < E * C
    idx = jnp.clip(flat_idx, 0, E * C - 1)
    picked = jnp.take(flat, idx.reshape(-1), axis=0)  # [T*K, H]
    picked = picked.reshape(*flat_idx.shape, H)
    w = jnp.where(valid, gates, 0.0)[..., None].astype(expert_out.dtype)
    return jnp.sum(w * picked, axis=1)


# ---------------------------------------------------------------------------
# pallas kernels (forward) — index-driven row gathers
# ---------------------------------------------------------------------------

def _dispatch_kernel(src_ref, tokens_ref, out_ref):
    """grid=(E,): fill one expert's ``[1, C, H]`` buffer by gathering rows
    of the VMEM-resident token block at scalar-prefetched indices."""
    from jax.experimental import pallas as pl

    e = pl.program_id(0)
    C = out_ref.shape[1]

    def body(c, _):
        idx = src_ref[e, c]
        safe = jnp.maximum(idx, 0)
        row = pl.load(tokens_ref, (pl.dslice(safe, 1), slice(None)))
        row = jnp.where(idx >= 0, row, jnp.zeros_like(row))
        pl.store(out_ref, (pl.dslice(0, 1), pl.dslice(c, 1), slice(None)),
                 row[None])
        return _

    jax.lax.fori_loop(0, C, body, 0)


def _combine_kernel(idx_ref, out_flat_ref, gates_ref, y_ref):
    """grid=(T/BT,): one token block's ``y[t] = Σ_k g·out[idx]`` with the
    flattened expert output resident in VMEM (pad row at E·C)."""
    from jax.experimental import pallas as pl

    t0 = pl.program_id(0) * y_ref.shape[0]
    BT = y_ref.shape[0]
    K = gates_ref.shape[1]

    def body(r, _):
        acc = jnp.zeros((1, y_ref.shape[1]), jnp.float32)
        for k in range(K):
            idx = idx_ref[t0 + r, k]
            row = pl.load(out_flat_ref, (pl.dslice(idx, 1), slice(None)))
            gk = pl.load(gates_ref, (pl.dslice(r, 1), pl.dslice(k, 1)))
            acc = acc + gk.astype(jnp.float32) * row.astype(jnp.float32)
        pl.store(y_ref, (pl.dslice(r, 1), slice(None)),
                 acc.astype(y_ref.dtype))
        return _

    jax.lax.fori_loop(0, BT, body, 0)


def _pallas_dispatch_fwd(tokens: jnp.ndarray, src_idx: jnp.ndarray,
                         interpret: bool) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, H = tokens.shape
    E, C = src_idx.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(E,),
        in_specs=[pl.BlockSpec((T, H), lambda e, src: (0, 0))],
        out_specs=pl.BlockSpec((1, C, H), lambda e, src: (e, 0, 0)),
    )
    return pl.pallas_call(
        _dispatch_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((E, C, H), tokens.dtype),
        interpret=interpret,
    )(src_idx, tokens)


def _pallas_combine_fwd(expert_out: jnp.ndarray, flat_idx: jnp.ndarray,
                        gates: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    E, C, H = expert_out.shape
    T, K = flat_idx.shape
    BT = min(_COMBINE_BLOCK_T, T)
    pad_T = (-T) % BT
    flat = jnp.concatenate(
        [expert_out.reshape(E * C, H),
         jnp.zeros((1, H), expert_out.dtype)], axis=0)
    gates_p = jnp.pad(gates, ((0, pad_T), (0, 0)))
    idx_p = jnp.pad(flat_idx, ((0, pad_T), (0, 0)),
                    constant_values=E * C)
    Tp = T + pad_T
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Tp // BT,),
        in_specs=[pl.BlockSpec((E * C + 1, H), lambda i, idx: (0, 0)),
                  pl.BlockSpec((BT, K), lambda i, idx: (i, 0))],
        out_specs=pl.BlockSpec((BT, H), lambda i, idx: (i, 0)),
    )
    y = pl.pallas_call(
        _combine_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Tp, H), expert_out.dtype),
        interpret=interpret,
    )(idx_p, flat, gates_p)
    return y[:T]


# -- custom_vjp wrappers: pallas forward, jnp-reference backward -----------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _pallas_dispatch(tokens, src_idx, interpret):
    return _pallas_dispatch_fwd(tokens, src_idx, interpret)


def _pallas_dispatch_vjp_fwd(tokens, src_idx, interpret):
    return _pallas_dispatch_fwd(tokens, src_idx, interpret), \
        (tokens.shape, src_idx)


def _pallas_dispatch_vjp_bwd(interpret, res, g):
    (T, H), src_idx = res
    # transpose of the gather: scatter-add each slot's cotangent back to
    # its source token (empty slots route to the dropped pad row)
    idx = jnp.where(src_idx >= 0, src_idx, T).reshape(-1)
    d_tokens = jnp.zeros((T + 1, H), g.dtype)
    d_tokens = d_tokens.at[idx].add(g.reshape(-1, H))[:T]
    return d_tokens, None


_pallas_dispatch.defvjp(_pallas_dispatch_vjp_fwd, _pallas_dispatch_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _pallas_combine(expert_out, flat_idx, gates, interpret):
    return _pallas_combine_fwd(expert_out, flat_idx, gates, interpret)


def _pallas_combine_vjp_fwd(expert_out, flat_idx, gates, interpret):
    y = _pallas_combine_fwd(expert_out, flat_idx, gates, interpret)
    return y, (expert_out, flat_idx, gates)


def _pallas_combine_vjp_bwd(interpret, res, g):
    expert_out, flat_idx, gates, = res
    E, C, H = expert_out.shape
    T, K = flat_idx.shape
    flat = jnp.concatenate(
        [expert_out.reshape(E * C, H),
         jnp.zeros((1, H), expert_out.dtype)], axis=0)
    picked = jnp.take(flat, flat_idx.reshape(-1), axis=0).reshape(T, K, H)
    d_gates = jnp.einsum("th,tkh->tk", g.astype(jnp.float32),
                         picked.astype(jnp.float32)).astype(gates.dtype)
    weighted = gates[..., None].astype(g.dtype) * g[:, None, :]  # [T,K,H]
    d_flat = jnp.zeros((E * C + 1, H), g.dtype)
    d_flat = d_flat.at[flat_idx.reshape(-1)].add(weighted.reshape(-1, H))
    d_eo = d_flat[: E * C].reshape(E, C, H)
    return d_eo, None, d_gates


_pallas_combine.defvjp(_pallas_combine_vjp_fwd, _pallas_combine_vjp_bwd)


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def pallas_dispatch(tokens: jnp.ndarray, src_idx: jnp.ndarray,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Pallas token dispatch: ``tokens [T, H]`` + ``src_idx [E, C]`` →
    ``[E, C, H]``.  Off-TPU (``interpret=None``) falls back to the jnp
    reference; ``interpret=True`` forces the kernel in interpret mode
    (the parity harness)."""
    if interpret is None:
        if jax.default_backend() != "tpu":
            return dispatch_reference(tokens, src_idx)
        interpret = False
    return _pallas_dispatch(tokens, src_idx, interpret)


def pallas_combine(expert_out: jnp.ndarray, flat_idx: jnp.ndarray,
                   gates: jnp.ndarray,
                   interpret: Optional[bool] = None) -> jnp.ndarray:
    """Pallas token combine: ``expert_out [E, C, H]`` + ``flat_idx/gates
    [T, K]`` → ``y [T, H]``.  Fallback semantics mirror
    :func:`pallas_dispatch`."""
    if interpret is None:
        if jax.default_backend() != "tpu":
            return combine_reference(expert_out, flat_idx, gates)
        interpret = False
    return _pallas_combine(expert_out, flat_idx, gates, interpret)


def dispatch_scratch_bytes(num_experts: int, capacity: int, hidden: int,
                           dtype=jnp.float32, k: int = 2) -> int:
    """Analytic transient bytes of the sparse dispatch plane (expert in/out
    buffers + pad rows + index arrays) for the memory ledger's
    ``collective_scratch`` pool."""
    itemsize = jnp.dtype(dtype).itemsize
    buffers = 2 * num_experts * capacity * hidden * itemsize  # in + out
    pad = 2 * hidden * itemsize
    indices = (num_experts * capacity + 1) * 4 + 2 * k * 4
    return int(buffers + pad + indices)


def choose_dispatch_impl(impl: str, num_tokens: int, num_experts: int,
                         capacity: int, sharded: bool = False) -> str:
    """Resolve a requested dispatch impl (``auto``/``dense``/``sparse``/
    ``pallas``) to a concrete one.

    ``auto``: small T·E·C keeps the fused dense einsum; expert-sharded
    meshes take the jnp sparse path (``pallas_call`` does not partition
    itself under GSPMD — the gather is the all-to-all boundary and belongs
    to the compiler); unsharded TPU gets the kernels.
    """
    if impl not in ("auto", "dense", "sparse", "pallas"):
        raise ValueError(
            f"unknown moe dispatch impl {impl!r} "
            "(expected auto|dense|sparse|pallas)")
    if impl != "auto":
        if impl == "pallas" and sharded:
            return "sparse"
        return impl
    if num_tokens * num_experts * capacity <= dense_crossover_tec():
        return "dense"
    if sharded or jax.default_backend() != "tpu":
        return "sparse"
    return "pallas"
