"""Int8 blockwise quantizer — ZeRO++-style compressed collectives.

Role parity: ``csrc/quantization/`` [K] — symmetric int8 (de)quantization
with per-row scales, used to compress the weights all-gather (qwZ) and
gradient reduce (qgZ) (arXiv 2306.10209 [P]).

The op is memory-bound and simple enough that XLA fuses the jnp reference
to a single pass; the Pallas kernel exists for fusion with surrounding
collective-permute steps and as the building block for quantized
collectives.  Both paths share numerics and are cross-checked in tests.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _ref_quantize(x2d):
    amax = jnp.max(jnp.abs(x2d), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x2d.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale[:, 0]


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def quantize_int8(x: jnp.ndarray, block_rows: int = 256,
                  interpret: bool | None = None
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-row int8 quantization of a 2D ``[R, C]`` array →
    ``(int8 [R, C], scales f32 [R])``.  Higher-rank inputs are flattened to
    rows of the last dim."""
    from jax.experimental import pallas as pl

    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    R, C = x2d.shape
    if interpret is None:
        if jax.default_backend() != "tpu":
            q, s = _ref_quantize(x2d)
            return q.reshape(shape), s.reshape(shape[:-1])
        interpret = False
    block_rows = min(block_rows, R)
    if R % block_rows:
        q, s = _ref_quantize(x2d)
        return q.reshape(shape), s.reshape(shape[:-1])
    q, s = pl.pallas_call(
        _quant_kernel,
        grid=(R // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, C), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((block_rows, C), lambda i: (i, 0)),
                   pl.BlockSpec((block_rows, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, C), jnp.int8),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32)],
        interpret=interpret,
    )(x2d)
    return q.reshape(shape), s[:, 0].reshape(shape[:-1])


def dequantize_int8(q: jnp.ndarray, scales: jnp.ndarray,
                    dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`quantize_int8`."""
    return (q.astype(jnp.float32) * scales[..., None]).astype(dtype)
