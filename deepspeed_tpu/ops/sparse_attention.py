"""Block-sparse attention — sparsity configs + masked attention.

Reference: ``deepspeed/ops/sparse_attention/`` + ``csrc/sparse_attention``
[K] (SURVEY §2.2 "Sparse attention"): Triton block-sparse kernels driven
by ``SparsityConfig`` subclasses (``Fixed``, ``BigBird``,
``BSLongformer``, ``Variable``, ``Dense``) whose ``make_layout`` emits a
[blocks, blocks] mask of which key blocks each query block touches.

TPU-first: the LAYOUT is the portable artifact.  Compute here applies the
block mask inside the standard fp32-softmax attention — XLA folds the
mask into the fused softmax, and because whole masked blocks contribute
-inf the compiler's dead-block elimination plus the mask'd softmax give
correctness on any backend.  The bandwidth win at long S belongs to a
Pallas splash-attention kernel consuming the same layout (the kernel
skips masked blocks' DMA entirely); layout→kernel hookup is the later
optimization, layout semantics are the parity surface.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class SparsityConfig:
    """Base: dense layout (reference ``DenseSparsityConfig`` behavior)."""

    def __init__(self, num_heads: int = 1, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def _blocks(self, seq_len: int) -> int:
        if seq_len % self.block:
            raise ValueError(f"seq_len {seq_len} not divisible by block "
                             f"{self.block}")
        return seq_len // self.block

    def _head_layout(self, seq_len: int, head: int) -> np.ndarray:
        """One head's [nb, nb] layout; subclasses with head-varying
        patterns (BigBird's random blocks) override or consume ``head``."""
        n = self._blocks(seq_len)
        return np.ones((n, n), np.int32)

    def make_layout(self, seq_len: int) -> np.ndarray:
        """[nb, nb] shared layout, or [num_heads, nb, nb] when
        ``different_layout_per_head`` (reference layout shapes).  Patterns
        that don't actually vary per head (Fixed/Longformer) collapse back
        to the shared 2-D form — h× identical masks would cost h× memory
        for nothing."""
        if self.different_layout_per_head:
            per_head = [self._head_layout(seq_len, h)
                        for h in range(self.num_heads)]
            if all(np.array_equal(per_head[0], l) for l in per_head[1:]):
                return per_head[0]
            return np.stack(per_head)
        return self._head_layout(seq_len, 0)


class FixedSparsityConfig(SparsityConfig):
    """Reference ``FixedSparsityConfig`` [K]: local windows of
    ``num_local_blocks`` + every window's last ``num_global_blocks``
    attended globally."""

    def __init__(self, num_heads: int = 1, block: int = 16,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional", **kw):
        super().__init__(num_heads, block, **kw)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def _head_layout(self, seq_len: int, head: int) -> np.ndarray:
        n = self._blocks(seq_len)
        lay = np.zeros((n, n), np.int32)
        for qb in range(n):
            w0 = (qb // self.num_local_blocks) * self.num_local_blocks
            lay[qb, w0:min(w0 + self.num_local_blocks, n)] = 1  # local window
        # global: the last num_global_blocks of every window are visible
        # to all queries (and attend everything)
        for w0 in range(0, n, self.num_local_blocks):
            g0 = min(w0 + self.num_local_blocks, n) - self.num_global_blocks
            for g in range(max(g0, 0), min(w0 + self.num_local_blocks, n)):
                lay[:, g] = 1
                lay[g, :] = 1
        if self.attention == "unidirectional":
            lay = np.tril(lay)
        return lay


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + designated global blocks (Longformer pattern)."""

    def __init__(self, num_heads: int = 1, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices=(0,), **kw):
        super().__init__(num_heads, block, **kw)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = tuple(global_block_indices)

    def _head_layout(self, seq_len: int, head: int) -> np.ndarray:
        n = self._blocks(seq_len)
        lay = np.zeros((n, n), np.int32)
        half = self.num_sliding_window_blocks // 2
        for qb in range(n):
            lay[qb, max(0, qb - half):min(n, qb + half + 1)] = 1
        for g in self.global_block_indices:
            if g < n:
                lay[:, g] = 1
                lay[g, :] = 1
        return lay


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding window + global blocks (BigBird pattern)."""

    def __init__(self, num_heads: int = 1, block: int = 16,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, seed: int = 0, **kw):
        super().__init__(num_heads, block, **kw)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.seed = seed

    def _head_layout(self, seq_len: int, head: int) -> np.ndarray:
        n = self._blocks(seq_len)
        lay = np.zeros((n, n), np.int32)
        half = self.num_sliding_window_blocks // 2
        # per-head layouts differ by their RANDOM blocks (reference BigBird)
        rng = np.random.RandomState(self.seed + head)
        for qb in range(n):
            lay[qb, max(0, qb - half):min(n, qb + half + 1)] = 1
            if n > self.num_random_blocks:
                lay[qb, rng.choice(n, self.num_random_blocks,
                                   replace=False)] = 1
        for g in range(min(self.num_global_blocks, n)):
            lay[:, g] = 1
            lay[g, :] = 1
            lay[:, n - 1 - g] = 1
            lay[n - 1 - g, :] = 1
        return lay


class VariableSparsityConfig(FixedSparsityConfig):
    """Reference name kept: fixed pattern with per-call window override."""


def block_layout_to_token_mask(layout: np.ndarray, block: int,
                               causal: bool = False) -> jnp.ndarray:
    """[nb, nb] (or per-head [h, nb, nb]) block layout → [S, S]
    (or [h, S, S]) boolean token mask."""
    if layout.ndim == 3:
        mask = jnp.asarray(np.stack(
            [np.kron(l, np.ones((block, block))) for l in layout]) > 0)
    else:
        mask = jnp.asarray(np.kron(layout, np.ones((block, block))) > 0)
    if causal:
        S = mask.shape[-1]
        mask = mask & jnp.tril(jnp.ones((S, S), bool))
    return mask


def sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     sparsity_config: SparsityConfig,
                     causal: bool = False,
                     key_padding_mask: Optional[jnp.ndarray] = None,
                     impl: str = "auto") -> jnp.ndarray:
    """[B, S, h, d] attention under a block-sparse layout.

    ``impl``: "auto" routes to the Pallas block-skipping kernel
    (:mod:`.pallas.block_sparse_attention`) on TPU when no padding mask is
    given — O(live·block) work per q-block; "dense" forces the masked
    reference below (also the kernel's numerics anchor)."""
    if impl == "auto" and key_padding_mask is None:
        import jax as _jax

        if _jax.default_backend() == "tpu":
            from .pallas.block_sparse_attention import block_sparse_attention

            return block_sparse_attention(q, k, v, sparsity_config, causal)
    S = q.shape[1]
    layout = sparsity_config.make_layout(S)
    mask = block_layout_to_token_mask(layout, sparsity_config.block, causal)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    m = mask[None] if mask.ndim == 3 else mask[None, None]
    if key_padding_mask is not None:
        m = m & key_padding_mask[:, None, None, :].astype(bool)
    s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    # a fully-masked query row softmaxes garbage — zero it explicitly
    p = jnp.where(jnp.any(m, axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
