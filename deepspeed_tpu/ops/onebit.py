"""1-bit compressed gradient reduction with error feedback.

Role parity: the reference 1-bit optimizer family —
``deepspeed/runtime/fp16/onebit/{adam,lamb,zoadam}.py`` [K] (papers: 1-bit
Adam arXiv 2102.02888, 0/1 Adam, 1-bit LAMB) — whose core mechanism is:
compress the worker-local update to sign bits + a scale, carry the
compression error into the next step (error feedback), and allreduce only
the compressed representation.

TPU-first shape: the compressed allreduce is a pure function over the DP
mesh axes designed to run inside ``jax.shard_map`` (partial-manual, so TP/SP
GSPMD axes compose): each worker packs the signs of (grad + residual) into
a uint8 bitmask (TRUE 1 bit/element on the wire — 32× smaller than fp32)
plus one fp32 scale per tensor, ``lax.all_gather``s the packed words over
ICI, and decompresses/averages locally.  The residual keeps what
compression lost, so the bias is corrected over steps (EF-SGD/1-bit Adam
guarantee).  Engine integration: ``OnebitAdam``/``OnebitLamb``/
``ZeroOneAdam`` config types flip the engine's grad computation into the
shard_map local-grad path with this reducer in place of the automatic
GSPMD psum (``runtime/engine.py``).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..comm.comm import all_gather_in_graph

_POW2 = np.asarray([1, 2, 4, 8, 16, 32, 64, 128], np.uint8)


def _pad_to(n: int, mult: int) -> int:
    return (n + mult - 1) // mult * mult


def pack_signs(x: jnp.ndarray) -> jnp.ndarray:
    """Flat fp tensor → uint8 bitmask of its sign bits (1 = non-negative).
    Length is padded up to a multiple of 8 elements."""
    n = x.size
    bits = (x.reshape(-1) >= 0).astype(jnp.uint8)
    padded = _pad_to(n, 8)
    if padded != n:
        bits = jnp.concatenate([bits, jnp.zeros((padded - n,), jnp.uint8)])
    return (bits.reshape(-1, 8) * _POW2).sum(axis=1).astype(jnp.uint8)


def unpack_signs(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """uint8 bitmask → ±1 fp32 signs of length ``n``."""
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    signs = bits.reshape(-1)[:n].astype(jnp.float32)
    return signs * 2.0 - 1.0


def compress(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """x → (packed_signs, scale, decompressed).  ``scale`` is the L1 mean —
    the magnitude that makes sign·scale an unbiased-ish estimate."""
    flat = x.reshape(-1).astype(jnp.float32)
    scale = jnp.mean(jnp.abs(flat))
    packed = pack_signs(flat)
    decompressed = (unpack_signs(packed, flat.size) * scale).reshape(x.shape)
    return packed, scale, decompressed


def onebit_allreduce(grad: jnp.ndarray, residual: jnp.ndarray,
                     axis_names: Sequence[str]
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Error-feedback compressed mean-allreduce of ONE tensor.

    Runs inside shard_map: ``grad`` is this worker's local gradient,
    ``residual`` its carried compression error.  Wire cost per worker:
    ``n/8`` bytes of signs + 4 bytes of scale (vs ``4n`` for fp32 psum).
    Returns (averaged decompressed update, new residual).
    """
    corrected = grad.astype(jnp.float32) + residual
    packed, scale, local_dec = compress(corrected)
    new_residual = corrected - local_dec

    names = tuple(axis_names)
    gathered = packed
    gscale = scale
    for ax in names:
        gathered = all_gather_in_graph(gathered, ax, tiled=False)
        gscale = all_gather_in_graph(gscale, ax, tiled=False)
    world = int(np.prod(gathered.shape[:len(names)]))
    gathered = gathered.reshape(world, -1)
    gscale = gscale.reshape(world)
    n = grad.size
    per_worker = jax.vmap(lambda p, s: unpack_signs(p, n) * s)(gathered,
                                                              gscale)
    avg = jnp.mean(per_worker, axis=0).reshape(grad.shape)
    return avg.astype(grad.dtype), new_residual


def onebit_reduce_tree(grads: Any, residuals: Any,
                       axis_names: Sequence[str]) -> Tuple[Any, Any]:
    """Pytree version of :func:`onebit_allreduce`."""
    flat_g, treedef = jax.tree.flatten(grads)
    flat_r = jax.tree.leaves(residuals)
    out_g, out_r = [], []
    for g, r in zip(flat_g, flat_r):
        a, nr = onebit_allreduce(g, r, axis_names)
        out_g.append(a)
        out_r.append(nr)
    return jax.tree.unflatten(treedef, out_g), jax.tree.unflatten(treedef,
                                                                  out_r)


def init_residuals(params: Any, dp_world: int = 0) -> Any:
    """Zeroed error-feedback state: one fp32 residual per param leaf.
    ``dp_world > 0`` prepends a worker dimension (the engine shards it over
    the DP axes so each worker owns exactly its own residual)."""
    lead = (dp_world,) if dp_world else ()
    return jax.tree.map(
        lambda p: jnp.zeros(lead + tuple(np.shape(p)), jnp.float32), params)


def wire_bytes(params: Any) -> Tuple[int, int]:
    """(compressed, uncompressed fp32) bytes per worker per reduction —
    what the comms logger reports for the byte-reduction claim."""
    n = sum(int(np.prod(np.shape(p))) for p in jax.tree.leaves(params))
    leaves = len(jax.tree.leaves(params))
    return (_pad_to(n, 8) // 8 + 4 * leaves, 4 * n)
