"""Native + Pallas ops (reference ``deepspeed/ops/`` [K])."""

from . import op_builder

__all__ = ["op_builder"]
