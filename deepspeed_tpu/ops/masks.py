"""Attention mask construction — ONE home for window/causal semantics.

Every attention path (Llama train/prefill, ring SP, dense references)
builds its mask here so the sliding-window definition cannot drift
between them: causal = ``iq >= ik``; window W limits reach to
``|iq - ik| < W`` — one-sided (past only) under causality, symmetric for
bidirectional use (a non-causal "window" that bounded only the past
would silently attend unboundedly forward).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def local_attention_mask(q_pos: jnp.ndarray, k_pos: jnp.ndarray,
                         causal: bool = True,
                         window: Optional[int] = None) -> jnp.ndarray:
    """[Sq, Sk] boolean mask from absolute position vectors."""
    dq = q_pos[:, None]
    dk = k_pos[None, :]
    mask = dq >= dk if causal else jnp.ones((q_pos.size, k_pos.size), bool)
    if window is not None:
        if causal:
            mask = mask & (dq - dk < window)
        else:
            mask = mask & (jnp.abs(dq - dk) < window)
    return mask
