"""DeepSpeedCPUAdam — host-RAM optimizer for ZeRO-Offload.

API parity with the reference ``deepspeed.ops.adam.DeepSpeedCPUAdam``
[L ACC-DS:41-47]: ctor ``(model_params, lr, betas, eps, weight_decay,
adamw_mode, ...)``, ``step()``.  TPU adaptation: ``model_params`` is a list
of numpy fp32 arrays (the host master shards); gradients arrive per-step as
matching numpy arrays (streamed d2h by the offload engine); the fused C++
kernel updates master + moments in place and can emit bf16 wire copies.
"""

from __future__ import annotations

import ctypes
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..op_builder import CPUAdamBuilder

_f32p = ctypes.POINTER(ctypes.c_float)
_u16p = ctypes.POINTER(ctypes.c_uint16)


def _fp(a: np.ndarray):
    return a.ctypes.data_as(_f32p)


class DeepSpeedCPUAdam:
    def __init__(self, model_params: Sequence[np.ndarray], lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, bias_correction: bool = True,
                 amsgrad: bool = False, adamw_mode: bool = True,
                 fp32_optimizer_states: bool = True):
        if amsgrad:
            raise NotImplementedError("amsgrad not supported (reference parity)")
        self.lib = CPUAdamBuilder.load()
        self.lib.ds_adam_step.argtypes = [
            _f32p, _f32p, _f32p, _f32p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int]
        self.lib.ds_adam_step_bf16.argtypes = [
            _f32p, _f32p, _f32p, _f32p, _u16p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int]
        # force writable owned copies: jax.device_get hands out read-only
        # views that ascontiguousarray would pass through unchanged
        self.params: List[np.ndarray] = [
            np.array(p, dtype=np.float32, order="C") for p in model_params]
        self.exp_avg = [np.zeros_like(p) for p in self.params]
        self.exp_avg_sq = [np.zeros_like(p) for p in self.params]
        self.defaults: Dict[str, Any] = dict(
            lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
            bias_correction=bias_correction, adamw_mode=adamw_mode)
        self.state_step = 0

    def begin_step(self, lr: Optional[float] = None) -> None:
        """Advance the shared step counter once per optimizer step; slots
        are then updated individually via :meth:`step_slot` (the offload
        engine's bucket pipeline interleaves them with transfers)."""
        self.state_step += 1
        self._lr = float(lr if lr is not None else self.defaults["lr"])

    def step_slot(self, i: int, grad: np.ndarray,
                  bf16_out: Optional[np.ndarray] = None) -> None:
        """Fused Adam(W) over slot ``i`` only.  ``bf16_out`` (uint16 view)
        optionally receives the updated params in bf16 wire format.  The
        ctypes call releases the GIL, so concurrent d2h waits and h2d
        dispatch in other threads overlap with this compute."""
        d = self.defaults
        p = self.params[i]
        g = np.ascontiguousarray(grad, dtype=np.float32)
        args = [_fp(p), _fp(g), _fp(self.exp_avg[i]), _fp(self.exp_avg_sq[i])]
        common = [ctypes.c_int64(p.size), ctypes.c_int(self.state_step),
                  ctypes.c_float(self._lr), ctypes.c_float(d["betas"][0]),
                  ctypes.c_float(d["betas"][1]), ctypes.c_float(d["eps"]),
                  ctypes.c_float(d["weight_decay"]),
                  ctypes.c_int(int(d["adamw_mode"])),
                  ctypes.c_int(int(d["bias_correction"]))]
        if bf16_out is not None:
            self.lib.ds_adam_step_bf16(
                *args, bf16_out.ctypes.data_as(_u16p), *common)
        else:
            self.lib.ds_adam_step(*args, *common)

    def step(self, grads: Sequence[np.ndarray],
             bf16_out: Optional[Sequence[np.ndarray]] = None,
             lr: Optional[float] = None) -> None:
        """One fused step over every shard. ``grads[i]`` matches
        ``self.params[i]``; optional ``bf16_out[i]`` (uint16 view) receives
        the updated params in bf16."""
        self.begin_step(lr)
        for i in range(len(self.params)):
            self.step_slot(i, grads[i],
                           None if bf16_out is None else bf16_out[i])
