from .cpu_adam import DeepSpeedCPUAdam
from .cpu_adagrad import DeepSpeedCPUAdagrad
from .cpu_lion import DeepSpeedCPULion

__all__ = ["DeepSpeedCPUAdam", "DeepSpeedCPUAdagrad", "DeepSpeedCPULion"]
