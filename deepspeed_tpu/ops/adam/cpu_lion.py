"""DeepSpeedCPULion (reference ``deepspeed.ops.lion.DeepSpeedCPULion``
[L ACC-DS:93-95])."""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..op_builder import CPUAdamBuilder

_f32p = ctypes.POINTER(ctypes.c_float)


class DeepSpeedCPULion:
    def __init__(self, model_params: Sequence[np.ndarray], lr: float = 1e-4,
                 betas: Tuple[float, float] = (0.9, 0.99),
                 weight_decay: float = 0.0):
        self.lib = CPUAdamBuilder.load()
        self.lib.ds_lion_step.argtypes = [
            _f32p, _f32p, _f32p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float]
        self.params: List[np.ndarray] = [
            np.array(p, dtype=np.float32, order="C") for p in model_params]
        self.exp_avg = [np.zeros_like(p) for p in self.params]
        self.lr, self.betas, self.weight_decay = lr, betas, weight_decay
        self.state_step = 0

    def begin_step(self, lr: Optional[float] = None) -> None:
        self.state_step += 1
        self._lr = float(lr if lr is not None else self.lr)

    def step_slot(self, i: int, grad: np.ndarray,
                  bf16_out: Optional[np.ndarray] = None) -> None:
        if bf16_out is not None:
            raise NotImplementedError("bf16 wire emit is Adam-only")
        p = self.params[i]
        g = np.ascontiguousarray(grad, dtype=np.float32)
        self.lib.ds_lion_step(
            p.ctypes.data_as(_f32p), g.ctypes.data_as(_f32p),
            self.exp_avg[i].ctypes.data_as(_f32p),
            ctypes.c_int64(p.size), ctypes.c_int(self.state_step),
            ctypes.c_float(self._lr),
            ctypes.c_float(self.betas[0]), ctypes.c_float(self.betas[1]),
            ctypes.c_float(self.weight_decay))

    def step(self, grads: Sequence[np.ndarray],
             lr: Optional[float] = None) -> None:
        self.begin_step(lr)
        for i in range(len(self.params)):
            self.step_slot(i, grads[i])
