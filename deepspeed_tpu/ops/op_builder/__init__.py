"""Op builder registry (reference ``op_builder/`` [K], shrunk per SURVEY §2.2:
the ~40-builder JIT matrix reduces to the two real native ops + Pallas
kernels, which are plain Python)."""

from .builder import CPUAdamBuilder, AsyncIOBuilder, OpBuilder, get_op_builder

__all__ = ["OpBuilder", "CPUAdamBuilder", "AsyncIOBuilder", "get_op_builder"]
