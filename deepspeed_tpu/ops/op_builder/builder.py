"""JIT builder for the native C++ host ops.

Reference parity: ``op_builder/builder.py:OpBuilder`` [K] — sources list,
``is_compatible()`` probe, ``load()`` that compiles on first use and caches.
TPU adaptation: no torch cpp_extension — a direct ``g++ -shared`` invocation
producing a plain C-ABI ``.so`` loaded with ctypes (pybind11 is not in the
image; SURVEY environment notes).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
from typing import Dict, List, Optional, Type

from ...utils.logging import logger

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".."))
_CACHE_DIR = os.environ.get(
    "DS_TPU_OP_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops"))


class OpBuilder:
    NAME = "op"
    SOURCES: List[str] = []  # repo-relative
    EXTRA_FLAGS: List[str] = []

    _loaded: Dict[str, ctypes.CDLL] = {}

    @classmethod
    def absolute_sources(cls) -> List[str]:
        return [os.path.join(_REPO_ROOT, s) for s in cls.SOURCES]

    @classmethod
    def is_compatible(cls) -> bool:
        return shutil.which("g++") is not None and all(
            os.path.exists(s) for s in cls.absolute_sources())

    @classmethod
    def _so_path(cls) -> str:
        h = hashlib.sha1()
        for s in cls.absolute_sources():
            with open(s, "rb") as f:
                h.update(f.read())
        h.update(" ".join(cls.EXTRA_FLAGS).encode())
        return os.path.join(_CACHE_DIR, f"{cls.NAME}_{h.hexdigest()[:12]}.so")

    @classmethod
    def build(cls) -> str:
        so = cls._so_path()
        if os.path.exists(so):
            return so
        os.makedirs(_CACHE_DIR, exist_ok=True)
        cmd = (["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17"]
               + cls.EXTRA_FLAGS + cls.absolute_sources() + ["-o", so + ".tmp"])
        logger.info(f"building native op {cls.NAME}: {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build of {cls.NAME} failed:\n{e.stderr}") from e
        os.replace(so + ".tmp", so)
        return so

    @classmethod
    def load(cls) -> ctypes.CDLL:
        if cls.NAME not in cls._loaded:
            cls._loaded[cls.NAME] = ctypes.CDLL(cls.build())
        return cls._loaded[cls.NAME]


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"
    SOURCES = ["csrc/adam/cpu_adam.cpp"]
    EXTRA_FLAGS = ["-fopenmp"]


class AsyncIOBuilder(OpBuilder):
    NAME = "async_io"
    SOURCES = ["csrc/aio/aio_engine.cpp"]
    EXTRA_FLAGS = ["-pthread"]


_BUILDERS: Dict[str, Type[OpBuilder]] = {
    CPUAdamBuilder.NAME: CPUAdamBuilder,
    AsyncIOBuilder.NAME: AsyncIOBuilder,
}


def get_op_builder(name: str) -> Optional[Type[OpBuilder]]:
    """Lookup by op name ("cpu_adam") or reference class name
    ("CPUAdamBuilder") — accelerator.get_op_builder uses the latter [K]."""
    b = _BUILDERS.get(name)
    if b is not None:
        return b
    for cls in _BUILDERS.values():
        if cls.__name__ == name:
            return cls
    return None
