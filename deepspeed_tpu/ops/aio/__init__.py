from .aio_handle import AIOHandle, aio_handle

__all__ = ["AIOHandle", "aio_handle"]
