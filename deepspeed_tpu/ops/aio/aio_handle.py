"""aio_handle — Python surface of the async NVMe engine.

API parity with the reference ``deepspeed.ops.op_builder.AsyncIOBuilder``
handle (``aio_handle(block_size, queue_depth, single_submit, overlap_events,
thread_count)`` + ``async_pread/async_pwrite/wait`` [K], config keys
[L ACC-DC:1187-1194]).
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from ..op_builder import AsyncIOBuilder


class AIOHandle:
    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 32,
                 single_submit: bool = False, overlap_events: bool = True,
                 thread_count: int = 4):
        self.lib = AsyncIOBuilder.load()
        self.lib.ds_aio_new.restype = ctypes.c_void_p
        self.lib.ds_aio_new.argtypes = [ctypes.c_int] * 5
        self.lib.ds_aio_free.argtypes = [ctypes.c_void_p]
        self.lib.ds_aio_pread.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64]
        self.lib.ds_aio_pwrite.argtypes = self.lib.ds_aio_pread.argtypes
        self.lib.ds_aio_pwrite_trunc.argtypes = self.lib.ds_aio_pread.argtypes
        self.lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
        self.lib.ds_aio_wait.restype = ctypes.c_int64
        self.lib.ds_aio_inflight.argtypes = [ctypes.c_void_p]
        self.lib.ds_aio_inflight.restype = ctypes.c_int64
        self.lib.ds_aio_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64)]
        self.lib.ds_aio_read_retries.argtypes = [ctypes.c_void_p]
        self.lib.ds_aio_read_retries.restype = ctypes.c_int64
        self._h = self.lib.ds_aio_new(block_size, queue_depth,
                                      int(single_submit), int(overlap_events),
                                      thread_count)

    def async_pread(self, buf: np.ndarray, path: str, offset: int = 0) -> None:
        assert buf.flags["C_CONTIGUOUS"]
        self.lib.ds_aio_pread(self._h, buf.ctypes.data, buf.nbytes,
                              path.encode(), offset)

    def async_pwrite(self, buf: np.ndarray, path: str, offset: int = 0,
                     truncate: bool = False) -> None:
        """``truncate=True`` drops stale tail bytes beyond this write (use
        for whole-file shard rewrites; offset writes into larger files must
        leave it False)."""
        assert buf.flags["C_CONTIGUOUS"]
        fn = (self.lib.ds_aio_pwrite_trunc if truncate
              else self.lib.ds_aio_pwrite)
        fn(self._h, buf.ctypes.data, buf.nbytes, path.encode(), offset)

    def sync_pread(self, buf: np.ndarray, path: str, offset: int = 0) -> None:
        self.async_pread(buf, path, offset)
        self.wait()

    def sync_pwrite(self, buf: np.ndarray, path: str, offset: int = 0,
                    truncate: bool = False) -> None:
        self.async_pwrite(buf, path, offset, truncate=truncate)
        self.wait()

    def wait(self) -> int:
        """Drain; returns the number of FAILED ops since the last wait."""
        return int(self.lib.ds_aio_wait(self._h))

    def inflight(self) -> int:
        return int(self.lib.ds_aio_inflight(self._h))

    def stats(self) -> dict:
        """Bytes moved through O_DIRECT vs the buffered fallback — the
        page-cache-bypass evidence (reference csrc/aio's defining
        property).  Buffered bytes > 0 on direct-incapable filesystems
        (tmpfs) and for sub-4KiB tails."""
        d = ctypes.c_int64(0)
        b = ctypes.c_int64(0)
        self.lib.ds_aio_stats(self._h, ctypes.byref(d), ctypes.byref(b))
        return {"direct_bytes": int(d.value), "buffered_bytes": int(b.value),
                "read_retries": int(self.lib.ds_aio_read_retries(self._h))}

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self.lib.ds_aio_free(self._h)
                self._h = None
        except Exception as e:  # interpreter teardown: lib may be gone
            from ...utils.logging import debug_once

            debug_once("aio/free", f"ds_aio_free failed in __del__ "
                                   f"({e!r}); handle leaked at exit")


def aio_handle(block_size: int = 1 << 20, queue_depth: int = 32,
               single_submit: bool = False, overlap_events: bool = True,
               thread_count: int = 4) -> AIOHandle:
    return AIOHandle(block_size, queue_depth, single_submit, overlap_events,
                     thread_count)
