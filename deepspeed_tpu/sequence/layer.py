"""Legacy Ulysses ``DistributedAttention`` (reference
``deepspeed/sequence/layer.py`` [K]: ``_SeqAllToAll`` + ``DistributedAttention``
— the Megatron-DeepSpeed sequence-parallel path).

TPU-native: the scatter/gather pair is ``jax.lax.all_to_all`` over the ``seq``
mesh axis; the wrapper matches the reference's call shape
``DistributedAttention(local_attn, sp_group)(q, k, v, *args)``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..parallel.mesh import AXIS_SEQ
from ..runtime.sequence_parallel.ulysses_sp import ulysses_attention
from ..utils import groups as groups_mod


class DistributedAttention:
    """seq-scatter → local attention over full sequence → seq-gather.

    ``local_attn(q, k, v, *args)`` computes attention on ``[B, S, h_local, d]``
    blocks.  With sp == 1 this is a passthrough.
    """

    def __init__(self, local_attn: Callable[..., jnp.ndarray],
                 sp_group: Any = None,
                 scatter_idx: int = 2, gather_idx: int = 1):
        if (scatter_idx, gather_idx) != (2, 1):
            raise NotImplementedError(
                "only the [B, S, h, d] layout (scatter heads, gather seq) "
                "is supported on TPU")
        self.local_attn = local_attn
        self.sp_group = sp_group

    def __call__(self, query: jnp.ndarray, key: jnp.ndarray,
                 value: jnp.ndarray, *args: Any, **kwargs: Any) -> jnp.ndarray:
        mesh = (self.sp_group.mesh if self.sp_group is not None
                else groups_mod.get_mesh())

        def attn(q, k, v):
            return self.local_attn(q, k, v, *args, **kwargs)

        return ulysses_attention(attn, query, key, value, mesh=mesh)
