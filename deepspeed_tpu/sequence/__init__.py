"""Legacy Ulysses module (reference ``deepspeed/sequence/`` [K])."""

from .layer import DistributedAttention

__all__ = ["DistributedAttention"]
