from .compress import (apply_layer_reduction, init_compression,
                       knowledge_distillation_loss, redundancy_clean,
                       student_initialize)
from .quantization import fake_quantize

__all__ = ["init_compression", "redundancy_clean", "fake_quantize",
           "apply_layer_reduction", "knowledge_distillation_loss",
           "student_initialize"]
