from .compress import init_compression, redundancy_clean
from .quantization import fake_quantize

__all__ = ["init_compression", "redundancy_clean", "fake_quantize"]
