"""Compression entry points.

Reference: ``deepspeed/compression/compress.py`` [K] —
``init_compression(model, deepspeed_config)`` wraps layers for QAT /
structured pruning per the ``compression_training`` config group;
``redundancy_clean`` makes pruning permanent.

TPU-first: models are functional, so "wrapping a module" becomes wrapping
the LOSS: ``init_compression`` returns a transformed loss whose params pass
through fake-quant / pruning masks on every forward (gradients flow via STE).
``redundancy_clean`` applies the masks destructively to the param pytree.

Coverage vs the reference config groups: ``weight_quantization`` (QAT),
``sparse_pruning`` (unstructured magnitude), ``row_pruning`` (structured
output-channel), ``head_pruning`` (whole attention heads, name-matched on
attn leaves), ``layer_reduction`` (student keeps a subset of stacked
layers) + a knowledge-distillation loss helper.  ``activation_quantization``
and ``channel_pruning`` remain gaps (activations aren't reachable from a
loss wrapper; models call ``quantization.fake_quantize`` directly).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import logger
from .quantization import fake_quantize


def _get(cfg: Dict[str, Any], *path, default=None):
    node = cfg
    for p in path:
        if not isinstance(node, dict) or p not in node:
            return default
        node = node[p]
    return node


def _compression_transform(ds_config: Dict[str, Any]
                           ) -> Callable[[Any], Any]:
    ct = ds_config.get("compression_training", {}) if ds_config else {}
    wq = _get(ct, "weight_quantization", "shared_parameters", default={}) or {}
    wq_enabled = wq.get("enabled", False)
    bits = int(_get(ct, "weight_quantization", "different_groups",
                    default={}).get("wq1", {}).get("params", {})
               .get("start_bits", 8)) if wq_enabled else 8
    sp = _get(ct, "sparse_pruning", "shared_parameters", default={}) or {}
    sp_enabled = sp.get("enabled", False)
    density = float(sp.get("dense_ratio", 0.5)) if sp_enabled else 1.0
    rp = _get(ct, "row_pruning", "shared_parameters", default={}) or {}
    rp_enabled = rp.get("enabled", False)
    rp_density = float(rp.get("dense_ratio", 0.5)) if rp_enabled else 1.0
    hp = _get(ct, "head_pruning", "shared_parameters", default={}) or {}
    hp_enabled = hp.get("enabled", False)
    hp_density = float(hp.get("dense_ratio", 0.5)) if hp_enabled else 1.0

    def _row_prune(p):
        # structured: zero whole OUTPUT channels (last dim) by L2 norm over
        # every other dim (reference row_pruning semantics)
        norms = jnp.sqrt(jnp.sum(jnp.square(p),
                                 axis=tuple(range(p.ndim - 1))))
        k = max(int(norms.size * rp_density), 1)
        thresh = jnp.sort(norms)[-k]
        return jnp.where(norms >= thresh, p, 0.0)

    HEAD_AXIS = {"wq": -2, "wk": -2, "wv": -2, "wo": -3}

    def _head_norms(p, name):
        axis = p.ndim + HEAD_AXIS[name]
        other = tuple(i for i in range(p.ndim) if i != axis)
        return jnp.sqrt(jnp.sum(jnp.square(p), axis=other))

    def _apply_head_mask(p, name, keep):
        axis = p.ndim + HEAD_AXIS[name]
        shape = [1] * p.ndim
        shape[axis] = p.shape[axis]
        return p * keep.reshape(shape)

    def _head_prune_groups(params: Any) -> Any:
        """Pre-pass: ONE keep-mask per attention group, decided from the
        COMBINED q/k/v/o head norms — per-leaf masks could disagree, and a
        head whose q is zeroed but whose v/o survive degrades to emitting
        its mean value (uniform softmax) instead of being excised."""

        def walk(node):
            if isinstance(node, dict) and all(
                    k in node for k in ("wq", "wk", "wv", "wo")):
                def mask_from(norms):
                    k = max(int(norms.size * hp_density), 1)
                    return norms >= jnp.sort(norms)[-k]

                nq = _head_norms(node["wq"], "wq")
                nk = _head_norms(node["wk"], "wk")
                if nk.size == nq.size:  # MHA: one mask for all four
                    keep = mask_from(nq + nk
                                     + _head_norms(node["wv"], "wv")
                                     + _head_norms(node["wo"], "wo"))
                    masks = {k: keep for k in HEAD_AXIS}
                else:  # GQA: q/o share a mask; kv groups get their own
                    keep_q = mask_from(nq + _head_norms(node["wo"], "wo"))
                    keep_kv = mask_from(nk + _head_norms(node["wv"], "wv"))
                    masks = {"wq": keep_q, "wo": keep_q,
                             "wk": keep_kv, "wv": keep_kv}
                return {kk: (_apply_head_mask(vv, kk, masks[kk])
                             if kk in HEAD_AXIS else vv)
                        for kk, vv in node.items()}
            if isinstance(node, dict):
                return {k: walk(v) for k, v in node.items()}
            return node

        return walk(params)

    def transform(params: Any) -> Any:
        if hp_enabled:
            params = _head_prune_groups(params)

        def leaf(path, p):
            if not jnp.issubdtype(p.dtype, jnp.floating) or p.ndim < 2:
                return p
            name = (path[-1].key if hasattr(path[-1], "key")
                    else str(path[-1]))
            in_attn = any(getattr(e, "key", "") == "attn" for e in path)
            out = p
            if rp_enabled and not (in_attn and name in HEAD_AXIS):
                out = _row_prune(out)
            if sp_enabled:
                k = max(int(p.size * density), 1)
                thresh = jnp.sort(jnp.abs(out).reshape(-1))[-k]
                out = jnp.where(jnp.abs(out) >= thresh, out, 0.0)
            if wq_enabled:
                out = fake_quantize(out, bits=bits)
            return out

        return jax.tree_util.tree_map_with_path(leaf, params)

    if not (wq_enabled or sp_enabled or rp_enabled or hp_enabled):
        return lambda params: params
    logger.info(f"init_compression: weight_quant={wq_enabled}(bits={bits}) "
                f"sparse_pruning={sp_enabled}(density={density}) "
                f"row_pruning={rp_enabled}(density={rp_density}) "
                f"head_pruning={hp_enabled}(density={hp_density})")
    return transform


def init_compression(model: Any, deepspeed_config: Dict[str, Any],
                     teacher_model: Any = None, mpu: Any = None) -> Any:
    """Wrap ``model`` (object with ``.loss``/``.forward``) so params pass
    through the configured compression transform each call."""
    transform = _compression_transform(deepspeed_config)

    aq = _get(deepspeed_config or {}, "compression_training",
              "activation_quantization", "shared_parameters",
              default={}) or {}

    class CompressedModel:
        def __init__(self, inner):
            self._inner = inner
            self.compression_transform = transform
            if aq.get("enabled"):
                # models consume this in their activation hot spots
                # (reference QuantAct wrapper role).  ORDER MATTERS: jit
                # captures the hook at trace time, so arm BEFORE building
                # engines — programs compiled earlier keep their old
                # behavior (same trace-time rule as every config knob)
                inner.act_quant_bits = int(aq.get("bits", 8))
                logger.info("activation quantization armed "
                            f"({inner.act_quant_bits}-bit); (re)build "
                            "engines AFTER init_compression — compiled "
                            "programs capture the hook at trace time")
            elif hasattr(inner, "act_quant_bits"):
                # a previous arming must not outlive its config
                inner.act_quant_bits = None

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def loss(self, params, batch):
            return self._inner.loss(transform(params), batch)

        def forward(self, params, *args, **kwargs):
            return self._inner.forward(transform(params), *args, **kwargs)

    if callable(getattr(model, "loss", None)):
        return CompressedModel(model)
    # bare loss function
    return lambda params, batch: model(transform(params), batch)


def redundancy_clean(params_or_model: Any, deepspeed_config: Dict[str, Any],
                     mpu: Any = None) -> Any:
    """Make compression permanent on a param pytree (reference: rewrites the
    modules; here: rewrites the leaves)."""
    transform = _compression_transform(deepspeed_config)
    return transform(params_or_model)


def apply_layer_reduction(params: Any, keep_layers, layers_key: str = "layers"
                          ) -> Any:
    """Reference ``layer_reduction`` [K]: build a shallower student by
    keeping ``keep_layers`` (teacher layer indices) of the stacked trunk —
    each kept layer initializes from its teacher layer (``teacher_layer``
    config semantics).  Works on any model whose per-layer params are
    stacked on dim 0 under ``params[layers_key]`` (this zoo's convention).
    """
    idx = jnp.asarray(list(keep_layers), jnp.int32)
    out = dict(params)
    out[layers_key] = jax.tree.map(lambda p: p[idx], params[layers_key])
    return out


def knowledge_distillation_loss(student_logits: jnp.ndarray,
                                teacher_logits: jnp.ndarray,
                                labels: Optional[jnp.ndarray] = None,
                                alpha: float = 0.5,
                                temperature: float = 1.0) -> jnp.ndarray:
    """KD objective: alpha * T^2 * KL(teacher_T || student_T)
    + (1-alpha) * CE(student, labels) — the reference compression
    examples' distillation form."""
    T = temperature
    t = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / T, axis=-1)
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / T, axis=-1)
    kl = jnp.sum(jnp.exp(t) * (t - s), axis=-1)
    loss = alpha * (T * T) * jnp.mean(kl)
    if labels is not None and alpha < 1.0:
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(student_logits.astype(jnp.float32),
                                  axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        ce = jnp.sum(jnp.where(valid, nll, 0.0)) / jnp.maximum(
            jnp.sum(valid), 1)
        loss = loss + (1.0 - alpha) * ce
    return loss


def student_initialize(student_model: Any, teacher_params: Any,
                       deepspeed_config: Dict[str, Any]) -> Any:
    """Reference ``student_initialization`` role: derive student params
    from the teacher per ``layer_reduction.teacher_layer``."""
    lr_cfg = _get(deepspeed_config or {}, "compression_training",
                  "layer_reduction", default={}) or {}
    if not lr_cfg.get("enabled", False):
        return teacher_params
    keep = lr_cfg.get("teacher_layer")
    if keep is None:
        n = int(lr_cfg.get("keep_number_layer", 1))
        total = jax.tree.leaves(teacher_params["layers"])[0].shape[0]
        step = max(total // n, 1)
        keep = list(range(0, total, step))[:n]
    return apply_layer_reduction(teacher_params, keep)
