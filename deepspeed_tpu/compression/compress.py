"""Compression entry points.

Reference: ``deepspeed/compression/compress.py`` [K] —
``init_compression(model, deepspeed_config)`` wraps layers for QAT /
structured pruning per the ``compression_training`` config group;
``redundancy_clean`` makes pruning permanent.

TPU-first: models are functional, so "wrapping a module" becomes wrapping
the LOSS: ``init_compression`` returns a transformed loss whose params pass
through fake-quant / pruning masks on every forward (gradients flow via STE).
``redundancy_clean`` applies the masks destructively to the param pytree.
Layer-reduction/distillation is a documented gap for a later round.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import logger
from .quantization import fake_quantize


def _get(cfg: Dict[str, Any], *path, default=None):
    node = cfg
    for p in path:
        if not isinstance(node, dict) or p not in node:
            return default
        node = node[p]
    return node


def _compression_transform(ds_config: Dict[str, Any]
                           ) -> Callable[[Any], Any]:
    ct = ds_config.get("compression_training", {}) if ds_config else {}
    wq = _get(ct, "weight_quantization", "shared_parameters", default={}) or {}
    wq_enabled = wq.get("enabled", False)
    bits = int(_get(ct, "weight_quantization", "different_groups",
                    default={}).get("wq1", {}).get("params", {})
               .get("start_bits", 8)) if wq_enabled else 8
    sp = _get(ct, "sparse_pruning", "shared_parameters", default={}) or {}
    sp_enabled = sp.get("enabled", False)
    density = float(sp.get("dense_ratio", 0.5)) if sp_enabled else 1.0

    def transform(params: Any) -> Any:
        def leaf(p):
            if not jnp.issubdtype(p.dtype, jnp.floating) or p.ndim < 2:
                return p
            out = p
            if sp_enabled:
                k = max(int(p.size * density), 1)
                thresh = jnp.sort(jnp.abs(p).reshape(-1))[-k]
                out = jnp.where(jnp.abs(out) >= thresh, out, 0.0)
            if wq_enabled:
                out = fake_quantize(out, bits=bits)
            return out

        return jax.tree.map(leaf, params)

    if not (wq_enabled or sp_enabled):
        return lambda params: params
    logger.info(f"init_compression: weight_quant={wq_enabled}(bits={bits}) "
                f"sparse_pruning={sp_enabled}(density={density})")
    return transform


def init_compression(model: Any, deepspeed_config: Dict[str, Any],
                     teacher_model: Any = None, mpu: Any = None) -> Any:
    """Wrap ``model`` (object with ``.loss``/``.forward``) so params pass
    through the configured compression transform each call."""
    transform = _compression_transform(deepspeed_config)

    class CompressedModel:
        def __init__(self, inner):
            self._inner = inner
            self.compression_transform = transform

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def loss(self, params, batch):
            return self._inner.loss(transform(params), batch)

        def forward(self, params, *args, **kwargs):
            return self._inner.forward(transform(params), *args, **kwargs)

    if callable(getattr(model, "loss", None)):
        return CompressedModel(model)
    # bare loss function
    return lambda params, batch: model(transform(params), batch)


def redundancy_clean(params_or_model: Any, deepspeed_config: Dict[str, Any],
                     mpu: Any = None) -> Any:
    """Make compression permanent on a param pytree (reference: rewrites the
    modules; here: rewrites the leaves)."""
    transform = _compression_transform(deepspeed_config)
    return transform(params_or_model)
