"""Quantization-aware-training primitives (reference
``deepspeed/compression/basic_layer.py`` QuantAct/Embedding/Linear wrappers
[K]) — functional: a fake-quant transform applied to param pytrees inside the
loss, straight-through estimator for gradients."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def fake_quantize(x: jnp.ndarray, bits: int = 8, symmetric: bool = True,
                  per_channel: bool = True) -> jnp.ndarray:
    """Quantize→dequantize with straight-through gradient (QAT path):
    ``x + sg(q(x) - x)`` — identity gradient everywhere, quantized value in
    the forward (the canonical STE formulation)."""
    qmax = 2.0 ** (bits - 1) - 1
    axis = tuple(range(1, x.ndim)) if (per_channel and x.ndim > 1) else None
    amax = jnp.max(jnp.abs(x), axis=axis, keepdims=axis is not None)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax) * scale
    return (x + jax.lax.stop_gradient(q.astype(x.dtype) - x)).astype(x.dtype)
