"""Quantization-aware-training primitives (reference
``deepspeed/compression/basic_layer.py`` QuantAct/Embedding/Linear wrappers
[K]) — functional: a fake-quant transform applied to param pytrees inside the
loss, straight-through estimator for gradients."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def fake_quantize(x: jnp.ndarray, bits: int = 8, symmetric: bool = True,
                  per_channel: bool = True) -> jnp.ndarray:
    """Quantize→dequantize with straight-through gradient (QAT path):
    ``x + sg(q(x) - x)`` — identity gradient everywhere, quantized value in
    the forward (the canonical STE formulation).  ``symmetric=False`` uses
    a dynamic [min, max] range (one-sided post-nonlinearity activations)."""
    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        axis = (tuple(range(1, x.ndim))
                if (per_channel and x.ndim > 1) else None)
        amax = jnp.max(jnp.abs(x), axis=axis, keepdims=axis is not None)
        scale = jnp.where(amax > 0, amax / qmax, 1.0)
        q = jnp.clip(jnp.round(x / scale), -qmax, qmax) * scale
    else:
        levels = 2.0 ** bits - 1
        axis = (tuple(range(1, x.ndim))
                if (per_channel and x.ndim > 1) else None)
        lo = jnp.min(x, axis=axis, keepdims=axis is not None)
        hi = jnp.max(x, axis=axis, keepdims=axis is not None)
        scale = jnp.where(hi > lo, (hi - lo) / levels, 1.0)
        q = jnp.round((x - lo) / scale) * scale + lo
    return (x + jax.lax.stop_gradient(q.astype(x.dtype) - x)).astype(x.dtype)


def quantize_activation(x: jnp.ndarray, bits: int = 8) -> jnp.ndarray:
    """Activation fake-quant (reference ``QuantAct`` role): the asymmetric
    per-tensor branch of :func:`fake_quantize` — one quantizer, two modes."""
    return fake_quantize(x, bits=bits, symmetric=False, per_channel=False)


def maybe_quantize_activation(model: Any, x: jnp.ndarray) -> jnp.ndarray:
    """The model-side QuantAct hook, in ONE home: quantize when
    ``init_compression`` armed ``model.act_quant_bits``, identity
    otherwise.  Models call this at their activation hot spots."""
    bits = getattr(model, "act_quant_bits", None)
    return quantize_activation(x, bits) if bits else x
