"""OptimizedLinear / LoRAOptimizedLinear — functional form.

Reference: ``deepspeed/linear/optimized_linear.py`` [K]:
``OptimizedLinear(input_dim, output_dim, lora_config, quantization_config)``
returns a module whose base weight is sharded+frozen (optionally
quantized) and whose LoRA adapters train.  Here the same capability is a
param-tree factory + pure apply, composing with the engine like any model:

    lin = LoRAOptimizedLinear(in, out, lora_config, quant_config)
    params = lin.init(rng)             # {"base" or "base_q", "lora_a/b"}
    y = lin.apply(params, x)
    mask = lora_trainable_mask(params) # optax.masked freeze of the base
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ..parallel.mesh import AXIS_TENSOR
from .config import LoRAConfig, QuantizationConfig

P = PartitionSpec


# one int8 group-quantizer serves qwZ and the linear subsystem — a scale
# or edge-case fix lands in both (runtime/zero/qwz.py owns the math)
from ..runtime.zero.qwz import _dequant as _dq
from ..runtime.zero.qwz import _quant as _q


def _quantize(w: jnp.ndarray, group: int):
    q, s = _q(w.astype(jnp.float32), group=group)
    return q, s.astype(jnp.float32)


def _dequantize(q: jnp.ndarray, scale: jnp.ndarray, group: int):
    return _dq(q, scale, q.shape, group=group)


class OptimizedLinear:
    """Base linear with optional int8-quantized frozen weight."""

    def __init__(self, input_dim: int, output_dim: int,
                 lora_config: Optional[LoRAConfig] = None,
                 quantization_config: Optional[QuantizationConfig] = None,
                 bias: bool = False, dtype: Any = jnp.bfloat16):
        if lora_config is not None:
            # reference behavior: lora_config upgrades to the LoRA class
            self.__class__ = LoRAOptimizedLinear
            LoRAOptimizedLinear.__init__(
                self, input_dim, output_dim, lora_config,
                quantization_config, bias=bias, dtype=dtype)
            return
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.quant = quantization_config
        self.bias = bias
        self.dtype = dtype

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        w = (jax.random.normal(rng, (self.input_dim, self.output_dim),
                               jnp.float32)
             / np.sqrt(self.input_dim))
        params: Dict[str, Any] = {}
        if self.quant is not None and self.quant.quantized_initialization:
            q, s = _quantize(w, self.quant.group_size)
            params["base_q"], params["base_scale"] = q, s
        else:
            params["base"] = w
        if self.bias:
            params["bias"] = jnp.zeros((self.output_dim,), jnp.float32)
        return params

    def _base_weight(self, params: Dict[str, Any]) -> jnp.ndarray:
        if "base_q" in params:
            return _dequantize(params["base_q"], params["base_scale"],
                               self.quant.group_size).astype(self.dtype)
        return params["base"].astype(self.dtype)

    def apply(self, params: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
        y = x.astype(self.dtype) @ self._base_weight(params)
        if "bias" in params:
            y = y + params["bias"].astype(self.dtype)
        return y

    __call__ = apply

    def param_specs(self) -> Dict[str, Any]:
        specs: Dict[str, Any] = {}
        if self.quant is not None and self.quant.quantized_initialization:
            specs["base_q"] = P(None, AXIS_TENSOR)
            specs["base_scale"] = P(None, None)
        else:
            specs["base"] = P(None, AXIS_TENSOR)
        if self.bias:
            specs["bias"] = P(AXIS_TENSOR)
        return specs


class LoRAOptimizedLinear(OptimizedLinear):
    """Frozen (possibly quantized) base + trainable rank-r adapters."""

    def __init__(self, input_dim: int, output_dim: int,
                 lora_config: Optional[LoRAConfig] = None,
                 quantization_config: Optional[QuantizationConfig] = None,
                 bias: bool = False, dtype: Any = jnp.bfloat16):
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.lora = lora_config or LoRAConfig()
        self.quant = quantization_config
        self.bias = bias
        self.dtype = dtype

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        r1, r2 = jax.random.split(rng)
        params = OptimizedLinear.init(self, r1)
        r = self.lora.lora_r
        # reference init: A ~ kaiming, B = 0 → adapter starts as identity
        params["lora_a"] = (jax.random.normal(r2, (self.input_dim, r),
                                              jnp.float32)
                            / np.sqrt(self.input_dim))
        params["lora_b"] = jnp.zeros((r, self.output_dim), jnp.float32)
        return params

    def apply(self, params: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype)
        base = jax.lax.stop_gradient(self._base_weight(params))  # frozen
        y = x @ base
        y = y + self.lora.scaling * (
            (x @ params["lora_a"].astype(self.dtype))
            @ params["lora_b"].astype(self.dtype))
        if "bias" in params:
            y = y + params["bias"].astype(self.dtype)
        return y

    __call__ = apply

    def param_specs(self) -> Dict[str, Any]:
        specs = OptimizedLinear.param_specs(self)
        specs["lora_a"] = P(None, None)
        specs["lora_b"] = P(None, AXIS_TENSOR)
        return specs


def lora_trainable_mask(params: Any) -> Any:
    """True for LoRA leaves, False for base/quantized leaves — feed to
    ``optax.masked`` so the optimizer updates adapters only (the
    reference's requires_grad split)."""
    def one(path, _):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return name.startswith("lora")

    return jax.tree_util.tree_map_with_path(one, params)


def lora_merge(params: Dict[str, Any], lora_config: LoRAConfig,
               group_size: int = 256) -> jnp.ndarray:
    """Fold adapters into a dense weight (export/serving path)."""
    if "base_q" in params:
        base = _dequantize(params["base_q"], params["base_scale"],
                           group_size)
    else:
        base = params["base"]
    return base + lora_config.scaling * (params["lora_a"]
                                         @ params["lora_b"])
