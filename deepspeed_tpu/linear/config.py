"""Config dataclasses for the linear subsystem (reference names [K])."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class LoRAConfig:
    """Reference ``deepspeed.linear.LoRAConfig`` [K]."""

    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1  # shards over the 'tensor' axis when >1

    @property
    def scaling(self) -> float:
        return self.lora_alpha / self.lora_r


@dataclasses.dataclass
class QuantizationConfig:
    """Reference ``deepspeed.linear.QuantizationConfig`` [K] — fp6/fp8
    there; int8 group quantization here (the TPU-supported narrow format;
    fp8 on TPU arrives with newer generations, gap documented)."""

    q_bits: int = 8
    group_size: int = 256
    quantized_initialization: bool = True
