"""Optimized linear + LoRA — ``deepspeed/linear/`` parity.

Reference: ``deepspeed/linear/{optimized_linear,quantization,config}.py``
[K] (SURVEY §2.5 "Optimized linear / LoRA"): ``OptimizedLinear`` shards a
frozen (optionally fp6/fp8-quantized) base weight and trains low-rank
LoRA adapters; ``LoRAConfig``/``QuantizationConfig`` carry the knobs.

TPU-first: the module is a functional param-tree factory — base weights
carry a ``tensor``-axis PartitionSpec like every other matmul weight,
quantization is int8 + group scales stored as the leaf format (dequant
fuses into the matmul), and freezing is an optax mask, not a module flag.
"""

from .config import LoRAConfig, QuantizationConfig
from .optimized_linear import (LoRAOptimizedLinear, OptimizedLinear,
                               lora_merge, lora_trainable_mask)

__all__ = ["LoRAConfig", "QuantizationConfig", "OptimizedLinear",
           "LoRAOptimizedLinear", "lora_trainable_mask", "lora_merge"]
