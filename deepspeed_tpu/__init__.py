"""deepspeed_tpu — TPU-native distributed training/inference framework.

Re-implements the capability surface of DeepSpeed (reference:
``deepspeed/__init__.py`` [K]) as an idiomatic JAX/XLA/Pallas stack: ZeRO
stages are GSPMD sharding policies, parallelism modes are mesh axes, the hot
path is one jitted train step.
"""

from .version import __version__
from . import comm
from .parallel import MeshLayout, build_mesh
from .utils import logger

__all__ = ["__version__", "comm", "MeshLayout", "build_mesh", "logger",
           "initialize", "init_inference", "init_distributed",
           "tp_model_init", "zero"]


def initialize(*args, **kwargs):
    """Public factory — mirrors ``deepspeed.initialize`` [L ACC:2358-2439].

    Returns ``(engine, optimizer, dataloader, lr_scheduler)``.  Imported
    lazily so light uses (comm/mesh only) don't pay engine import cost.
    """
    from .runtime.entry import initialize as _initialize

    return _initialize(*args, **kwargs)


def init_inference(*args, **kwargs):
    """Mirrors ``deepspeed.init_inference`` (SURVEY §3.6)."""
    from .inference import init_inference as _init_inference

    return _init_inference(*args, **kwargs)


def init_distributed(*args, **kwargs):
    return comm.init_distributed(*args, **kwargs)


def tp_model_init(*args, **kwargs):
    """Mirrors ``deepspeed.tp_model_init`` [L HF-DS:468-473]."""
    from .runtime.tensor_parallel import tp_model_init as _tp

    return _tp(*args, **kwargs)


def __getattr__(name):
    if name == "zero":
        from .runtime import zero as _zero

        return _zero
    raise AttributeError(f"module 'deepspeed_tpu' has no attribute {name!r}")
