"""deepspeed_tpu — TPU-native distributed training/inference framework.

Re-implements the capability surface of DeepSpeed (reference:
``deepspeed/__init__.py`` [K]) as an idiomatic JAX/XLA/Pallas stack: ZeRO
stages are GSPMD sharding policies, parallelism modes are mesh axes, the hot
path is one jitted train step.
"""

from .version import __version__
from . import comm
from .parallel import MeshLayout, build_mesh
from .utils import logger

__all__ = ["__version__", "comm", "MeshLayout", "build_mesh", "logger",
           "initialize"]


def initialize(*args, **kwargs):
    """Public factory — mirrors ``deepspeed.initialize`` [L ACC:2358-2439].

    Returns ``(engine, optimizer, dataloader, lr_scheduler)``.  Imported
    lazily so light uses (comm/mesh only) don't pay engine import cost.
    """
    from .runtime.entry import initialize as _initialize

    return _initialize(*args, **kwargs)
