from .checkpointing import CheckpointConfig, checkpoint, configure

__all__ = ["checkpoint", "configure", "CheckpointConfig"]
