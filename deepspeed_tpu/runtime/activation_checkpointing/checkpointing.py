"""Activation checkpointing — the reference API over ``jax.checkpoint``.

Reference: ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
[K] — drop-in ``checkpoint(function, *args)`` with extras: partitioned
activations across TP ranks, CPU checkpointing, contiguous memory, RNG-state
tracking (SURVEY §2.1).

TPU-first mapping: ``jax.checkpoint`` (remat) subsumes the hook machinery;
the extras become remat POLICIES —
* ``partition_activations`` → saveables carry their sharding, so saved
  residuals are already partitioned (GSPMD; nothing to do)
* ``cpu_checkpointing`` → ``jax.checkpoint`` with ``offload`` policy
  (``save_and_offload_only_these_names`` / host memory kind)
* RNG tracking → functional PRNG keys thread explicitly; nothing to track.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax

from ...utils.logging import logger


@dataclasses.dataclass
class CheckpointConfig:
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


_CONFIG = CheckpointConfig()


def configure(mpu_: Any = None, deepspeed_config: Any = None,
              partition_activations: Optional[bool] = None,
              contiguous_checkpointing: Optional[bool] = None,
              num_checkpoints: Optional[int] = None,
              checkpoint_in_cpu: Optional[bool] = None,
              synchronize: Optional[bool] = None,
              profile: Optional[bool] = None) -> None:
    """Reference ``configure`` signature; updates the module-level policy."""
    global _CONFIG
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing", None)
        if ac is not None:
            _CONFIG = CheckpointConfig(
                partition_activations=ac.partition_activations,
                cpu_checkpointing=ac.cpu_checkpointing,
                contiguous_memory_optimization=ac.contiguous_memory_optimization,
                number_checkpoints=ac.number_checkpoints,
                synchronize_checkpoint_boundary=ac.synchronize_checkpoint_boundary,
                profile=ac.profile)
    for key, val in dict(partition_activations=partition_activations,
                         contiguous_memory_optimization=contiguous_checkpointing,
                         number_checkpoints=num_checkpoints,
                         cpu_checkpointing=checkpoint_in_cpu,
                         synchronize_checkpoint_boundary=synchronize,
                         profile=profile).items():
        if val is not None:
            setattr(_CONFIG, key, val)


def _policy():
    cp = jax.checkpoint_policies
    if _CONFIG.cpu_checkpointing:
        try:
            return cp.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=[],
                offload_src="device", offload_dst="pinned_host")
        except Exception:  # older jax — fall back to recompute-everything
            logger.warning("cpu_checkpointing policy unavailable; "
                           "using nothing_saveable")
            return cp.nothing_saveable
    return cp.dots_with_no_batch_dims_saveable


def checkpoint(function: Callable, *args: Any) -> Any:
    """Reference drop-in: checkpoint ``function(*args)`` under the configured
    policy and run it immediately."""
    return jax.checkpoint(function, policy=_policy())(*args)


def checkpoint_wrapped(function: Callable) -> Callable:
    """Return the remat-wrapped function (for scan bodies etc.)."""
    return jax.checkpoint(function, policy=_policy())
