"""Checkpoint save/load.

Capability parity with the reference engine checkpointing + checkpoint-engine
backends (SURVEY §5.4): ``engine.save_checkpoint(dir, tag?)`` writes
``<dir>/<tag=global_step{N}>/`` plus a ``latest`` tag file
[L HF-DS:492, ACC:3665-3669]; ``engine.load_checkpoint`` restores
module+optimizer+scheduler+client state; resume tolerates a DIFFERENT
mesh/world size (the reference needs the separate universal-checkpoint
pipeline for that — orbax gives reshard-on-load natively, which is exactly
SURVEY §5.4's TPU mapping).

Layout per tag directory:
    state/            orbax sharded pytree (params, opt_state, step, scaler)
    client_state.json user + engine bookkeeping (global_steps, skipped, …)
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from ..utils.logging import log_dist, logger
from ..utils.jax_compat import ckpt_metadata_tree

LATEST_FILE = "latest"


def _tag_for(engine, tag: Optional[str]) -> str:
    return tag if tag is not None else f"global_step{engine.global_steps}"


def _ckpt_engine_for(engine):
    ceng = getattr(engine, "_ckpt_engine", None)
    if ceng is None:
        from .checkpoint_engine import make_checkpoint_engine

        ceng = make_checkpoint_engine(engine.config)
        engine._ckpt_engine = ceng
    return ceng


def _globalize_tree(tree, mesh):
    """Multi-controller: re-place host-local (single-device) leaves —
    eager scalars like ``state.step`` or a restored optax ``count`` — as
    mesh-replicated global arrays (same values on every process by the
    SPMD contract).  Orbax cannot serialize host-local arrays in a
    multi-host setting, and a committed single-device leaf poisons any
    jit that also takes global arguments.  Jit-produced leaves already
    carry global shardings and pass through."""
    from ..parallel.mesh import global_put, replicated

    rep = replicated(mesh)

    def fix(x):
        if (isinstance(x, jax.Array) and x.is_fully_addressable
                and len(x.sharding.device_set) == 1):
            return global_put(np.asarray(x), rep)
        return x

    return jax.tree.map(fix, tree)


def _globalize_state(engine):
    if jax.process_count() == 1 or getattr(engine, "mesh", None) is None:
        return
    engine.state = _globalize_tree(engine.state, engine.mesh)
    infinity = getattr(engine, "infinity", None)
    if infinity is not None:
        infinity.res_opt_state = _globalize_tree(infinity.res_opt_state,
                                                 engine.mesh)


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict[str, Any]] = None) -> str:
    from ..telemetry import get_telemetry

    tel = get_telemetry()
    with tel.span("checkpoint/save", args={"dir": save_dir}):
        path = _save_checkpoint_impl(engine, save_dir, tag, client_state)
    tel.inc_counter("checkpoint/saves", help="engine checkpoint saves")
    return path


def _save_checkpoint_impl(engine, save_dir: str, tag: Optional[str],
                          client_state: Optional[Dict[str, Any]]) -> str:
    _globalize_state(engine)
    tag = _tag_for(engine, tag)
    ckpt_dir = os.path.abspath(os.path.join(save_dir, tag))
    os.makedirs(ckpt_dir, exist_ok=True)

    # main state goes through the configured backend: sync, or async
    # (orbax AsyncCheckpointer — returns after the device→host snapshot,
    # writes behind training; the reference's decoupled engine role).
    # The `latest` durability marker is a commit callback so an async save
    # that dies mid-write never leaves `latest` naming a torn checkpoint.
    ceng = _ckpt_engine_for(engine)

    def _write_latest():
        with open(os.path.join(save_dir, LATEST_FILE), "w") as fh:
            fh.write(tag)

    ceng.save(engine.state, os.path.join(ckpt_dir, "state"),
              commit_fn=_write_latest)

    with ocp.StandardCheckpointer() as saver:
        infinity = getattr(engine, "infinity", None)
        if infinity is not None:
            # ZeRO-Infinity: the trunk lives in the swapper (host/NVMe) —
            # persist fp32 masters + Adam moments ONE LAYER AT A TIME so the
            # nvme tier's O(buffer_count) host-memory bound survives the save
            sw = infinity.swapper
            for i in range(sw.L):
                saver.save(
                    os.path.join(ckpt_dir, "infinity_trunk",
                                 f"layer_{i:05d}"),
                    {"master": sw.layer_master_tree(i),
                     "moments": sw.layer_moments(i)}, force=True)
            saver.save(os.path.join(ckpt_dir, "infinity_resident_opt"),
                       infinity.res_opt_state, force=True)
        if getattr(engine, "offload_opt", None) is not None:
            # ZeRO-Offload: moments live host-side in the C++ optimizer;
            # the attribute set varies per optimizer (Adam: both moments,
            # Adagrad: sq only, Lion: avg only)
            moments = {k: list(v) for k, v in
                       engine.offload_opt.state_dict_arrays().items()
                       if k != "step"}
            saver.save(os.path.join(ckpt_dir, "offload_state"), moments,
                       force=True)

    # sync the scheduler to the APPLIED step (excludes fp16 overflow skips;
    # the per-step fast path tracks global_steps to avoid a device sync)
    engine.lr_scheduler.last_step = int(engine.state.step)
    meta = {
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "offload_step": (engine.offload_opt.opt.state_step
                         if getattr(engine, "offload_opt", None) else 0),
        "infinity_step": (engine.infinity.swapper.state_step
                          if getattr(engine, "infinity", None) else 0),
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        "client_state": client_state or {},
        "ds_config_stage": engine.config.zero_optimization.stage,
    }
    with open(os.path.join(ckpt_dir, "client_state.json"), "w") as fh:
        json.dump(meta, fh, default=str)

    # reference ships zero_to_fp32.py into the checkpoint dir
    # [L trainer.py:4218]; the `latest` tag file was written by the
    # checkpoint engine's commit (deferred past durability when async)
    try:
        import shutil

        from ..utils import zero_to_fp32 as z2f

        shutil.copy(z2f.__file__, os.path.join(save_dir, "zero_to_fp32.py"))
    except Exception as e:
        # non-fatal convenience copy: broad on purpose — __file__ can be
        # None (frozen/zipapp) raising TypeError, and NOTHING here may
        # fail the real checkpoint that was just written
        from ..utils.logging import debug_once

        debug_once("checkpoint/zero_to_fp32_copy",
                   f"zero_to_fp32.py convenience copy skipped ({e!r})")
    log_dist(f"saved checkpoint {ckpt_dir}")
    return ckpt_dir


def _resolve_tag(load_dir: str, tag: Optional[str]) -> Optional[str]:
    if tag is not None:
        return tag
    latest = os.path.join(load_dir, LATEST_FILE)
    if os.path.exists(latest):
        with open(latest) as fh:
            return fh.read().strip()
    # fall back to newest global_step* dir (reference glob [L HF-DS:492])
    candidates = [d for d in os.listdir(load_dir)
                  if d.startswith("global_step")
                  and os.path.isdir(os.path.join(load_dir, d))]
    if not candidates:
        return None
    return max(candidates, key=lambda d: int(d.replace("global_step", "") or 0))


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_module_only: bool = False
                    ) -> Tuple[Optional[str], Optional[Dict[str, Any]]]:
    from ..telemetry import get_telemetry

    tel = get_telemetry()
    with tel.span("checkpoint/load", args={"dir": load_dir}):
        out = _load_checkpoint_impl(engine, load_dir, tag,
                                    load_optimizer_states, load_module_only)
    if out[0] is not None:
        tel.inc_counter("checkpoint/loads", help="engine checkpoint loads")
    return out


def _load_checkpoint_impl(engine, load_dir: str, tag: Optional[str],
                          load_optimizer_states: bool,
                          load_module_only: bool
                          ) -> Tuple[Optional[str], Optional[Dict[str, Any]]]:
    tag = _resolve_tag(load_dir, tag)
    if tag is None:
        logger.warning(f"no checkpoint found under {load_dir}")
        return None, None
    ckpt_dir = os.path.abspath(os.path.join(load_dir, tag))
    # join any in-flight async save before reading (it may be this tag)
    # — including one dispatched by a DIFFERENT engine instance (a fresh
    # engine resuming a tag its predecessor is still flushing; waiting
    # only on our own engine leaves that torn-read race to GC timing)
    _ckpt_engine_for(engine).wait()
    from .checkpoint_engine import join_inflight_save

    join_inflight_save(ckpt_dir)
    _globalize_state(engine)  # restore targets must be globally shardable

    # Restore INTO the engine's current sharded layout: orbax reshards on
    # load, so a checkpoint written on a different mesh/world restores
    # correctly (the reference's universal-checkpoint capability).
    def abstract(x):
        return jax.ShapeDtypeStruct(np.shape(x), x.dtype,
                                    sharding=getattr(x, "sharding", None))

    params_only = load_module_only or not load_optimizer_states
    state_path = os.path.join(ckpt_dir, "state")
    with ocp.StandardCheckpointer() as loader:
        if params_only:
            # Build the non-params target from the SAVED metadata so a
            # module-only load works against a DIFFERENT optimizer than the
            # one that saved (reference: load_module_only skips optimizer
            # state [K]); only the params subtree binds to engine shardings.
            meta = ckpt_metadata_tree(loader, state_path)
            target = jax.tree.map(
                lambda am: jax.ShapeDtypeStruct(tuple(am.shape), am.dtype),
                meta)
            target["params"] = jax.tree.map(abstract, engine.state.params)
            restored = loader.restore(state_path, target)
            engine.state = engine.state._replace(params=restored["params"])
        else:
            target = jax.tree.map(abstract, engine.state)
            engine.state = loader.restore(state_path, target)

    infinity = getattr(engine, "infinity", None)
    if infinity is not None:
        trunk_path = os.path.join(ckpt_dir, "infinity_trunk")
        if os.path.exists(trunk_path):
            sw = infinity.swapper
            with ocp.StandardCheckpointer() as loader:
                for i in range(sw.L):  # layer-at-a-time, like the save
                    lp = os.path.join(trunk_path, f"layer_{i:05d}")
                    meta_tree = ckpt_metadata_tree(loader, lp)
                    target = jax.tree.map(
                        lambda am: jax.ShapeDtypeStruct(tuple(am.shape),
                                                        am.dtype),
                        meta_tree)
                    entry = loader.restore(lp, target)
                    sw.load_layer(
                        i, entry["master"],
                        entry["moments"] if not params_only else None)
            if not params_only:
                opt_path = os.path.join(ckpt_dir, "infinity_resident_opt")
                if os.path.exists(opt_path):
                    with ocp.StandardCheckpointer() as loader:
                        target = jax.tree.map(abstract,
                                              infinity.res_opt_state)
                        infinity.res_opt_state = loader.restore(opt_path,
                                                                target)
        # resident params were restored into engine.state above
        infinity.resident = engine.state.params

    offload = getattr(engine, "offload_opt", None)
    if offload is not None:
        restored_master = False
        offload_path = os.path.join(ckpt_dir, "offload_state")
        if os.path.exists(offload_path) and not params_only:
            with ocp.StandardCheckpointer() as loader:
                target = {k: [jax.ShapeDtypeStruct(a.shape, a.dtype)
                              for a in v]
                          for k, v in offload.state_dict_arrays().items()
                          if k != "step"}
                # legacy checkpoints (pre-round-3) carry no 'master' entry;
                # probe the saved tree instead of masking restore errors
                saved_keys = set(ckpt_metadata_tree(loader, offload_path))
                if "master" not in saved_keys:
                    target.pop("master", None)
                    log_dist("offload restore: legacy checkpoint without "
                             "fp32 masters — moments restored, masters "
                             "reseeded from device params (exact only for "
                             "an fp32 wire)")
                restored_off = loader.restore(offload_path, target)
            restored_master = offload.load_state_arrays(restored_off)
        if not restored_master:
            # legacy/params-only checkpoint: re-seed host fp32 master slices
            # from the restored device params (exact only for an fp32 wire)
            offload.reseed_masters(engine.state.params)

    meta_path = os.path.join(ckpt_dir, "client_state.json")
    client_state: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as fh:
            meta = json.load(fh)
        engine.global_steps = int(meta.get("global_steps", 0))
        engine.micro_steps = int(meta.get("micro_steps", 0))
        if offload is not None and not params_only:
            offload.opt.state_step = int(meta.get("offload_step", 0))
        if infinity is not None and not params_only:
            infinity.swapper.state_step = int(meta.get("infinity_step", 0))
            infinity.global_steps = int(meta.get("global_steps", 0))
        if meta.get("lr_scheduler"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        client_state = meta.get("client_state", {})
    log_dist(f"loaded checkpoint {ckpt_dir}")
    return ckpt_dir, client_state


def load_universal_checkpoint(engine, universal_dir: str) -> None:
    """Load a ``ds_to_universal`` directory into the engine under ANY mesh.

    Reference: the ``--load_universal`` path of ``deepspeed/runtime/
    engine.py`` consuming ``checkpoint/ds_to_universal.py`` output (SURVEY
    §5.4).  Each per-param fp32 file lands via ``jax.device_put`` onto the
    TARGET state's sharding (the resharding the reference does with its
    pattern-matched slice merges falls out of GSPMD placement); Adam
    moments fill the matching ``mu``/``nu`` leaves of the optax state by
    path suffix, and the step counter resumes.
    """
    import json as _json

    meta_path = os.path.join(universal_dir, "universal_metadata.json")
    with open(meta_path) as f:
        meta = _json.load(f)
    zero_dir = os.path.join(universal_dir, "zero")

    def _load(key: str, name: str) -> np.ndarray:
        return np.load(os.path.join(zero_dir, key, name + ".npy"))

    def _put(arr: np.ndarray, like):
        arr = arr.astype(like.dtype)
        sh = getattr(like, "sharding", None)
        return jax.device_put(arr, sh) if sh is not None else jnp.asarray(
            arr)

    from ..utils.zero_to_fp32 import path_key

    flat, treedef = jax.tree_util.tree_flatten_with_path(
        engine.state.params)
    new_leaves = []
    for path, leaf in flat:
        key = path_key(path)
        if key not in meta["params"]:
            raise KeyError(
                f"universal checkpoint has no parameter '{key}' "
                f"(has: {sorted(meta['params'])[:8]}…)")
        new_leaves.append(_put(_load(key, "fp32"), leaf))
    params = jax.tree_util.tree_unflatten(treedef, new_leaves)

    oflat, otreedef = jax.tree_util.tree_flatten_with_path(
        engine.state.opt_state)
    new_opt = []
    for path, leaf in oflat:
        parts = path_key(path).split("/")
        repl = None
        for field, fname in (("mu", "exp_avg"), ("nu", "exp_avg_sq")):
            if field in parts:
                suffix = "/".join(parts[parts.index(field) + 1:])
                entry = meta["params"].get(suffix)
                if entry and entry.get("has_moments") and tuple(
                        entry["shape"]) == tuple(np.shape(leaf)):
                    repl = _put(_load(suffix, fname), leaf)
        if repl is None and "count" in parts and np.ndim(leaf) == 0:
            # optax's bias-correction step counter — without it the
            # resumed Adam re-warms from step 0 and the trajectory drifts
            repl = jnp.asarray(int(meta["step"]), leaf.dtype)
        new_opt.append(repl if repl is not None else leaf)
    opt_state = jax.tree_util.tree_unflatten(otreedef, new_opt)

    engine.state = engine.state._replace(
        params=params, opt_state=opt_state,
        step=jnp.asarray(int(meta["step"]), jnp.int32))
    engine.global_steps = int(meta["step"])
    log_dist(f"loaded universal checkpoint {universal_dir} "
             f"(step {meta['step']})")
