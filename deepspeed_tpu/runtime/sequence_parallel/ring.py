"""Ring attention — sequence parallelism past the head-count limit.

Reference positioning: NOT in DeepSpeed core (SURVEY §5.7 row 3 — Ulysses
is its answer; ring belongs to other stacks).  Built here as the
parity-plus long-context path the survey plans: Ulysses' maximum SP
degree is ``num_heads/tp`` (each rank needs ≥1 head); ring attention
(arXiv 2310.01889 [P] / blockwise 2305.19370) shards the SEQUENCE through
the whole computation, so SP scales with chips, not heads.

TPU-first formulation: a ``shard_map`` over the ``seq`` axis; each device
owns one contiguous sequence block of Q/K/V; K/V blocks rotate around the
ring with ``lax.ppermute`` (ICI-neighbor traffic) while each device folds
the visiting block into its queries' online-softmax state (m, l, acc) —
the flash-attention accumulator generalized across devices.  Causality
skips fully-masked visits via ``jnp.where`` on the accumulator update
(the compute still runs — lockstep SPMD — but XLA sees a uniform ring
step it can pipeline with the permute).  The backward pass is jax.grad
through the scan+ppermute, the transpose ring.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from ...comm.comm import ppermute as _ppermute
from ...parallel.mesh import AXIS_SEQ, DP_AXES
from ...utils import groups as groups_mod
from ...utils.jax_compat import shard_map as _shard_map

P = PartitionSpec


def _ring_attention_local(q, k, v, *, axis_name: str, sp: int,
                          causal: bool, window=None):
    """Per-device body: ``q [B, Sl, h, d]``, ``k/v [B, Sl, kv_h, d]`` with
    ``kv_h | h`` — GQA groups rotate at their stored width and expand
    per-visit (rotating pre-expanded heads would multiply the ppermute
    bytes by h/kv_h for data derivable locally)."""
    B, Sl, h, d = q.shape
    n_rep = h // k.shape[2]
    my = jax.lax.axis_index(axis_name)
    scale = 1.0 / np.sqrt(d)
    qf = q.astype(jnp.float32) * scale

    ring = [(i, (i + 1) % sp) for i in range(sp)]

    def visit(carry, r):
        kb, vb, m, l, acc = carry
        src = (my - r) % sp  # whose block is visiting this round
        kbf = kb.astype(jnp.float32)
        vbf = vb.astype(jnp.float32)
        if n_rep > 1:
            kbf = jnp.repeat(kbf, n_rep, axis=2)
            vbf = jnp.repeat(vbf, n_rep, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kbf)
        if causal or window is not None:
            from ...ops.masks import local_attention_mask

            # global positions: mine = my*Sl + iq, theirs = src*Sl + ik
            iq = my * Sl + jnp.arange(Sl)
            ik = src * Sl + jnp.arange(Sl)
            mask = local_attention_mask(iq, ik, causal=causal, window=window)
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_blk = jnp.max(s, axis=-1)                      # [B, h, Sl]
        m_new = jnp.maximum(m, m_blk)
        # fully-masked visits (src entirely in my future) produce -inf
        # rows; keep the old state there
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 1.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = (acc * alpha[..., None]
               + jnp.einsum("bhqk,bkhd->bhqd", p, vbf))
        m = m_new
        # rotate K/V to the next rank (a no-op compute-wise on the last
        # visit, but keeping the scan body uniform lets XLA overlap the
        # permute with the next visit's einsum)
        kb = _ppermute(kb, ring, axis_name)
        vb = _ppermute(vb, ring, axis_name)
        return (kb, vb, m, l, acc), None

    m0 = jnp.full((B, h, Sl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, h, Sl), jnp.float32)
    acc0 = jnp.zeros((B, h, Sl, d), jnp.float32)
    (_, _, m, l, acc), _ = jax.lax.scan(
        visit, (k, v, m0, l0, acc0), jnp.arange(sp))
    out = acc / jnp.maximum(l, 1e-20)[..., None]         # [B, h, Sl, d]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool = True,
                   mesh: Optional[Mesh] = None,
                   window: Optional[int] = None) -> jnp.ndarray:
    """Sequence-parallel attention over the ``seq`` mesh axis.

    ``q,k,v``: GLOBAL ``[B, S, h, d]`` arrays (seq-sharded or not — the
    shard_map partitions them); returns ``[B, S, h, d]``.  Unlike
    :func:`ulysses_attention` there is no head-count bound: SP degree is
    limited only by ``S % sp == 0``.  Positions are global, so RoPE must
    be applied BEFORE calling (on globally-indexed positions).
    """
    mesh = mesh if mesh is not None else groups_mod.get_mesh()
    sp = int(mesh.shape.get(AXIS_SEQ, 1))
    if sp == 1:
        return _plain_attention(q, k, v, causal, window)
    if q.shape[1] % sp:
        raise ValueError(f"sequence {q.shape[1]} not divisible by sp={sp}")

    # manualize ONLY the seq axis (batch/dp stays GSPMD-auto) — same
    # partial-manual convention as ulysses_attention so the two compose
    # with the surrounding engine shardings identically
    from ...utils.jax_compat import abstract_mesh_or_none

    ctx = abstract_mesh_or_none()
    sm_mesh = ctx if ctx is not None and ctx.shape else mesh
    body = partial(_ring_attention_local, axis_name=AXIS_SEQ, sp=sp,
                   causal=causal, window=window)
    spec = P(None, AXIS_SEQ, None, None)
    return _shard_map(body, mesh=sm_mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False,
                         axis_names={AXIS_SEQ})(q, k, v)


def _plain_attention(q, k, v, causal, window=None):
    """Dense fallback/reference — one home for the math
    (``ops/pallas/flash_attention._reference_attention``), GQA-expanded."""
    n_rep = q.shape[2] // k.shape[2]
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    from ...ops.pallas.flash_attention import _reference_attention

    return _reference_attention(q, k, v, causal, window)
