"""Sequence parallelism (Ulysses / ALST) — the fork's flagship subsystem.

Reference: ``deepspeed/runtime/sequence_parallel/ulysses_sp.py``
[L ACC:2398-2437] (UlyssesSPAttentionHF, UlyssesSPDataLoaderAdapter,
SequenceTiledCompute/TiledMLP) and the legacy
``deepspeed/sequence/layer.py:DistributedAttention`` [K].
"""

from .ring import ring_attention
from .ulysses_sp import (SequenceTiledCompute, TiledMLP, UlyssesSPAttentionHF,
                         UlyssesSPDataLoaderAdapter, sequence_tiled_loss,
                         ulysses_attention)

__all__ = [
    "ulysses_attention", "ring_attention", "UlyssesSPAttentionHF",
    "UlyssesSPDataLoaderAdapter", "SequenceTiledCompute", "TiledMLP",
    "sequence_tiled_loss",
]
