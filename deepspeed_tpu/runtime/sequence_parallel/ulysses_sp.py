"""Ulysses/ALST sequence parallelism, TPU-native.

Reference behavior (``runtime/sequence_parallel/ulysses_sp.py``
[L ACC:2398-2437], arXiv 2309.14509 / 2506.13996 [P]): activations ride
sequence-sharded everywhere EXCEPT attention; at the attention boundary an
all-to-all converts seq-sharding → head-sharding (full sequence, h/sp heads
per rank), attention runs locally, and a second all-to-all converts back.
Plus: a dataloader adapter handing each SP rank its sequence slice, and
tiled compute (MLP / logits+loss chunked over the sequence) so activation
memory is O(tile), not O(N).

TPU-first: the all-to-alls are ``jax.lax.all_to_all`` over the ``seq`` mesh
axis inside ``shard_map`` — an ICI-native collective XLA schedules directly.
This replaces both the reference's torch-dist all-to-all AND the
GSPMD-constraint formulation (which trips XLA's "involuntary full
rematerialization" on the seq↔head reshard); tiled compute is
``lax.scan`` + ``jax.checkpoint`` over sequence chunks.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...comm.comm import all_to_all_in_graph
from ...parallel.mesh import AXIS_SEQ, AXIS_TENSOR, DP_AXES
from ...utils import groups as groups_mod
from ...utils.jax_compat import shard_map as _shard_map

P = PartitionSpec


def ulysses_attention(attn_fn: Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray],
                                        jnp.ndarray],
                      q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """All-to-all seq↔heads around ``attn_fn`` (the Ulysses core).

    ``q,k,v``: global ``[B, S, h, d]`` arrays, sequence-sharded over the
    ``seq`` axis (and heads over ``tensor`` if TP is active).  ``attn_fn``
    receives per-device blocks with the FULL sequence and ``h/(sp·tp)`` heads
    and must be position-exact (RoPE etc. happen inside it on global
    positions).  Falls back to a direct call when the seq axis is 1.
    """
    mesh = mesh if mesh is not None else groups_mod.get_mesh()
    sp = int(mesh.shape.get(AXIS_SEQ, 1))
    if sp == 1:
        # still shard heads over tensor via ordinary GSPMD; no seq comm needed
        return attn_fn(q, k, v)

    # Manualize ONLY the seq axis: batch/head sharding stays with GSPMD, and
    # the partial-manual form composes under an enclosing pipeline shard_map
    # (whose context mesh must be reused — a concrete Mesh would mismatch).
    from ...utils.jax_compat import abstract_mesh_or_none

    ctx = abstract_mesh_or_none()
    sm_mesh = ctx if ctx is not None and ctx.shape else mesh
    spec = P(None, AXIS_SEQ, None, None)

    def inner(ql, kl, vl):
        # local [B, S/sp, h, d] → [B, S, h/sp, d]
        ql = all_to_all_in_graph(ql, AXIS_SEQ, split_axis=2,
                                 concat_axis=1, tiled=True)
        kl = all_to_all_in_graph(kl, AXIS_SEQ, split_axis=2,
                                 concat_axis=1, tiled=True)
        vl = all_to_all_in_graph(vl, AXIS_SEQ, split_axis=2,
                                 concat_axis=1, tiled=True)
        ol = attn_fn(ql, kl, vl)
        # back: [B, S, h/sp, d] → [B, S/sp, h, d]
        return all_to_all_in_graph(ol, AXIS_SEQ, split_axis=1,
                                   concat_axis=2, tiled=True)

    return _shard_map(inner, mesh=sm_mesh,
                         in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={AXIS_SEQ},
                         check_vma=False)(q, k, v)


# ----------------------------------------------------------------------
# tiled compute (ALST memory reducers)
# ----------------------------------------------------------------------

class SequenceTiledCompute:
    """Chunk a seq-wise function through ``lax.scan`` + remat.

    Reference: ``SequenceTiledCompute`` autograd fn [L ACC signature];
    activation memory becomes O(S/tiles) — the ALST enabler for multi-M-token
    sequences.
    """

    @staticmethod
    def apply(fn: Callable[[jnp.ndarray], jnp.ndarray], x: jnp.ndarray,
              tiles: int, seq_axis: int = 1) -> jnp.ndarray:
        if tiles <= 1:
            return fn(x)
        S = x.shape[seq_axis]
        if S % tiles:
            raise ValueError(f"seq len {S} not divisible by tiles={tiles}")
        xs = jnp.moveaxis(
            x.reshape(x.shape[:seq_axis] + (tiles, S // tiles)
                      + x.shape[seq_axis + 1:]), seq_axis, 0)

        def body(_, xt):
            return None, jax.checkpoint(fn)(xt)

        _, ys = jax.lax.scan(body, None, xs)
        ys = jnp.moveaxis(ys, 0, seq_axis)
        return ys.reshape(x.shape[:seq_axis] + (S,) + ys.shape[seq_axis + 2:])


class TiledMLP:
    """Seq-tiled pointwise MLP application (reference ``TiledMLP`` [L]).

    Valid for any token-wise fn (an MLP block, a norm+MLP residual…)."""

    @staticmethod
    def apply(mlp_fn: Callable[[jnp.ndarray], jnp.ndarray], x: jnp.ndarray,
              tiles: int) -> jnp.ndarray:
        return SequenceTiledCompute.apply(mlp_fn, x, tiles, seq_axis=1)


def sequence_tiled_loss(logits_fn: Callable[[jnp.ndarray], jnp.ndarray],
                        hidden: jnp.ndarray, labels: jnp.ndarray,
                        tiles: int) -> jnp.ndarray:
    """Tiled final-projection + cross-entropy (never materializes the full
    ``[B, S, V]`` logits — the dominant activation at large vocab).

    Returns (sum_nll, valid_count) reduced over all positions; labels use the
    HF ``-100`` ignore convention.
    """
    B, S, H = hidden.shape
    if tiles <= 1 or S % tiles:
        tiles = 1
    hs = jnp.moveaxis(hidden.reshape(B, tiles, S // tiles, H), 1, 0)
    ls = jnp.moveaxis(labels.reshape(B, tiles, S // tiles), 1, 0)

    def body(acc, xs):
        h, lab = xs

        def chunk_nll(h):
            logits = logits_fn(h).astype(jnp.float32)
            valid = lab != -100
            safe = jnp.where(valid, lab, 0)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
            return (jnp.sum(jnp.where(valid, nll, 0.0)),
                    jnp.sum(valid.astype(jnp.int32)))

        nll_sum, count = jax.checkpoint(chunk_nll)(h)
        return (acc[0] + nll_sum, acc[1] + count), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), (hs, ls))
    return total / jnp.maximum(count, 1)


# ----------------------------------------------------------------------
# dataloader adapter + registration API (reference signatures)
# ----------------------------------------------------------------------

class UlyssesSPDataLoaderAdapter:
    """Hand each SP rank its sequence slice of every batch
    [L ACC:2431-2437 signature parity].

    In the single-controller GSPMD world the engine consumes GLOBAL batches,
    so slicing is only needed in multi-process (one process per host) runs:
    each process slices for its own sp_rank and the global array is assembled
    with ``jax.make_array_from_process_local_data`` by the dataloader.
    """

    def __init__(self, dl: Any, sp_rank: Optional[int] = None,
                 sp_group: Any = None, sp_world_size: Optional[int] = None,
                 device: Any = None):
        self.dl = dl
        grp = sp_group if sp_group is not None else (
            groups_mod.get_sequence_parallel_group())
        self.sp_world_size = (int(sp_world_size) if sp_world_size is not None
                              else grp.size)
        self.sp_rank = (int(sp_rank) if sp_rank is not None
                        else grp.rank_of_process())
        self.device = device

    def _slice(self, x):
        if not hasattr(x, "ndim") or x.ndim < 2:
            return x
        S = x.shape[1]
        if S % self.sp_world_size:
            raise ValueError(
                f"sequence length {S} not divisible by sp={self.sp_world_size}")
        chunk = S // self.sp_world_size
        return x[:, self.sp_rank * chunk:(self.sp_rank + 1) * chunk]

    def __iter__(self) -> Iterator[Any]:
        for batch in self.dl:
            yield jax.tree.map(self._slice, batch)

    def __len__(self) -> int:
        return len(self.dl)


class UlyssesSPAttentionHF:
    """Registration façade with the reference's classmethod signature
    [L ACC:2409-2430].

    The reference monkey-patches HF *torch* attention; TPU-native models get
    Ulysses via :func:`ulysses_attention` / mesh constraints instead, so this
    classmethod's job reduces to (1) validating the geometry and (2) handing
    back an ``mpu`` whose group getters accelerate/HF consume.
    """

    @classmethod
    def register_with_transformers(cls, model_name_or_path: Any = None,
                                   core_attn_implementation: str = "sdpa",
                                   sequence_parallel_size: int = 1,
                                   max_length: Optional[int] = None,
                                   micro_batch_size: int = 1,
                                   seq_length_is_variable: bool = True,
                                   **_kwargs: Any):
        if sequence_parallel_size == 1:
            return None
        mesh = groups_mod.get_mesh()
        sp = int(mesh.shape.get(AXIS_SEQ, 1))
        if sp != sequence_parallel_size:
            raise ValueError(
                f"mesh seq axis is {sp}, requested sp={sequence_parallel_size};"
                " build the mesh with the matching MeshLayout first")
        if max_length and max_length % sp:
            raise ValueError(f"max_length {max_length} not divisible by sp={sp}")

        class _MPU:
            @staticmethod
            def get_sequence_parallel_group():
                return groups_mod.get_sequence_parallel_group()

            @staticmethod
            def get_sequence_parallel_world_size():
                return groups_mod.get_sequence_parallel_world_size()

            @staticmethod
            def get_sequence_parallel_rank():
                return groups_mod.get_sequence_parallel_rank()

        return _MPU()
