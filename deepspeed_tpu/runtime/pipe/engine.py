"""Pipeline engine.

Capability parity target: the reference ``deepspeed/runtime/pipe/engine.py``
(1F1B ``TrainSchedule`` instruction streams, P2P activations, tied-weight
grad all-reduce [K]) — see SURVEY §3.5.

TPU-native execution model: the microbatch loop compiles to a
``jax.lax.scan`` whose body advances every stage one tick and moves boundary
activations with ``ppermute`` along the ``pipe`` mesh axis inside
``shard_map`` (GPipe-style fill/drain — arithmetically identical gradients to
1F1B; 1F1B's benefit is eager-mode memory scheduling that XLA handles
differently).  That path lives in ``parallel/pipeline.py`` once the ``pipe``
axis size is > 1.

With ``pipe == 1`` the API lowers onto a fused sequential program (stages
chained inside one jit).  With ``pipe > 1`` the stage chains execute the
REAL fill/drain schedule — ``parallel.pipeline.pipeline_apply_stages``'s
lax.scan + ppermute ring over the pipe mesh axis (each rank runs only its
own stage via lax.switch).  Homogeneous layer-stack models get the 1F1B
schedule through ``DeepSpeedEngine`` directly (``pipeline.schedule``
config key); heterogeneous-stage 1F1B is future work — GPipe-through-
autodiff computes identical gradients with a larger activation footprint.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import jax
import jax.numpy as jnp

from ..config import DeepSpeedConfig
from ..engine import DeepSpeedEngine
from .module import PipelineModule, TiedLayerSpec


class PipelineEngine(DeepSpeedEngine):
    """Executes a PipelineModule; ``train_batch(data_iter)`` replaces the
    fwd/bwd/step triple (reference contract)."""

    def __init__(self, module: PipelineModule, config: DeepSpeedConfig,
                 mesh=None, optimizer=None, lr_schedule=None):
        if module.loss_fn is None:
            raise ValueError("PipelineModule needs loss_fn for training")
        rng = jax.random.PRNGKey(config.seed)
        # Tied layers share ONE param leaf: autodiff sums the cotangents from
        # every use site, which is exactly the reference's tied-weight grad
        # all-reduce across stages. (Duplicating the leaf would both untie the
        # weights and crash buffer donation.)
        params: dict[str, Any] = {"layers": {}, "tied": {}}
        for i, spec in enumerate(module.specs):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in params["tied"]:
                    params["tied"][spec.key] = spec.build(jax.random.fold_in(rng, i))
            else:
                params["layers"][str(i)] = spec.build(jax.random.fold_in(rng, i))

        from ...utils import groups as groups_mod
        from ...parallel.mesh import AXIS_PIPE

        eff_mesh = mesh if mesh is not None else groups_mod.get_mesh()
        pp = int(eff_mesh.shape.get(AXIS_PIPE, 1)) if eff_mesh else 1

        def _apply_spec(p, i, spec, x):
            layer_p = (p["tied"][spec.key] if isinstance(spec, TiedLayerSpec)
                       else p["layers"][str(i)])
            return spec.apply_fn(layer_p, x)

        if pp > 1:
            # REAL pipeline execution: partition the spec chain into pp
            # stage fns and run the ppermute fill/drain schedule.
            # Heterogeneous stage chains have no 1F1B here (PARITY:
            # future work) — GPipe-through-autodiff computes identical
            # gradients at a larger activation footprint.
            from ...utils.logging import logger

            logger.info(
                f"pipeline engine: pp={pp} heterogeneous stage chain "
                f"takes the GPipe fill/drain schedule (identical "
                f"gradients to 1F1B; larger activation footprint — "
                f"heterogeneous-stage 1F1B is future work)")
            from ...parallel.pipeline import pipeline_apply_stages

            bounds = module.stage_bounds(pp)

            def _stage_fn(s):
                lo, hi = bounds[s], bounds[s + 1]

                def run(p, x):
                    for i in range(lo, hi):
                        x = _apply_spec(p, i, module.specs[i], x)
                    return x
                return run

            stage_fns = [_stage_fn(s) for s in range(pp)]
            M = int(config.pipeline.num_micro_batches or pp)

            def loss_fn(p, batch):
                x, y = batch
                rows = x.shape[0]
                if rows % M:
                    raise ValueError(
                        f"batch rows {rows} not divisible by pipeline "
                        f"microbatches {M}")
                micro_x = x.reshape((M, rows // M) + x.shape[1:])
                outs = pipeline_apply_stages(stage_fns, p, micro_x,
                                             eff_mesh)
                outs = outs.reshape((rows,) + outs.shape[2:])
                return module.loss_fn(outs, y)
        else:
            def loss_fn(p, batch):
                x, y = batch
                for i, spec in enumerate(module.specs):
                    x = _apply_spec(p, i, spec, x)
                return module.loss_fn(x, y)

        super().__init__(loss_fn=loss_fn, params=params, config=config,
                         optimizer=optimizer, lr_schedule=lr_schedule,
                         module=module, mesh=mesh)
        self.pipeline_module = module

    def train_batch(self, data_iter: Optional[Iterator] = None, batch=None):
        """Consume one GLOBAL batch (or pull GAS microbatches from the
        iterator) and run one compiled optimizer step."""
        if batch is None:
            if data_iter is None:
                raise ValueError("train_batch needs data_iter or batch")
            micros = [next(data_iter)
                      for _ in range(self.gradient_accumulation_steps)]
            batch = (micros[0] if len(micros) == 1 else
                     jax.tree.map(lambda *xs: jnp.concatenate(xs), *micros))
        metrics = self.train_step(batch)
        return metrics["loss"]

    def eval_batch(self, data_iter: Iterator):
        batch = next(data_iter)
        return self.eval_loss(batch)
