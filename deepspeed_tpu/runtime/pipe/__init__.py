from .module import LayerSpec, PipelineModule, TiedLayerSpec

__all__ = ["LayerSpec", "PipelineModule", "TiedLayerSpec"]
