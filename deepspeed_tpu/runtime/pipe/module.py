"""Pipeline module description.

Capability parity with the reference ``deepspeed/runtime/pipe/module.py`` [K]:
``PipelineModule(layers=[LayerSpec...], num_stages, partition_method)``,
``LayerSpec``/``TiedLayerSpec``.  Here a "layer" is a pure stage function
``(params_i, activations) -> activations`` plus an init; the pipeline engine
(``pipe/engine.py``) schedules them 1F1B over the ``pipe`` mesh axis with
``ppermute`` — no torch Module graph walking needed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence


@dataclasses.dataclass
class LayerSpec:
    """Deferred layer: built per-stage so params materialize only where used."""

    init_fn: Callable[..., Any]  # rng -> params for this layer
    apply_fn: Callable[..., Any]  # (params, x) -> x
    name: str = "layer"

    def build(self, rng):
        return self.init_fn(rng)


@dataclasses.dataclass
class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared with another (e.g. embedding/unembedding).
    ``key`` names the tie group; the pipeline engine replicates tied params on
    all owning stages and all-reduces their grads (reference behavior)."""

    key: str = "tied"


class PipelineModule:
    """A sequence of layer specs partitioned into pipeline stages."""

    def __init__(self, layers: Sequence[LayerSpec], num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "uniform", topology=None,
                 activation_checkpoint_interval: int = 0):
        self.specs: List[LayerSpec] = list(layers)
        self.num_stages = num_stages or 1
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        if partition_method not in ("uniform", "parameters"):
            # type:regex partitioning needs module metadata; document gap
            raise ValueError(f"unsupported partition_method {partition_method}")
        self.parts = self._partition_uniform(len(self.specs), self.num_stages)

    @staticmethod
    def _partition_uniform(n_layers: int, n_stages: int) -> List[int]:
        """Boundaries: stage i owns layers [parts[i], parts[i+1])."""
        base, extra = divmod(n_layers, n_stages)
        bounds = [0]
        for i in range(n_stages):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        return bounds

    def stage_layers(self, stage_id: int) -> List[LayerSpec]:
        return self.specs[self.parts[stage_id]:self.parts[stage_id + 1]]

    def stage_bounds(self, n_stages: int) -> List[int]:
        """Stage boundaries for an EXECUTION width that may differ from the
        module's declared ``num_stages`` (the engine partitions over the
        actual pipe mesh axis)."""
        if n_stages == self.num_stages:
            return self.parts
        return self._partition_uniform(len(self.specs), n_stages)
