"""ds_config parsing + validation.

Capability parity with the reference ``deepspeed/runtime/config.py`` [K]; key
inventory and batch-size invariant from SURVEY §5.6.  Accepts the same JSON
documents (path, dict, or base64 string [L ACC-DS:145-156]) an HF/accelerate
user would pass to DeepSpeed, including ``"auto"`` placeholders.

Batch math [L HF-DS:139-140, ACC:2223-2228]:

    train_batch_size = micro_batch × gradient_accumulation_steps × dp_world

where ``dp_world = world_size / (tp × pp × sp)`` — sequence-parallel ranks
consume the SAME batch shards (they split the sequence dim), so sp divides
out exactly like tp/pp.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import Field, model_validator

from ..utils.logging import logger
from .config_utils import AUTO, DeepSpeedConfigModel, is_auto
from .zero.config import DeepSpeedZeroConfig


# ---------------------------------------------------------------------------
# precision
# ---------------------------------------------------------------------------


class FP16Config(DeepSpeedConfigModel):
    enabled: Union[bool, str] = False  # may be "auto"
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 → dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0


class BF16Config(DeepSpeedConfigModel):
    enabled: Union[bool, str] = False
    # reference: bf16 grad accumulation dtype option
    immediate_grad_update: bool = True


class AMPConfig(DeepSpeedConfigModel):
    enabled: Union[bool, str] = False
    opt_level: str = "O1"


# ---------------------------------------------------------------------------
# optimizer / scheduler
# ---------------------------------------------------------------------------


class OptimizerParams(DeepSpeedConfigModel):
    lr: Union[float, str] = 1e-3
    betas: Union[List[float], str] = Field(default_factory=lambda: [0.9, 0.999])
    eps: Union[float, str] = 1e-8
    weight_decay: Union[float, str] = 0.0
    momentum: float = 0.0  # sgd
    # onebit/compression extras accepted via extra="allow"


class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "AdamW"
    params: OptimizerParams = Field(default_factory=OptimizerParams)
    legacy_fusion: bool = False


class SchedulerParams(DeepSpeedConfigModel):
    # WarmupLR / WarmupDecayLR / WarmupCosineLR
    warmup_min_lr: Union[float, str] = 0.0
    warmup_max_lr: Union[float, str] = 1e-3
    warmup_num_steps: Union[int, str] = 1000
    warmup_type: str = "log"
    total_num_steps: Union[int, str, None] = None
    # WarmupCosineLR
    warmup_min_ratio: float = 0.0
    cos_min_ratio: float = 1e-4
    # OneCycle / LRRangeTest take their own keys via extra="allow"


class SchedulerConfig(DeepSpeedConfigModel):
    type: str = "WarmupLR"
    params: SchedulerParams = Field(default_factory=SchedulerParams)


# ---------------------------------------------------------------------------
# feature subsystems (schema parity; behavior lives in their modules)
# ---------------------------------------------------------------------------


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """Reference ``activation_checkpointing`` group.  On TPU these map onto
    ``jax.checkpoint`` policies: ``partition_activations`` → remat with
    sharded residuals; ``cpu_checkpointing`` → offload policy."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class AioConfig(DeepSpeedConfigModel):
    """NVMe async-IO engine knobs (ZeRO-Infinity) [L ACC-DC:1187-1194]."""

    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True
    use_gds: bool = False


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    #: also count in-graph collectives per EXECUTION via effectful host
    #: callbacks (per-local-shard counts; measurable overhead — see
    #: comm.CommsLogger)
    exec_counts: bool = False
    prof_all: bool = True
    prof_ops: List[str] = Field(default_factory=list)
    debug: bool = False


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class TelemetryWatchdogConfig(DeepSpeedConfigModel):
    """``telemetry.watchdog`` — hang/straggler watchdog
    (``telemetry/watchdog.py``).  Fed by ``engine.train_step`` progress
    notifications (comms-logger activity is a secondary liveness
    signal); on no progress within ``hang_timeout_s`` it dumps a
    flight-recorder debug bundle and runs ``action``.  Independent of
    ``telemetry.enabled`` — a production run can keep the hub off and
    the watchdog on."""

    enabled: bool = False
    hang_timeout_s: float = 300.0
    #: 0 → hang_timeout_s / 4, capped at 10s
    poll_interval_s: float = 0.0
    action: Literal["log", "raise", "exit"] = "log"
    #: treat comms-logger counter movement as liveness (a long compile or
    #: giant eager collective is slow, not hung)
    comm_liveness: bool = True
    #: bounded device-liveness check on the trip path: jax.devices()/
    #: memory_stats() on a deadline thread, so a dead accelerator tunnel
    #: yields a fail-fast bundle with a ``device_unresponsive``
    #: annotation instead of a 180 s+ hang (BENCH_r05)
    device_probe: bool = True
    device_probe_timeout_s: float = 20.0
    #: byte cap on the heartbeat payload (JSON size).  The payload is
    #: versioned (``v``) and fields drop in a deterministic order
    #: (``telemetry.watchdog.HEARTBEAT_DROP_ORDER``) when over the cap,
    #: counted by ``elastic/heartbeat_fields_dropped_total``; <= 0
    #: disables the cap
    heartbeat_max_bytes: int = 1024


class TelemetryHealthConfig(DeepSpeedConfigModel):
    """``telemetry.health`` — streaming anomaly detectors over the
    engine's StepRecords (``telemetry/health.py``): NaN/Inf loss,
    loss-spike z-score, grad-norm explosion, fp16 loss-scale collapse,
    throughput regression.  Active when telemetry step records are on."""

    enabled: bool = True
    window: int = 32
    min_points: int = 8
    loss_spike_zscore: float = 6.0
    grad_norm_ratio: float = 10.0
    loss_scale_floor: float = 1.0
    consecutive_scale_drops: int = 3
    throughput_frac: float = 0.5
    #: steps whose compile_ms >= frac * step_time are compile-dominated:
    #: excluded from the throughput-regression window (and from the
    #: watchdog step-time EWMA)
    compile_dominated_frac: float = 0.5
    #: recompile events within `window` steps that raise a
    #: recompile_storm health event; <= 0 disables the rule
    recompile_storm_threshold: int = 3
    #: raise a control_plane_degraded event when a rendezvous-store
    #: client exhausts its retry budget (one per outage streak)
    control_plane: bool = True


class FlightRecorderConfig(DeepSpeedConfigModel):
    """``telemetry.flight_recorder`` — the black box
    (``telemetry/flight_recorder.py``): bounded rings of recent
    StepRecords/HealthEvents/annotations, dumped as a debug bundle
    (manifest + Chrome-trace slice + env report + per-thread stacks) on
    demand, fatal signal, unhandled exception, or watchdog trip."""

    enabled: bool = True
    max_records: int = 256
    #: default: <telemetry.output_path>/<job_name>/debug_bundles
    output_path: str = ""
    #: install SIGTERM/SIGABRT handlers + sys.excepthook at initialize()
    install_handlers: bool = True
    #: keep only the newest N bundle dirs per dump dir (repeated watchdog
    #: trips must not fill the disk); <= 0 keeps everything
    retain_bundles: int = 5


class TelemetryAggregationConfig(DeepSpeedConfigModel):
    """``telemetry.aggregation`` — the cross-host observability plane
    (``telemetry/{aggregator,collective_ledger}.py``): each host
    publishes its debug bundle through the elastic rendezvous store
    (shared-FS fallback) and rank 0 / the operator CLI assembles ONE
    cluster archive; a per-rank collective ledger rides the heartbeats
    for live desync detection and lands full tails in the archive."""

    enabled: bool = False
    #: store-value chunk size for published bundle tarballs
    chunk_bytes: int = 262144
    #: size cap per published bundle (largest side files dropped first;
    #: the manifest always ships)
    max_bundle_bytes: int = 33554432
    #: shared-filesystem fallback drop dir ("" = store transport only)
    shared_fs_path: str = ""
    #: rank-0 / operator collect timeout
    collect_timeout_s: float = 30.0
    #: per-rank monotonic ledger of collectives fed by the comms logger
    ledger_enabled: bool = True
    ledger_max_entries: int = 4096
    #: ledger entries embedded in each debug bundle (comparison window)
    ledger_tail: int = 64
    #: also feed the ledger's EXEC lane from execution probes
    #: (comms_logger.record_exec).  Off by default: device callbacks are
    #: unordered, so the exec chain is per-host forensics only — the
    #: trace-sourced census (profiling.collective_trace.feed_exec_census)
    #: is the cross-rank-comparable execution-order source
    ledger_exec_feed: bool = False
    #: cross-process metrics rollup (telemetry/rollup.py): every worker
    #: ships its registry snapshot + step-record batch on the publisher
    #: tick; rank 0 merges them into one per-node-labeled view
    metrics_rollup: bool = True
    #: publish cadence (seconds) for the snapshot/step batch; the
    #: heartbeat tick is the transport, this bounds its payload rate
    metrics_push_every_s: float = 2.0
    #: compact StepRecord streaming to the rollup: bounded ring, batched
    #: on the publisher tick, degraded-mode buffered (flushes exactly
    #: once after a store restart — the rollup dedups by sequence)
    step_stream: bool = True
    step_stream_len: int = 256


class TelemetryMemoryConfig(DeepSpeedConfigModel):
    """``telemetry.memory`` — the memory observability plane
    (``telemetry/memory/``): the per-pool HBM/host byte ledger fed by
    allocation-site hooks, per-step ``peak_hbm_bytes``/RSS/swap-IO on
    StepRecords, OOM forensics (``memory.json`` + descriptive
    ``HBMExhaustedError``), and the memory health rules.  Active when
    ``telemetry.enabled`` is on or a flight recorder exists."""

    enabled: bool = True
    #: jax.live_arrays() census cadence in steps (O(all buffers) — too
    #: expensive per step); <= 0 disables the census
    live_census_every: int = 16
    #: live arrays kept in forensics breakdowns (memory.json, `mem top`)
    top_k: int = 10
    #: memory_pressure health rule: HBM used fraction threshold and the
    #: consecutive steps above it before the rule fires; frac <= 0
    #: disables
    pressure_frac: float = 0.92
    pressure_steps: int = 8
    #: host_memory_leak health rule: consecutive-growth window and the
    #: minimum growth of the newest sample over the window median;
    #: window < 2 disables
    leak_window: int = 16
    leak_frac: float = 0.05


class TelemetryNumericsConfig(DeepSpeedConfigModel):
    """``telemetry.numerics`` — the numerics observability plane
    (``telemetry/numerics/``): in-graph per-layer tensor-health probes
    (nonfinite/absmax/underflow/saturation stat vectors riding the
    step's aux output), grad-path norms and update/param ratios, MoE
    gate telemetry, NaN origin bisection on ``nan_loss``
    (``numerics.json`` + ``NonFiniteOriginReport``), and the
    ``underflow_creep``/``layer_grad_explosion``/``router_collapse``
    health rules.  Probes are an IDENTITY when disabled — same jaxpr,
    zero recompiles."""

    enabled: bool = False
    #: sampled-capture cadence in steps: every Nth step dispatches the
    #: probed step program (its own jit site — compiled once); <= 0
    #: means forensic-only (the probed program never runs unless a
    #: non-finite loss triggers the bisection)
    every: int = 32
    #: run the all-probes forward bisection when a fenced loss goes
    #: non-finite, naming the first bad layer in the health event /
    #: rollback annotation / numerics.json
    forensic_on_nan: bool = True
    #: underflow_creep health rule: worst per-probe bf16-subnormal
    #: fraction threshold and consecutive sampled captures above it
    #: before the rule fires (suggesting a loss-scale bump); frac <= 0
    #: disables
    underflow_frac: float = 0.05
    underflow_steps: int = 3
    #: layer_grad_explosion health rule: a single layer's grad norm
    #: exceeding ``ratio`` x the median layer grad norm (with the
    #: median above ``floor``) names that layer; ratio <= 0 disables
    layer_grad_ratio: float = 20.0
    layer_grad_floor: float = 1e-8
    #: router_collapse health rule: mean gating entropy (nats) below
    #: this floor for ``entropy_steps`` consecutive MoE captures means
    #: the router is sending everything to one expert; floor <= 0
    #: disables
    entropy_floor: float = 0.30
    entropy_steps: int = 3
    #: sample MoE gate telemetry (moe/* gauges) even when ``enabled``
    #: is false — the gate stats are already computed by top_k_gating,
    #: so publishing them costs one extra scan output, not a probe pass
    moe_gauges: bool = True


class TelemetryPerfConfig(DeepSpeedConfigModel):
    """``telemetry.perf`` — the performance observability plane
    (``telemetry/perf/``): compile/recompile tracking over every engine
    jit site, the goodput wall-clock ledger, and the perf-regression
    sentinel's knobs.  Active when ``telemetry.enabled`` is on."""

    enabled: bool = True
    #: tracked_jit at every engine jit site: compile events, recompile
    #: cause diffs, per-site program table in debug bundles
    compile_tracker: bool = True
    compile_max_events: int = 512
    #: classify step-loop wall time into productive/compile/stall/
    #: recovery/checkpoint buckets; rolling goodput rides heartbeats
    goodput: bool = True
    #: rolling-goodput window (seconds) for the heartbeat fraction
    goodput_window_s: float = 600.0
    #: step-anatomy plane (``telemetry/anatomy``): harvest FLOPs/bytes
    #: rooflines from every AOT compile, enable engine.capture_anatomy
    anatomy: bool = True
    #: fenced steps per capture_anatomy trace window
    anatomy_capture_steps: int = 2
    #: programs in the roofline predicted-vs-measured join
    anatomy_top_k: int = 5


class TelemetryProfilerConfig(DeepSpeedConfigModel):
    """``telemetry.profiler`` — the fleet-synchronized profiler capture
    plane (``telemetry/profiler/``): each worker polls the rendezvous
    store for ``telemetry profile`` capture commands, arms
    ``jax.profiler`` for the agreed step-index window, publishes its
    measured device lanes + calibration report back through the store,
    and (optionally) runs a duty-cycled continuous capture.  When
    disabled the train step never sees the plane — same jaxpr, zero
    recompiles."""

    enabled: bool = True
    #: bounded ring of on-disk trace dirs per worker (oldest evicted)
    ring: int = 4
    #: steps of arming lead when proposing the shared capture window
    lead: int = 3
    #: duty-cycle continuous capture: percent of each period spent
    #: tracing (0 disables); capture time is booked to the goodput
    #: ``profiler`` bucket
    duty_cycle_pct: float = 0.0
    #: steps per duty-cycle period
    duty_period_steps: int = 64
    #: trace-dir ring location (default: a tmpdir per process)
    out_dir: str = ""


class TelemetryConfig(DeepSpeedConfigModel):
    """``telemetry`` config group — the unified telemetry subsystem
    (``deepspeed_tpu/telemetry/``): span tracer + metrics registry +
    per-step records, exported as JSONL / Prometheus text / Chrome trace.
    Registered as a fourth ``MonitorMaster`` backend, so it composes with
    the ``tensorboard``/``wandb``/``csv_monitor`` groups."""

    enabled: bool = False
    output_path: str = ""            # base dir (default: telemetry_logs/)
    job_name: str = "DeepSpeedJobName"
    #: append one JSON object per event/step to <out>/events.jsonl
    jsonl: bool = True
    #: write Prometheus text exposition to <out>/metrics.prom on flush()
    prometheus: bool = True
    #: export host spans as <out>/trace.json (Chrome-trace JSON,
    #: correlatable with profiling/collective_trace.py device lanes)
    chrome_trace: bool = False
    #: assemble a per-optimizer-step StepRecord in the engine
    step_records: bool = True
    #: fence the device (fetch the loss scalar) before stamping step time —
    #: step_time_ms then measures DEVICE time, not dispatch backpressure.
    #: false = ASYNC recording: no per-step sync at all — records keep
    #: dispatch time + comm/memory stats but carry NaN metric fields and
    #: no rates (pulling loss would block; the whole point is overlap)
    device_fence: bool = True
    max_span_events: int = 100000
    watchdog: TelemetryWatchdogConfig = Field(
        default_factory=TelemetryWatchdogConfig)
    health: TelemetryHealthConfig = Field(
        default_factory=TelemetryHealthConfig)
    flight_recorder: FlightRecorderConfig = Field(
        default_factory=FlightRecorderConfig)
    aggregation: TelemetryAggregationConfig = Field(
        default_factory=TelemetryAggregationConfig)
    perf: TelemetryPerfConfig = Field(default_factory=TelemetryPerfConfig)
    memory: TelemetryMemoryConfig = Field(
        default_factory=TelemetryMemoryConfig)
    numerics: TelemetryNumericsConfig = Field(
        default_factory=TelemetryNumericsConfig)
    profiler: TelemetryProfilerConfig = Field(
        default_factory=TelemetryProfilerConfig)


class ServingTracingConfig(DeepSpeedConfigModel):
    """``serving.tracing`` config group — distributed request tracing
    (``deepspeed_tpu/serving/tracing.py``): per-request lifecycle
    records (queue wait, admission, preempt/replay, prefill/transfer/
    decode phases, token timings) in a bounded ring, head-based sampled
    with always-on capture of anomalous requests, shipped cross-process
    over the telemetry rollup and assembled by ``python -m
    deepspeed_tpu.serving trace <id>``."""

    enabled: bool = True
    #: head-based sample rate (deterministic on the trace id, so every
    #: process that touches a request reaches the same verdict);
    #: anomalous requests (replayed / preempted / failed / slow TTFT)
    #: are ALWAYS recorded, even at 0.0
    sample_rate: float = 1.0
    #: committed records retained (the ring is also the window each
    #: rollup publication ships — the store holds the recent history)
    ring: int = 256
    #: TTFT above this (ms) force-samples the request as anomalous
    #: (0 disables the threshold)
    anomaly_ttft_ms: float = 2000.0
    #: per-record cap on token timestamps kept for gap percentiles
    token_timings: int = 512


class ServingSLOConfig(DeepSpeedConfigModel):
    """``serving.slo`` config group — declarative service-level
    objectives (``deepspeed_tpu/serving/slo.py``): per-class TTFT/TPOT
    p99 bounds, availability (1 − 429/5xx rate), and token-budget
    saturation, evaluated continuously against the PR-13 metrics
    rollup with fast/slow multi-window burn rates.  Alert transitions
    become health events, ``serving_slo_*`` gauges, and flight-recorder
    annotations."""

    enabled: bool = True
    #: per-class TTFT p99 bound (ms); 0 disables that class's objective
    interactive_ttft_p99_ms: float = 2000.0
    batch_ttft_p99_ms: float = 10000.0
    background_ttft_p99_ms: float = 0.0
    #: per-class TPOT p50 bound (ms/token); 0 disables
    interactive_tpot_p50_ms: float = 500.0
    #: availability objective: 1 − (429 + 5xx) / requests
    availability_target: float = 0.999
    #: queued-token budget saturation bound (fraction of
    #: ``serving.network.queue_token_budget`` queued, worst class)
    token_budget_saturation: float = 0.9
    #: multi-window burn-rate evaluation windows (seconds) — the alert
    #: fires only when BOTH windows burn error budget faster than
    #: ``burn_rate_threshold`` (fast window confirms it is happening
    #: NOW, slow window that it is sustained)
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_rate_threshold: float = 2.0
    #: evaluation cadence (s) — each tick consumes one rollup snapshot
    evaluate_every_s: float = 1.0


class ServingAutoscalerConfig(DeepSpeedConfigModel):
    """``serving.autoscaler`` config group — the rollup-driven policy
    loop (``deepspeed_tpu/serving/autoscaler.py``): replaces dead
    workers through the launcher, scales decode workers on queue depth
    + token-budget saturation, scales prefill workers on TTFT prefill
    share, and scales down only through the kill-safe drain path.
    Every decision is a trace-id-stamped scaling event riding the
    telemetry rollup into ``cluster_trace.json`` and debug bundles."""

    enabled: bool = False
    min_workers: int = 1
    max_workers: int = 8
    #: scale decode UP past this mean queued-requests-per-worker
    queue_depth_high: float = 4.0
    #: scale decode DOWN below this (with the fleet above min_workers)
    queue_depth_low: float = 0.5
    #: scale decode UP past this outstanding-token saturation (fraction
    #: of ``serving.max_outstanding_tokens`` per worker)
    token_saturation_high: float = 0.85
    #: scale prefill UP past this fraction of TTFT spent in prefill
    #: (disaggregated fleets only)
    ttft_prefill_share_high: float = 0.6
    #: consecutive breaching evaluations before a scaling action
    hysteresis_ticks: int = 3
    #: minimum seconds between scaling actions (replacements exempt —
    #: a dead worker is replaced immediately)
    cooldown_s: float = 30.0
    evaluate_every_s: float = 1.0


class ServingConfig(DeepSpeedConfigModel):
    """``serving`` config group — the production serving plane
    (``deepspeed_tpu/serving/``): paged prefix-sharing KV cache over the
    inference-v2 block pool, an SLO-aware streaming front-end
    (submit/stream/cancel with ``interactive``/``batch``/``background``
    latency classes, admission control, preemptible decode slots), and
    multi-replica routing (prefix affinity + least outstanding tokens,
    replica health from the device-liveness latch / hang watchdog)."""

    enabled: bool = False
    #: engine replicas behind the router (each owns a full KV pool)
    replicas: int = 1
    #: share identical prompt-prefix pages across requests (the trie)
    prefix_sharing: bool = True
    #: cached (refcount-0, trie-indexed) pages kept at most; 0 = bounded
    #: only by pool pressure (LRU reclaimed by allocation)
    prefix_cache_max_blocks: int = 0
    #: per-replica admitted-but-unfinished token budget
    max_outstanding_tokens: int = 8192
    #: fraction of the allocatable pool kept clear of batch/background
    #: reservations so interactive admission never waits on pages
    interactive_reserve_frac: float = 0.10
    #: admit only interactive work when the memory ledger reports HBM
    #: headroom below this fraction (0 disables the check)
    min_hbm_headroom_frac: float = 0.0
    #: interactive may preempt background decode slots (KV retained)
    preemption: bool = True
    #: router prefix-affinity threshold (tokens)
    affinity_min_tokens: int = 16
    #: decode sampling temperature (0 = greedy; greedy makes the
    #: replica-death re-queue splice exact)
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    #: per-handle stream bound (tokens): a consumer stalled past this
    #: many unread tokens loses the oldest (drop-oldest; pump never
    #: blocks)
    stream_buffer: int = 4096
    #: interactive TTFT target (ms), exported with the serving metrics
    interactive_ttft_slo_ms: float = 500.0
    #: under the HBM-headroom floor, preemption RELEASES the victim's
    #: KV pages to the cached-free LRU tier (re-admission recomputes
    #: via the prefix trie) instead of keeping them resident
    preempt_release_pages: bool = True
    #: the network serving plane (HTTP/SSE front door,
    #: process-per-replica workers, disaggregated prefill/decode)
    network: "ServingNetworkConfig" = Field(
        default_factory=lambda: ServingNetworkConfig())
    #: distributed request tracing (per-request lifecycle records,
    #: cross-process timeline assembly)
    tracing: ServingTracingConfig = Field(
        default_factory=ServingTracingConfig)
    #: declarative SLOs with multi-window burn-rate alerting over the
    #: cross-process metrics rollup
    slo: ServingSLOConfig = Field(default_factory=ServingSLOConfig)
    #: rollup-driven fleet autoscaler (traced scaling decisions,
    #: drain-path scale-down)
    autoscaler: ServingAutoscalerConfig = Field(
        default_factory=ServingAutoscalerConfig)


class ServingNetworkConfig(DeepSpeedConfigModel):
    """``serving.network`` config group — the network serving plane
    (``deepspeed_tpu/serving/{frontdoor,worker,remote,kv_transfer}``):
    an HTTP/SSE front door over the submit/stream/cancel API,
    process-per-replica worker backends registered in the rendezvous
    store, and disaggregated prefill/decode over the page-granular
    checksum-gated KV transport."""

    enabled: bool = False
    #: front-door bind address (port 0 = ephemeral)
    host: str = "127.0.0.1"
    port: int = 0
    #: replica worker PROCESSES to launch behind the door
    workers: int = 2
    #: of the fleet, dedicated prefill replicas (with ``disaggregate``)
    prefill_workers: int = 1
    #: run the prefill -> KV-page-stream -> decode pipeline
    disaggregate: bool = False
    #: per-class queued-token budget: past it the door answers 429 +
    #: Retry-After (backpressure) instead of queueing
    queue_token_budget: int = 32768
    retry_after_s: float = 1.0
    #: SSE idle heartbeat period (also dead-client detection cadence)
    sse_heartbeat_s: float = 5.0
    #: KV-page transfer chunk size (base64 chars per protocol line)
    kv_chunk_bytes: int = 64 * 1024
    #: network front-end pump idle sleep
    poll_interval_s: float = 0.005
    #: worker health-probe (ping) timeout
    probe_timeout_s: float = 2.0
    #: ping cadence (a fresh TCP connection per endpoint per probe;
    #: transport failures mark endpoints dead instantly regardless)
    probe_every_s: float = 1.0
    rpc_timeout_s: float = 30.0
    #: rendezvous store for worker registration/discovery (None: the
    #: launcher wires endpoints directly)
    store_endpoint: Optional[str] = None
    #: front-door structured access log: one JSONL line per request
    #: (ts, method, path, status, class, trace id, duration, tokens,
    #: close reason); "" disables
    access_log: str = ""
    #: rotate the live access log past this size (one ``.1``
    #: predecessor kept)
    access_log_max_bytes: int = 8 << 20


class ResilienceConfig(DeepSpeedConfigModel):
    """``resilience`` config group — the self-healing plane
    (``deepspeed_tpu/resilience/``): tiered async snapshots of the full
    training state, an automatic recovery policy (rollback on NaN/scale
    collapse, resume-from-snapshot on restart, emergency save on
    watchdog trip), and a deterministic fault-injection harness."""

    enabled: bool = False
    #: engine-driven snapshot cadence (optimizer steps)
    snapshot_interval: int = 50
    #: tier-1 flush root (``<dir>/snap-<step>[-emergency]/``)
    snapshot_dir: str = "resilience_snapshots"
    #: newest tier-1 snapshot dirs kept on disk (double-buffered default)
    keep_snapshots: int = 2
    #: tier 0 (double-buffered in-host-memory copies) is structurally
    #: required — tiers 1/2 flush FROM it — so it has no off switch.
    #: tier 1: async background flush through the checkpoint engine,
    #: checksummed manifest gating every restore
    disk_tier: bool = True
    #: "sync" | "async" — tier-1 flush mode (async = the whole flush
    #: job runs on a background worker thread over the tier-0 host
    #: copy; only the device→host capture blocks the step path)
    flush_engine: Literal["sync", "async"] = "async"
    #: tier 2: replicate each flushed snapshot to the buddy host's store
    #: slot via the chunked rendezvous transport (needs an elastic store)
    buddy_tier: bool = False
    buddy_chunk_bytes: int = 262144
    buddy_max_bytes: int = 268435456
    #: health-event kinds that trigger an automatic rollback
    rollback_on: List[str] = Field(default_factory=lambda: [
        "nan_loss", "loss_scale_collapse"])
    #: recoveries (rollbacks + resumes) before the policy gives up
    max_recoveries: int = 3
    #: capped exponential backoff between recoveries
    backoff_base_s: float = 1.0
    backoff_max_s: float = 60.0
    #: healthy steps after which the recovery budget re-arms
    recovery_reset_steps: int = 100
    #: flush the newest tier-0 snapshot to disk when the watchdog trips
    #: (the host is responsive enough to run the listener; params may be
    #: hung on device, but the host copy is already taken)
    emergency_save_on_trip: bool = True
    #: deterministic fault specs (``kind@step[:k=v,...]``), e.g.
    #: ``kill_rank@120:rank=1``, ``nan_loss@64``, ``stall@32:seconds=90``,
    #: ``corrupt_snapshot@40``; the DS_FAULTS env var appends more
    faults: List[str] = Field(default_factory=list)


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False  # [L HF-DS:179-182]
    parallel_write: Dict[str, Any] = Field(default_factory=dict)
    writer: Optional[Dict[str, Any]] = None
    #: {"type": "sync"|"async"} — async = orbax AsyncCheckpointer (the
    #: reference's DecoupledCheckpointEngine role)
    checkpoint_engine: Dict[str, Any] = Field(default_factory=dict)


class TensorParallelConfig(DeepSpeedConfigModel):
    """``tensor_parallel`` group (AutoTP training) [L HF-DS:464]."""

    autotp_size: int = 1
    tp_overlap_comm: bool = False


class SequenceParallelConfig(DeepSpeedConfigModel):
    """TPU-native grouping of the fork's ALST/Ulysses knobs."""

    sp_size: int = 1
    seq_length_is_variable: bool = True
    attention_backend: str = "auto"  # auto|splash|dot


class PipelineConfig(DeepSpeedConfigModel):
    stages: int = 1
    partition_method: str = "parameters"
    num_micro_batches: Optional[int] = None
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    #: which schedule executes when stages > 1 (reference TrainSchedule =
    #: 1f1b; SURVEY §3.5).  "1f1b": one-forward-one-backward via
    #: parallel.pipeline.pipeline_train_1f1b (O(pp) stashed activations);
    #: "gpipe": fill/drain forward + autodiff backward; "interleaved":
    #: gpipe with virtual stages
    schedule: str = "1f1b"


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.1


class AutotuningConfig(DeepSpeedConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = False
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    max_train_batch_size: Optional[int] = None
    mp_size: int = 1


class TuningConfig(DeepSpeedConfigModel):
    """``tuning`` config group — the telemetry-driven autotuning plane
    (``deepspeed_tpu/tuning/``): offline search scored from telemetry,
    the best-known-config store keyed by (model fingerprint, mesh,
    device kind, jax version), and sentinel-gated promotion.  Distinct
    from the legacy ``autotuning`` group (the launcher-driven reference
    API shape, now a shim over this plane)."""

    enabled: bool = True
    #: consult the store at initialize() and apply the promoted entry's
    #: overrides (user-pinned knobs always win)
    auto_apply: bool = True
    #: store file ("" = $DS_TUNING_STORE, else the per-user default;
    #: the package-shipped seeded store is always the read-only
    #: fallback)
    store_path: str = ""
    #: search defaults — ``tuning.SearchEngine.from_config(runner, space,
    #: cfg.tuning)`` consumes strategy/warmup/timed/max_candidates/score
    #: and pushes hbm_margin_frac onto the memory model
    strategy: Literal["grid", "successive_halving"] = "successive_halving"
    warmup_steps: int = 1
    timed_steps: int = 3
    #: cap on candidates entering the measurement phase (0 = all)
    max_candidates: int = 0
    #: score metric for trial ranking
    score: str = "tokens_per_sec"
    #: HBM fraction the calibrated memory model keeps clear of the
    #: state estimate when pruning (activations/scratch headroom)
    hbm_margin_frac: float = 0.05


class DataEfficiencyConfig(DeepSpeedConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = Field(default_factory=dict)
    data_routing: Dict[str, Any] = Field(default_factory=dict)


class HybridEngineConfig(DeepSpeedConfigModel):
    """Reference ``hybrid_engine`` group (``runtime/hybrid_engine.py`` [K]):
    one engine flipping between ZeRO-3 training and inference generate for
    RLHF.  TP size / cache-release knobs kept for config parity; on TPU the
    flip is free (same sharded arrays serve both programs)."""

    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


class CompileConfig(DeepSpeedConfigModel):
    """torch.compile interop group — on TPU everything is compiled; kept so
    configs round-trip and so `deepcompile` flags are visible."""

    deepcompile: bool = False
    offload_activation: bool = False
    offload_opt_states: bool = False


class KernelsConfig(DeepSpeedConfigModel):
    """``kernels`` config group — the Pallas kernel plane
    (``deepspeed_tpu/ops/pallas/``): which custom kernels serve the step
    hot path, and their tuning knobs.  Every knob here is a tuning-plane
    dimension (``tuning/space.py``) so the PR-9 search picks winners per
    (model, mesh, device_kind); the defaults are the conservative
    XLA-reference paths."""

    #: route model attention (llama/bert builders honor this) through the
    #: Pallas flash kernel family instead of the XLA einsum+softmax
    flash_attention: bool = False
    #: flash kernel block sizes; 0 = the seq-length-aware table
    #: (``ops/pallas/lattice.auto_flash_blocks``)
    flash_block_q: int = 0
    flash_block_k: int = 0
    #: one-pass fused Adam over ZeRO shards (``ops/pallas/
    #: fused_optimizer.py``): moments + grad-norm + unscale/clip in two
    #: HBM passes instead of the optax chain's 3–4 sweeps.  Requires a
    #: config-built adam/adamw-family optimizer; silently kept off for
    #: offload/1-bit/1F1B paths (logged).
    fused_adam: bool = False
    #: ZeRO-3 collective–compute overlap: explicit chunked-ppermute ring
    #: all-gather/reduce-scatter (``comm/overlap.py``) instead of the
    #: monolithic GSPMD collectives that serialize against the matmuls
    #: they feed
    overlap_collectives: bool = False
    #: ring payload granularity (chunks per shard); more chunks = finer
    #: pipelining but more per-hop latency — a tuning dimension
    overlap_chunks: int = 4


class MoEConfig(DeepSpeedConfigModel):
    """``moe`` config group — the expert-parallel execution plane
    (``deepspeed_tpu/moe/``): how many ways the ``expert`` mesh axis is
    carved, how much slack the capacity budget gets, and which dispatch
    implementation moves tokens.  Capacity factor / ep degree / dispatch
    impl are tuning-plane dimensions (``tuning/space.py``); ZeRO composes
    over the flattened ``("expert", "data")`` tuple so expert-sharded
    params still shard their optimizer state over all data ranks."""

    #: expert-parallel degree: size of the ``expert`` mesh axis.  1 keeps
    #: the axis trivial (pre-PR-19 behavior); >1 requires
    #: world/(tp·pp·sp) divisible by it and is mutually exclusive with
    #: MiCS, which repurposes the expert axis as its replica axis.
    expert_parallel_size: int = 1
    #: token dispatch implementation: ``auto`` | ``dense`` | ``sparse`` |
    #: ``pallas`` (``ops/pallas/moe_dispatch.choose_dispatch_impl``)
    dispatch_impl: str = "auto"
    #: override the model's train capacity factor (0 = keep the model's)
    capacity_factor: float = 0.0
    #: pad expert capacity up to the next multiple of the expert axis so
    #: expert-axis sharding constraints never silently drop
    pad_capacity_to_ep: bool = True
    #: random-token-selection under capacity pressure (reference use_rts);
    #: active only when a gating rng is threaded through the step
    use_rts: bool = False

    @model_validator(mode="after")
    def _check(self):
        if self.expert_parallel_size < 1:
            raise ValueError("moe.expert_parallel_size must be >= 1")
        if self.dispatch_impl not in ("auto", "dense", "sparse", "pallas"):
            raise ValueError(
                f"moe.dispatch_impl {self.dispatch_impl!r} not in "
                "auto|dense|sparse|pallas")
        return self


# ---------------------------------------------------------------------------
# top-level
# ---------------------------------------------------------------------------


def _load_config_payload(config: Union[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Accept dict, JSON file path, or base64-encoded JSON [L ACC-DS:145-156]."""
    if isinstance(config, dict):
        return dict(config)
    if isinstance(config, (str, os.PathLike)):
        path = os.fspath(config)
        if os.path.exists(path):
            with open(path) as fh:
                return json.load(fh)
        try:
            return json.loads(base64.urlsafe_b64decode(path).decode())
        except Exception:
            try:
                return json.loads(path)
            except Exception:
                raise ValueError(
                    f"Expected a dict, JSON file path, JSON string, or base64 "
                    f"payload; got {path!r} (file does not exist)")
    raise TypeError(f"unsupported config type {type(config)}")


class DeepSpeedConfig(DeepSpeedConfigModel):
    """The validated top-level config (reference class of the same name)."""

    train_batch_size: Union[int, str, None] = None
    train_micro_batch_size_per_gpu: Union[int, str, None] = None
    gradient_accumulation_steps: Union[int, str, None] = None
    steps_per_print: Union[int, float] = 10
    wall_clock_breakdown: bool = False
    dump_state: bool = False
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    gradient_clipping: Union[float, str] = 0.0
    memory_breakdown: bool = False
    disable_allgather: bool = False
    sparse_gradients: bool = False
    zero_allow_untested_optimizer: bool = False  # [L HF-DS:392]
    zero_force_ds_cpu_optimizer: bool = True  # [L ACC:2365-2367]
    seed: int = 1234

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    amp: AMPConfig = Field(default_factory=AMPConfig)
    zero_optimization: DeepSpeedZeroConfig = Field(default_factory=DeepSpeedZeroConfig)
    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig)
    aio: AioConfig = Field(default_factory=AioConfig)
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
    telemetry: TelemetryConfig = Field(default_factory=TelemetryConfig)
    serving: ServingConfig = Field(default_factory=ServingConfig)
    resilience: ResilienceConfig = Field(default_factory=ResilienceConfig)
    checkpoint: CheckpointConfig = Field(default_factory=CheckpointConfig)
    tensor_parallel: TensorParallelConfig = Field(default_factory=TensorParallelConfig)
    sequence_parallel: SequenceParallelConfig = Field(
        default_factory=SequenceParallelConfig)
    pipeline: PipelineConfig = Field(default_factory=PipelineConfig)
    elasticity: ElasticityConfig = Field(default_factory=ElasticityConfig)
    autotuning: AutotuningConfig = Field(default_factory=AutotuningConfig)
    tuning: TuningConfig = Field(default_factory=TuningConfig)
    data_efficiency: DataEfficiencyConfig = Field(default_factory=DataEfficiencyConfig)
    hybrid_engine: HybridEngineConfig = Field(default_factory=HybridEngineConfig)
    compile: CompileConfig = Field(default_factory=CompileConfig)
    kernels: KernelsConfig = Field(default_factory=KernelsConfig)
    moe: MoEConfig = Field(default_factory=MoEConfig)
    compression_training: Dict[str, Any] = Field(default_factory=dict)
    curriculum_learning: Dict[str, Any] = Field(default_factory=dict)

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    @classmethod
    def from_dict_or_path(cls, config: Union[str, Dict[str, Any]],
                          world_size: Optional[int] = None,
                          tp: int = 1, pp: int = 1, sp: int = 1) -> "DeepSpeedConfig":
        payload = _load_config_payload(config)
        cfg = cls.model_validate(payload)
        cfg.resolve_batch_sizes(world_size=world_size, tp=tp, pp=pp, sp=sp)
        return cfg

    # ------------------------------------------------------------------
    # batch math — the reference invariant
    # ------------------------------------------------------------------

    def resolve_batch_sizes(self, world_size: Optional[int] = None,
                            tp: int = 1, pp: int = 1, sp: int = 1) -> None:
        """Given any subset of (train_batch, micro_batch, grad_accum), infer
        the rest and validate  train = micro × gas × dp_world.
        """
        if world_size is None:
            import jax

            world_size = jax.device_count()
        denom = tp * pp * sp
        if world_size % denom:
            raise ValueError(f"world_size={world_size} not divisible by "
                             f"tp*pp*sp={denom}")
        dp_world = world_size // denom

        tb = None if is_auto(self.train_batch_size) else self.train_batch_size
        mb = (None if is_auto(self.train_micro_batch_size_per_gpu)
              else self.train_micro_batch_size_per_gpu)
        gas = (None if is_auto(self.gradient_accumulation_steps)
               else self.gradient_accumulation_steps)

        if tb is not None and mb is not None and gas is None:
            if tb % (mb * dp_world):
                raise ValueError(
                    f"train_batch_size={tb} not divisible by micro_batch×dp "
                    f"({mb}×{dp_world})")
            gas = tb // (mb * dp_world)
        elif tb is not None and gas is not None and mb is None:
            if tb % (gas * dp_world):
                raise ValueError(
                    f"train_batch_size={tb} not divisible by grad_accum×dp "
                    f"({gas}×{dp_world})")
            mb = tb // (gas * dp_world)
        elif mb is not None:
            gas = gas or 1
            tb = tb or mb * gas * dp_world
        elif tb is not None:
            gas = 1
            if tb % dp_world:
                raise ValueError(f"train_batch_size={tb} not divisible by "
                                 f"dp_world={dp_world}")
            mb = tb // dp_world
        else:
            tb, mb, gas = dp_world, 1, 1  # reference default micro=1,gas=1

        if tb != mb * gas * dp_world:
            raise ValueError(
                f"Batch invariant violated: train_batch_size={tb} != "
                f"micro={mb} × grad_accum={gas} × dp_world={dp_world}. "
                f"(world={world_size}, tp={tp}, pp={pp}, sp={sp})")

        self.train_batch_size = tb
        self.train_micro_batch_size_per_gpu = mb
        self.gradient_accumulation_steps = gas

    # ------------------------------------------------------------------
    # convenience
    # ------------------------------------------------------------------

    def dtype(self):
        """Precedence: bf16 > fp16 > fp32 (TPU-first: bf16 needs no scaler)."""
        import jax.numpy as jnp

        if self.bf16.enabled is True:
            return jnp.bfloat16
        if self.fp16.enabled is True:
            return jnp.float16
        return jnp.float32

    def resolve_auto_precision(self, default: str = "bf16") -> None:
        if is_auto(self.bf16.enabled):
            self.bf16.enabled = default == "bf16"
        if is_auto(self.fp16.enabled):
            self.fp16.enabled = default == "fp16"
        if is_auto(self.amp.enabled):
            self.amp.enabled = False

    def print_config(self) -> None:
        logger.info(json.dumps(self.model_dump(mode="json"), indent=2, default=str))
