"""Pluggable checkpoint engines — sync + decoupled (async) backends.

Reference: ``deepspeed/runtime/checkpoint_engine/`` [K] (SURVEY §2.1 row
"Checkpoint engines"): ``TorchCheckpointEngine`` (synchronous
``torch.save``), ``DecoupledCheckpointEngine`` (background async save),
``NebulaCheckpointEngine`` (MSFT service — documented out of scope).

TPU-first: orbax already implements the hard part — ``AsyncCheckpointer``
blocks only for the device→host copy, then serializes to storage on a
background thread, which is donation-safe (the next ``train_step`` can
invalidate the device buffers; the host copy is already taken).  The
engine classes here supply the reference's lifecycle surface
(create/save/load/commit/wait) around the two orbax modes.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..utils.logging import log_dist, logger
from ..utils.jax_compat import ckpt_metadata_tree

#: sidecar integrity manifest written next to every saved checkpoint tree
SIDECAR_MANIFEST = "ds_manifest.json"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity validation (truncated / corrupt /
    missing files).  The message names the first offending file — the
    resilience tier-fallback catches this and tries the next snapshot
    instead of restoring garbage."""


def _iter_payload_files(path: str):
    """Every regular file under ``path`` except the sidecar itself,
    as (relative_name, absolute_path), deterministic order."""
    for root, _dirs, files in os.walk(path):
        for f in sorted(files):
            rel = os.path.relpath(os.path.join(root, f), path)
            if rel in (SIDECAR_MANIFEST, SIDECAR_MANIFEST + ".tmp"):
                continue
            yield rel, os.path.join(root, f)


def _sha256_file(p: str) -> str:
    h = hashlib.sha256()
    with open(p, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _is_write_coordinator() -> bool:
    """Multi-controller: exactly ONE process may stamp the sidecar —
    N processes writing (and hashing files mid-finalize on other hosts)
    over one shared tree would race each other into a manifest that
    matches nobody.  Orbax's save barrier has completed by the time the
    engines call this, so process 0 sees the finished tree."""
    try:
        return jax.process_index() == 0
    except Exception:
        return True  # single-controller / distributed not initialized


def write_sidecar_manifest(path: str) -> Dict[str, Any]:
    """Stamp ``<path>/ds_manifest.json`` with per-file size + sha256 of
    everything the serializer wrote.  Called AFTER the write is complete
    (sync: right after save; async: after wait_until_finished) and
    BEFORE any durability marker, so a manifest's existence implies the
    payload it describes was fully on disk at stamp time."""
    files = {rel: {"bytes": os.path.getsize(p), "sha256": _sha256_file(p)}
             for rel, p in _iter_payload_files(path)}
    manifest = {"version": 1, "files": files}
    tmp = os.path.join(path, SIDECAR_MANIFEST + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1)
    os.replace(tmp, os.path.join(path, SIDECAR_MANIFEST))  # atomic
    return manifest


def verify_sidecar_manifest(path: str, strict: bool = False,
                            deep: Optional[bool] = None) -> bool:
    """Validate ``path`` against its sidecar manifest.

    Returns True when a sidecar exists and every file matches.  Without
    a sidecar: False when ``strict`` (resilience snapshots REQUIRE the
    manifest — a missing one means the flush never committed), else
    True (legacy checkpoints predate the sidecar).  Raises
    :class:`CheckpointCorruptionError` naming the first mismatch.

    ``deep`` (default: same as ``strict``) controls whether file
    CONTENTS are re-hashed.  The shallow pass (existence + size) is one
    ``stat`` per file and catches torn/truncated trees; the deep pass
    re-reads everything — right for the resilience checksum gate, too
    expensive to impose on every ordinary multi-GB checkpoint load.
    """
    deep = strict if deep is None else deep
    mp = os.path.join(path, SIDECAR_MANIFEST)
    if not os.path.isdir(path):
        raise CheckpointCorruptionError(
            f"checkpoint {path!r} does not exist or is not a directory")
    if not os.path.exists(mp):
        if strict:
            raise CheckpointCorruptionError(
                f"checkpoint {path!r} has no {SIDECAR_MANIFEST} sidecar — "
                f"the save never completed (or predates integrity "
                f"manifests)")
        return True
    try:
        with open(mp) as fh:
            manifest = json.load(fh)
        files = manifest["files"]
    except (OSError, ValueError, KeyError) as e:
        raise CheckpointCorruptionError(
            f"checkpoint {path!r}: unreadable sidecar manifest "
            f"{SIDECAR_MANIFEST} ({e!r})") from e
    for rel, meta in sorted(files.items()):
        p = os.path.join(path, rel)
        if not os.path.exists(p):
            raise CheckpointCorruptionError(
                f"checkpoint {path!r}: file {rel!r} listed in the "
                f"manifest is missing (torn/partial checkpoint)")
        size = os.path.getsize(p)
        if size != int(meta["bytes"]):
            raise CheckpointCorruptionError(
                f"checkpoint {path!r}: file {rel!r} is {size} bytes, "
                f"manifest says {meta['bytes']} (truncated write)")
        if deep and _sha256_file(p) != meta["sha256"]:
            raise CheckpointCorruptionError(
                f"checkpoint {path!r}: file {rel!r} fails its sha256 "
                f"checksum (bit-rot or partial overwrite)")
    return True


class CheckpointEngine:
    """Reference base-class surface."""

    def __init__(self, config_params: Any = None):
        self.config_params = config_params

    def create(self, tag: str) -> None:  # bookkeeping hook
        pass

    def save(self, state_tree: Any, path: str,
             commit_fn: Optional[Any] = None) -> None:
        """``commit_fn()`` runs only once the write is DURABLE — the sync
        engine calls it immediately, the async engine defers it to
        wait()/commit() so durability markers (the ``latest`` file) never
        name a checkpoint that is still being written."""
        raise NotImplementedError

    def load(self, path: str, target: Any = None,
             map_location: Any = None) -> Any:
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        """Reference semantics: returns True once the tag is durable."""
        return True

    def wait(self) -> None:
        pass


def _charge_checkpoint_goodput(seconds: float) -> None:
    """Feed blocking checkpoint time into the goodput ledger
    (telemetry/perf) — MAIN-thread saves only: a background flush
    (async snapshot worker, watchdog emergency writer) overlaps the
    step loop and charging it would double-count wall time."""
    try:
        if threading.current_thread() is not threading.main_thread():
            return
        from ..telemetry.perf import get_goodput_ledger

        get_goodput_ledger().add("checkpoint", max(seconds, 0.0))
    except Exception as e:  # accounting is optional; the save is not
        from ..utils.logging import debug_once

        debug_once("checkpoint/goodput",
                   f"checkpoint goodput charge failed ({e!r})")


class TorchCheckpointEngine(CheckpointEngine):
    """Synchronous save (reference name kept for config parity; the
    serialization is orbax, not torch)."""

    def save(self, state_tree: Any, path: str,
             commit_fn: Optional[Any] = None) -> None:
        t0 = time.perf_counter()
        with ocp.StandardCheckpointer() as saver:
            saver.save(path, state_tree, force=True)
        # integrity sidecar BEFORE the durability marker: a manifest's
        # existence implies the payload it hashes was fully written.
        # Process 0 only — the tree is shared, the stamp must not race
        if _is_write_coordinator():
            write_sidecar_manifest(path)
        if commit_fn is not None:
            commit_fn()
        _charge_checkpoint_goodput(time.perf_counter() - t0)

    def load(self, path: str, target: Any = None,
             map_location: Any = None) -> Any:
        # integrity-gate the read: a truncated/torn file raises a
        # DESCRIPTIVE CheckpointCorruptionError here instead of orbax
        # deserializing garbage.  Shallow (stat-only) by design — the
        # resilience restore path layers the deep sha256 pass on top
        # (verify strict=True); ordinary checkpoint loads must not pay
        # a full re-read of a multi-GB tree
        verify_sidecar_manifest(path)
        with ocp.StandardCheckpointer() as loader:
            if target is None:
                meta = ckpt_metadata_tree(loader, path)
                target = jax.tree.map(
                    lambda am: jax.ShapeDtypeStruct(tuple(am.shape),
                                                    am.dtype), meta)
            try:
                return loader.restore(path, target)
            except CheckpointCorruptionError:
                raise
            except Exception as e:
                # orbax's failure on a torn tree is opaque — but only
                # claim corruption when the bytes actually fail a DEEP
                # verify; a clean-hashing tree means the failure is
                # structural (wrong target/shape/dtype) and must surface
                # as the programming error it is, not get silently
                # discarded by the resilience tier fallback
                try:
                    verify_sidecar_manifest(path, deep=True)
                except CheckpointCorruptionError as ce:
                    raise CheckpointCorruptionError(
                        f"checkpoint {path!r} failed to restore "
                        f"({type(e).__name__}: {e}); integrity check "
                        f"agrees: {ce}") from e
                raise


#: process-wide in-flight async saves, keyed by absolute path.  A READER
#: must never race a background writer — even one owned by a different
#: engine instance (a fresh engine loading the tag another engine is
#: still flushing).  Relying on GC to __del__-join the writer is a race.
#: Values are WEAK references: an engine abandoned mid-save still joins
#: through its __del__ (pre-existing behavior); the registry must not
#: pin it — and its checkpointer — for the process lifetime.
_inflight_lock = threading.Lock()
_inflight: Dict[str, Any] = {}  # path -> weakref to the engine


def join_inflight_save(path: str) -> None:
    """Join ANY engine's in-flight async save of ``path`` or a tree
    above/below it.  Called by every load path before reading."""
    path = os.path.abspath(path)
    with _inflight_lock:
        engines = set()
        for p in list(_inflight):
            if (p == path or p.startswith(path + os.sep)
                    or path.startswith(p + os.sep)):
                eng = _inflight[p]()
                if eng is None:
                    _inflight.pop(p, None)  # collected; __del__ joined it
                else:
                    engines.add(eng)
    for eng in engines:
        eng.wait()


class DecoupledCheckpointEngine(CheckpointEngine):
    """Async save: returns after the device→host snapshot; storage writes
    happen on orbax's background thread.  ``wait()``/``commit()`` join the
    in-flight save (the engine calls ``wait`` before the next save and on
    teardown, so at most one save is in flight — reference decoupled
    engine's queue-depth-1 behavior)."""

    def __init__(self, config_params: Any = None):
        super().__init__(config_params)
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        self._pending: Optional[str] = None
        self._pending_commit: Optional[Any] = None

    def save(self, state_tree: Any, path: str,
             commit_fn: Optional[Any] = None) -> None:
        t0 = time.perf_counter()
        self.wait()
        self._ckptr.save(path, args=ocp.args.StandardSave(state_tree),
                         force=True)
        # only the BLOCKING part (join previous + device→host snapshot)
        # counts as checkpoint time; the storage write overlaps training
        _charge_checkpoint_goodput(time.perf_counter() - t0)
        self._pending = path
        self._pending_commit = commit_fn
        import weakref

        with _inflight_lock:
            _inflight[os.path.abspath(path)] = weakref.ref(self)
        log_dist(f"async checkpoint save started: {path}")

    def load(self, path: str, target: Any = None,
             map_location: Any = None) -> Any:
        self.wait()                # our own in-flight write
        join_inflight_save(path)   # ...and any OTHER engine's
        return TorchCheckpointEngine().load(path, target)

    def commit(self, tag: str) -> bool:
        self.wait()
        return True

    def wait(self) -> None:
        if self._pending is not None:
            self._ckptr.wait_until_finished()
            pending, self._pending = self._pending, None
            with _inflight_lock:
                ref = _inflight.get(os.path.abspath(pending))
                if ref is not None and ref() in (self, None):
                    _inflight.pop(os.path.abspath(pending), None)
            try:
                # the background writer just finished: hash what it wrote
                # before the commit marker can name it (process 0 only)
                if _is_write_coordinator():
                    write_sidecar_manifest(pending)
            except OSError as e:
                logger.warning(f"async checkpoint: sidecar manifest for "
                               f"{pending} failed ({e!r})")
            if self._pending_commit is not None:
                commit, self._pending_commit = self._pending_commit, None
                commit()

    def __del__(self):
        try:
            self.wait()
            self._ckptr.close()
        except Exception as e:  # interpreter teardown
            from ..utils.logging import debug_once

            debug_once("checkpoint/del",
                       f"async checkpointer close in __del__ failed "
                       f"({e!r}); a background save may be truncated "
                       f"(the manifest gate will refuse it on load)")


def make_checkpoint_engine(config) -> CheckpointEngine:
    """Select the backend from ``checkpoint.checkpoint_engine`` config
    (``{"type": "sync"|"async"}``; reference selects decoupled/nebula the
    same way)."""
    ce = getattr(config.checkpoint, "checkpoint_engine", None) or {}
    kind = str(ce.get("type", "sync")).lower()
    if kind in ("async", "decoupled"):
        return DecoupledCheckpointEngine(ce)
    return TorchCheckpointEngine(ce)
