"""Pluggable checkpoint engines — sync + decoupled (async) backends.

Reference: ``deepspeed/runtime/checkpoint_engine/`` [K] (SURVEY §2.1 row
"Checkpoint engines"): ``TorchCheckpointEngine`` (synchronous
``torch.save``), ``DecoupledCheckpointEngine`` (background async save),
``NebulaCheckpointEngine`` (MSFT service — documented out of scope).

TPU-first: orbax already implements the hard part — ``AsyncCheckpointer``
blocks only for the device→host copy, then serializes to storage on a
background thread, which is donation-safe (the next ``train_step`` can
invalidate the device buffers; the host copy is already taken).  The
engine classes here supply the reference's lifecycle surface
(create/save/load/commit/wait) around the two orbax modes.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..utils.logging import log_dist
from ..utils.jax_compat import ckpt_metadata_tree


class CheckpointEngine:
    """Reference base-class surface."""

    def __init__(self, config_params: Any = None):
        self.config_params = config_params

    def create(self, tag: str) -> None:  # bookkeeping hook
        pass

    def save(self, state_tree: Any, path: str,
             commit_fn: Optional[Any] = None) -> None:
        """``commit_fn()`` runs only once the write is DURABLE — the sync
        engine calls it immediately, the async engine defers it to
        wait()/commit() so durability markers (the ``latest`` file) never
        name a checkpoint that is still being written."""
        raise NotImplementedError

    def load(self, path: str, target: Any = None,
             map_location: Any = None) -> Any:
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        """Reference semantics: returns True once the tag is durable."""
        return True

    def wait(self) -> None:
        pass


class TorchCheckpointEngine(CheckpointEngine):
    """Synchronous save (reference name kept for config parity; the
    serialization is orbax, not torch)."""

    def save(self, state_tree: Any, path: str,
             commit_fn: Optional[Any] = None) -> None:
        with ocp.StandardCheckpointer() as saver:
            saver.save(path, state_tree, force=True)
        if commit_fn is not None:
            commit_fn()

    def load(self, path: str, target: Any = None,
             map_location: Any = None) -> Any:
        with ocp.StandardCheckpointer() as loader:
            if target is None:
                meta = ckpt_metadata_tree(loader, path)
                target = jax.tree.map(
                    lambda am: jax.ShapeDtypeStruct(tuple(am.shape),
                                                    am.dtype), meta)
            return loader.restore(path, target)


class DecoupledCheckpointEngine(CheckpointEngine):
    """Async save: returns after the device→host snapshot; storage writes
    happen on orbax's background thread.  ``wait()``/``commit()`` join the
    in-flight save (the engine calls ``wait`` before the next save and on
    teardown, so at most one save is in flight — reference decoupled
    engine's queue-depth-1 behavior)."""

    def __init__(self, config_params: Any = None):
        super().__init__(config_params)
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        self._pending: Optional[str] = None
        self._pending_commit: Optional[Any] = None

    def save(self, state_tree: Any, path: str,
             commit_fn: Optional[Any] = None) -> None:
        self.wait()
        self._ckptr.save(path, args=ocp.args.StandardSave(state_tree),
                         force=True)
        self._pending = path
        self._pending_commit = commit_fn
        log_dist(f"async checkpoint save started: {path}")

    def load(self, path: str, target: Any = None,
             map_location: Any = None) -> Any:
        self.wait()  # never read a tag that is still being written
        return TorchCheckpointEngine().load(path, target)

    def commit(self, tag: str) -> bool:
        self.wait()
        return True

    def wait(self) -> None:
        if self._pending is not None:
            self._ckptr.wait_until_finished()
            self._pending = None
            if self._pending_commit is not None:
                commit, self._pending_commit = self._pending_commit, None
                commit()

    def __del__(self):
        try:
            self.wait()
            self._ckptr.close()
        except Exception:
            pass


def make_checkpoint_engine(config) -> CheckpointEngine:
    """Select the backend from ``checkpoint.checkpoint_engine`` config
    (``{"type": "sync"|"async"}``; reference selects decoupled/nebula the
    same way)."""
    ce = getattr(config.checkpoint, "checkpoint_engine", None) or {}
    kind = str(ce.get("type", "sync")).lower()
    if kind in ("async", "decoupled"):
        return DecoupledCheckpointEngine(ce)
    return TorchCheckpointEngine(ce)
