"""Config-model base machinery.

Capability parity with the reference ``deepspeed/runtime/config_utils.py`` [K]:
``DeepSpeedConfigModel`` — a pydantic base that (a) tolerates unknown keys,
(b) supports deprecated-field aliasing with warnings, and (c) understands the
``"auto"`` placeholder convention (every key may be the literal string
``"auto"``, resolved late — part of the public contract, SURVEY §5.6
[L HF-DS:105-131]).
"""

from __future__ import annotations

from typing import Any, Dict, TypeVar, Union

from pydantic import BaseModel, ConfigDict, model_validator

from ..utils.logging import logger

AUTO = "auto"

T = TypeVar("T")
#: Field type for keys that accept the "auto" placeholder.
AutoOr = Union  # use as AutoOr[Literal["auto"], int] — kept for readability


def is_auto(value: Any) -> bool:
    return isinstance(value, str) and value == AUTO


class DeepSpeedConfigModel(BaseModel):
    """Base for every subsystem config.

    ``deprecated_aliases`` on a subclass maps old key → new key; old keys are
    accepted with a warning (the reference's deprecated-field machinery).
    """

    model_config = ConfigDict(extra="allow", populate_by_name=True,
                              validate_assignment=True)

    #: old-name → new-name mapping, overridden by subclasses.
    deprecated_aliases: Dict[str, str] = {}

    @model_validator(mode="before")
    @classmethod
    def _apply_deprecated_aliases(cls, data: Any) -> Any:
        if not isinstance(data, dict):
            return data
        aliases = {}
        # class-var default, possibly overridden
        default = cls.model_fields.get("deprecated_aliases")
        if default is not None and default.default:
            aliases = default.default
        for old, new in aliases.items():
            if old in data:
                logger.warning(
                    f"{cls.__name__}: config key '{old}' is deprecated, use '{new}'")
                data.setdefault(new, data.pop(old))
        return data

    def resolve_auto(self, **resolved: Any) -> None:
        """Replace ``"auto"`` fields with supplied values (late resolution)."""
        for key, value in resolved.items():
            if hasattr(self, key) and is_auto(getattr(self, key)):
                setattr(self, key, value)


def get_scalar_param(config_dict: Dict[str, Any], name: str, default: Any) -> Any:
    """Reference helper name: fetch a top-level scalar with default."""
    return config_dict.get(name, default)
