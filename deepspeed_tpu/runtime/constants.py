"""Config key names + defaults.

Capability parity with the reference ``deepspeed/runtime/constants.py`` [K].
Only the names that form the public ds_config contract are spelled out; the
pydantic models in ``config.py`` are the source of truth for defaults.
"""

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_CLIPPING = "gradient_clipping"
STEPS_PER_PRINT = "steps_per_print"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"

OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"
FP16 = "fp16"
BF16 = "bf16"
AMP = "amp"
ZERO_OPTIMIZATION = "zero_optimization"

# Optimizer type names accepted by config["optimizer"]["type"] (case-insens.).
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
LION_OPTIMIZER = "lion"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
MUON_OPTIMIZER = "muon"

DEFAULT_LOSS_SCALE_POWER = 16
PIPE_REPLICATED = "ds_pipe_replicated"

ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
