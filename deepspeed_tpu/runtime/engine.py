"""DeepSpeedEngine — the training engine as ONE compiled XLA program per step.

Capability parity with the reference ``deepspeed/runtime/engine.py`` [K]
(~4k LoC): config-driven optimizer/ZeRO/precision assembly, gradient
accumulation, loss scaling + overflow skip, gradient clipping, LR scheduling,
throughput/monitor logging, and the public train-loop contract
``engine.backward(loss)`` / ``engine.step()`` /
``set_gradient_accumulation_boundary`` [L ACC-DS:264-281].

TPU-first architecture (SURVEY §7): instead of an eager module wrapper with
hooks, the engine compiles the whole optimizer step — microbatch scan (grad
accumulation), fp32 accumulation, overflow check, clip, optax update, ZeRO
sharding constraints — into a single ``jit`` with donated state.  GSPMD
inserts every collective the reference issues by hand (psum for DP, reduce-
scatter for stage 2, all-gather for stage 3).  The eager
``backward()``/``step()`` surface is a thin compat shim that buffers
microbatches and fires the compiled step at the accumulation boundary —
mandatory because separate host-side backward/step calls would break XLA
fusion.
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec

from ..comm import comm as dist
from ..parallel.mesh import DP_AXES, MeshLayout
from ..utils import groups as groups_mod
from ..utils.logging import log_dist, logger
from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from .config import DeepSpeedConfig
from .lr_schedules import LRScheduler, Schedule, get_lr_schedule
from .optimizers import build_optimizer
from .precision import (DynamicLossScaler, LossScaleState, cast_tree,
                        clip_grads_by_global_norm, global_grad_norm,
                        has_overflow)
from .zero.sharder import ZeroShardingPolicy
from ..utils.jax_compat import shard_map as _shard_map
from ..telemetry import numerics

LossFn = Callable[[Any, Any], jnp.ndarray]  # (params, batch) -> scalar loss


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray  # i32 — optimizer steps taken (skips excluded)
    loss_scale: LossScaleState
    skipped_steps: jnp.ndarray  # i32
    # per-worker communication state: 1-bit error-feedback residuals
    # (leading dim = DP world, sharded over the DP axes); () when unused
    comm_state: Any = ()


class DeepSpeedEngine:
    """One engine = (loss_fn, params, config) compiled over the active mesh."""

    def __init__(self,
                 loss_fn: LossFn,
                 params: Any,
                 config: DeepSpeedConfig,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 lr_schedule: Optional[Schedule] = None,
                 module: Any = None,
                 mesh=None):
        self.config = config
        self.loss_fn = loss_fn
        self.module = module
        self.mesh = mesh if mesh is not None else groups_mod.get_mesh()
        self.policy = ZeroShardingPolicy.from_config(self.mesh,
                                                     config.zero_optimization)
        # Model-provided TP/SP placement (reference analogue: AutoTP policy);
        # ZeRO DP sharding is composed on top by the policy.
        self.base_specs = (module.param_specs()
                          if callable(getattr(module, "param_specs", None))
                          else None)
        from ..parallel.mesh import AXIS_TENSOR

        if (self.base_specs is None
                and int(self.mesh.shape.get(AXIS_TENSOR, 1)) > 1):
            # AutoTP fallback: models without hand-authored specs get
            # name-pattern-inferred tensor placement (reference AutoTP for
            # arbitrary modules); GSPMD keeps any inference correct
            from .tensor_parallel import infer_tp_specs

            self.base_specs = infer_tp_specs(params)
            log_dist("AutoTP: inferred tensor-parallel specs from param "
                     "names (model provides no param_specs)")
        from .zero.config import OffloadDeviceEnum

        self.offload_enabled = (config.zero_optimization.offload_optimizer_device()
                                != OffloadDeviceEnum.none)
        if self.offload_enabled and optimizer is not None:
            # reference behavior [L ACC:2365-2367]: offload requires the DS
            # CPU optimizer unless zero_force_ds_cpu_optimizer is disabled
            if config.zero_force_ds_cpu_optimizer:
                raise ValueError(
                    "a client optimizer cannot be combined with "
                    "offload_optimizer; remove it or set "
                    "zero_force_ds_cpu_optimizer: false to acknowledge the "
                    "config-derived CPU optimizer will be used instead")
            logger.warning("offload_optimizer active: ignoring the client "
                           "optimizer, using the config-derived CPU optimizer")
            optimizer = None
        self.offload_opt = None  # built after state init (needs placed params)
        self.infinity = None     # ZeRO-Infinity layer-streaming executor
        self._infinity_requested = (
            config.zero_optimization.offload_param_device()
            != OffloadDeviceEnum.none)
        if self._infinity_requested:
            streamable = all(
                callable(getattr(module, m, None))
                for m in ("embed_fwd", "decoder_layer", "head_loss",
                          "batch_labels"))
            if not streamable:
                raise ValueError(
                    "offload_param requires a layer-streamable module "
                    "(embed_fwd/decoder_layer/head_loss protocol — see "
                    "runtime/swap_tensor/infinity_engine.py); "
                    f"{type(module).__name__} does not implement it")
            eff_mesh = mesh if mesh is not None else groups_mod.get_mesh()
            world = int(np.prod(list(eff_mesh.shape.values())))
            if world > 1 and getattr(module, "mesh", None) is None:
                raise ValueError(
                    "ZeRO-Infinity layer streaming on a multi-device mesh "
                    "requires the module to be built WITH that mesh (its "
                    "per-layer programs carry the sharding constraints); "
                    "pass mesh= to the model constructor")
            if int(eff_mesh.shape.get("pipe", 1)) > 1:
                raise NotImplementedError(
                    "layer streaming is itself layer-sequential; combine it "
                    "with dp/tp/sp axes, not pipe")

        self.compute_dtype = config.dtype()
        self.fp16_enabled = config.fp16.enabled is True
        self.bf16_enabled = config.bf16.enabled is True

        # --- pipeline schedule routing (reference TrainSchedule = 1F1B) --
        from ..parallel.mesh import AXIS_PIPE

        pp = int(self.mesh.shape.get(AXIS_PIPE, 1))
        self._pp_1f1b = (
            pp > 1
            and str(config.pipeline.schedule).lower() == "1f1b"
            and isinstance(params, dict) and "layers" in params
            and all(callable(getattr(module, m, None))
                    for m in ("embed_fwd", "decoder_layer", "head_loss",
                              "batch_labels")))
        self.last_pipe_stats = None  # set at trace time by _pp_1f1b_grads
        from ..parallel.mesh import AXIS_TENSOR as _AT

        fallback_reason = None
        compressed_comm = (
            config.zero_optimization.zero_quantized_gradients
            or config.zero_optimization.zero_quantized_weights
            or (config.optimizer is not None
                and "onebit" in config.optimizer.type.lower().replace("-",
                                                                      "")))
        self._pp_1f1b_manual_tp = False
        tp = int(self.mesh.shape.get(_AT, 1))
        if self._pp_1f1b and tp > 1:
            # XLA's SPMD partitioner CHECK-fails on the 1F1B partial-manual
            # shard_map combined with tensor-axis GSPMD constraints inside
            # (spmd_partitioner_util.cc partition-group mismatch, verified
            # on jax 0.9 CPU).  The workaround manualizes the TENSOR axis
            # too: the model supplies a Megatron column/row layer with
            # explicit collectives (decoder_layer_manual_tp), leaving no
            # tensor constraint inside the region.  Models without that
            # hook (or with a seq axis, whose constraints would hit the
            # same CHECK) fall back to GPipe-through-autodiff, which
            # partitions fine and computes identical gradients at a larger
            # activation footprint.
            from ..parallel.mesh import AXIS_SEQ as _AS

            cfg_m = getattr(module, "config", None)
            shards_ok = (
                cfg_m is not None
                and getattr(cfg_m, "num_heads", 0) > 0
                and getattr(cfg_m, "num_heads", 0) % tp == 0
                and getattr(cfg_m, "num_kv_heads", 0) > 0
                and getattr(cfg_m, "num_kv_heads", 0) % tp == 0
                and getattr(cfg_m, "intermediate_size", 0) > 0
                and getattr(cfg_m, "intermediate_size", 0) % tp == 0)
            if (callable(getattr(module, "decoder_layer_manual_tp", None))
                    and int(self.mesh.shape.get(_AS, 1)) == 1
                    and shards_ok):
                self._pp_1f1b_manual_tp = True
            else:
                fallback_reason = ("+ tensor parallelism trips an XLA "
                                   "partitioner limitation (and this "
                                   "module has no manual-TP layer hook)")
        if fallback_reason is None and self._pp_1f1b and compressed_comm:
            fallback_reason = ("does not compose with compressed-comm "
                              "paths (1-bit/qwZ/qgZ)")
        if fallback_reason is not None:
            log_dist(f"pipeline.schedule=1f1b {fallback_reason} — falling "
                     f"back to the GPipe (autodiff) schedule")
            self._pp_1f1b = False
        elif (pp > 1 and not self._pp_1f1b
              and str(config.pipeline.schedule).lower() == "1f1b"):
            log_dist("pipeline.schedule=1f1b needs the layer-streamable "
                     "module protocol (embed_fwd/decoder_layer/head_loss) "
                     "— running the module's own pipeline path instead")
        gas = config.gradient_accumulation_steps
        self.gradient_accumulation_steps = int(gas) if isinstance(gas, int) else 1
        self.micro_batch_size = config.train_micro_batch_size_per_gpu
        self.train_batch_size = config.train_batch_size

        # --- LR schedule -------------------------------------------------
        if lr_schedule is not None:
            self._schedule = lr_schedule
        elif config.scheduler is not None:
            params_d = dict(config.scheduler.params.model_dump())
            params_d.update(config.scheduler.params.model_extra or {})
            self._schedule = get_lr_schedule(config.scheduler.type, params_d)
        else:
            base_lr = 1e-3
            if config.optimizer is not None and not isinstance(
                    config.optimizer.params.lr, str):
                base_lr = float(config.optimizer.params.lr)
            self._schedule = lambda step: base_lr
        self.lr_scheduler = LRScheduler(self._schedule)

        # --- 1-bit compressed-gradient family (reference fp16/onebit [K]) -
        opt_name = (config.optimizer.type.lower().replace("_", "")
                    if config.optimizer is not None else "")
        self.onebit_enabled = opt_name in ("onebitadam", "onebitlamb",
                                           "zerooneadam")
        self.onebit_freeze_step = 0
        if self.onebit_enabled:
            # reference OnebitAdam `freeze_step` [K]: full-precision warmup
            # before compression kicks in (variance estimates settle first)
            extra = (config.optimizer.params.model_extra or {})
            self.onebit_freeze_step = int(extra.get("freeze_step", 0) or 0)
            if self.policy.stage >= 2:
                raise ValueError(
                    "1-bit optimizers compress the DP gradient allreduce; "
                    "ZeRO stage >= 2 reduce-scatters instead — use stage 0/1 "
                    "(reference has the same restriction)")
            if self.fp16_enabled:
                raise NotImplementedError(
                    "1-bit compression + fp16 loss scaling not supported; "
                    "use bf16/fp32")
            if self.mesh is not None and int(
                    self.mesh.shape.get("pipe", 1)) > 1:
                raise NotImplementedError("1-bit + pipeline parallelism "
                                          "not supported yet")
            if self.offload_enabled or self._infinity_requested:
                raise NotImplementedError(
                    "1-bit optimizers are not supported with optimizer/param "
                    "offload (the offload step would discard the error-"
                    "feedback residuals) — pick one")

        # --- ZeRO++ qwZ: int8 quantized-weight all-gather -----------------
        # (runtime/zero/qwz.py: sharded master → int8+scales → replicated
        # sharding constraint, so the GSPMD all-gather moves int8 bytes;
        # straight-through backward)
        self.qwz_enabled = bool(config.zero_optimization.zero_quantized_weights)
        if self.qwz_enabled and (self.offload_enabled
                                 or self._infinity_requested):
            raise NotImplementedError(
                "zero_quantized_weights + offload/infinity not supported "
                "(those paths own their own param movement)")
        self.qgz_enabled = bool(config.zero_optimization.zero_quantized_gradients)
        if self.qgz_enabled:
            if self.onebit_enabled:
                raise ValueError("zero_quantized_gradients and 1-bit "
                                 "optimizers are mutually exclusive "
                                 "compression schemes")
            if self.offload_enabled or self._infinity_requested:
                raise NotImplementedError(
                    "zero_quantized_gradients + offload not supported yet")
            if self.mesh is not None and int(
                    self.mesh.shape.get("pipe", 1)) > 1:
                raise NotImplementedError("qgZ + pipeline parallelism "
                                          "not supported yet")
            from .zero.qgz import wire_bytes as _qgz_bytes

            # params aren't placed yet; log after state init instead
            self._log_qgz_bytes = _qgz_bytes

        # --- optimizer ---------------------------------------------------
        self.optimizer = optimizer if optimizer is not None else build_optimizer(
            config, lr=self._schedule)
        clip = config.gradient_clipping
        self.gradient_clipping = 0.0 if isinstance(clip, str) else float(clip)

        # --- Pallas kernel plane (kernels.* config group) ----------------
        kcfg = config.kernels
        self.overlap_zero3 = bool(kcfg.overlap_collectives)
        self.overlap_chunks = max(int(kcfg.overlap_chunks), 1)
        self.fused_adam_enabled = False
        self._fused_adam_cfg = None
        if kcfg.fused_adam:
            fused_ok = (optimizer is None
                        and opt_name in ("adam", "fusedadam", "adamw",
                                         "deepspeedcpuadam")
                        and not (self.offload_enabled
                                 or self._infinity_requested
                                 or self.onebit_enabled or self._pp_1f1b))
            if not fused_ok:
                log_dist("kernels.fused_adam requested but the active "
                         "optimizer/path is not a config-built adam "
                         "family (or offload/1-bit/1F1B owns the update) "
                         "— keeping the optax chain")
            else:
                from ..ops.pallas.fused_optimizer import FusedAdamConfig

                op = config.optimizer.params if config.optimizer else None
                betas = getattr(op, "betas", [0.9, 0.999])
                if isinstance(betas, str):  # "auto"
                    betas = [0.9, 0.999]
                eps_v = getattr(op, "eps", 1e-8)
                wd_v = getattr(op, "weight_decay", 0.0)
                self._fused_adam_cfg = FusedAdamConfig(
                    b1=float(betas[0]), b2=float(betas[1]),
                    eps=1e-8 if isinstance(eps_v, str) else float(eps_v),
                    weight_decay=(0.0 if isinstance(wd_v, str)
                                  else float(wd_v)),
                    # build_optimizer maps adamw/cpu-adam to optax.adamw
                    # (decoupled decay); plain adam takes additive L2
                    decoupled_wd=opt_name in ("adamw", "deepspeedcpuadam"))
                self.fused_adam_enabled = True
                log_dist("kernels.fused_adam: one-pass fused Adam update "
                         f"active ({self._fused_adam_cfg})")

        # --- loss scaler (fp16 only; bf16/fp32 need none) ----------------
        # Scale cap 2^15: the loss cotangent enters the f16 subgraph as the
        # scale itself, and f16 max is 65504 — a 2^16 seed is inf before the
        # first multiply. (The dynamic grower may probe 2^16 and back off.)
        fp16 = config.fp16
        self.loss_scaler = (DynamicLossScaler.from_config(fp16)
                            if self.fp16_enabled else None)

        # --- unified telemetry (telemetry/) ------------------------------
        # (before state init so placement spans of the build are captured)
        from ..telemetry import configure_from_config, get_telemetry

        if config.telemetry.enabled:
            configure_from_config(config.telemetry)
        elif "enabled" in config.telemetry.model_fields_set:
            # an EXPLICIT {"telemetry": {"enabled": false}} turns the
            # process-global hub off (a defaulted-off config leaves a hub
            # another job enabled alone)
            get_telemetry().configure(enabled=False)
        self.telemetry = get_telemetry()
        self._telemetry_steps = bool(config.telemetry.enabled
                                     and config.telemetry.step_records)
        self._telemetry_fence = bool(config.telemetry.device_fence)
        #: recent per-step records (bench/autotuner read the SAME numbers
        #: the engine logged — they can never disagree)
        self.step_records: collections.deque = collections.deque(maxlen=512)
        #: last comms_logger exec_totals snapshot — StepRecords carry the
        #: per-step DELTA (the cumulative number is already comm_bytes)
        self._last_exec_totals = (0.0, 0.0)
        self.last_step_record = None
        #: analytic model FLOPs per optimizer step; callers that know the
        #: model shape set it so StepRecords carry TFLOPS/MFU
        self.flops_per_step = 0.0
        # ADVICE round-5: under `deepspeed --autotuning` candidate profiling
        # every step is fenced, so samples/sec ranks candidates by DEVICE
        # step time instead of host dispatch/queue backpressure
        self._autotuning_fence = bool(os.environ.get("DS_AUTOTUNING_RESULT"))

        # --- active diagnostics: flight recorder / watchdog / health ------
        # (telemetry/{flight_recorder,watchdog,health}.py — ISSUE 2)
        tcfg = config.telemetry
        self.flight_recorder = None
        self.watchdog = None
        self.health = None
        wd_cfg, h_cfg = tcfg.watchdog, tcfg.health
        from ..telemetry.flight_recorder import recorder_from_config

        self.flight_recorder = recorder_from_config(tcfg)
        if wd_cfg.enabled:
            from ..telemetry import HangWatchdog, set_watchdog

            self.watchdog = HangWatchdog(
                hang_timeout_s=wd_cfg.hang_timeout_s,
                poll_interval_s=wd_cfg.poll_interval_s,
                action=wd_cfg.action, comm_liveness=wd_cfg.comm_liveness,
                # None when the recorder is disabled — the watchdog then
                # trips WITHOUT writing bundles (the operator said no)
                recorder=self.flight_recorder,
                device_probe=wd_cfg.device_probe,
                device_probe_timeout_s=wd_cfg.device_probe_timeout_s,
                heartbeat_max_bytes=getattr(wd_cfg, "heartbeat_max_bytes",
                                            1024))
            # process-global handle: the elastic agent folds the
            # watchdog's heartbeat_payload into rendezvous heartbeats
            set_watchdog(self.watchdog)
            # start NOW, not after the first step: the most common hang
            # (a misconfigured mesh's first collective) happens INSIDE
            # the first train_step, before any progress notification
            self.watchdog.start()
        # collective ledger (telemetry/collective_ledger.py — ISSUE 3):
        # every comms-logger record feeds a monotonic per-rank ledger
        # whose tail hash rides elastic heartbeats (live desync) and
        # whose tail lands in every debug bundle (offline divergence)
        self.collective_ledger = None
        agg_cfg = tcfg.aggregation
        if agg_cfg.enabled and agg_cfg.ledger_enabled:
            from ..telemetry import configure_collective_ledger

            self.collective_ledger = configure_collective_ledger(
                max_entries=agg_cfg.ledger_max_entries,
                tail=agg_cfg.ledger_tail,
                exec_feed=agg_cfg.ledger_exec_feed,
                recorder=self.flight_recorder)
        if h_cfg.enabled and self._telemetry_steps:
            from ..telemetry import HealthMonitor

            self.health = HealthMonitor(
                window=h_cfg.window, min_points=h_cfg.min_points,
                loss_spike_zscore=h_cfg.loss_spike_zscore,
                grad_norm_ratio=h_cfg.grad_norm_ratio,
                loss_scale_floor=h_cfg.loss_scale_floor,
                consecutive_scale_drops=h_cfg.consecutive_scale_drops,
                throughput_frac=h_cfg.throughput_frac,
                compile_dominated_frac=h_cfg.compile_dominated_frac,
                recompile_storm_threshold=h_cfg.recompile_storm_threshold,
                control_plane=h_cfg.control_plane,
                memory_pressure_frac=tcfg.memory.pressure_frac,
                memory_pressure_steps=tcfg.memory.pressure_steps,
                host_leak_window=tcfg.memory.leak_window,
                host_leak_frac=tcfg.memory.leak_frac,
                numerics_underflow_frac=tcfg.numerics.underflow_frac,
                numerics_underflow_steps=tcfg.numerics.underflow_steps,
                numerics_layer_grad_ratio=tcfg.numerics.layer_grad_ratio,
                numerics_layer_grad_floor=tcfg.numerics.layer_grad_floor,
                numerics_entropy_floor=tcfg.numerics.entropy_floor,
                numerics_entropy_steps=tcfg.numerics.entropy_steps,
                registry=(self.telemetry.registry if self.telemetry.enabled
                          else None),
                recorder=self.flight_recorder)

        # --- performance observability plane (telemetry/perf — ISSUE 5) --
        # compile/recompile tracking over every engine jit site + the
        # goodput wall-clock ledger.  Configured BEFORE _init_state so
        # the build-time programs (optimizer init, bf16 wire cast, 1-bit
        # residuals) are in the compile table too.
        self.compile_tracker = None
        self.goodput = None
        self.cost_ledger = None
        self._last_anatomy = None
        self._anatomy_cfg = pcfg = tcfg.perf
        self._compile_dominated_frac = float(h_cfg.compile_dominated_frac)
        if pcfg.enabled and tcfg.enabled:
            from ..telemetry.perf import (configure_compile_tracker,
                                          configure_goodput_ledger)

            if pcfg.compile_tracker:
                self.compile_tracker = configure_compile_tracker(
                    enabled=True, max_events=pcfg.compile_max_events,
                    recorder=self.flight_recorder)
            if pcfg.goodput:
                self.goodput = configure_goodput_ledger(
                    enabled=True, window_s=pcfg.goodput_window_s,
                    recorder=self.flight_recorder)
            # anatomy plane (ISSUE 17): the cost ledger rides the
            # compile tracker — every AOT compile is harvested for
            # FLOPs/HBM/collective bytes + a roofline verdict at the
            # moment the executable exists, so the steady state pays
            # nothing
            if pcfg.anatomy and self.compile_tracker is not None:
                from ..telemetry.anatomy import configure_cost_ledger

                self.cost_ledger = configure_cost_ledger(
                    tracker=self.compile_tracker,
                    recorder=self.flight_recorder)

        # --- fleet profiler capture plane (telemetry/profiler — ISSUE 20) --
        # the plane is installed (or not) by initialize()/the serving
        # worker; the engine only holds the reference so train_step can
        # feed the step index (two attribute reads when no window is
        # armed) and stamps its anatomy site for the calibration join
        self._profiler_plane = None
        if tcfg.enabled and tcfg.profiler.enabled:
            from ..telemetry.profiler import get_profiler_plane

            self._profiler_plane = get_profiler_plane()
            if self._profiler_plane is not None:
                self._profiler_plane.site = self._anatomy_site()
                if tcfg.profiler.duty_cycle_pct > 0.0:
                    self._profiler_plane.enable_duty_cycle()

        # --- memory observability plane (telemetry/memory — ISSUE 7) -----
        # per-pool byte ledger fed by the allocation sites below
        # (_init_state placement, offload, swappers, KV pool, snapshots),
        # per-step HBM/RSS/swap-IO samples on StepRecords, and the OOM
        # catch around the step dispatch.  Configured BEFORE _init_state
        # so placement registers into a live ledger.
        self.memory_ledger = None
        mem_cfg = tcfg.memory
        if mem_cfg.enabled and (tcfg.enabled
                                or self.flight_recorder is not None):
            from ..telemetry.memory import configure_memory_ledger

            self.memory_ledger = configure_memory_ledger(
                enabled=True, top_k=mem_cfg.top_k,
                recorder=self.flight_recorder)
        self._mem_census_every = int(mem_cfg.live_census_every)

        # --- numerics observability plane (telemetry/numerics — ISSUE 18) --
        # in-graph tensor-health probes: sampled steps run a SEPARATE
        # jitted step variant whose trace carries the probe stats in an
        # aux output pytree (the base step's program is never touched —
        # probes off means today's exact jaxpr), and a non-finite loss
        # triggers the probes-on forensic re-run that NAMES the first
        # bad layer (see _run_nonfinite_forensics)
        ncfg = tcfg.numerics
        self._numerics_cfg = ncfg
        self._last_numerics: Optional[Dict[str, Any]] = None
        self._last_nonfinite_report = None
        self._numerics_step_fn = None
        self._moe_step_fn = None
        self._forensic_fwd_fn = None
        self._numerics_context: Optional[Dict[str, Any]] = None
        if self.flight_recorder is not None and (ncfg.enabled
                                                 or ncfg.moe_gauges):
            # every bundle carries the latest capture (the CLI's
            # `numerics show` fallback when no numerics.json exists)
            self.flight_recorder.register_context(
                "numerics", lambda: self._numerics_context)

        # --- place state on the mesh, sharded per ZeRO stage -------------
        self.state = self._init_state(params)
        if self.qgz_enabled:
            q, f = self._log_qgz_bytes(self.state.params)
            log_dist(f"qgZ: DP grad reduction wire bytes {f/2**20:.1f} MiB "
                     f"→ {q/2**20:.1f} MiB per step ({f/q:.1f}× reduction)")

        # --- self-healing resilience plane (resilience/ — ISSUE 4) -------
        # snapshots + recovery policy + fault injection.  The injector is
        # independent of `resilience.enabled`: injecting faults WITHOUT
        # recovery is how you prove the failure actually breaks a run.
        self.snapshots = None
        self.resilience = None
        from ..resilience.faults import FaultInjector

        self.fault_injector = FaultInjector.from_config(
            config.resilience, recorder=self.flight_recorder)
        rcfg = config.resilience
        if rcfg.enabled:
            from ..resilience import (RecoveryPolicy, SnapshotManager,
                                      SnapshotUnsupportedError,
                                      check_snapshot_support)

            try:
                check_snapshot_support(self)
            except SnapshotUnsupportedError as e:
                # degrade, don't die: the job still trains (and ordinary
                # checkpoints still cover it) — only the self-healing
                # rollback/resume loop is unavailable on this engine
                logger.warning(
                    f"resilience: snapshots DISABLED for this run — {e}")
                rcfg = None
        if rcfg is not None and rcfg.enabled:
            self.snapshots = SnapshotManager(
                self, rcfg, recorder=self.flight_recorder)
            self.resilience = RecoveryPolicy(
                self, self.snapshots, rcfg, recorder=self.flight_recorder)
            if self.watchdog is not None:
                # emergency-save-if-responsive on the trip edge (runs on
                # the watchdog thread BEFORE its raise/exit action)
                self.watchdog.add_trip_listener(
                    self.resilience.on_watchdog_trip)
            elif rcfg.emergency_save_on_trip:
                logger.warning(
                    "resilience: emergency_save_on_trip is set but the "
                    "hang watchdog is off — hangs will NOT trigger an "
                    "emergency snapshot (enable telemetry.watchdog)")
            # the policy checks the loss scalar itself, but every OTHER
            # rollback trigger arrives as a HealthMonitor event — which
            # only exists when telemetry step records are on
            inert = [k for k in rcfg.rollback_on
                     if k != "nan_loss" and self.health is None]
            if inert:
                logger.warning(
                    f"resilience: rollback_on includes {inert} but the "
                    f"health monitor is off (it needs telemetry.enabled "
                    f"+ step_records + health.enabled) — those triggers "
                    f"will never fire; only the direct NaN-loss check "
                    f"is active")
            log_dist(f"resilience: snapshots every "
                     f"{rcfg.snapshot_interval} steps -> "
                     f"{rcfg.snapshot_dir} (tiers: memory"
                     + (", disk" if rcfg.disk_tier else "")
                     + (", buddy" if rcfg.buddy_tier else "") + ")")
        self._train_step_fn = None  # compiled lazily (first call)
        #: forced-partial-boundary programs, keyed by microbatch count
        self._partial_step_fns: Dict[int, Any] = {}
        self._warmup_step_fn = None  # 1-bit warmup variant
        self._eval_loss_fn = None

        # --- random-LTD (data_efficiency.data_routing) --------------------
        # keep-count changes along a quantized schedule; each bucket gets
        # its own compiled step (the model reads ltd_keep at trace time)
        self._ltd_cfg = None
        self._ltd_sched = None
        self._ltd_fns: Dict[int, Any] = {}
        de = config.data_efficiency
        routing = (de.data_routing.get("random_ltd", {})
                   if de.enabled else {})
        if routing.get("enabled"):
            ids = tuple(routing.get("random_ltd_layer_id", []))
            if not hasattr(self.module, "ltd_keep"):
                logger.warning("random_ltd enabled but the model has no "
                               "ltd_keep support; ignoring")
            elif not ids:
                # explicit beats implicit: without layer ids the model
                # would silently never drop a token while the engine
                # compiles a redundant program per keep bucket
                logger.warning("random_ltd enabled but random_ltd_layer_id "
                               "is empty; ignoring (list the layers to "
                               "apply token dropping to)")
            else:
                self._ltd_cfg = dict(routing)
                self.module.ltd_layer_ids = ids

        # --- compat-mode bookkeeping -------------------------------------
        self._pending_batch: Any = None
        self._microbatch_buffer: List[Any] = []
        self._accumulation_boundary_forced: Optional[bool] = None
        self.global_steps = 0
        self.micro_steps = 0
        self.last_metrics: Dict[str, Any] = {}
        self._last_health_events: List[Any] = []
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=int(self.train_batch_size or 1))
        self.steps_per_print = config.steps_per_print
        self.monitor = None  # attached by monitor subsystem when configured

    # ------------------------------------------------------------------
    # state construction
    # ------------------------------------------------------------------

    def _jit(self, fn, site: str, static_context=None, **jit_kwargs):
        """``jax.jit`` through the compile tracker (telemetry/perf):
        every engine program gets a compile event with lower/compile
        timing, and a recompile of the same site records a structured
        cause diff.  ``static_context`` names the closure-baked statics
        (gas, 1-bit warmup flag, LTD keep bucket) so a recompile caused
        by one of THOSE is named, not just 'signature changed'.  With
        the tracker off this IS ``jax.jit``."""
        from ..telemetry.perf import tracked_jit

        return tracked_jit(fn, site=site, tracker=self.compile_tracker,
                           static_context=static_context, **jit_kwargs)

    def _init_state(self, params: Any) -> TrainState:
        if self._infinity_requested:
            # ZeRO-Infinity: trunk params NEVER touch the device whole —
            # the streaming executor owns them (host/NVMe tier); only the
            # small resident subtree (embed/norm/head) lives in self.state
            from .swap_tensor import LayerStreamingEngine

            self.infinity = LayerStreamingEngine(
                self.module, params, self.config, self._schedule,
                mesh=getattr(self.module, "mesh", None),
                base_specs=self.base_specs)
            scale_state = LossScaleState(jnp.float32(1.0), jnp.int32(0),
                                         jnp.int32(0))
            if self.memory_ledger is not None:
                # only the small resident subtree (embed/norm/head) lives
                # on device; the trunk is the swapper's host planes,
                # registered by PartitionedParamSwapper itself
                self.memory_ledger.register_tree(
                    "params", "infinity/resident_params",
                    self.infinity.resident,
                    tag="Infinity resident subtree (embed/norm/head)")
            return TrainState(params=self.infinity.resident, opt_state=(),
                              step=jnp.int32(0), loss_scale=scale_state,
                              skipped_steps=jnp.int32(0))
        params = jax.tree.map(jnp.asarray, params)
        param_shardings = self.policy.param_shardings(params, self.base_specs)
        with self.telemetry.span("zero/param_placement",
                                 args={"stage": self.policy.stage}):
            params = jax.device_put(params, param_shardings)
            if self.telemetry.enabled:
                # block on the placed tree so the span measures the
                # transfer, not the enqueue (device_put is async)
                jax.block_until_ready(params)
        if self.memory_ledger is not None:
            # the ZeRO placement site IS the params allocation: register
            # the logical tree bytes (per-device residency is bytes/dp at
            # stage 3 — the drift cross-check compares against the local
            # device, so the snapshot records both views)
            self.memory_ledger.register_tree(
                "params", "engine/placed_params", params,
                tag=f"zero stage {self.policy.stage} placed model params")
            # stage >= 2 grads exist only INSIDE the compiled step in
            # their reduce-scattered layout — tracked as transient fp32
            # bytes so the breakdown names them without skewing the
            # steady-state drift metric
            grad_bytes = sum(
                int(np.prod(np.shape(p))) * 4
                for p in jax.tree.leaves(params))
            self.memory_ledger.register(
                "grads", "engine/step_grads", grad_bytes, transient=True,
                tag="fp32 grad accumulators (transient, inside-step)")
            # kernel scratch attribution (ISSUE 12): the Pallas planes
            # that live OUTSIDE the params/grads/optimizer pools get
            # named entries under collective_scratch so peak_hbm gating
            # and OOM forensics can point at them
            mc = getattr(self.module, "config", None)
            if getattr(mc, "attn_impl", "") == "flash":
                # keyed on the MODEL's route (the signal that decides
                # whether the kernel actually runs), not the
                # kernels.flash_attention config knob — the knob only
                # steers builders that construct the model
                heads = int(getattr(mc, "num_heads", 0) or 0)
                max_s = int(getattr(mc, "max_seq_len", 0) or 0)
                layers = int(getattr(mc, "num_layers", 1) or 1)
                rows = int(self.micro_batch_size or 0)
                if heads and max_s and rows:
                    # fwd lse + bwd delta, fp32 per (row, head, pos); one
                    # layer's planes live at a time under remat
                    self.memory_ledger.register(
                        "collective_scratch", "engine/flash_softmax_stats",
                        2 * rows * heads * max_s * 4 * (1 if getattr(
                            mc, "remat", True) else layers),
                        transient=True,
                        tag="flash attention lse/delta softmax stats")
            if self.overlap_zero3 and self.policy.stage >= 3:
                from ..comm.overlap import staging_bytes

                dp_world = int(np.prod([self.mesh.shape[a]
                                        for a in DP_AXES]))
                ring_bytes = sum(
                    staging_bytes(np.shape(p),
                                  getattr(p, "dtype", jnp.float32),
                                  self.overlap_chunks) // max(dp_world, 1)
                    for p in jax.tree.leaves(params))
                self.memory_ledger.register(
                    "collective_scratch", "engine/overlap_ring_staging",
                    ring_bytes, transient=True,
                    tag=f"ZeRO-3 overlap ring payloads "
                        f"(chunks={self.overlap_chunks})")

        if self.offload_enabled:
            # optimizer states live on the HOST (ZeRO-Offload): fp32 master +
            # moments in numpy, updated by the fused C++ kernel
            from .zero.offload import CPUOffloadOptimizer

            opt_cfg = self.config.optimizer
            opt_name = (opt_cfg.type if opt_cfg is not None else "AdamW")
            # bf16 wire needs the C++ kernel's fused bf16 emit — Adam-only;
            # Lion/Adagrad offload stays on the fp32 wire
            wire_bf16 = (self.bf16_enabled and opt_name.lower()
                         in ("adam", "adamw", "cpu_adam"))
            self.offload_opt = CPUOffloadOptimizer(
                params,
                optimizer_name=opt_name,
                optimizer_params=(dict(opt_cfg.params.model_dump())
                                  if opt_cfg is not None else {}),
                schedule=self._schedule,
                policy=self.policy, base_specs=self.base_specs,
                wire_bf16=wire_bf16)
            opt_state = ()
            if wire_bf16:
                # bf16 wire: the device copy lives in bf16 (fp32 masters are
                # host-side) — halves HBM and h2d bytes, same compute as the
                # on-device bf16 path which casts fp32→bf16 every step
                params = self._jit(lambda t: cast_tree(t, jnp.bfloat16),
                                   "engine/bf16_wire_cast",
                                   out_shardings=param_shardings)(params)
        else:
            opt_shapes = jax.eval_shape(self.optimizer.init, params)
            opt_shardings = self.policy.opt_state_shardings(
                opt_shapes, tx=self.optimizer, base_specs=self.base_specs)
            opt_state = self._jit(self.optimizer.init, "engine/opt_init",
                                  out_shardings=opt_shardings)(params)
            if self.memory_ledger is not None:
                self.memory_ledger.register_tree(
                    "optimizer", "engine/opt_state", opt_state,
                    tag=f"optax state (zero stage {self.policy.stage})")

        scale_state = (self.loss_scaler.init_state() if self.loss_scaler
                       else LossScaleState(jnp.float32(1.0), jnp.int32(0),
                                           jnp.int32(0)))
        comm_state: Any = ()
        if self.onebit_enabled:
            # per-worker error-feedback residuals: [dp_world, *param_shape],
            # sharded over the DP axes so each worker owns exactly its own;
            # ONE compiled program materializes the whole pytree sharded
            from ..ops.onebit import init_residuals

            dp_world = int(np.prod([self.mesh.shape[a] for a in DP_AXES]))
            res_shardings = jax.tree.map(
                lambda _: NamedSharding(self.mesh, PartitionSpec(DP_AXES)),
                params)
            comm_state = self._jit(
                # dp_world is static by design: a mesh change rebuilds
                # the engine (fresh jit sites), never retraces this one
                lambda: init_residuals(params, dp_world),  # dslint: disable=recompile-hazard
                "engine/onebit_residuals",
                out_shardings=res_shardings)()
            if self.memory_ledger is not None:
                self.memory_ledger.register_tree(
                    "collective_scratch", "engine/onebit_residuals",
                    comm_state, tag="1-bit error-feedback residuals")
        return TrainState(params=params, opt_state=opt_state,
                          step=jnp.int32(0), loss_scale=scale_state,
                          skipped_steps=jnp.int32(0), comm_state=comm_state)

    def _state_shardings(self, state: TrainState) -> TrainState:
        def of(x):
            s = getattr(x, "sharding", None)
            return s if isinstance(s, NamedSharding) else NamedSharding(
                self.mesh, PartitionSpec())

        return jax.tree.map(of, state)

    def mesh_topology(self) -> Dict[str, Any]:
        """This engine's mesh topology — stamped into every snapshot
        manifest and compared by the reshard-on-restore guard (a
        snapshot taken on a different mesh re-lays onto THIS one, or
        fails with a MeshMismatchError naming both)."""
        from ..parallel.mesh import mesh_topology

        return mesh_topology(self.mesh)

    # ------------------------------------------------------------------
    # the compiled train step
    # ------------------------------------------------------------------

    def _pp_1f1b_grads(self, compute_params, batch, scale=None):
        """Grads + mean loss through the 1F1B schedule.

        Bridges the module's layer-streamable protocol (embed_fwd /
        decoder_layer / head_loss — the same contract Infinity streams
        through) onto ``pipeline_train_1f1b``'s (embed_fn, layer_fn,
        head_fn) surface; MoE aux loss rides the activation carry.
        Reference: ``runtime/pipe/engine.py`` TrainSchedule execution
        (SURVEY §3.5)."""
        from ..parallel.mesh import AXIS_PIPE
        from ..parallel.pipeline import pipeline_train_1f1b

        mod = self.module
        # host attribute, not a device value — no sync happens here
        aux_coef = float(getattr(mod, "aux_loss_coef", 0.0))  # dslint: disable=host-sync-hot-path
        gas = self.gradient_accumulation_steps
        pp = int(self.mesh.shape[AXIS_PIPE])
        rows = jax.tree.leaves(batch)[0].shape[0]
        m_pipe = int(getattr(getattr(mod, "config", None),
                             "pp_microbatches", 0) or pp)
        M = gas * m_pipe
        if rows % M:
            raise ValueError(
                f"batch rows {rows} not divisible by pipeline microbatches "
                f"{M} (gas {gas} × pp micro {m_pipe})")
        micro = jax.tree.map(
            lambda x: x.reshape((M, rows // M) + x.shape[1:]), batch)
        resident = {k: v for k, v in compute_params.items()
                    if k != "layers"}

        def embed_fn(ep, mb):
            ids, _ = mod.batch_labels(mb)
            return (mod.embed_fwd(ep, ids), jnp.float32(0.0))

        manual_tp = getattr(self, "_pp_1f1b_manual_tp", False)
        layer_impl = (mod.decoder_layer_manual_tp if manual_tp
                      else mod.decoder_layer)
        from ..parallel.mesh import AXIS_TENSOR as _ATg

        tp_now = int(self.mesh.shape.get(_ATg, 1))
        # the module declares which resident leaves its manual-TP head
        # reads; the TENSOR-SHARDED ones among them (from the module's own
        # param_specs — no key names hardcoded here) are the vocab-scale
        # leaves the split exists to keep off the replicated path
        head_keys = tuple(getattr(mod, "manual_tp_head_param_keys", ()))
        base = self.base_specs or {}

        def _tensor_dim(key):
            spec = base.get(key)
            if spec is None:
                return None
            ent = tuple(spec)
            for i, e in enumerate(ent):
                axes = e if isinstance(e, (tuple, list)) else (e,)
                if any(a == _ATg for a in axes if a):
                    return i
            return None

        sharded_head_keys = [k for k in head_keys
                             if k in resident and _tensor_dim(k) is not None]

        def _divides(key):
            dim = _tensor_dim(key)
            shape = np.shape(jax.tree.leaves(resident[key])[0])
            return shape[dim] % max(tp_now, 1) == 0

        vocab_parallel = (
            manual_tp
            and callable(getattr(mod, "head_loss_manual_tp", None))
            and not getattr(getattr(mod, "config", None), "tie_embeddings",
                            True)
            and bool(sharded_head_keys)
            and all(k in resident for k in head_keys)
            # shard_map hard-errors on non-divisible dims: a GPT-2-like
            # vocab (50257) must keep the replicated head, not crash
            and all(_divides(k) for k in sharded_head_keys))
        head_impl = (mod.head_loss_manual_tp if vocab_parallel
                     else mod.head_loss)

        def layer_fn(lp, act):
            x, aux = act
            nx, naux = layer_impl(lp, x)
            return (nx, aux + naux)

        def head_fn(hp, act, mb):
            x, aux = act
            loss = head_impl(hp, x, mb) + aux_coef * aux
            # fp16 loss scaling INSIDE the schedule: the 1/M cotangent
            # seed then carries the scale through every stage's fp16 vjp
            return loss * scale if scale is not None else loss

        manual_axes: tuple = ()
        trunk_specs = None
        head_specs = None
        if manual_tp:
            # tensor joins the manual set; the trunk in/out specs carry
            # the model's pipe+tensor placement (manual axes only — dp/
            # ZeRO placement on other dims stays with GSPMD outside)
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import AXIS_PIPE as _AP
            from ..parallel.mesh import AXIS_TENSOR as _AT2
            manual_axes = (_AT2,)
            keep = {_AP, _AT2}

            def manual_only(spec):
                out = []
                for e in tuple(spec):
                    if isinstance(e, (tuple, list)):
                        kept = tuple(a for a in e if a in keep)
                        out.append(kept if kept else None)
                    else:
                        out.append(e if e in keep else None)
                return P(*out)

            trunk_specs = jax.tree.map(
                manual_only, mod.param_specs()["layers"],
                is_leaf=lambda s: isinstance(s, P))
            if vocab_parallel:
                # vocab-parallel head (Megatron parallel CE): the
                # module's tensor-sharded head leaves enter with their
                # OWN param_specs placement (manual axes only); the rest
                # stay replicated
                head_specs = {
                    k: (manual_only(base[k]) if k in sharded_head_keys
                        else jax.tree.map(lambda _: P(), resident[k]))
                    for k in head_keys}

        # under the vocab-parallel head each manual-region argument
        # carries ONLY what its role reads: the embed side drops lm_head
        # (embed_fwd never touches it), the head side drops embed
        # (head_loss_manual_tp reads final_norm + lm_head) — a redundant
        # replicated [V, H]-scale copy PLUS its fp32 zero-grad scan-carry
        # accumulator per device is the footprint at stake on each side
        embed_resident = resident
        head_resident = resident
        if vocab_parallel:
            embed_resident = {k: v for k, v in resident.items()
                              if k not in sharded_head_keys}
            head_resident = {k: v for k, v in resident.items()
                             if k in head_keys}

        loss, (g_trunk, g_emb, g_head), stats = pipeline_train_1f1b(
            layer_fn, compute_params["layers"], embed_fn, embed_resident,
            head_fn, head_resident, micro, self.mesh,
            manual_axes=manual_axes, trunk_specs=trunk_specs,
            head_specs=head_specs)
        self.last_pipe_stats = dict(stats, schedule="1f1b",
                                    manual_tp=manual_tp,
                                    vocab_parallel_head=vocab_parallel)
        grads = {}
        for k in set(g_emb) | set(g_head):
            if k in g_emb and k in g_head:
                grads[k] = jax.tree.map(jnp.add, g_emb[k], g_head[k])
            else:
                grads[k] = g_emb[k] if k in g_emb else g_head[k]
        grads["layers"] = g_trunk
        return grads, loss

    def _stage3_manual_infos(self, compute_params, label: str):
        """Per-leaf manual-sharding projections for the explicit stage-3
        shard_map branches (qgZ int8 comm, ring-overlap comm): how each
        param/grad leaf's DP axes project into the manual region.  One
        home so the two branches cannot drift."""
        policy = self.policy
        dp_set = set(DP_AXES)
        if tuple(policy.shard_axes) != tuple(DP_AXES):
            raise NotImplementedError(
                f"{label} + MiCS sub-group sharding not supported (the "
                f"manual reduce must cover every DP axis)")

        def _manual_proj(spec, shape):
            entries = list(spec) + [None] * (len(shape) - len(spec))
            man_entries, dims = [], []
            for i, e in enumerate(entries):
                axes = (e if isinstance(e, tuple)
                        else ((e,) if e is not None else ()))
                man = tuple(a for a in axes if a in dp_set)
                auto = tuple(a for a in axes if a not in dp_set)
                if man and auto:
                    raise NotImplementedError(
                        f"{label}: leaf mixes DP and model axes on one dim")
                man_entries.append(man if man else None)
                if man:
                    dims.append(i)
            if len(dims) > 1:
                raise NotImplementedError(f"{label}: multi-dim DP sharding")
            dim = dims[0] if dims else None
            return (PartitionSpec(*man_entries), dim,
                    man_entries[dim] if dim is not None else None)

        def _leaf_info(p, b):
            if b is not None:
                for e in tuple(b):
                    axes = (e if isinstance(e, tuple)
                            else ((e,) if e else ()))
                    if any(a in dp_set for a in axes):
                        raise NotImplementedError(
                            f"{label} does not support model params "
                            f"sharded over DP axes (expert-stacked MoE "
                            f"weights)")
            shape = np.shape(p)
            pin, pdim, paxes = _manual_proj(policy.param_spec(p, b), shape)
            gout, gdim, gaxes = _manual_proj(policy.grad_spec(p, b), shape)
            return {"pin": pin, "pdim": pdim, "paxes": paxes,
                    "gout": gout, "gdim": gdim, "gaxes": gaxes}

        if self.base_specs is None:
            info = jax.tree.map(lambda p: _leaf_info(p, None),
                                compute_params)
        else:
            info = jax.tree.map(_leaf_info, compute_params,
                                self.base_specs)
        pin_tree = jax.tree.map(lambda p, i: i["pin"], compute_params,
                                info)
        gout_tree = jax.tree.map(lambda p, i: i["gout"], compute_params,
                                 info)
        return info, pin_tree, gout_tree

    def _grad_core(self, onebit: Optional[bool] = None,
                   fused_prep: bool = False):
        """Shared microbatch-scan gradient computation: accumulation, loss
        (un)scaling, ZeRO grad constraints, overflow screen, clipping.  Used
        by BOTH the fused on-device step and the offload grad-only step so
        the two paths cannot drift.

        ``fused_prep=True`` (the kernels.fused_adam path): the separate
        unscale/clip HBM sweeps are SKIPPED — grads return still
        loss-scaled, the global grad-norm comes from ONE Pallas read
        (``tree_sqsum``), and everything the chain applied per element
        (unscale × clip × overflow-zero) folds into the single ``mult``
        scalar the fused update kernel consumes."""
        gas = self.gradient_accumulation_steps
        fp16 = self.fp16_enabled
        dtype = self.compute_dtype
        clip = self.gradient_clipping
        policy = self.policy
        loss_fn = self.loss_fn

        onebit = self.onebit_enabled if onebit is None else onebit
        qgz = self.qgz_enabled
        mesh = self.mesh

        def microbatch_scan(compute_params, micro, scale):
            """gas-scan of value_and_grad, fp32 accumulation.

            Numerics plane: when a collector is active AT TRACE TIME the
            loss closure brackets the forward with scan_mark/scan_drain
            and the per-micro probe stats exit value_and_grad via
            ``has_aux`` and the gas scan via its ``ys`` (folded over the
            gas axis after the scan closes).  When no collector is
            active this traces today's exact jaxpr — ``ys`` is None and
            value_and_grad has no aux."""
            coll = numerics.active()

            def grad_of_micro(mb):
                def scaled_loss(p):
                    loss = loss_fn(p, mb)
                    return (loss * scale / gas).astype(jnp.float32) if fp16 \
                        else loss / gas

                def scaled_loss_aux(p):
                    mark = numerics.scan_mark()
                    loss = loss_fn(p, mb)
                    aux = numerics.scan_drain(mark)
                    scaled = (loss * scale / gas).astype(jnp.float32) \
                        if fp16 else loss / gas
                    return scaled, (aux or {})

                if coll is None:
                    return jax.value_and_grad(scaled_loss)(compute_params), \
                        None
                (loss, aux), grads = jax.value_and_grad(
                    scaled_loss_aux, has_aux=True)(compute_params)
                return (loss, grads), (aux or None)

            def body(acc, mb):
                loss_acc, grads_acc = acc
                (loss, grads), ys = grad_of_micro(mb)
                grads_acc = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), grads_acc, grads)
                return (loss_acc + loss.astype(jnp.float32), grads_acc), ys

            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), compute_params)
            totals, ys = jax.lax.scan(
                body, (jnp.float32(0.0), zero_grads), micro)
            numerics.scan_collect(ys, combine=True)
            return totals

        def compute(state: TrainState, batch):
            if self._ltd_cfg is not None and isinstance(batch, dict):
                # step rides as a per-row leaf (survives the gas reshape) so
                # the model's LTD token selection is fresh every step
                rows = jax.tree.leaves(batch)[0].shape[0]
                batch = {**batch,
                         "_step": jnp.full((rows,), state.step, jnp.int32)}
            compute_params = (cast_tree(state.params, dtype)
                              if dtype != jnp.float32 else state.params)
            if self.qwz_enabled:
                from .zero.qwz import qwz_compress_tree

                compute_params = qwz_compress_tree(
                    compute_params, mesh,
                    threshold=policy.persistence_threshold,
                    base_specs=self.base_specs)
            scale = state.loss_scale.scale

            if self._pp_1f1b and not (onebit or qgz or self.qwz_enabled):
                # 1F1B pipeline schedule (reference TrainSchedule): grads
                # come from the lockstep tick scan in parallel/pipeline.py
                # — O(pp) stashed activations per stage — instead of
                # autodiff through the module's GPipe forward.  The
                # pipeline microbatch count absorbs gas (both are "grads
                # summed over micros of the mean loss").  fp16: the
                # per-micro loss is scaled INSIDE the schedule (cotangents
                # ride scaled through the fp16 backward), unscaled here;
                # the overflow vote is globally consistent by construction
                # — grads are one logical SPMD array, so every stage
                # computes the same isfinite reduction (the reference
                # all-reduces a per-stage overflow flag to the same end).
                grads, mean_loss = self._pp_1f1b_grads(
                    compute_params, batch, scale=scale if fp16 else None)
                if fp16:
                    grads = jax.tree.map(lambda g: g / scale, grads)
                    mean_loss = mean_loss / scale
                grads = policy.apply_grad_constraints(grads,
                                                      self.base_specs)
                overflow = has_overflow(grads) if fp16 else jnp.bool_(False)
                grads = jax.tree.map(
                    lambda g: jnp.where(overflow, 0.0, g), grads)
                if clip > 0:
                    grads, grad_norm = clip_grads_by_global_norm(grads,
                                                                 clip)
                else:
                    grad_norm = global_grad_norm(grads)
                return (grads, mean_loss, overflow, grad_norm,
                        state.comm_state)

            # [global_batch, ...] -> [gas, global_batch/gas, ...]
            micro = jax.tree.map(
                lambda x: x.reshape((gas, x.shape[0] // gas) + x.shape[1:]),
                batch)

            if qgz and policy.stage >= 3:
                # qgZ under ZeRO-3 (round 3): params enter the partial-manual
                # shard_map in their stage-3 DP-SHARDED layout (no more
                # program-long replication), are all-gathered over DP inside,
                # and grads leave via a single-hop int8 reduce-scatter that
                # lands them directly in the stage-3 grad/opt-state layout —
                # the reference's qgZ lives inside stage3.py the same way
                # (SURVEY §2.1 ZeRO++ row).  Transient peak = params/tp
                # during the grad step (the fused path gathers per-layer;
                # layer-granular gather here is future work).
                from .zero.qgz import (quantized_allreduce,
                                       quantized_reduce_scatter)

                P = PartitionSpec
                info, pin_tree, gout_tree = self._stage3_manual_infos(
                    compute_params, "qgZ stage>=3")

                def local3(params_shards, micro_local):
                    def gather(p, i):
                        if i["pdim"] is None:
                            return p
                        return dist.all_gather_in_graph(
                            p, i["paxes"], axis=i["pdim"], tiled=True)
                    params_full = jax.tree.map(gather, params_shards, info)
                    # probe tracers cannot exit a shard_map body — probes
                    # become identities here (dispatch never samples this
                    # path; this is the trace-time guarantee)
                    with numerics.suppressed():
                        loss_sum, grads = microbatch_scan(params_full,
                                                          micro_local, scale)

                    def reduce(g, i):
                        if i["gdim"] is None:
                            return quantized_allreduce(g, DP_AXES)
                        return quantized_reduce_scatter(g, i["gaxes"],
                                                        i["gdim"])
                    grads = jax.tree.map(reduce, grads, info)
                    mean_loss = dist.pmean(loss_sum, DP_AXES)
                    return mean_loss, grads

                mean_loss, grads = _shard_map(
                    local3, mesh=mesh,
                    in_specs=(pin_tree, P(None, DP_AXES)),
                    out_specs=(P(), gout_tree),
                    axis_names=set(DP_AXES), check_vma=False)(
                        compute_params, micro)
                new_comm = state.comm_state
            elif (self.overlap_zero3 and policy.stage >= 3
                  and not (onebit or qgz or self.qwz_enabled)):
                # collective–compute overlap for stage 3 (kernels.
                # overlap_collectives): the same explicit shard_map shape
                # as the qgZ branch, but the param gather and grad reduce
                # are CHUNKED ppermute rings (comm/overlap.py) instead of
                # monolithic collectives — chunk i's compute runs while
                # chunk i+1 is in flight, where GSPMD's single all-gather
                # serializes against the first matmul it feeds.  Every
                # ring hop goes through the comm verbs, so the
                # CollectiveLedger census sees the ring.
                from ..comm import overlap as ovl

                P = PartitionSpec
                info, pin_tree, gout_tree = self._stage3_manual_infos(
                    compute_params, "overlap stage>=3")
                ring_chunks = self.overlap_chunks
                dp_world = int(np.prod([mesh.shape[a] for a in DP_AXES]))

                def _fit_chunks(dim_size: int) -> int:
                    c = min(ring_chunks, max(dim_size, 1))
                    while c > 1 and dim_size % c:
                        c -= 1
                    return c

                def local3o(params_shards, micro_local):
                    def gather(p, i):
                        if i["pdim"] is None:
                            return p
                        return ovl.ring_all_gather(
                            p, i["paxes"], axis=i["pdim"],
                            chunks=_fit_chunks(p.shape[i["pdim"]]))
                    params_full = jax.tree.map(gather, params_shards, info)
                    with numerics.suppressed():
                        loss_sum, grads = microbatch_scan(params_full,
                                                          micro_local, scale)

                    def reduce(g, i):
                        if i["gdim"] is None:
                            return dist.pmean(g, DP_AXES)
                        shard = g.shape[i["gdim"]] // dp_world
                        out = ovl.ring_reduce_scatter(
                            g, i["gaxes"], axis=i["gdim"],
                            chunks=_fit_chunks(shard))
                        return out / dp_world  # mean (matches pmean/qgZ)
                    grads = jax.tree.map(reduce, grads, info)
                    mean_loss = dist.pmean(loss_sum, DP_AXES)
                    return mean_loss, grads

                mean_loss, grads = _shard_map(
                    local3o, mesh=mesh,
                    in_specs=(pin_tree, P(None, DP_AXES)),
                    out_specs=(P(), gout_tree),
                    axis_names=set(DP_AXES), check_vma=False)(
                        compute_params, micro)
                new_comm = state.comm_state
            elif onebit or qgz:
                # compressed-comm path: per-worker LOCAL grads inside a
                # partial-manual shard_map over the DP axes (TP/SP stay
                # GSPMD-auto), then a compressed allreduce instead of psum —
                # 1-bit error-feedback signs or qgZ int8 2-hop (ZeRO++)
                from ..ops.onebit import onebit_reduce_tree
                from .zero.qgz import qgz_reduce_tree

                P = PartitionSpec

                def local(params_c, micro_local, residuals):
                    with numerics.suppressed():
                        loss_sum, grads = microbatch_scan(params_c,
                                                          micro_local, scale)
                    if onebit:
                        res = jax.tree.map(lambda r: jnp.squeeze(r, 0),
                                           residuals)
                        grads, new_res = onebit_reduce_tree(grads, res,
                                                            DP_AXES)
                        new_res = jax.tree.map(lambda r: r[None], new_res)
                    else:
                        grads = qgz_reduce_tree(grads, DP_AXES)
                        new_res = residuals
                    mean_loss = dist.pmean(loss_sum, DP_AXES)
                    return mean_loss, grads, new_res

                res_spec = P(DP_AXES) if onebit else P()
                mean_loss, grads, new_comm = _shard_map(
                    local, mesh=mesh,
                    in_specs=(P(), P(None, DP_AXES), res_spec),
                    out_specs=(P(), P(), res_spec),
                    axis_names=set(DP_AXES), check_vma=False)(
                        compute_params, micro, state.comm_state)
            else:
                loss_sum, grads = microbatch_scan(compute_params, micro,
                                                  scale)
                mean_loss = loss_sum
                new_comm = state.comm_state

            if fused_prep:
                # kernels.fused_adam: NO per-element unscale/clip sweeps.
                # One Pallas read of the (still-scaled) grads yields the
                # norm; overflow falls out of its finiteness (any non-
                # finite grad poisons the sum); unscale × clip × zero
                # collapse into the `mult` scalar the update kernel folds
                # into its single pass.
                from ..ops.pallas.fused_optimizer import tree_sqsum

                if fp16:
                    mean_loss = mean_loss / scale
                grads = policy.apply_grad_constraints(grads,
                                                      self.base_specs)
                raw_norm = jnp.sqrt(tree_sqsum(grads))  # scaled-grad norm
                overflow = ((~jnp.isfinite(raw_norm)) if fp16
                            else jnp.bool_(False))
                safe = jnp.where(jnp.isfinite(raw_norm), raw_norm, 0.0)
                grad_norm = safe / scale if fp16 else safe
                if clip > 0:
                    factor = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                else:
                    factor = jnp.float32(1.0)
                mult = jnp.where(overflow, 0.0, factor)
                if fp16:
                    mult = mult / scale
                return (grads, mean_loss, overflow, grad_norm, mult,
                        new_comm)

            if fp16:
                grads = jax.tree.map(lambda g: g / scale, grads)
                mean_loss = mean_loss / scale  # undo scaling; /gas already in

            # ZeRO stage >= 2: pin grads to their reduce-scattered layout.
            grads = policy.apply_grad_constraints(grads, self.base_specs)

            overflow = has_overflow(grads) if fp16 else jnp.bool_(False)
            grads = jax.tree.map(lambda g: jnp.where(overflow, 0.0, g), grads)

            if clip > 0:
                grads, grad_norm = clip_grads_by_global_norm(grads, clip)
            else:
                grad_norm = global_grad_norm(grads)
            return grads, mean_loss, overflow, grad_norm, new_comm

        return compute

    def _build_fused_train_step(self, onebit: Optional[bool] = None):
        """kernels.fused_adam step: the optax chain's update (moments →
        bias correction → direction → apply, each its own HBM sweep plus
        the separate unscale/clip sweeps in the core) is replaced by TWO
        Pallas passes over the ZeRO shard — the grad-norm read inside
        the fused-prep core and the one-pass update here."""
        from ..ops.pallas.fused_optimizer import apply_fused_adam

        fp16 = self.fp16_enabled
        schedule = self._schedule
        scaler = self.loss_scaler
        fused_cfg = self._fused_adam_cfg
        core = self._grad_core(onebit, fused_prep=True)

        def step_fn(state: TrainState, batch):
            (grads, mean_loss, overflow, grad_norm, mult,
             new_comm) = core(state, batch)
            lr = jnp.asarray(schedule(state.step), jnp.float32)
            new_params, new_opt_state = apply_fused_adam(
                state.opt_state, state.params, grads, lr, mult, fused_cfg)

            if fp16:
                keep = lambda new, old: jax.tree.map(
                    lambda n, o: jnp.where(overflow, o, n), new, old)
                new_params = keep(new_params, state.params)
                new_opt_state = keep(new_opt_state, state.opt_state)
                new_scale = scaler.update(state.loss_scale, overflow)
            else:
                new_scale = state.loss_scale

            new_state = TrainState(
                params=new_params, opt_state=new_opt_state,
                step=state.step + jnp.where(overflow, 0, 1),
                loss_scale=new_scale,
                skipped_steps=state.skipped_steps + jnp.where(overflow, 1,
                                                              0),
                comm_state=new_comm)
            metrics = {
                "loss": mean_loss,
                "grad_norm": grad_norm,
                "lr": lr,
                "loss_scale": state.loss_scale.scale,
                "overflow": overflow,
            }
            return new_state, metrics

        state_shardings = self._state_shardings(self.state)
        batch_sharding = NamedSharding(self.mesh, PartitionSpec(DP_AXES))
        onebit_now = self.onebit_enabled if onebit is None else bool(onebit)
        return self._jit(
            step_fn, "engine/train_step_fused",
            static_context={
                "gas": self.gradient_accumulation_steps,
                "onebit": onebit_now,
                "ltd_keep": getattr(self.module, "ltd_keep", None),
            },
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,))

    def _build_train_step(self, onebit: Optional[bool] = None,
                          numerics_mode: Optional[str] = None):
        """``numerics_mode`` selects the numerics-plane step variant:
        ``None`` is the base step (today's exact program), ``"numerics"``
        / ``"moe"`` are the sampled-capture variants traced at their OWN
        jit sites — turning the plane on never invalidates the base
        step's compile cache."""
        if self.fused_adam_enabled:
            return self._build_fused_train_step(onebit)
        fp16 = self.fp16_enabled
        schedule = self._schedule
        scaler = self.loss_scaler
        tx = self.optimizer
        core = self._grad_core(onebit)
        # forensic precondition: the probes-on re-run localizes the NaN
        # origin by replaying the forward on the params the bad loss came
        # from — but the state is donated, so the only copy left after
        # the step is new_params.  Guarding the update on a non-finite
        # loss keeps that copy equal to the pre-step params (fp16 already
        # does this via overflow-skip; fp32 would otherwise apply the NaN
        # grads and poison every layer, making the re-run blame layer 0).
        guard_nonfinite = (self._numerics_cfg.enabled
                           and self._numerics_cfg.forensic_on_nan)

        def step_fn(state: TrainState, batch):
            grads, mean_loss, overflow, grad_norm, new_comm = core(state,
                                                                   batch)

            updates, new_opt_state = tx.update(grads, state.opt_state,
                                               state.params)
            new_params = optax.apply_updates(state.params, updates)

            if fp16:
                keep = lambda new, old: jax.tree.map(
                    lambda n, o: jnp.where(overflow, o, n), new, old)
                new_params = keep(new_params, state.params)
                new_opt_state = keep(new_opt_state, state.opt_state)
                new_scale = scaler.update(state.loss_scale, overflow)
            else:
                new_scale = state.loss_scale
            if guard_nonfinite and not fp16:
                bad = ~jnp.isfinite(mean_loss)
                hold = lambda new, old: jax.tree.map(
                    lambda n, o: jnp.where(bad, o, n), new, old)
                new_params = hold(new_params, state.params)
                new_opt_state = hold(new_opt_state, state.opt_state)

            new_state = TrainState(
                params=new_params, opt_state=new_opt_state,
                step=state.step + jnp.where(overflow, 0, 1),
                loss_scale=new_scale,
                skipped_steps=state.skipped_steps + jnp.where(overflow, 1, 0),
                comm_state=new_comm)
            metrics = {
                "loss": mean_loss,
                "grad_norm": grad_norm,
                "lr": jnp.asarray(schedule(state.step), jnp.float32),
                "loss_scale": state.loss_scale.scale,
                "overflow": overflow,
            }
            coll = numerics.active()
            if coll is not None:
                # grad-path health sliced from THIS step's existing
                # pytrees (no extra forward): per-module grad norms, the
                # per-layer [L] norm vector, update/param ratios
                if coll.want_probes:
                    for k, v in numerics.grad_stats(
                            grads, updates, state.params).items():
                        coll.add(k, v)
                aux = coll.harvest()
                if aux:
                    metrics = dict(metrics, numerics=aux)
            return new_state, metrics

        state_shardings = self._state_shardings(self.state)
        batch_sharding = NamedSharding(self.mesh, PartitionSpec(DP_AXES))
        onebit_now = self.onebit_enabled if onebit is None else bool(onebit)
        site = ("engine/train_step" if numerics_mode is None
                else f"engine/train_step_{numerics_mode}")
        return self._jit(
            step_fn, site,
            # the documented recompile hazards, named so a recompile's
            # cause diff says WHICH boundary was crossed: tail-batch gas,
            # the 1-bit warmup edge, the active LTD keep bucket
            static_context={
                "gas": self.gradient_accumulation_steps,
                "onebit": onebit_now,
                "ltd_keep": getattr(self.module, "ltd_keep", None),
                **({"numerics": numerics_mode} if numerics_mode else {}),
            },
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,))

    def _build_grad_step(self):
        """Offload mode: the device program ends at clipped grads + metrics;
        the optimizer update happens on the host (C++ CPU Adam)."""
        fp16 = self.fp16_enabled
        schedule = self._schedule
        scaler = self.loss_scaler
        core = self._grad_core()
        policy = self.policy
        base_specs = self.base_specs

        wire_bf16 = (self.offload_opt is not None
                     and self.offload_opt.wire_bf16)

        def grad_fn(state: TrainState, batch):
            grads, mean_loss, overflow, grad_norm, _ = core(state, batch)
            # land grads in the host-partition (opt-state) layout: each
            # process's d2h pull is exactly its master slice — reduce-scatter
            # over DP instead of all-reduce whenever stage >= 1
            grads = policy.apply_offload_grad_constraints(grads, base_specs)
            if wire_bf16:
                # bf16 grad wire (reference sends fp16 grads to the CPU
                # optimizer): halves d2h bytes; accumulation stayed fp32
                grads = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)
            new_scale = (scaler.update(state.loss_scale, overflow)
                         if fp16 else state.loss_scale)
            metrics = {
                "loss": mean_loss,
                "grad_norm": grad_norm,
                "lr": jnp.asarray(schedule(state.step), jnp.float32),
                "loss_scale": state.loss_scale.scale,
                "overflow": overflow,
            }
            return grads, metrics, new_scale

        state_shardings = self._state_shardings(self.state)
        batch_sharding = NamedSharding(self.mesh, PartitionSpec(DP_AXES))
        return self._jit(
            grad_fn, "engine/grad_step",
            static_context={"gas": self.gradient_accumulation_steps,
                            "wire_bf16": wire_bf16},
            in_shardings=(state_shardings, batch_sharding))

    def _offload_train_step(self, batch) -> Dict[str, Any]:
        if self._train_step_fn is None:
            self._train_step_fn = self._build_grad_step()
        grads, metrics, new_scale = self._train_step_fn(self.state, batch)
        overflow = bool(metrics["overflow"]) if self.fp16_enabled else False
        st = self.state
        if overflow:
            self.state = st._replace(
                loss_scale=new_scale,
                skipped_steps=st.skipped_steps + 1)
        else:
            new_params = self.offload_opt.step(grads, int(st.step))
            self.state = st._replace(params=new_params, step=st.step + 1,
                                     loss_scale=new_scale)
        return metrics

    # ------------------------------------------------------------------
    # idiomatic API — one call per optimizer step
    # ------------------------------------------------------------------

    def _feed_batch(self, batch):
        """Assemble the GLOBAL batch under multi-controller execution.

        Single process: pass through (the jit's in_shardings place it).
        Multi-process (``jax.process_count() > 1``): host leaves are this
        process's LOCAL rows — the per-rank slice its dataloader produced,
        the reference's per-rank batch feeding — and are assembled into
        global dp-sharded arrays via
        ``jax.make_array_from_process_local_data``; leaves that are already
        global jax.Arrays pass through untouched."""
        if jax.process_count() == 1:
            return batch
        from ..parallel.mesh import global_feed

        sh = NamedSharding(self.mesh, PartitionSpec(DP_AXES))
        return jax.tree.map(lambda x: global_feed(x, sh), batch)

    def _dispatch_train_step(self, batch) -> Dict[str, Any]:
        """Route the (assembled, global) batch to the right compiled-step
        family and return its metrics."""
        if self.infinity is not None:
            metrics = self.infinity.train_step(batch)
            stepped = 0 if bool(metrics.get("overflow", False)) else 1
            self.state = self.state._replace(
                params=self.infinity.resident,
                step=self.state.step + stepped)
        elif self.offload_enabled:
            metrics = self._offload_train_step(batch)
        elif (self.onebit_enabled
              and self.global_steps < self.onebit_freeze_step):
            # 1-bit warmup phase: full-precision DP reduction until
            # freeze_step (reference OnebitAdam semantics)
            if self._warmup_step_fn is None:
                self._warmup_step_fn = self._build_train_step(onebit=False)
            self.state, metrics = self._warmup_step_fn(self.state, batch)
        elif self._ltd_cfg is not None:
            # random-LTD: pick this step's keep bucket, (re)use its program
            from .data_pipeline.random_ltd import RandomLTDScheduler

            seq = jax.tree.leaves(batch)[0].shape[1]
            if self._ltd_sched is None or seq > self._ltd_sched.seq_len:
                # rebuild on longer sequences: a curriculum-truncated FIRST
                # batch must not cap the keep schedule for the whole run
                self._ltd_sched = RandomLTDScheduler(self._ltd_cfg, seq)
            keep = min(self._ltd_sched.keep_count(self.global_steps), seq)
            self.module.ltd_keep = None if keep >= seq else keep
            key = keep if keep < seq else -1
            if key not in self._ltd_fns:
                self._ltd_fns[key] = self._build_train_step()
            self.state, metrics = self._ltd_fns[key](self.state, batch)
        else:
            fn, coll = self._select_numerics_step()
            if fn is not None:
                # sampled numerics capture: the variant's own jit site —
                # the base step's compile cache is untouched, and the
                # collector is active for the trace (and harmlessly for
                # every cached call after it)
                with numerics.collecting(coll):
                    self.state, metrics = fn(self.state, batch)
            else:
                if self._train_step_fn is None:
                    self._train_step_fn = self._build_train_step()
                self.state, metrics = self._train_step_fn(self.state, batch)
        return metrics

    def _select_numerics_step(self):
        """(step_fn, collector) when the numerics plane samples THIS
        step, else (None, None).  Full captures need ``numerics.enabled``;
        with the plane off but ``moe_gauges`` on, a MoE model still gets
        its routing telemetry (satellite: gate stats are never discarded)
        through the lighter ``engine/train_step_moe`` variant.  Only the
        plain dispatch path samples — infinity/offload/1-bit-warmup/LTD
        keep their own programs probe-free."""
        ncfg = self._numerics_cfg
        every = int(ncfg.every)
        if self.fused_adam_enabled or every <= 0 \
                or (self.global_steps + 1) % every:
            return None, None
        if ncfg.enabled:
            if self._numerics_step_fn is None:
                self._numerics_step_fn = self._build_train_step(
                    numerics_mode="numerics")
            return self._numerics_step_fn, numerics.Collector(
                probes=True, moe=True, tag="sample")
        if ncfg.moe_gauges and getattr(self.module, "_moe_layer",
                                       None) is not None:
            if self._moe_step_fn is None:
                self._moe_step_fn = self._build_train_step(
                    numerics_mode="moe")
            return self._moe_step_fn, numerics.Collector(
                probes=False, moe=True, tag="moe")
        return None, None

    def _ingest_numerics_capture(self, named: Dict[str, Any]) -> None:
        """Host-side decode of a sampled capture: ``numerics/*`` and
        ``moe/*`` gauges, the summary staged for this step's
        ``StepRecord.extra['numerics']`` (the health rules' input), and
        the full per-probe table into the debug-bundle context."""
        try:
            decoded = numerics.decode(named)
        except Exception as e:  # telemetry must never kill the step
            logger.error(f"numerics: capture decode failed: {e!r}")
            return
        summary = numerics.summarize(decoded)
        first = numerics.first_nonfinite(decoded["probes"],
                                         decoded["order"])
        self._numerics_context = {
            "step": self.global_steps, "first_nonfinite": first,
            "summary": summary,
            **{k: decoded[k] for k in ("probes", "order", "grads",
                                       "update_ratio", "moe")}}
        extra = dict(summary)
        if first:
            extra["first_nonfinite"] = first
        self._last_numerics = extra
        for key in ("underflow_frac", "saturated_frac", "zero_frac",
                    "absmax", "nonfinite_total", "layer_grad_max"):
            if key in summary:
                self.telemetry.set_gauge(
                    f"numerics/{key}", float(summary[key]),  # dslint: disable=host-sync-hot-path — decode() already pulled the capture; these are host floats
                    help="worst-case probe stat of the last sampled "
                         "numerics capture")
        for src, name in (("gate_entropy", "moe/gate_entropy"),
                          ("moe_drop_rate", "moe/drop_rate"),
                          ("moe_overflow_frac", "moe/overflow_frac"),
                          ("moe_load_imbalance", "moe/load_imbalance")):
            if src in summary:
                self.telemetry.set_gauge(
                    name, float(summary[src]),  # dslint: disable=host-sync-hot-path — same: post-decode host floats
                    help="MoE gate telemetry from the last sampled step")

    def _numerics_forensic_capture(self, batch):
        """Probes-on loss forward on the failed ``(params, batch)`` —
        its own jit site, compiled only on the first failure ever."""
        if self._forensic_fwd_fn is None:
            loss_fn = self.loss_fn
            dtype = self.compute_dtype

            def fwd(params, b):
                p = (cast_tree(params, dtype)
                     if dtype != jnp.float32 else params)
                mark = numerics.scan_mark()
                loss = loss_fn(p, b)
                aux = numerics.scan_drain(mark)
                return loss, (aux or {})

            self._forensic_fwd_fn = self._jit(fwd,
                                              "engine/numerics_forensics")
        coll = numerics.Collector(probes=True, moe=True, tag="forensic")
        with numerics.collecting(coll):
            loss, aux = self._forensic_fwd_fn(self.state.params, batch)
        return loss, aux

    def _run_nonfinite_forensics(self, batch, loss_val: float) -> None:
        """Non-finite loss seen: re-run the forward with every probe on
        and localize the first bad tensor in program order.  The report
        is staged for the nan_loss health event and the resilience
        rollback annotation; the bundle gets ``numerics.json``."""
        try:
            _, aux = self._numerics_forensic_capture(batch)
            report = numerics.report_from_capture(
                aux, self.global_steps, loss_val,
                recorder=self.flight_recorder)
        except Exception as e:  # forensics must not mask the failure
            logger.error(f"numerics: forensic capture failed: {e!r}")
            return
        self._last_nonfinite_report = report
        self._numerics_context = report.report
        summary = dict(report.report.get("summary") or {})
        summary["forensic"] = 1.0
        if report.report.get("first_nonfinite"):
            summary["first_nonfinite"] = report.report["first_nonfinite"]
        self._last_numerics = summary
        logger.error(f"numerics: {report}")

    def train_step(self, batch) -> Dict[str, Any]:
        """Run ONE full optimizer step (fwd+bwd over all microbatches + update)
        as a single compiled program.  ``batch`` holds the full global batch
        (micro × gas × dp_world leading dim) — or, multi-process, this
        process's local rows (see :meth:`_feed_batch`)."""
        self.tput_timer.start()
        t_step0 = time.perf_counter()
        plane = self._profiler_plane
        if plane is not None:
            # fleet profiler window arm/disarm (ISSUE 20) — outside the
            # jitted program; two attribute reads when nothing is armed
            plane.on_step(self.global_steps)
        batch = self._feed_batch(batch)
        if self.snapshots is not None and self.snapshots.snapshots_taken == 0:
            # step-0 baseline: a failure inside the FIRST snapshot
            # interval must roll back to init, not give up for want of
            # any snapshot at all
            self.snapshots.take()
        if self.fault_injector is not None:
            # chaos harness: fire any fault scheduled for THIS step
            # (kill/stall/NaN-poison/corrupt-snapshot) before dispatch
            batch = self.fault_injector.apply(self.global_steps + 1, batch,
                                              engine=self)
        trk = self.compile_tracker
        if trk is not None:
            # marks for per-step compile attribution: whatever the
            # tracker records between here and the fence happened INSIDE
            # this step's wall time
            _c_ev0, _c_rc0 = trk.events_total, trk.recompiles_total
            _c_ms0 = trk.time_ms_total
        _stall0_s = (self.goodput.totals()["stall"]
                     if self.goodput is not None else 0.0)
        fenced = (self.config.wall_clock_breakdown
                  or self._autotuning_fence
                  or (self._telemetry_steps and self._telemetry_fence))
        try:
            with self.telemetry.span("engine/train_step",
                                     args={"step": self.global_steps}):
                metrics = self._dispatch_train_step(batch)
            # the sampled numerics aux rides the metrics pytree out of
            # the jitted step — peel it off before anything float()s or
            # iterates the metrics dict
            numerics_aux = (metrics.pop("numerics", None)
                            if isinstance(metrics, dict) else None)
            if fenced:
                # breakdown/autotuning/telemetry trade throughput for
                # truth (the reference inserts barriers the same way): a
                # scalar fetch is the only reliable fence, so timers and
                # StepRecords see DEVICE step time, not dispatch time —
                # and it is also where an async RESOURCE_EXHAUSTED from
                # this step's program surfaces
                float(metrics["loss"])  # dslint: disable=host-sync-hot-path — the fence IS the point
        except Exception as e:
            from ..telemetry.memory.oom import handle_oom, is_oom_error

            if self.memory_ledger is None or not is_oom_error(e):
                raise
            # OOM forensics: ledger breakdown + top live arrays into the
            # debug bundle (memory.json), re-raised as a descriptive
            # error naming the top pools instead of a raw XLA traceback
            raise handle_oom(e, recorder=self.flight_recorder,
                             step=self.global_steps) from e
        step_time_s = time.perf_counter() - t_step0
        compile_ms, compile_events, recompile_events = 0.0, 0, 0
        if trk is not None:
            compile_events = trk.events_total - _c_ev0
            recompile_events = trk.recompiles_total - _c_rc0
            compile_ms = trk.time_ms_total - _c_ms0
        #: this step spent most of its wall time in XLA lower/compile —
        #: excluded from the watchdog EWMA and the health throughput
        #: window (a first-step or rebucketing compile must not skew
        #: straggler ratios or trip a false throughput regression)
        compile_dominated = (
            compile_ms > 0.0
            and compile_ms >= self._compile_dominated_frac
            * step_time_s * 1e3)
        if self.goodput is not None:
            # any stall the watchdog charged DURING this step (a tripped
            # hang that later unblocked) is already accounted — charge
            # only the remainder, or the interval would count twice
            stalled_s = self.goodput.totals()["stall"] - _stall0_s
            self.goodput.add_step(max(step_time_s - stalled_s, 0.0),
                                  compile_ms / 1e3)
        self.tput_timer.stop(sync=False)
        from ..utils import debug as _debug

        if _debug.enabled():
            _debug.check_step(metrics)
        self.global_steps += 1
        result_path = os.environ.get("DS_AUTOTUNING_RESULT")
        if (result_path and self.global_steps
                == int(os.environ.get("DS_AUTOTUNING_STEPS", "8"))):
            # candidate profiling run under `deepspeed --autotuning`: every
            # step was fenced above (_autotuning_fence), so per-step
            # timings are device times; report and let the orchestrator
            # reap the process
            import json as _json

            float(metrics["loss"])  # drain any unfenced tail  # dslint: disable=host-sync-hot-path
            t = self.tput_timer
            tmp = result_path + ".tmp"
            with open(tmp, "w") as f:
                _json.dump({"samples_per_sec": t.samples_per_sec(),
                            "avg_step_time_s": t.avg_step_time(),
                            "steps": self.global_steps}, f)
            os.replace(tmp, result_path)  # atomic: no torn reads
        self.lr_scheduler.last_step = self.global_steps
        self.last_metrics = metrics
        if numerics_aux:
            # one device→host pull of a few hundred floats, sampled
            # steps only: decode, publish gauges, stage the summary for
            # this step's record and the bundle context
            self._ingest_numerics_capture(numerics_aux)
        if self._numerics_cfg.enabled and self._numerics_cfg.forensic_on_nan:
            try:
                _lv = float(metrics["loss"])  # dslint: disable=host-sync-hot-path — NaN triage needs the scalar
            except Exception:
                _lv = 0.0
            if not np.isfinite(_lv):
                # forensic capture BEFORE the record/health/resilience
                # consumers run, so the nan_loss event and the rollback
                # annotation can NAME the first bad layer
                self._run_nonfinite_forensics(batch, _lv)
        if self.watchdog is not None:
            # a completed step IS progress (the daemon started at build);
            # a compile-dominated step still notifies but contributes no
            # EWMA sample — its time was the compiler's, not the step's
            self.watchdog.notify_progress(
                self.global_steps,
                None if compile_dominated else step_time_s)
        if self._telemetry_steps:
            self._record_step_telemetry(
                batch, metrics, step_time_s, fenced,
                compile_ms=compile_ms, compile_events=compile_events,
                recompile_events=recompile_events)
        rolled_back = False
        if self.resilience is not None:
            # recovery policy: a NaN'd loss / scale collapse rolls the
            # engine back to the last good snapshot (the offending data
            # window is skipped — this batch is never refed); healthy
            # steps feed the snapshot cadence instead.  observe_step
            # pulls the loss scalar — resilience trades overlap for
            # catching the NaN before it ages another interval.
            rolled_back = self.resilience.observe_step(
                metrics, self._last_health_events)
            if rolled_back:
                metrics = dict(metrics, rolled_back=True)
            else:
                self.snapshots.maybe_snapshot()
        if not rolled_back and self.steps_per_print and self.global_steps \
                % int(self.steps_per_print) == 0:
            # printing requires the values; the pull is gated to the
            # steps_per_print cadence
            m = {k: float(v) for k, v in metrics.items()}  # dslint: disable=host-sync-hot-path
            line = (f"step={self.global_steps} loss={m['loss']:.4f} "
                    f"lr={m['lr']:.3e} grad_norm={m['grad_norm']:.3f} "
                    f"loss_scale={m['loss_scale']:.0f}")
            if self.config.wall_clock_breakdown:
                # fused-step engine: fwd/bwd/step are ONE program, so the
                # reference's per-phase split collapses to step wall time +
                # throughput (+ a memory line, the other half of the
                # reference's breakdown prints)
                from ..utils.memory import memory_status

                t = self.tput_timer
                mem = memory_status()
                line += (f" | step_time={t.avg_step_time() * 1e3:.1f}ms "
                         f"samples/s={t.samples_per_sec():.1f} "
                         f"hbm={mem.get('device_in_use_GB', 0):.2f}GB")
            log_dist(line)
        if self.monitor is not None and not rolled_back:
            # a rolled-back step's metrics are the FAILED step's (NaN
            # loss) while global_steps already points at the restored
            # step — logging them would stamp a NaN onto a healthy step
            self.monitor.write_events(
                [(f"Train/{k}", v, self.global_steps)
                 for k, v in metrics.items()
                 if k not in ("overflow", "rolled_back")])
        fp = self.config.flops_profiler
        if fp.enabled and self.global_steps == int(fp.profile_step):
            self._emit_module_profile(batch, fp)
        return metrics

    def _record_step_telemetry(self, batch, metrics: Dict[str, Any],
                               step_time_s: float, fenced: bool,
                               compile_ms: float = 0.0,
                               compile_events: int = 0,
                               recompile_events: int = 0) -> None:
        """Assemble + publish this step's :class:`~..telemetry.StepRecord`
        (the numbers are device-true when ``fenced``; the float() pulls
        below force the same sync anyway)."""
        from ..comm.comm import comms_logger
        from ..telemetry import StepRecord, collect_memory_stats

        leaves = [l for l in jax.tree.leaves(batch)
                  if getattr(l, "ndim", 0) >= 1]
        rows = int(leaves[0].shape[0]) if leaves else 0
        seq = (int(leaves[0].shape[1])
               if leaves and leaves[0].ndim >= 2 else 1)
        dt = max(step_time_s, 1e-9)
        tflops = mfu = 0.0
        # rate/TFLOPS/MFU fields only when the step was fenced: an
        # unfenced step_time is host DISPATCH time, and a rate derived
        # from it would overstate throughput by orders of magnitude
        if self.flops_per_step and fenced:
            tflops = self.flops_per_step / dt / 1e12
            try:
                from ..profiling.flops_profiler.profiler import (
                    peak_flops_per_chip)

                peak = float(peak_flops_per_chip())
                if peak > 0:
                    mfu = self.flops_per_step / dt / peak
            except Exception as e:  # unknown device kind — MFU stays None
                from ..utils.logging import debug_once

                debug_once("telemetry/mfu_peak",
                           f"peak-FLOPs lookup failed ({e!r}); "
                           f"StepRecord.mfu omitted")
        nan = float("nan")
        extra: Dict[str, Any] = {}
        if compile_events or compile_ms:
            # compile attribution (telemetry/perf): lets the health
            # monitor exclude compile-dominated steps from the
            # throughput window and operators see where step N's wall
            # time actually went
            extra["compile_ms"] = round(compile_ms, 3)
            extra["compile_events"] = int(compile_events)
            extra["recompile_events"] = int(recompile_events)
        if self.memory_ledger is not None:
            # per-step memory plane numbers ride extra (ISSUE 7):
            # peak_hbm_bytes / hbm_frac / host_rss_bytes / swap_io_bytes
            # (+ a live-array census every _mem_census_every steps) — the
            # health monitor's memory_pressure and host_memory_leak
            # rules read exactly these fields
            census = (self._mem_census_every > 0
                      and self.global_steps % self._mem_census_every
                      == 1 % self._mem_census_every)  # every=1 → each step
            extra.update(self.memory_ledger.step_sample(live_census=census))
        if self._last_anatomy is not None:
            # the capture's compact summary rides the NEXT step record
            # once (anatomy plane) — bundles and the rollup see where
            # the traced window's device time went
            extra["anatomy"] = self._last_anatomy
            self._last_anatomy = None
        if self._last_numerics is not None:
            # this step's sampled/forensic capture summary — the
            # underflow_creep / layer_grad_explosion / router_collapse
            # health rules read exactly these keys
            extra["numerics"] = self._last_numerics
            self._last_numerics = None
        if comms_logger.enabled and comms_logger.exec_counts:
            # THIS step's execution-probe activity: shard-normalized
            # cumulative totals (satellite: no more hand-dividing by
            # jax.local_device_count()), diffed against the previous
            # record's snapshot; clamped so a mid-run logger reset
            # can't go negative
            eops, ebytes = comms_logger.exec_totals(per_step=True)
            prev = self._last_exec_totals
            self._last_exec_totals = (eops, ebytes)
            extra["comm_exec_ops"] = max(0.0, eops - prev[0])
            extra["comm_exec_bytes"] = max(0.0, ebytes - prev[1])
        rec = StepRecord(
            step=self.global_steps,
            step_time_ms=step_time_s * 1e3,
            device_fenced=bool(fenced),
            samples_per_sec=rows / dt if fenced else 0.0,
            tokens_per_sec=rows * seq / dt if fenced else 0.0,
            # unfenced mode is the ASYNC-recording path (device_fence:
            # false buys back dispatch/execute overlap) — scalar pulls
            # would block on the step, so metric fields stay NaN there
            loss=float(metrics.get("loss", 0.0)) if fenced else nan,
            grad_norm=float(metrics.get("grad_norm", 0.0)) if fenced
            else nan,
            lr=float(metrics.get("lr", 0.0)) if fenced else nan,
            loss_scale=float(metrics.get("loss_scale", 1.0)) if fenced
            else nan,
            overflow=bool(metrics.get("overflow", False)) if fenced
            else False,
            skipped_steps=int(self.state.skipped_steps) if fenced else -1,
            comm_bytes=comms_logger.total_bytes(),
            comm_ops=comms_logger.total_ops(),
            tflops=tflops, mfu=mfu,
            # with the memory ledger on, reuse the device/host readings
            # step_sample just took (and its census already rode extra)
            # — the record must not pay memory_stats + procfs twice;
            # without it, the legacy path with its 16-step census
            memory=(self.memory_ledger.status(cached=True)
                    if self.memory_ledger is not None
                    else collect_memory_stats(
                        include_live_buffers=self.global_steps % 16 == 1)),
            extra=extra)
        self.last_step_record = rec
        self.step_records.append(rec)
        self.telemetry.record_step(rec)
        if self.flight_recorder is not None:
            self.flight_recorder.record_step(rec)
        if self.health is not None:
            events = self.health.observe(rec)
            self._last_health_events = events  # resilience policy input
            if events and self.monitor is not None:
                self.monitor.write_health_events(events)

    def _emit_module_profile(self, batch, fp) -> None:
        """One-shot per-module flops/latency table at ``profile_step``
        (reference FlopsProfiler behavior, SURVEY §2.5)."""
        try:
            from ..profiling.flops_profiler.profiler import (
                format_module_table, profile_model_modules)

            rows = profile_model_modules(
                self.module, self.state.params, batch,
                module_depth=int(fp.module_depth),
                top_modules=int(fp.top_modules) if not fp.detailed else 0)
            text = format_module_table(rows)
            if fp.output_file:
                with open(fp.output_file, "w") as f:
                    f.write(text + "\n")
            log_dist("flops profiler (per-module, step "
                     f"{self.global_steps}):\n{text}")
        except Exception as e:
            logger.warning(f"flops profiler: per-module table unavailable "
                           f"({e})")

    def eval_loss(self, batch) -> jnp.ndarray:
        batch = self._feed_batch(batch)
        if self.infinity is not None:
            return self.infinity.eval_loss(batch)
        if self._eval_loss_fn is None:
            dtype = self.compute_dtype

            def fwd(params, b):
                p = cast_tree(params, dtype) if dtype != jnp.float32 else params
                return self.loss_fn(p, b)

            self._eval_loss_fn = self._jit(fwd, "engine/eval_loss")
        return self._eval_loss_fn(self.state.params, batch)

    # ------------------------------------------------------------------
    # autotuning trial hook (tuning/ — ISSUE 9)
    # ------------------------------------------------------------------

    def trial_run(self, batch, warmup_steps: int = 1,
                  timed_steps: int = 3) -> Dict[str, Any]:
        """Run ``warmup_steps`` + ``timed_steps`` optimizer steps with a
        per-step device fence and return a telemetry-sourced summary for
        the tuning plane: tokens/sec and step-time p50 from this
        engine's OWN device-fenced StepRecords (falling back to the
        fenced wall clock when telemetry is off), MFU when
        ``flops_per_step`` is set, the window's compile cost from the
        compile tracker (already charged to the goodput ``compile``
        bucket by ``train_step``), and the memory ledger's per-step
        HBM numbers.  The per-step loss fetch is the fence — on
        tunneled platforms ``block_until_ready`` is a no-op, so this is
        the only number that measures the DEVICE."""
        warmup_steps = max(int(warmup_steps), 0)
        timed_steps = max(int(timed_steps), 1)
        trk = self.compile_tracker
        ev0 = trk.events_total if trk is not None else 0
        ms0 = trk.time_ms_total if trk is not None else 0.0
        for _ in range(warmup_steps):
            m = self.train_step(batch)
            float(m["loss"])  # warmup fence: compiles stay out of timing
        mark = (self.step_records[-1].step if self.step_records
                else self.global_steps)
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            m = self.train_step(batch)
            float(m["loss"])  # the per-step fence IS the measurement
        wall_s = time.perf_counter() - t0
        leaves = [l for l in jax.tree.leaves(batch)
                  if getattr(l, "ndim", 0) >= 1]
        rows = int(leaves[0].shape[0]) if leaves else 0
        seq = (int(leaves[0].shape[1])
               if leaves and leaves[0].ndim >= 2 else 1)
        out: Dict[str, Any] = {"timed_steps": timed_steps,
                               "wall_s": wall_s}
        recs = [r for r in self.step_records
                if r.step > mark and r.device_fenced]
        if recs:
            times = sorted(r.step_time_ms for r in recs)
            tps = sorted(r.tokens_per_sec for r in recs)
            out["source"] = "telemetry"
            out["step_time_p50_ms"] = times[len(times) // 2]
            out["tokens_per_sec"] = tps[len(tps) // 2]
            sps = sorted(r.samples_per_sec for r in recs)
            out["samples_per_sec"] = sps[len(sps) // 2]
            mfus = sorted(r.mfu for r in recs if r.mfu)
            if mfus:
                out["mfu"] = mfus[len(mfus) // 2]
            mem = recs[-1].extra or {}
            for k in ("peak_hbm_bytes", "hbm_headroom_frac"):
                if k in mem:
                    out[k] = mem[k]
        else:
            dt = wall_s / timed_steps
            out["source"] = "wall_clock"
            out["step_time_p50_ms"] = dt * 1e3
            out["samples_per_sec"] = rows / max(dt, 1e-9)
            out["tokens_per_sec"] = rows * seq / max(dt, 1e-9)
        if trk is not None:
            out["compile_events"] = trk.events_total - ev0
            out["compile_s"] = (trk.time_ms_total - ms0) / 1e3
        if self.memory_ledger is not None and "peak_hbm_bytes" not in out:
            sample = self.memory_ledger.step_sample()
            for k in ("peak_hbm_bytes", "hbm_headroom_frac"):
                if k in sample:
                    out[k] = sample[k]
        if self.cost_ledger is not None and "step_time_p50_ms" in out:
            # roofline headroom (anatomy plane): 1 - predicted/measured
            # for the step program — the tuning tie-breaker (a config
            # near its roofline is fast BECAUSE of the hardware, not by
            # accident of an unexplained stall going quiet this trial)
            head = self.cost_ledger.headroom(
                self._anatomy_site(), out["step_time_p50_ms"] * 1e3)
            if head is not None:
                out["roofline_headroom"] = head
        return out

    def _anatomy_site(self) -> str:
        """The tracked jit site of the CURRENT step program (offload
        engines step through grad_step; everyone else the fused step)."""
        if self.cost_ledger is not None:
            for site in ("engine/train_step_fused", "engine/train_step",
                         "engine/grad_step"):
                if self.cost_ledger.entry_for(site):
                    return site
        return "engine/train_step_fused"

    def capture_anatomy(self, batch, steps: Optional[int] = None,
                        trace_dir: Optional[str] = None,
                        feed_census: Optional[bool] = None
                        ) -> Dict[str, Any]:
        """Step anatomy (ISSUE 17): trace ``steps`` fenced train steps
        under ONE shared profiler session and return the attribution
        summary — compute / exposed-collective / overlapped-collective /
        host-sync buckets, measured overlap hiding, and the roofline
        predicted-vs-measured join for this engine's step program.

        The exec-order census (when ``aggregation.ledger_exec_feed`` is
        on, or ``feed_census=True``) is fed from the SAME trace — one
        profiler window serves both consumers; nested sessions raise in
        jax, so this is the only safe composition.  The compact summary
        also lands on the next StepRecord's ``extra['anatomy']``, the
        ``anatomy/*`` gauges, and the debug-bundle context.
        """
        from ..telemetry.anatomy import capture_step_anatomy
        from ..telemetry.anatomy.ledger import get_cost_ledger

        cfg = self._anatomy_cfg
        n = int(steps if steps is not None
                else cfg.anatomy_capture_steps)
        if feed_census is None:
            feed_census = bool(getattr(
                self.config.telemetry.aggregation, "ledger_exec_feed",
                False))
        ledger = self.cost_ledger or get_cost_ledger()

        def _one(b):
            m = self.train_step(b)
            float(m["loss"])  # the per-step fence IS the window edge
            return m["loss"]

        summary = capture_step_anatomy(
            _one, batch, steps=n, trace_dir=trace_dir,
            site=self._anatomy_site(), ledger=ledger,
            top_k=int(cfg.anatomy_top_k), feed_census=feed_census)
        if not summary.get("deferred"):
            compact = {k: summary.get(k) for k in (
                "window_us", "steps", "compute_us", "coll_exposed_us",
                "coll_overlapped_us", "host_sync_us", "idle_us",
                "comm_fraction", "overlap_hiding_frac",
                "attributed_frac", "roofline_top")}
            self._last_anatomy = compact
            self.telemetry.set_gauge(
                "anatomy/comm_fraction",
                float(summary.get("comm_fraction") or 0.0),
                help="exposed-collective fraction of step wall time")
            if summary.get("overlap_hiding_frac") is not None:
                self.telemetry.set_gauge(
                    "anatomy/overlap_hiding_frac",
                    float(summary["overlap_hiding_frac"]),
                    help="collective time hidden under compute")
            self.telemetry.set_gauge(
                "anatomy/attributed_frac",
                float(summary.get("attributed_frac") or 0.0),
                help="fenced step time the trace explains")
        return summary

    # ------------------------------------------------------------------
    # DeepSpeed compat surface: forward / backward / step
    # ------------------------------------------------------------------

    def forward(self, batch):
        """Compat fwd: record the microbatch, return its loss (lazy array)."""
        self._pending_batch = batch
        return self.eval_loss(batch)

    __call__ = forward

    def backward(self, loss=None):
        """Compat bwd: queue the pending microbatch for the fused step.
        The actual gradient computation happens inside the compiled program
        fired by :meth:`step` at the accumulation boundary."""
        if self._pending_batch is None:
            raise RuntimeError("backward() called without a prior forward()")
        self._microbatch_buffer.append(self._pending_batch)
        self._pending_batch = None
        self.micro_steps += 1
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        if self._accumulation_boundary_forced is not None:
            return self._accumulation_boundary_forced
        return len(self._microbatch_buffer) >= self.gradient_accumulation_steps

    def set_gradient_accumulation_boundary(self, is_boundary: bool) -> None:
        """[L ACC-DS:264-281] external override of the GAS boundary."""
        self._accumulation_boundary_forced = is_boundary

    def step(self):
        """Compat step: no-op until the accumulation boundary, then fire the
        compiled train step over the buffered microbatches."""
        if not self.is_gradient_accumulation_boundary():
            return
        if not self._microbatch_buffer:
            return
        buffered = self._microbatch_buffer
        self._microbatch_buffer = []
        n = len(buffered)
        batch = (buffered[0] if n == 1 else
                 jax.tree.map(lambda *xs: jnp.concatenate(xs), *buffered))
        if n == self.gradient_accumulation_steps:
            return self.train_step(batch)
        # partial accumulation (forced boundary): the program bakes GAS
        # in, so n needs its own — built once per distinct n and CACHED
        # (round-3 weak item 7: a workload that forces the same partial
        # boundary every epoch must not pay a recompile each time)
        logger.warning(f"stepping with {n} buffered microbatches "
                       f"(configured GAS={self.gradient_accumulation_steps})")
        saved_gas, saved_fn = self.gradient_accumulation_steps, self._train_step_fn
        saved_warm = self._warmup_step_fn
        saved_ltd = self._ltd_fns
        saved_inf_gas = self.infinity.gas if self.infinity is not None else None
        self.gradient_accumulation_steps = n
        if self.infinity is not None:
            self.infinity.gas = n  # the streaming executor baked its own
        # every GAS-baking program family gets a per-n cache entry —
        # warmup (1-bit) and LTD programs recompile per n too
        cached = self._partial_step_fns.get(n, (None, None, {}))
        self._train_step_fn, self._warmup_step_fn, self._ltd_fns = cached
        try:
            return self.train_step(batch)
        finally:
            self._partial_step_fns[n] = (self._train_step_fn,
                                         self._warmup_step_fn,
                                         self._ltd_fns)
            self.gradient_accumulation_steps = saved_gas
            if self.infinity is not None:
                self.infinity.gas = saved_inf_gas
            self._train_step_fn = saved_fn
            self._warmup_step_fn = saved_warm
            self._ltd_fns = saved_ltd

    # ------------------------------------------------------------------
    # introspection parity
    # ------------------------------------------------------------------

    def get_global_grad_norm(self) -> Optional[float]:
        if "grad_norm" not in self.last_metrics:
            return None
        return float(self.last_metrics["grad_norm"])

    def get_lr(self) -> List[float]:
        # state.step excludes overflow-skipped steps — it is the step the
        # compiled program actually fed to the schedule (global_steps counts
        # skips too and would drift ahead after any fp16 overflow).
        applied_step = int(self.state.step)
        self.lr_scheduler.last_step = applied_step
        return [float(self._schedule(applied_step))]

    def get_loss_scale(self) -> float:
        return float(self.state.loss_scale.scale)

    @property
    def overflow(self) -> bool:
        """fp16 skip signal of the LAST step [L ACC-DS:306-319]."""
        if "overflow" not in self.last_metrics:
            return False
        return bool(self.last_metrics["overflow"])

    @property
    def skipped_steps(self) -> int:
        return int(self.state.skipped_steps)

    def zero_grad(self) -> None:
        pass  # grads are step-local values in a functional engine

    def allreduce_gradients(self) -> None:
        pass  # GSPMD inserts DP grad reduction inside the compiled step

    def train(self, mode: bool = True):
        return self

    def eval(self):
        return self

    def compile(self, backend: Any = None,
                compile_kwargs: Optional[Dict[str, Any]] = None) -> None:
        """Compat [L ACC:2441-2446]: the reference exposes torch.compile
        here; on TPU every step is already an XLA program, so this just
        builds the train-step executable eagerly instead of on first call.
        ``backend``/``compile_kwargs`` accepted and ignored."""
        if (self._train_step_fn is None and not self.offload_enabled
                and self.infinity is None):
            self._train_step_fn = self._build_train_step()
        self.is_compiled = True

    def _zero3_consolidated_16bit_state_dict(
            self, exclude_frozen_parameters: bool = False):
        """Gather the (possibly ZeRO-3-sharded) params into replicated host
        bf16 arrays [L ACC:4042] — device_get assembles the logical array
        regardless of sharding."""
        return jax.tree.map(
            lambda p: np.asarray(jax.device_get(p)).astype(
                jnp.bfloat16 if jnp.issubdtype(p.dtype, jnp.floating)
                else p.dtype),
            self.state.params)

    # checkpointing implemented in runtime/checkpointing.py, attached by entry
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        exclude_frozen_parameters=False):
        from .checkpointing import save_checkpoint as _save

        return _save(self, save_dir, tag=tag, client_state=client_state or {})

    def load_universal_checkpoint(self, universal_dir):
        """Resume from a ``ds_to_universal`` per-parameter directory at
        THIS engine's parallelism layout (reference --load_universal)."""
        from .checkpointing import load_universal_checkpoint

        return load_universal_checkpoint(self, universal_dir)

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True,
                        load_lr_scheduler_states=True,
                        load_module_only=False):
        from .checkpointing import load_checkpoint as _load

        return _load(self, load_dir, tag=tag,
                     load_optimizer_states=load_optimizer_states,
                     load_module_only=load_module_only)
