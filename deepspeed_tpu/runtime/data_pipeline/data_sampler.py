"""Curriculum data sampling.

Reference: ``deepspeed/runtime/data_pipeline/data_sampling/data_sampler.py``
[K] — ``DeepSpeedDataSampler``: difficulty-metric-indexed sampling where
only samples whose difficulty ≤ the scheduler's current value are eligible,
with deterministic shuffling per epoch.  The index-from-metric-files
machinery (MapReduce over tokenized datasets) collapses to "caller supplies
a difficulty value per sample" — the analysis tooling is out of scope, the
*training-time* behavior is the parity surface.

Two curriculum modes, both reference behaviors:

* **sample pools** (``CurriculumSampler``): eligible-sample pool grows with
  difficulty (e.g. vocabulary rarity, external difficulty scores);
* **sequence truncation** (``truncate_batch``): the classic seqlen
  curriculum — batches truncated to the scheduled length (difficulty IS
  the sequence length, reference ``curriculum_learning`` legacy mode).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class CurriculumSampler:
    """Yield sample indices whose difficulty ≤ current schedule value."""

    def __init__(self, difficulties: Sequence[float],
                 scheduler: CurriculumScheduler, seed: int = 1234):
        self.difficulties = np.asarray(difficulties)
        self.order = np.argsort(self.difficulties, kind="stable")
        self.sorted_difficulties = self.difficulties[self.order]
        self.scheduler = scheduler
        self.seed = seed

    def eligible_count(self, step: int) -> int:
        d = self.scheduler.get_difficulty(step)
        return int(np.searchsorted(self.sorted_difficulties, d, side="right"))

    def sample(self, step: int, batch_size: int) -> np.ndarray:
        """Batch of indices drawn uniformly from the eligible pool
        (deterministic in (seed, step))."""
        n = self.eligible_count(step)
        if n == 0:
            raise ValueError("no samples eligible at current difficulty "
                             f"{self.scheduler.get_difficulty(step)}")
        rng = np.random.default_rng((self.seed, step))
        return self.order[rng.integers(0, n, size=batch_size)]


class DeepSpeedDataSampler:
    """Reference-named iterator facade: wraps a dataset + difficulty metric
    into an infinite curriculum batch stream."""

    def __init__(self, dataset: Any, difficulties: Sequence[float],
                 batch_size: int, curriculum_config: Dict[str, Any],
                 seed: int = 1234):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.scheduler = CurriculumScheduler(curriculum_config)
        self.sampler = CurriculumSampler(difficulties, self.scheduler, seed)
        self.global_step = 0

    def set_step(self, step: int) -> None:
        self.global_step = int(step)
        self.scheduler.update_difficulty(step)

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        idx = self.sampler.sample(self.global_step, self.batch_size)
        self.global_step += 1
        batch = [self.dataset[int(i)] for i in idx]
        if isinstance(batch[0], dict):
            return {k: np.stack([b[k] for b in batch]) for k in batch[0]}
        return np.stack(batch)


class CurriculumDataLoader:
    """Wrap any batch iterable with seqlen-curriculum truncation driven by
    the engine's step counter (the legacy ``curriculum_learning`` config's
    runtime behavior: batches shrink to the scheduled difficulty early in
    training and grow back; shapes bucket via ``difficulty_step``)."""

    def __init__(self, loader: Any, scheduler: CurriculumScheduler,
                 step_fn: Any):
        self.loader = loader
        self.scheduler = scheduler
        self.step_fn = step_fn  # () -> current global step

    def __iter__(self) -> Iterator[Any]:
        for batch in self.loader:
            seqlen = self.scheduler.get_difficulty(int(self.step_fn()))
            yield (truncate_batch(batch, seqlen)
                   if isinstance(batch, dict) else batch)

    def __len__(self) -> int:
        return len(self.loader)


def truncate_batch(batch: Dict[str, Any], seqlen: int,
                   keys: Optional[Sequence[str]] = None) -> Dict[str, Any]:
    """Seqlen-curriculum batch post-processor: truncate sequence-shaped
    entries to ``seqlen`` (reference legacy ``curriculum_learning`` applies
    exactly this to input_ids/attention_mask/labels)."""
    keys = keys or ("input_ids", "attention_mask", "labels",
                    "token_type_ids")
    out = dict(batch)
    for k in keys:
        v = out.get(k)
        if v is not None and getattr(v, "ndim", 0) >= 2:
            out[k] = v[:, :seqlen]
    return out
