"""Data-efficiency pipeline — curriculum learning + random-LTD.

Reference: ``deepspeed/runtime/data_pipeline/`` [K] (SURVEY §2.1 row
"Data efficiency"): ``data_sampling/data_sampler.py`` (difficulty-ordered
curriculum sampling), ``curriculum_scheduler.py`` (difficulty schedules),
``data_routing/`` (random layerwise token dropping, csrc/random_ltd
gather/scatter kernels).

TPU adaptations: the gather/scatter kernels are ``jnp.take``/segment
scatter (XLA handles them, SURVEY §2.2 "Random-LTD" row); schedules snap
to power-of-two-ish buckets so changing curriculum state reuses a small
set of compiled programs instead of recompiling every step.
"""

from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import (CurriculumDataLoader, CurriculumSampler,
                           DeepSpeedDataSampler)
from .random_ltd import RandomLTDScheduler, random_ltd_apply

__all__ = ["CurriculumScheduler", "CurriculumSampler", "CurriculumDataLoader",
           "DeepSpeedDataSampler", "RandomLTDScheduler", "random_ltd_apply"]
