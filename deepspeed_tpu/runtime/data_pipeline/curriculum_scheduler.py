"""Curriculum difficulty schedules.

Reference: ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py`` [K]
— ``CurriculumScheduler`` with schedule types ``fixed_linear``,
``fixed_root``, ``fixed_discrete`` and ``custom``; state =
``current_difficulty`` updated per step between ``min_difficulty`` and
``max_difficulty`` (the legacy ``curriculum_learning`` config group uses
the same schema).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    """difficulty(step) per the reference's schedule family.

    config keys: ``schedule_type`` + ``schedule_config`` —
      fixed_linear:   {total_curriculum_step, difficulty_step}
      fixed_root:     {total_curriculum_step, difficulty_step, root_degree}
      fixed_discrete: {difficulty: [...], max_step: [...]}
    plus top-level ``min_difficulty`` / ``max_difficulty``.
    """

    def __init__(self, config: Dict[str, Any],
                 custom_fn: Optional[Callable[[int], int]] = None):
        self.min = int(config.get("min_difficulty", 1))
        self.max = int(config.get("max_difficulty", self.min))
        self.schedule_type = config.get("schedule_type", FIXED_LINEAR)
        self.schedule = dict(config.get("schedule_config", {}))
        self.custom_fn = custom_fn
        if self.schedule_type == CUSTOM and custom_fn is None:
            raise ValueError("custom schedule needs custom_fn")
        if self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            self.total = int(self.schedule.get("total_curriculum_step", 1))
            # difficulty snaps to multiples of difficulty_step — the
            # reference uses this for tensor-core alignment; here it is the
            # recompile-bucketing knob (seq-len curricula change shapes)
            self.step_quantum = int(self.schedule.get("difficulty_step", 8))
        self.current_difficulty = self.min
        self.first_step = True

    def _fixed_linear(self, step: int) -> int:
        frac = min(step / max(self.total, 1), 1.0)
        d = self.min + (self.max - self.min) * frac
        return int(d)

    def _fixed_root(self, step: int) -> int:
        degree = float(self.schedule.get("root_degree", 2))
        frac = min(step / max(self.total, 1), 1.0) ** (1.0 / degree)
        return int(self.min + (self.max - self.min) * frac)

    def _fixed_discrete(self, step: int) -> int:
        diffs = self.schedule["difficulty"]
        max_steps = self.schedule["max_step"]
        for d, s in zip(diffs, max_steps):
            if step <= s:
                return int(d)
        return int(diffs[-1])

    def get_difficulty(self, step: int) -> int:
        if self.schedule_type == FIXED_LINEAR:
            d = self._fixed_linear(step)
        elif self.schedule_type == FIXED_ROOT:
            d = self._fixed_root(step)
        elif self.schedule_type == FIXED_DISCRETE:
            return min(self._fixed_discrete(step), self.max)
        elif self.schedule_type == CUSTOM:
            return int(self.custom_fn(step))
        else:
            raise ValueError(f"unknown schedule_type {self.schedule_type}")
        q = max(self.step_quantum, 1)
        d = (d // q) * q
        return max(self.min, min(d, self.max))

    def update_difficulty(self, step: int) -> int:
        self.current_difficulty = self.get_difficulty(step)
        return self.current_difficulty
