"""Random layerwise token dropping (random-LTD).

Reference: ``deepspeed/runtime/data_pipeline/data_routing/`` +
``csrc/random_ltd/`` [K] (arXiv 2211.11586 [P]): during training, middle
layers process a random SUBSET of tokens; dropped tokens bypass the layer
unchanged.  The kept-token count follows a schedule from
``random_ltd_schedule.min_value`` up to the full sequence.

TPU-first: the reference needs gather/scatter CUDA kernels; under XLA the
same data movement is ``jnp.take_along_axis`` + scatter-add, fused into
the surrounding program (SURVEY §2.2 "Random-LTD" row: "no kernel
needed").  The kept count is static per compiled program; the scheduler
quantizes it (``difficulty_step``) so a whole training run touches only a
handful of program shapes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from .curriculum_scheduler import CurriculumScheduler


def random_ltd_apply(layer_fn: Callable[..., jnp.ndarray],
                     x: jnp.ndarray, keep: int, rng: jax.Array,
                     mask: jnp.ndarray = None) -> jnp.ndarray:
    """Run ``layer_fn`` on ``keep`` randomly-selected tokens of
    ``x [B, S, H]``; other tokens pass through unchanged.

    ``keep`` must be a static Python int (it sets the compiled shape).
    Selection is without replacement, per batch row, order-preserving —
    the reference's sorted-gather semantics, so RoPE/position handling
    inside ``layer_fn`` sees monotone positions.

    With ``mask [B, S]`` (attention/padding mask), ``layer_fn`` is called
    as ``layer_fn(sub, sub_mask)`` with the mask gathered by the same
    indices — the single home of the select/gather/scatter logic for both
    standalone use and model integrations.
    """
    B, S, H = x.shape
    keep = int(keep)
    if keep >= S:
        return layer_fn(x) if mask is None else layer_fn(x, mask)
    # per-row random permutation → first `keep` sorted = uniform subset
    scores = jax.random.uniform(rng, (B, S))
    idx = jnp.argsort(scores, axis=1)[:, :keep]
    idx = jnp.sort(idx, axis=1)  # order-preserving gather
    sub = jnp.take_along_axis(x, idx[:, :, None], axis=1)  # [B, keep, H]
    if mask is None:
        out_sub = layer_fn(sub)
    else:
        out_sub = layer_fn(sub, jnp.take_along_axis(mask, idx, axis=1))
    # scatter processed tokens back over the identity residual
    return x.at[jnp.arange(B)[:, None], idx].set(out_sub)


class RandomLTDScheduler:
    """Kept-token schedule (reference ``random_ltd_schedule`` schema:
    ``{min_value, max_value, schedule_type: fixed_linear,
    schedule_config: {require_steps, seq_per_step}}``)."""

    def __init__(self, config: Dict[str, Any], seq_len: int):
        sched = dict(config.get("random_ltd_schedule", {}))
        self.seq_len = int(seq_len)
        cfg = {
            "min_difficulty": int(sched.get("min_value", seq_len // 2)),
            "max_difficulty": int(sched.get("max_value", seq_len)),
            "schedule_type": sched.get("schedule_type", "fixed_linear"),
            "schedule_config": {
                "total_curriculum_step":
                    int(sched.get("schedule_config", {}).get(
                        "require_steps", 1000)),
                "difficulty_step":
                    int(sched.get("schedule_config", {}).get(
                        "seq_per_step", 16)),
            },
        }
        self.scheduler = CurriculumScheduler(cfg)
        self.layer_ids = list(config.get("random_ltd_layer_id", []))

    def keep_count(self, step: int) -> int:
        return min(self.scheduler.get_difficulty(step), self.seq_len)

    def applies_to(self, layer_id: int) -> bool:
        return not self.layer_ids or layer_id in self.layer_ids
