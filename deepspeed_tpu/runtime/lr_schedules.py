"""LR schedules.

Capability parity with the reference ``deepspeed/runtime/lr_schedules.py``:
``VALID_LR_SCHEDULES = LRRangeTest | OneCycle | WarmupLR | WarmupDecayLR |
WarmupCosineLR`` [L ACC:2239], with the reference's parameter names (§5.6
[L HF-DS:169-171, 258-267]).

TPU-first design: every schedule is a pure function ``step -> lr`` (jittable,
usable inside the compiled train step via ``optax``), wrapped in a small
stateful class that provides the reference's ``step()`` / ``get_lr()`` /
``state_dict()`` / ``load_state_dict()`` surface for compat-mode callers.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp

LRRANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"

VALID_LR_SCHEDULES = [LRRANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR,
                      WARMUP_COSINE_LR]

Schedule = Callable[[Any], Any]  # step (int or traced int) -> lr


def _warmup(step, warmup_min_lr: float, warmup_max_lr: float,
            warmup_num_steps: int, warmup_type: str = "log"):
    """Shared warmup ramp; 'log' matches the reference default."""
    warmup_num_steps = max(warmup_num_steps, 1)
    frac = jnp.clip(step / warmup_num_steps, 0.0, 1.0)
    if warmup_type == "log":
        # log-space ramp: lr rises fast early (reference default behavior)
        gamma = jnp.log1p(frac * (math.e - 1.0))
    else:
        gamma = frac
    return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000, warmup_type: str = "log",
              **_: Any) -> Schedule:
    def schedule(step):
        return _warmup(step, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                       warmup_type)

    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_: Any) -> Schedule:
    """Linear decay to 0 after warmup (reference WarmupDecayLR)."""

    def schedule(step):
        lr = _warmup(step, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                     warmup_type)
        decay_frac = jnp.clip(
            (total_num_steps - step) / max(total_num_steps - warmup_num_steps, 1),
            0.0, 1.0)
        return jnp.where(step < warmup_num_steps, lr, warmup_max_lr * decay_frac)

    return schedule


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 1e-4,
                     warmup_max_lr: float = 1e-3, warmup_type: str = "log",
                     **_: Any) -> Schedule:
    """Warmup then cosine decay to cos_min_ratio×max (reference WarmupCosineLR)."""

    def schedule(step):
        warm = _warmup(step, warmup_min_ratio * warmup_max_lr, warmup_max_lr,
                       warmup_num_steps, warmup_type)
        progress = jnp.clip(
            (step - warmup_num_steps) / max(total_num_steps - warmup_num_steps, 1),
            0.0, 1.0)
        cosine = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (
            1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_num_steps, warm, warmup_max_lr * cosine)

    return schedule


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_: Any) -> Schedule:
    """LR range test (Smith): lr grows with step to find the usable band."""

    def schedule(step):
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle(cycle_min_lr: float = 1e-3, cycle_max_lr: float = 1e-2,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0,
              **_: Any) -> Schedule:
    """1cycle policy: min→max over first phase, max→min over second, then decay."""
    second = cycle_second_step_size or cycle_first_step_size
    cycle_len = cycle_first_step_size + second

    def schedule(step):
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * jnp.clip(
            step / cycle_first_step_size, 0.0, 1.0)
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * jnp.clip(
            (step - cycle_first_step_size) / second, 0.0, 1.0)
        in_cycle = jnp.where(step < cycle_first_step_size, up, down)
        if decay_step_size > 0:
            post = cycle_min_lr * (1 - decay_lr_rate) ** (
                (step - cycle_len) / decay_step_size)
            return jnp.where(step < cycle_len, in_cycle, post)
        return jnp.where(step < cycle_len, in_cycle, cycle_min_lr)

    return schedule


_FACTORIES: Dict[str, Callable[..., Schedule]] = {
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    WARMUP_COSINE_LR: warmup_cosine_lr,
    LRRANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
}


def get_lr_schedule(name: str, params: Dict[str, Any]) -> Schedule:
    if name not in _FACTORIES:
        raise ValueError(
            f"Unknown scheduler '{name}'; valid: {VALID_LR_SCHEDULES}")
    clean = {k: v for k, v in params.items() if not (isinstance(v, str) and v == "auto")
             and v is not None}
    return _FACTORIES[name](**clean)


class LRScheduler:
    """Stateful wrapper giving the reference's scheduler object surface."""

    def __init__(self, schedule: Schedule, last_step: int = 0):
        self.schedule = schedule
        self.last_step = last_step

    def step(self, increment: int = 1) -> None:
        self.last_step += increment

    def get_lr(self) -> List[float]:
        return [float(self.schedule(self.last_step))]

    def get_last_lr(self) -> List[float]:
        return self.get_lr()

    def state_dict(self) -> Dict[str, Any]:
        return {"last_step": self.last_step}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.last_step = int(state["last_step"])
