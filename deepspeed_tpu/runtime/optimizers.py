"""Optimizer factory: ds_config ``optimizer`` block → optax transform.

Capability parity with the reference's ``engine._configure_optimizer`` name
matrix [K]: Adam/AdamW (fused + CPU variants collapse to one XLA-fused optax
adam — the fused/multi-tensor distinction is meaningless under XLA, SURVEY
§2.2), Lamb, Lion, SGD, Adagrad, Muon; the 1-bit family (OnebitAdam,
OnebitLamb, ZeroOneAdam) maps onto error-feedback compressed-gradient
wrappers (see ``ops/onebit.py``); offload variants are selected by the ZeRO
offload config, not the optimizer name.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import optax

from ..utils.logging import logger
from .config import DeepSpeedConfig, OptimizerConfig
from .config_utils import is_auto

ScheduleOrFloat = Union[float, Callable[[Any], Any]]


def _clean_params(cfg: OptimizerConfig) -> dict:
    p = cfg.params.model_dump()
    extra = cfg.params.model_extra or {}
    p.update(extra)
    return {k: v for k, v in p.items() if not is_auto(v)}


def build_optimizer(config: DeepSpeedConfig,
                    lr: Optional[ScheduleOrFloat] = None) -> optax.GradientTransformation:
    """Build the base optimizer (no clipping — the engine owns grad clipping so
    the reported grad-norm matches the clipped value, like the reference)."""
    opt_cfg = config.optimizer or OptimizerConfig()
    name = opt_cfg.type.lower().replace("_", "")
    p = _clean_params(opt_cfg)
    learning_rate = lr if lr is not None else p.get("lr", 1e-3)
    betas = p.get("betas", [0.9, 0.999])
    b1, b2 = float(betas[0]), float(betas[1])
    eps = float(p.get("eps", 1e-8))
    wd = float(p.get("weight_decay", 0.0))

    if name in ("adam", "fusedadam"):
        # reference Adam applies additive (L2) weight decay inside the update
        if wd:
            return optax.chain(optax.add_decayed_weights(wd),
                               optax.adam(learning_rate, b1=b1, b2=b2, eps=eps))
        return optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
    if name in ("adamw", "deepspeedcpuadam"):
        return optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if name in ("lamb", "fusedlamb", "onebitlamb"):
        if name == "onebitlamb":
            logger.info("OnebitLamb: base lamb update; the engine routes "
                        "grads through the 1-bit error-feedback compressed "
                        "allreduce (ops/onebit.py)")
        return optax.lamb(learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if name in ("lion", "deepspeedcpulion"):
        # the OptimizerParams field default [0.9, 0.999] is Adam's; Lion's
        # conventional default is [0.9, 0.99] — only honor explicit betas
        if "betas" in opt_cfg.params.model_fields_set and not is_auto(
                opt_cfg.params.betas):
            lion_b1, lion_b2 = float(betas[0]), float(betas[1])
        else:
            lion_b1, lion_b2 = 0.9, 0.99
        return optax.lion(learning_rate, b1=lion_b1, b2=lion_b2, weight_decay=wd)
    if name == "sgd":
        return optax.sgd(learning_rate, momentum=float(p.get("momentum", 0.0)))
    if name in ("adagrad", "deepspeedcpuadagrad"):
        return optax.adagrad(learning_rate, eps=eps)
    if name in ("onebitadam", "zerooneadam"):
        logger.info(f"{opt_cfg.type}: base adam update; the engine routes "
                    "grads through the 1-bit error-feedback compressed "
                    "allreduce (ops/onebit.py)")
        return optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
    if name == "muon":
        try:
            from optax.contrib import muon

            return muon(learning_rate)
        except ImportError:
            raise ValueError("Muon optimizer not available in this optax")
    raise ValueError(f"Unknown optimizer type '{opt_cfg.type}'")
