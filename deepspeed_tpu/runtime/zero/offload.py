"""ZeRO-Offload: optimizer states + master weights in host RAM, C++ Adam.

Reference: ``zero_optimization.offload_optimizer.device=cpu`` routes the
optimizer to ``DeepSpeedCPUAdam`` over fp32 master shards in pinned host
memory while the device keeps only compute params (SURVEY §2.3 ZeRO-Offload
row; csrc/adam role per §2.2).

TPU-first split: the jitted device program computes gradients (microbatch
scan + clip + overflow check) and STOPS; the host runs the fused C++
Adam(W)/Adagrad/Lion over numpy master shards and pushes updated params back
to their device shardings.  This is the step-splitting SURVEY §7 hard-part 2
prescribes — the one boundary where the single-program model must break.

Partitioning + overlap design (round 3 — bucketed read-ahead/write-behind,
role parity with the reference's ``swap_tensor/pipelined_optimizer_swapper``
read-ahead/write-behind loop, SURVEY §2.1):

* Masters/moments are kept per *addressable shard* of the param's ZeRO
  opt-state layout (``ZeroShardingPolicy.offload_shardings``).  At stage ≥ 1
  that layout is DP-sharded, so host memory per process is ``total/dp`` —
  the reference's ZeRO partitioning of CPU optimizer state across ranks —
  and the whole path is multi-process safe: only ``addressable_shards`` are
  ever pulled (never a ``device_get`` of a global array).
* The device grad program lands grads directly in that layout
  (``apply_offload_grad_constraints``): a reduce-scatter, not an all-reduce.
* **Bucket pipeline**: shards are grouped into ~``bucket_bytes`` buckets.
  d2h is issued asynchronously for every shard up front
  (``copy_to_host_async``), then the step runs double-buffered: the main
  thread blocks on bucket *i+1*'s grads landing while a worker thread runs
  the fused C++ Adam over bucket *i* and immediately dispatches its updated
  params h2d (``device_put`` is async).  The ctypes optimizer call releases
  the GIL, so host compute, d2h waits, and h2d dispatch genuinely overlap.
* **bf16 wire** (``wire_bf16=True``, engine sets it when bf16 is enabled):
  device params live in bf16 (halving HBM *and* h2d bytes — the reference
  keeps fp16 compute params on device with fp32 masters on CPU the same
  way); the C++ kernel emits the bf16 copy directly (``ds_adam_step_bf16``)
  so no extra host cast pass.  Grads arrive bf16 over the wire too (the
  grad program casts after fp32 accumulation — reference sends fp16 grads
  to the CPU optimizer).  Masters stay fp32 on host and are checkpointed.
* Finally a single cached jitted identity reshards the assembled tree back
  to the param layout (XLA all-gather over ICI — a no-op when layouts
  already match, e.g. ZeRO-3).
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from ...telemetry.perf import get_compile_tracker, tracked_jit
from ...utils.logging import log_dist


def _index_key(index) -> tuple:
    """Hashable key for a shard's global index (tuple of slices)."""
    return tuple((s.start, s.stop, s.step) for s in index)


class _LeafEntry:
    """One unique shard of one param leaf: master slot + device replicas."""

    __slots__ = ("index", "devices", "slot")

    def __init__(self, index, slot):
        self.index = index
        self.devices = []
        self.slot = slot


class CPUOffloadOptimizer:
    """Host-side optimizer over per-shard slices of the param pytree."""

    def __init__(self, params: Any, optimizer_name: str, optimizer_params: Any,
                 schedule: Callable[[int], float], policy: Any = None,
                 base_specs: Any = None, bucket_bytes: int = 32 << 20,
                 wire_bf16: bool = False):
        leaves, self.treedef = jax.tree.flatten(params)
        self.param_shardings = [leaf.sharding for leaf in leaves]
        self.global_shapes = [tuple(leaf.shape) for leaf in leaves]
        self.schedule = schedule

        if policy is not None:
            host_sh_tree = policy.offload_shardings(params, base_specs)
            self.host_shardings = jax.tree.leaves(host_sh_tree)
        else:
            self.host_shardings = list(self.param_shardings)

        # Reshard params into the host-partition layout and pull ONLY the
        # process-addressable shards (multi-process safe by construction).
        host_sh_by_tree = jax.tree.unflatten(self.treedef, self.host_shardings)
        to_host_layout = tracked_jit(lambda t: t, "offload/to_host_layout",
                                     tracker=get_compile_tracker(),
                                     out_shardings=host_sh_by_tree)
        resharded = jax.tree.leaves(to_host_layout(params))

        flat_masters: List[np.ndarray] = []
        self.layouts: List[List[_LeafEntry]] = []
        for leaf in resharded:
            seen: Dict[tuple, _LeafEntry] = {}
            entries: List[_LeafEntry] = []
            for shard in leaf.addressable_shards:
                key = _index_key(shard.index)
                if key not in seen:
                    entry = _LeafEntry(shard.index, len(flat_masters))
                    flat_masters.append(
                        np.array(shard.data, dtype=np.float32, order="C"))
                    seen[key] = entry
                    entries.append(entry)
                seen[key].devices.append(shard.device)
            self.layouts.append(entries)
        self.num_slots = len(flat_masters)

        self.wire_bf16 = bool(wire_bf16)
        # slot → device replicas, for worker-thread h2d dispatch
        self._slot_devices: List[list] = [None] * self.num_slots
        for entries in self.layouts:
            for e in entries:
                self._slot_devices[e.slot] = e.devices
        # ~bucket_bytes groups of consecutive slots — the unit of the
        # d2h-wait / C++-Adam / h2d-dispatch pipeline
        self.buckets: List[List[int]] = []
        cur: List[int] = []
        cur_bytes = 0
        for slot, m in enumerate(flat_masters):
            cur.append(slot)
            cur_bytes += m.nbytes
            if cur_bytes >= bucket_bytes:
                self.buckets.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            self.buckets.append(cur)
        # staging buffers the C++ kernel writes bf16 params into (wire copy)
        self._bf16_stage = ([np.empty(m.shape, np.uint16) for m in flat_masters]
                            if self.wire_bf16 else None)
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ds-offload")
        self.last_timings: Dict[str, float] = {}

        # Cached reshard of the updated (host-layout) tree → param layout.
        param_sh_tree = jax.tree.unflatten(self.treedef, self.param_shardings)
        self._to_param_layout = tracked_jit(
            lambda t: t, "offload/to_param_layout",
            tracker=get_compile_tracker(), out_shardings=param_sh_tree)
        self._to_host_layout = None  # built lazily for grad trees

        name = optimizer_name.lower()
        op = dict(optimizer_params or {})
        lr = op.get("lr", 1e-3)
        lr = 1e-3 if isinstance(lr, str) else float(lr)
        wd = op.get("weight_decay", 0.0)
        wd = 0.0 if isinstance(wd, str) else float(wd)
        if name in ("adam", "adamw", "cpu_adam"):
            from ...ops.adam import DeepSpeedCPUAdam

            betas = tuple(op.get("betas", (0.9, 0.999)))
            eps = float(op.get("eps", 1e-8))
            self.opt = DeepSpeedCPUAdam(flat_masters, lr=lr, betas=betas,
                                        eps=eps, weight_decay=wd,
                                        adamw_mode=(name != "adam"))
        elif name == "adagrad":
            from ...ops.adam import DeepSpeedCPUAdagrad

            self.opt = DeepSpeedCPUAdagrad(flat_masters, lr=lr,
                                           eps=float(op.get("eps", 1e-10)),
                                           weight_decay=wd)
        elif name == "lion":
            from ...ops.adam import DeepSpeedCPULion

            self.opt = DeepSpeedCPULion(flat_masters, lr=lr,
                                        betas=tuple(op.get("betas", (0.9, 0.99))),
                                        weight_decay=wd)
        else:
            raise ValueError(
                f"offload_optimizer does not support optimizer '{optimizer_name}'")
        total = sum(m.nbytes for m in self.opt.params)
        log_dist(f"ZeRO-Offload: {name} states on host "
                 f"({total / 2**20:.1f} MiB master slice/process, "
                 f"{self.num_slots} shards, "
                 f"dp-partitioned={policy is not None and policy.stage >= 1})")
        # memory plane (telemetry/memory): the offload optimizer IS the
        # allocation site for the host-side optimizer state — masters +
        # moments under "optimizer", the bf16 wire staging under
        # "swap_staging"; per-step d2h/h2d traffic feeds record_io
        from ...telemetry.memory import get_memory_ledger

        self._mem = get_memory_ledger()
        if self._mem.enabled:
            moments = sum(
                sum(a.nbytes for a in getattr(self.opt, attr, []) or [])
                for attr in ("exp_avg", "exp_avg_sq"))
            self._mem.register(
                "optimizer", "offload/host_masters", total + moments,
                space="host",
                tag=f"{name} fp32 masters + moments ({self.num_slots} "
                    f"shards)")
            if self._bf16_stage is not None:
                self._mem.register(
                    "swap_staging", "offload/bf16_stage",
                    sum(s.nbytes for s in self._bf16_stage), space="host",
                    tag="bf16 wire staging buffers")

    # ------------------------------------------------------------------
    # the per-step host round trip
    # ------------------------------------------------------------------

    def _update_bucket(self, bucket: List[int], grads_np: List[np.ndarray],
                       h2d: List[Optional[list]]) -> None:
        """Worker-thread body: fused C++ step over one bucket's slots, then
        immediately dispatch the updated params h2d (write-behind).  Runs
        concurrently with the main thread's d2h wait on the next bucket."""
        t0 = time.perf_counter()
        for slot, g in zip(bucket, grads_np):
            if self.wire_bf16:
                stage = self._bf16_stage[slot]
                self.opt.step_slot(slot, g, bf16_out=stage)
                src = stage.view(ml_dtypes.bfloat16)
            else:
                self.opt.step_slot(slot, g)
                src = self.opt.params[slot]
            t1 = time.perf_counter()
            self.last_timings["host_opt_s"] += t1 - t0
            h2d[slot] = [jax.device_put(src, d)
                         for d in self._slot_devices[slot]]
            if self._mem.enabled:
                # worker thread — record_io is lock-guarded
                self._mem.record_io(
                    "h2d", src.nbytes * len(self._slot_devices[slot]))
            t0 = time.perf_counter()
            self.last_timings["h2d_dispatch_s"] += t0 - t1

    def step(self, grads: Any, step_index: int) -> Any:
        """grads: device pytree (ideally already in the host-partition
        layout via ``apply_offload_grad_constraints``) → updated device
        params in their original shardings."""
        t_start = time.perf_counter()
        grad_leaves = jax.tree.leaves(grads)
        needs_reshard = any(
            not g.sharding.is_equivalent_to(s, len(g.shape))
            for g, s in zip(grad_leaves, self.host_shardings))
        if needs_reshard:
            if self._to_host_layout is None:
                host_sh_tree = jax.tree.unflatten(self.treedef,
                                                  self.host_shardings)
                self._to_host_layout = tracked_jit(
                    lambda t: t, "offload/grads_to_host_layout",
                    tracker=get_compile_tracker(),
                    out_shardings=host_sh_tree)
            grad_leaves = jax.tree.leaves(self._to_host_layout(grads))

        # one single-device array per unique shard, d2h started async up
        # front so transfers stream in slot (= bucket) order while earlier
        # buckets are being consumed
        shard_data: List[Optional[Any]] = [None] * self.num_slots
        for leaf, entries in zip(grad_leaves, self.layouts):
            by_key = {}
            for shard in leaf.addressable_shards:
                by_key[_index_key(shard.index)] = shard.data
            for e in entries:
                data = by_key[_index_key(e.index)]
                data.copy_to_host_async()
                shard_data[e.slot] = data

        self.last_timings = {"d2h_wait_s": 0.0, "host_opt_s": 0.0,
                             "h2d_dispatch_s": 0.0}
        self.opt.begin_step(float(self.schedule(step_index)))
        h2d: List[Optional[list]] = [None] * self.num_slots
        pending = None
        for bucket in self.buckets:
            t0 = time.perf_counter()
            grads_np = []
            for slot in bucket:
                g = np.asarray(shard_data[slot])  # blocks on THIS bucket only
                if self._mem.enabled:
                    self._mem.record_io("d2h", g.nbytes)
                if g.dtype != np.float32:
                    g = g.astype(np.float32)  # bf16 wire → fp32 for the opt
                grads_np.append(g)
                shard_data[slot] = None  # release the device grad shard
            self.last_timings["d2h_wait_s"] += time.perf_counter() - t0
            if pending is not None:
                pending.result()  # double buffer: at most one bucket in flight
            pending = self._pool.submit(self._update_bucket, bucket,
                                        grads_np, h2d)
        if pending is not None:
            pending.result()

        # assemble global arrays in the host layout from the already-
        # dispatched per-shard device arrays, then one compiled reshard back
        # to the param layout
        new_leaves = []
        for shape, sharding, entries in zip(self.global_shapes,
                                            self.host_shardings, self.layouts):
            arrays = []
            for e in entries:
                arrays.extend(h2d[e.slot])
            new_leaves.append(jax.make_array_from_single_device_arrays(
                shape, sharding, arrays))
        new_tree = jax.tree.unflatten(self.treedef, new_leaves)
        out = self._to_param_layout(new_tree)
        self.last_timings["step_total_s"] = time.perf_counter() - t_start
        return out

    # ------------------------------------------------------------------
    # checkpoint plumbing — logical (re-assembled) arrays
    # ------------------------------------------------------------------

    def _assemble(self, slot_values: List[np.ndarray]) -> List[np.ndarray]:
        """Per-leaf logical arrays from the process-local slots.  With
        multi-process DP partitioning each process fills only its own slices
        (checkpointing multi-process offload state needs per-process files,
        as in the reference's zero_pp_rank_* layout)."""
        out = []
        for shape, entries in zip(self.global_shapes, self.layouts):
            arr = np.zeros(shape, np.float32)
            for e in entries:
                arr[e.index] = slot_values[e.slot]
            out.append(arr)
        return out

    def state_dict_arrays(self) -> Any:
        moments = {}
        if hasattr(self.opt, "exp_avg"):
            moments["exp_avg"] = self._assemble(self.opt.exp_avg)
        if hasattr(self.opt, "exp_avg_sq"):
            moments["exp_avg_sq"] = self._assemble(self.opt.exp_avg_sq)
        # fp32 masters travel in the checkpoint (reference optim_state
        # layout): with a bf16 wire the device copy is lossy, so masters
        # cannot be reconstructed from params on resume
        moments["master"] = self._assemble(self.opt.params)
        moments["step"] = self.opt.state_step
        return moments

    def load_state_arrays(self, state: Any) -> bool:
        """Restore host state; returns True when fp32 masters were in the
        checkpoint (the caller must NOT reseed them from device params)."""
        for key in ("exp_avg", "exp_avg_sq"):
            if key in state and hasattr(self.opt, key):
                slots = getattr(self.opt, key)
                for leaf_i, src in enumerate(state[key]):
                    src = np.asarray(src, dtype=np.float32)
                    for e in self.layouts[leaf_i]:
                        np.copyto(slots[e.slot], src[e.index])
        restored_master = "master" in state
        if restored_master:
            for leaf_i, src in enumerate(state["master"]):
                src = np.asarray(src, dtype=np.float32)
                for e in self.layouts[leaf_i]:
                    np.copyto(self.opt.params[e.slot], src[e.index])
        if "step" in state:
            self.opt.state_step = int(state["step"])
        return restored_master

    def reseed_masters(self, params: Any) -> None:
        """Refresh host master slices from (restored) device params."""
        host_sh_tree = jax.tree.unflatten(self.treedef, self.host_shardings)
        resharded = jax.tree.leaves(
            tracked_jit(lambda t: t, "offload/reseed_masters",
                        tracker=get_compile_tracker(),
                        out_shardings=host_sh_tree)(params))
        for leaf, entries in zip(resharded, self.layouts):
            by_key = {_index_key(s.index): s.data
                      for s in leaf.addressable_shards}
            for e in entries:
                np.copyto(self.opt.params[e.slot],
                          np.asarray(by_key[_index_key(e.index)],
                                     dtype=np.float32))
