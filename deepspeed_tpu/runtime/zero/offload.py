"""ZeRO-Offload: optimizer states + master weights in host RAM, C++ Adam.

Reference: ``zero_optimization.offload_optimizer.device=cpu`` routes the
optimizer to ``DeepSpeedCPUAdam`` over fp32 master shards in pinned host
memory while the device keeps only compute params (SURVEY §2.3 ZeRO-Offload
row; csrc/adam role per §2.2).

TPU-first split: the jitted device program computes gradients (microbatch
scan + clip + overflow check) and STOPS; the host runs the fused C++
Adam(W)/Adagrad/Lion over numpy master shards and pushes updated params back
to their device shardings.  This is the step-splitting SURVEY §7 hard-part 2
prescribes — the one boundary where the single-program model must break.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import log_dist


class CPUOffloadOptimizer:
    """Host-side optimizer over the flattened param pytree."""

    def __init__(self, params: Any, optimizer_name: str, optimizer_params: Any,
                 schedule: Callable[[int], float]):
        leaves, self.treedef = jax.tree.flatten(params)
        self.shardings = [leaf.sharding for leaf in leaves]
        host = [np.asarray(jax.device_get(leaf), dtype=np.float32)
                for leaf in leaves]
        self.schedule = schedule
        name = optimizer_name.lower()
        op = dict(optimizer_params or {})
        lr = op.get("lr", 1e-3)
        lr = 1e-3 if isinstance(lr, str) else float(lr)
        wd = op.get("weight_decay", 0.0)
        wd = 0.0 if isinstance(wd, str) else float(wd)
        if name in ("adam", "adamw", "cpu_adam"):
            from ...ops.adam import DeepSpeedCPUAdam

            betas = tuple(op.get("betas", (0.9, 0.999)))
            eps = float(op.get("eps", 1e-8))
            self.opt = DeepSpeedCPUAdam(host, lr=lr, betas=betas, eps=eps,
                                        weight_decay=wd,
                                        adamw_mode=(name != "adam"))
        elif name == "adagrad":
            from ...ops.adam import DeepSpeedCPUAdagrad

            self.opt = DeepSpeedCPUAdagrad(host, lr=lr,
                                           eps=float(op.get("eps", 1e-10)),
                                           weight_decay=wd)
        elif name == "lion":
            from ...ops.adam import DeepSpeedCPULion

            self.opt = DeepSpeedCPULion(host, lr=lr,
                                        betas=tuple(op.get("betas", (0.9, 0.99))),
                                        weight_decay=wd)
        else:
            raise ValueError(
                f"offload_optimizer does not support optimizer '{optimizer_name}'")
        log_dist(f"ZeRO-Offload: {name} states on host "
                 f"({sum(h.nbytes for h in host) / 2**20:.1f} MiB master)")

    def step(self, grads: Any, step_index: int) -> Any:
        """grads: device pytree → updated device params (original shardings)."""
        grad_leaves = jax.tree.leaves(grads)
        grads_np = [np.asarray(jax.device_get(g), dtype=np.float32)
                    for g in grad_leaves]
        lr = float(self.schedule(step_index))
        self.opt.step(grads_np, lr=lr)
        new_leaves = [
            jax.device_put(jnp.asarray(p), s)
            for p, s in zip(self.opt.params, self.shardings)]
        return jax.tree.unflatten(self.treedef, new_leaves)

    def state_dict_arrays(self) -> Any:
        """Moments as a pytree for checkpointing."""
        moments = {"exp_avg": getattr(self.opt, "exp_avg", None),
                   "exp_avg_sq": getattr(self.opt, "exp_avg_sq", None),
                   "step": self.opt.state_step}
        return {k: v for k, v in moments.items() if v is not None}

    def load_state_arrays(self, state: Any) -> None:
        for key in ("exp_avg", "exp_avg_sq"):
            if key in state and hasattr(self.opt, key):
                for dst, src in zip(getattr(self.opt, key), state[key]):
                    np.copyto(dst, np.asarray(src, dtype=np.float32))
        if "step" in state:
            self.opt.state_step = int(state["step"])
        # master params re-seeded from the engine's current params by caller
