"""ZeRO as a GSPMD sharding policy.

The reference implements ZeRO with explicit machinery: flattened contiguous
buffers, bucketed reduce-scatter hooks, a gather/release state machine
(``deepspeed/runtime/zero/stage_1_and_2.py``, ``stage3.py``,
``partition_parameters.py``, ``partitioned_param_coordinator.py`` [K],
~11k LoC).  Under XLA/GSPMD the same memory states are *sharding
annotations*; the compiler inserts and overlaps the all-gathers and
reduce-scatters the reference schedules by hand (SURVEY §7):

    stage 0: params, grads, opt-state replicated; grads psum over DP.
    stage 1: opt-state sharded over DP; params replicated.
    stage 2: + grads reduce-scattered (transient inside the jitted step —
             realized as a sharding constraint on the grad pytree).
    stage 3: + params sharded over DP (FSDP); XLA all-gathers per use site
             with latency hiding ≈ the reference's prefetch coordinator.

Per-tensor rule: shard the largest dimension divisible by the DP world size
(ties → first), leaving tensors smaller than
``stage3_param_persistence_threshold`` replicated — the direct analogue of the
reference's persisted-small-params optimization [L ACC:2289-2319].

MiCS (``zero/mics.py`` [K]) falls out for free: a ``mics_shard_size`` < DP
world shards params over a sub-axis and replicates across the rest — we
express it by sharding over only the ``data`` axis while replicating over
``expert``, or via explicit shard sizes when finer control lands.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...parallel.mesh import DP_AXES
from .config import DeepSpeedZeroConfig

# pytree-of-PartitionSpec utilities work leaf-wise via tree_map.


def dp_shardable_dim(shape: Tuple[int, ...], dp_size: int,
                     taken: Optional[Sequence[Optional[Any]]] = None
                     ) -> Optional[int]:
    """THE placement rule, factored out: the largest free dim of
    ``shape`` divisible by ``dp_size`` (ties → earliest), or None when
    nothing shards (the leaf replicates over DP).  ``taken`` marks dims
    a base spec already occupies.  Shared by the live sharding-spec
    computation below and the OFFLINE reshard pre-check
    (``resilience verify --target-mesh`` asks "how would this manifest's
    recorded leaves lay out at dp=N?" without building an engine)."""
    if dp_size <= 1 or not shape:
        return None
    entries = list(taken) if taken is not None else [None] * len(shape)
    entries += [None] * (len(shape) - len(entries))
    candidates = [(dim, i) for i, dim in enumerate(shape)
                  if entries[i] is None and dim % dp_size == 0]
    if not candidates:
        return None
    _, best = max(candidates, key=lambda t: (t[0], -t[1]))
    return best


def reshard_layout_report(state_shapes: Sequence[Sequence[Any]],
                          dp_size: int) -> Dict[str, Any]:
    """Offline layout preview for a snapshot manifest's recorded
    ``state_shapes`` (``[path, shape]`` pairs) at a TARGET dp world:
    which leaves would DP-shard under the placement rule and which
    would fall back to replication (correct either way — replication is
    the rule's documented fallback, so this is capacity guidance, not a
    compatibility gate)."""
    sharded: List[str] = []
    replicated: List[str] = []
    for entry in state_shapes or []:
        name, shape = str(entry[0]), tuple(int(d) for d in entry[1])
        if dp_shardable_dim(shape, dp_size) is not None:
            sharded.append(name)
        else:
            replicated.append(name)
    return {"dp_size": int(dp_size), "sharded": sharded,
            "replicated": replicated,
            "sharded_count": len(sharded),
            "replicated_count": len(replicated)}


@dataclasses.dataclass(frozen=True)
class ZeroShardingPolicy:
    """Maps a ZeRO stage onto PartitionSpecs for param/grad/opt-state leaves."""

    mesh: Mesh
    stage: int
    persistence_threshold: int = 0
    shard_axes: Tuple[str, ...] = DP_AXES
    #: hpZ (ZeRO++): the *param* (secondary) partition may span a SUB-group
    #: of the DP world — the bf16 compute copy shards only over the inner
    #: 'data' axis (ICI-local all-gathers) while grads/opt-state stay
    #: sharded over the full DP world.  None → same axes as everything.
    param_shard_axes: Tuple[str, ...] = None

    @classmethod
    def from_config(cls, mesh: Mesh, config: DeepSpeedZeroConfig) -> "ZeroShardingPolicy":
        threshold = config.stage3_param_persistence_threshold
        if isinstance(threshold, str):  # unresolved "auto"
            threshold = 100_000
        shard_axes = DP_AXES
        # MiCS: shard over the inner 'data' axis only; replicate over 'expert'.
        if config.mics_shard_size not in (-1, 0) and config.mics_shard_size < int(
                np.prod([mesh.shape[a] for a in DP_AXES])):
            shard_axes = ("data",)
        param_axes = None
        hpz = int(config.zero_hpz_partition_size or 1)
        if hpz > 1 and config.stage >= 3:
            inner = int(mesh.shape.get("data", 1))
            if hpz != inner:
                raise ValueError(
                    f"zero_hpz_partition_size={hpz} must equal the inner "
                    f"'data' mesh axis size ({inner}) — the secondary "
                    "partition maps onto the ICI-local axis (lay the mesh "
                    "out so data=hpz and expert carries the rest of DP)")
            param_axes = ("data",)
        return cls(mesh=mesh, stage=config.stage,
                   persistence_threshold=int(threshold),
                   shard_axes=shard_axes, param_shard_axes=param_axes)

    @property
    def dp_size(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.shard_axes]))

    # ------------------------------------------------------------------
    # per-leaf spec rules
    # ------------------------------------------------------------------

    def _shard_spec_for_shape(
            self, shape: Tuple[int, ...],
            base: Optional[PartitionSpec] = None,
            axes: Optional[Tuple[str, ...]] = None) -> PartitionSpec:
        """Largest free dim divisible by dp_size gets the DP axes.

        ``base`` carries model-provided specs (TP ``tensor`` axis, etc. —
        reference analogue: AutoTP's column/row decision); ZeRO composes by
        claiming a dim the base left unsharded.  With no eligible dim the
        tensor stays in its base placement (replicated over DP) — the
        reference's same fallback for unpartitionable tensors.
        """
        entries = list(base) if base is not None else []
        entries += [None] * (len(shape) - len(entries))
        base_spec = PartitionSpec(*entries) if any(
            e is not None for e in entries) else PartitionSpec()
        if not shape:
            return base_spec
        # never reuse a mesh axis the base already occupies (e.g. MoE expert-
        # stacked weights carry 'expert', which is also a ZeRO DP axis)
        used_axes = set()
        for e in entries:
            if e is not None:
                used_axes.update(e if isinstance(e, tuple) else (e,))
        shard_axes = axes if axes is not None else self.shard_axes
        free_axes = tuple(a for a in shard_axes if a not in used_axes)
        free_size = int(np.prod([dict(self.mesh.shape)[a]
                                 for a in free_axes])) if free_axes else 1
        if free_size == 1:
            return base_spec
        if int(np.prod(shape)) <= self.persistence_threshold:
            return base_spec  # persisted small param — stay replicated over DP
        best = dp_shardable_dim(shape, free_size, taken=entries)
        if best is None:
            return base_spec
        entries[best] = free_axes
        return PartitionSpec(*entries)

    def _base_or_empty(self, base: Optional[PartitionSpec],
                       shape: Tuple[int, ...]) -> PartitionSpec:
        if base is None:
            return PartitionSpec()
        entries = list(base) + [None] * (len(shape) - len(base))
        return PartitionSpec(*entries)

    def param_spec(self, leaf: Any,
                   base: Optional[PartitionSpec] = None) -> PartitionSpec:
        shape = tuple(np.shape(leaf))
        if self.stage < 3:
            return self._base_or_empty(base, shape)
        # hpZ: the compute copy shards over the inner (ICI-local) sub-axes
        return self._shard_spec_for_shape(shape, base,
                                          axes=self.param_shard_axes)

    def grad_spec(self, leaf: Any,
                  base: Optional[PartitionSpec] = None) -> PartitionSpec:
        # stage >= 2: grads live reduce-scattered; in-jit this is a constraint.
        shape = tuple(np.shape(leaf))
        if self.stage < 2:
            return self._base_or_empty(base, shape)
        return self._shard_spec_for_shape(shape, base)

    def opt_state_spec(self, leaf: Any,
                       base: Optional[PartitionSpec] = None) -> PartitionSpec:
        # stage >= 1: optimizer states (incl. fp32 master copies) sharded.
        shape = tuple(np.shape(leaf))
        if self.stage < 1:
            return self._base_or_empty(base, shape)
        return self._shard_spec_for_shape(shape, base)

    # ------------------------------------------------------------------
    # pytree-level helpers — ``base_specs`` is a matching pytree of
    # PartitionSpecs from the model (TP/SP placement) or None
    # ------------------------------------------------------------------

    def _map_with_base(self, fn, tree: Any, base_specs: Any) -> Any:
        if base_specs is None:
            return jax.tree.map(lambda p: fn(p, None), tree)
        return jax.tree.map(fn, tree, base_specs)

    def param_shardings(self, params: Any, base_specs: Any = None) -> Any:
        from ...telemetry import get_telemetry

        with get_telemetry().span("zero/param_shardings",
                                  args={"stage": self.stage}):
            return self._map_with_base(
                lambda p, b: NamedSharding(self.mesh, self.param_spec(p, b)),
                params, base_specs)

    def param_specs(self, params: Any, base_specs: Any = None) -> Any:
        return self._map_with_base(
            lambda p, b: self.param_spec(p, b), params, base_specs)

    def grad_specs(self, params: Any, base_specs: Any = None) -> Any:
        return self._map_with_base(
            lambda p, b: self.grad_spec(p, b), params, base_specs)

    def opt_state_shardings(self, opt_state: Any, tx: Any = None,
                            base_specs: Any = None) -> Any:
        """Shardings for an optax state pytree.  Leaves that mirror a param
        shape (mu/nu/master copies) shard like params-at-stage≥1; scalar
        counters replicate.  With model ``base_specs`` the param↔state
        correspondence comes from ``optax.tree_map_params`` so TP axes carry
        into the mirrored moments."""
        from ...telemetry import get_telemetry

        with get_telemetry().span("zero/opt_state_shardings",
                                  args={"stage": self.stage}):
            return self._opt_state_shardings(opt_state, tx, base_specs)

    def _opt_state_shardings(self, opt_state: Any, tx: Any = None,
                             base_specs: Any = None) -> Any:
        if base_specs is not None and tx is not None:
            import optax

            def for_param_leaf(leaf, base):
                return NamedSharding(
                    self.mesh, self.opt_state_spec(leaf, base)
                    if np.ndim(leaf) > 0 else PartitionSpec())

            def for_other_leaf(leaf):
                return NamedSharding(
                    self.mesh, self.opt_state_spec(leaf)
                    if np.ndim(leaf) > 0 else PartitionSpec())

            return optax.tree_map_params(
                tx, for_param_leaf, opt_state, base_specs,
                transform_non_params=for_other_leaf)

        def leaf_sharding(leaf):
            return NamedSharding(
                self.mesh, self.opt_state_spec(leaf)
                if np.ndim(leaf) > 0 else PartitionSpec())

        return jax.tree.map(leaf_sharding, opt_state)

    def offload_shardings(self, params: Any, base_specs: Any = None) -> Any:
        """Host-partition layout for ZeRO-Offload masters: each param leaf in
        its opt-state placement (stage ≥ 1 → DP-sharded), so every process
        keeps only its own slice of the fp32 master + moments — the
        reference's partitioning of CPU optimizer state across DP ranks."""
        return self._map_with_base(
            lambda p, b: NamedSharding(self.mesh, self.opt_state_spec(p, b)),
            params, base_specs)

    def apply_offload_grad_constraints(self, grads: Any,
                                       base_specs: Any = None) -> Any:
        """Inside-jit (offload mode): land grads in the host-partition layout
        so each process's d2h pull is exactly its master slice — a reduce-
        scatter instead of an all-reduce whenever stage ≥ 1."""
        if self.stage < 1:
            return grads
        return self._map_with_base(
            lambda g, b: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh, self.opt_state_spec(g, b))),
            grads, base_specs)

    def apply_grad_constraints(self, grads: Any, base_specs: Any = None) -> Any:
        """Inside-jit: force reduce-scatter placement of grads (stage ≥ 2)."""
        if self.stage < 2:
            return grads
        return self._map_with_base(
            lambda g, b: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh,
                                 self._shard_spec_for_shape(g.shape, b))),
            grads, base_specs)


def sharded_zeros_like(policy: ZeroShardingPolicy, tree: Any, kind: str = "param"):
    """Materialize a zeroed pytree directly in its sharded layout (never builds
    the full tensor on one device — the ``zero.Init`` principle)."""
    spec_fn = {"param": policy.param_spec, "grad": policy.grad_spec,
               "opt": policy.opt_state_spec}[kind]

    def make(leaf):
        sharding = NamedSharding(policy.mesh, spec_fn(leaf))
        # deliberately UNtracked: a fresh zero-arg lambda per leaf has an
        # empty, identical signature at one site, so the tracker would
        # misreport every leaf after the first as a causeless recompile
        # and inflate compile/recompiles_total at init
        return jax.jit(lambda: jax.numpy.zeros(np.shape(leaf), leaf.dtype),  # dslint: disable=untracked-jit
                       out_shardings=sharding)()

    out = jax.tree.map(make, tree)
    from ...telemetry.memory import get_memory_ledger, unique_key

    led = get_memory_ledger()
    if led.enabled:
        # zero.Init materialization is a real allocation site: account
        # the tree under its ZeRO role (unique key — callers materialize
        # several trees through this site)
        pool = {"param": "params", "grad": "grads",
                "opt": "optimizer"}[kind]
        led.register_tree(pool, unique_key(f"sharder/zeros_like/{kind}"),
                          out, tag=f"sharded_zeros_like kind={kind}")
    return out
