from .config import (DeepSpeedZeroConfig, DeepSpeedZeroOffloadOptimizerConfig,
                     DeepSpeedZeroOffloadParamConfig, OffloadDeviceEnum)

__all__ = ["DeepSpeedZeroConfig", "DeepSpeedZeroOffloadOptimizerConfig",
           "DeepSpeedZeroOffloadParamConfig", "OffloadDeviceEnum"]
