from .config import (DeepSpeedZeroConfig, DeepSpeedZeroOffloadOptimizerConfig,
                     DeepSpeedZeroOffloadParamConfig, OffloadDeviceEnum)
from .init_ctx import GatheredParameters, Init
from .sharder import ZeroShardingPolicy

__all__ = ["DeepSpeedZeroConfig", "DeepSpeedZeroOffloadOptimizerConfig",
           "DeepSpeedZeroOffloadParamConfig", "OffloadDeviceEnum",
           "Init", "GatheredParameters", "ZeroShardingPolicy"]
