"""qgZ — ZeRO++ quantized gradient reduction (arXiv 2306.10209 [P]).

Role parity: the ``zero_quantized_gradients`` path inside the reference's
``zero/stage3.py`` + ``csrc/quantization`` kernels [K]: gradients cross the
wire as int8 + group scales instead of fp32/bf16, cutting DP-reduction
bytes ~4× (the win the paper targets for cross-node DCN links; on TPU the
same scheme relieves DCN in multi-slice meshes and ICI at large dp).

Scheme (the paper's 2-hop, all-to-all based reduce):

    1. each worker splits its local grad into ``world`` chunks, int8-
       quantizes each (group-wise scales), ``all_to_all``s them — after
       this hop worker w holds every worker's quantized chunk w;
    2. dequantize + sum locally → worker w owns the reduced chunk w;
    3. quantize the reduced chunk, ``all_gather``, dequantize → replicated
       mean gradient.

Wire bytes/worker ≈ 2n·int8 (+ scales) vs 8n for fp32 ring RS+AG → ~4×.
Runs inside the engine's partial-manual ``shard_map`` over the DP axes
(same harness as the 1-bit path); quantization reuses the int8 math of
``ops/pallas/quantizer.py`` (jnp form — inside shard_map the arrays are
small per-device blocks and XLA fuses the (de)quant into the collective
schedule).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ...comm.comm import all_gather_in_graph, all_to_all_in_graph
from ...utils.jax_compat import axis_size as _axis_size

GROUP = 256  # quantization group size (scale granularity)


def _quant_groups(flat: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[n] fp32 (n % GROUP == 0) → (int8 [n], scales f32 [n/GROUP])."""
    g = flat.reshape(-1, GROUP)
    amax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(g / scale), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale[:, 0]


def _dequant_groups(q: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    return (q.reshape(-1, GROUP).astype(jnp.float32)
            * scales[:, None]).reshape(-1)


def quantized_allreduce(g: jnp.ndarray, axis_names: Sequence[str]
                        ) -> jnp.ndarray:
    """Mean-allreduce of one tensor with int8 wire format (inside
    shard_map; ``g`` is this worker's local gradient)."""
    names = tuple(axis_names)
    world = 1
    for ax in names:
        world *= _axis_size(ax)
    if world == 1:
        return g

    flat = g.reshape(-1).astype(jnp.float32)
    n = flat.size
    pad = -n % (world * GROUP)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    chunks = flat.reshape(world, -1)          # [W, c]

    # hop 1: quantize chunks, all-to-all so worker w collects chunk w
    q, s = jax.vmap(_quant_groups)(chunks)    # [W, c] int8, [W, c/G] f32
    q = all_to_all_in_graph(q[:, None], names, split_axis=0, concat_axis=1,
                            tiled=False)      # [1, W, c]
    s = all_to_all_in_graph(s[:, None], names, split_axis=0, concat_axis=1,
                            tiled=False)
    partial = jax.vmap(_dequant_groups)(q[0], s[0])   # [W, c] f32
    reduced = jnp.sum(partial, axis=0) / world        # [c] — my chunk, meaned

    # hop 2: quantize the reduced chunk, all-gather, dequantize
    q2, s2 = _quant_groups(reduced)
    q2 = all_gather_in_graph(q2, names, tiled=False)  # [W, c] (stacked axes
    s2 = all_gather_in_graph(s2, names, tiled=False)  # collapse to W)
    q2 = q2.reshape(world, -1)
    s2 = s2.reshape(world, -1)
    out = jax.vmap(_dequant_groups)(q2, s2).reshape(-1)
    if pad:
        out = out[:n]
    return out.reshape(g.shape).astype(g.dtype)


def quantized_reduce_scatter(g: jnp.ndarray, axis_names: Sequence[str],
                             dim: int) -> jnp.ndarray:
    """int8 single-hop reduce-scatter of one tensor along ``dim`` — the
    stage-3 form of qgZ: each worker ends up holding only ITS slice of the
    mean gradient (matching the ZeRO-3 grad/opt-state layout), so hop 2
    (all-gather) never happens and wire bytes drop to ~1×int8 vs 4×fp32.

    Inside shard_map; ``g`` is this worker's full local gradient."""
    names = tuple(axis_names)
    world = 1
    for ax in names:
        world *= _axis_size(ax)
    if world == 1:
        return g

    gm = jnp.moveaxis(g, dim, 0).astype(jnp.float32)
    per = gm.shape[0] // world
    rest = int(np.prod(gm.shape[1:])) if gm.ndim > 1 else 1
    n = per * rest
    flat = gm.reshape(world, n)
    pad = -n % GROUP
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))

    q, s = jax.vmap(_quant_groups)(flat)      # [W, n'] int8, [W, n'/G] f32
    q = all_to_all_in_graph(q[:, None], names, split_axis=0, concat_axis=1,
                            tiled=False)       # [1, W, n']
    s = all_to_all_in_graph(s[:, None], names, split_axis=0, concat_axis=1,
                            tiled=False)
    partial = jax.vmap(_dequant_groups)(q[0], s[0])   # [W, n'] f32
    red = jnp.sum(partial, axis=0) / world
    if pad:
        red = red[:n]
    out = red.reshape((per,) + tuple(gm.shape[1:]))
    return jnp.moveaxis(out, 0, dim).astype(g.dtype)


def qgz_reduce_tree(grads: Any, axis_names: Sequence[str]) -> Any:
    return jax.tree.map(lambda g: quantized_allreduce(g, axis_names), grads)


def wire_bytes(params: Any) -> Tuple[int, int]:
    """(quantized, fp32) DP-reduction bytes per worker — int8 payload plus
    fp32 group scales for both hops, vs fp32 reduce-scatter + all-gather."""
    n = sum(int(np.prod(np.shape(p))) for p in jax.tree.leaves(params))
    quant = 2 * (n + 4 * (n // GROUP))
    return quant, 8 * n
