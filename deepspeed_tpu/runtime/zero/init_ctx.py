"""zero.Init / GatheredParameters — ZeRO-3 construction-time API.

Reference: ``zero/partition_parameters.py`` [K] — ``zero.Init`` patches
``nn.Parameter.__new__`` so params are partitioned at construction
[L HF-MU:2306]; ``GatheredParameters(params, modifier_rank=)`` temporarily
assembles full params for surgery [L HF-MU:3218].

TPU-first: params are pytrees and sharding is metadata, so
* ``Init`` = materialize the init function DIRECTLY into its ZeRO sharding
  (``jax.jit(init_fn, out_shardings=...)``) — the full model never exists on
  one device, which is the entire point of the reference machinery;
* ``GatheredParameters`` = a context that hands out the assembled host copy
  and (with ``modifier_rank``) writes modifications back into the sharded
  arrays on exit.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np

from ...telemetry.perf import get_compile_tracker, tracked_jit
from ...utils import groups as groups_mod
from .config import DeepSpeedZeroConfig
from .sharder import ZeroShardingPolicy


class Init:
    """Context + materializer.  Usage::

        with zero.Init(config_dict_or_path=ds_config, mesh=mesh) as zinit:
            params = zinit.materialize(model.init_params, rng,
                                       base_specs=model.param_specs())
    """

    def __init__(self, module: Any = None, data_parallel_group: Any = None,
                 mem_efficient_linear: bool = True, remote_device: Any = None,
                 pin_memory: bool = False, config_dict_or_path: Any = None,
                 config: Any = None, enabled: bool = True, dtype: Any = None,
                 mpu: Any = None, mesh: Any = None):
        self.enabled = enabled
        self.mesh = mesh if mesh is not None else groups_mod.get_mesh()
        payload = config_dict_or_path if config_dict_or_path is not None else config
        zero_cfg = DeepSpeedZeroConfig()
        if isinstance(payload, dict):
            zero_cfg = DeepSpeedZeroConfig.model_validate(
                payload.get("zero_optimization", {}))
        elif payload is not None:
            from ..config import _load_config_payload

            zero_cfg = DeepSpeedZeroConfig.model_validate(
                _load_config_payload(payload).get("zero_optimization", {}))
        if zero_cfg.stage < 3:
            zero_cfg = zero_cfg.model_copy(update={"stage": 3})
        self.policy = ZeroShardingPolicy.from_config(self.mesh, zero_cfg)

    def __enter__(self) -> "Init":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def materialize(self, init_fn: Callable[..., Any], *args,
                    base_specs: Any = None) -> Any:
        """Run ``init_fn(*args)`` with every output leaf born sharded."""
        if not self.enabled:
            return init_fn(*args)
        shapes = jax.eval_shape(init_fn, *args)
        shardings = self.policy.param_shardings(shapes, base_specs)
        return tracked_jit(init_fn, "zero_init/materialize",
                           tracker=get_compile_tracker(),
                           out_shardings=shardings)(*args)


class GatheredParameters:
    """Assemble sharded params on host; write back if ``modifier_rank`` is
    set (None → read-only view, reference semantics)."""

    def __init__(self, params: Any, modifier_rank: Optional[int] = None,
                 fwd_module: Any = None, enabled: bool = True):
        self.params = params
        self.modifier_rank = modifier_rank
        self.enabled = enabled
        self.gathered: Any = None

    def __enter__(self) -> Any:
        if not self.enabled:
            return self.params
        self.gathered = jax.tree.map(
            lambda p: np.array(jax.device_get(p)), self.params)
        return self.gathered

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is not None or not self.enabled:
            return
        if self.modifier_rank is not None:
            # jax arrays are immutable, so the write-back materializes as a
            # NEW pytree in the original shardings: callers read .result
            # (torch mutates in place; this is the functional equivalent)
            self.result = jax.tree.map(
                lambda old, new: jax.device_put(
                    new, getattr(old, "sharding", None)),
                self.params, self.gathered)
