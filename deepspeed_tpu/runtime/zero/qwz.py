"""qwZ — ZeRO++ quantized-weight all-gather (arXiv 2306.10209 [P]).

Role parity: ``zero_quantized_weights`` inside the reference's
``zero/stage3.py`` + ``csrc/quantization`` [K]: ZeRO-3's parameter
all-gathers move int8 + group scales instead of fp16, halving (vs bf16)
the gather bytes that dominate stage-3 comm.

TPU-first formulation: the gather is GSPMD-inserted, so qwZ becomes a
dtype trick in the program — quantize the SHARDED fp32 master leaf
(elementwise, stays sharded), pin the int8 tensor (and its scales) to a
REPLICATED sharding constraint, then dequantize locally.  The constraint
forces the compiler to place the all-gather on the int8 representation:
wire bytes drop ~4× vs fp32 / ~2× vs bf16, and the dequant runs
post-gather on every chip.  The backward is straight-through (cotangent
flows to the master unchanged) — exactly the reference semantics, where
quantization is gather compression, not a training-math change; the
LOSSY part (compute sees int8-rounded weights) is also shared with the
reference.

Group scheme: blocks of ``GROUP`` along the last dim when it divides,
else one scale per last-dim row — shape-preserving, so the leaf's
sharding plan (ZeRO/TP) is untouched through the quantize step.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

GROUP = 256


def _quant(p32: jnp.ndarray, group: int = GROUP):
    if p32.ndim == 0:
        # 0-d leaves (scalars) can't be grouped — and aren't worth wiring
        # as int8; return as-is (callers treat scale=None as "not quantized")
        return p32, None
    d = p32.shape[-1] if p32.ndim else 1
    if d % group == 0:
        g = p32.reshape(*p32.shape[:-1], d // group, group)
    else:
        g = p32[..., None, :]  # one group per row
    amax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(g / scale), -127, 127).astype(jnp.int8)
    return q.reshape(p32.shape), scale[..., 0]


def _dequant(q: jnp.ndarray, scale: jnp.ndarray, shape,
             group: int = GROUP) -> jnp.ndarray:
    d = shape[-1] if shape else 1
    if shape and d % group == 0:
        g = q.reshape(*shape[:-1], d // group, group)
    else:
        g = q[..., None, :]
    return (g.astype(jnp.float32) * scale[..., None]).reshape(shape)


def make_qwz(mesh: Mesh, base_spec: Optional[PartitionSpec] = None
             ) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Per-leaf weight compressor bound to ``mesh``.

    ``base_spec`` is the leaf's MODEL placement (TP column/row split etc.):
    the int8 tensor is pinned to exactly that spec — replicated over the
    ZeRO DP axes (undoing the stage-3 shard → the all-gather lands on
    int8) while every TP/pipe axis the model claimed stays sharded, so
    qwZ never materializes a weight TP was keeping split.
    """
    target = NamedSharding(mesh, base_spec or PartitionSpec())
    replicated = NamedSharding(mesh, PartitionSpec())

    def _impl(p: jnp.ndarray) -> jnp.ndarray:
        q, s = _quant(p.astype(jnp.float32))
        if s is None:  # 0-d leaf — nothing to group-quantize
            return p
        # the constraint is THE mechanism: the DP all-gather lands on int8
        q = jax.lax.with_sharding_constraint(q, target)
        s = jax.lax.with_sharding_constraint(s, replicated)  # tiny
        return _dequant(q, s, p.shape).astype(p.dtype)

    @jax.custom_vjp
    def qwz(p):
        return _impl(p)

    def fwd(p):
        return _impl(p), None

    def bwd(_, g):  # straight-through: gather compression, not new math
        return (g,)

    qwz.defvjp(fwd, bwd)
    return qwz


def qwz_compress_tree(params: Any, mesh: Mesh, threshold: int = 0,
                      base_specs: Any = None) -> Any:
    """Apply qwZ to every float leaf larger than ``threshold`` elements
    (small/persisted leaves stay full precision, mirroring the reference's
    persistence-threshold interplay).  ``base_specs`` — matching pytree of
    model PartitionSpecs (TP placement to preserve)."""

    def one(p, spec):
        if (not jnp.issubdtype(p.dtype, jnp.floating)
                or int(np.prod(p.shape)) <= threshold):
            return p
        return make_qwz(mesh, spec)(p)

    if base_specs is None:
        return jax.tree.map(lambda p: one(p, None), params)
    return jax.tree.map(one, params, base_specs)
