"""ZeRO config schema.

Capability parity with the reference ``deepspeed/runtime/zero/config.py`` and
``offload_config.py`` [K]; key inventory from SURVEY §5.6 [L ACC-DC:1136-1171,
HF-DS:216-255].  On TPU most knobs that tune the reference's hand-rolled
gather/prefetch machinery (bucket sizes, prefetch, persistence thresholds,
overlap_comm) are accepted for config compatibility but are advisory: GSPMD
schedules the equivalent collectives.  They are still recorded and surfaced so
configs round-trip, and a few (e.g. offload devices) change real behavior.
"""

from __future__ import annotations

from enum import Enum
from typing import Literal, Optional, Union

from pydantic import Field

from ..config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """``zero_optimization.offload_param`` (stage 3)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """``zero_optimization.offload_optimizer`` (stages 1-3)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0  # fraction of optimizer computed on offload device


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    """``zero_optimization.*``"""

    stage: Literal[0, 1, 2, 3] = 0

    # stage 1/2 machinery — advisory on TPU (GSPMD owns comm scheduling).
    allgather_partitions: bool = True
    allgather_bucket_size: Union[int, str] = 500_000_000
    overlap_comm: Optional[bool] = None  # reference default depends on stage
    reduce_scatter: bool = True
    reduce_bucket_size: Union[int, str] = 500_000_000  # may be "auto"
    contiguous_gradients: bool = True
    round_robin_gradients: bool = False

    # stage 3
    stage3_prefetch_bucket_size: Union[int, str] = 50_000_000  # may be "auto"
    stage3_param_persistence_threshold: Union[int, str] = 100_000  # may be "auto"
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    stage3_module_granularity_threshold: int = 0

    # offload
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    # ZeRO++ (qwZ / hpZ / qgZ)
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False
    zero_hpz_partition_size: int = 1

    # MiCS (hybrid shard)
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False

    # misc parity knobs
    sub_group_size: int = 1_000_000_000
    elastic_checkpoint: bool = False
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    cpu_offload: Optional[bool] = Field(default=None, deprecated=True)
    param_persistence_threshold: Optional[int] = None
    model_persistence_threshold: Optional[int] = None
    zeropp_loco_param: Optional[dict] = None
    log_trace_cache_warnings: bool = False

    def offload_optimizer_device(self) -> OffloadDeviceEnum:
        if self.cpu_offload:  # deprecated bool form
            return OffloadDeviceEnum.cpu
        if self.offload_optimizer is None:
            return OffloadDeviceEnum.none
        return self.offload_optimizer.device

    def offload_param_device(self) -> OffloadDeviceEnum:
        if self.offload_param is None:
            return OffloadDeviceEnum.none
        return self.offload_param.device

    def resolve_auto_from_hidden_size(self, hidden_size: int) -> None:
        """The reference's ``"auto"`` heuristics [L HF-DS:216-255]:
        reduce_bucket_size = hidden², prefetch = 0.9·hidden²,
        persistence threshold = 10·hidden."""
        from ..config_utils import is_auto

        if is_auto(self.reduce_bucket_size):
            self.reduce_bucket_size = hidden_size * hidden_size
        if is_auto(self.stage3_prefetch_bucket_size):
            self.stage3_prefetch_bucket_size = int(0.9 * hidden_size * hidden_size)
        if is_auto(self.stage3_param_persistence_threshold):
            self.stage3_param_persistence_threshold = 10 * hidden_size
