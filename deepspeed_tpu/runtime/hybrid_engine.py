"""Hybrid engine — one engine for RLHF's train ↔ generate flip.

Reference: ``deepspeed/runtime/hybrid_engine.py`` [K] —
``DeepSpeedHybridEngine(DeepSpeedEngine)``: trains under ZeRO-3, then for
the RLHF experience-generation phase gathers the sharded params and runs
kernel-injected inference, flipping back without reloading weights
(SURVEY §2.1 "Hybrid engine (RLHF)" row).

TPU-first collapse: the reference's flip machinery exists because torch
inference kernels need contiguous full weights while ZeRO-3 holds shards.
Under GSPMD both the train step AND the generate programs consume the SAME
sharded param pytree — the "flip" is just dispatching a different compiled
program against ``engine.state.params``.  What remains worth building is
exactly this class: the shared-weights lifecycle (generate always sees the
latest optimizer step, no copy), the jitted prefill/decode reuse across
flips, and the generate-throughput metrics the reference logs.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..telemetry.perf import get_compile_tracker, tracked_jit
from ..utils.logging import log_dist


class DeepSpeedHybridEngine:
    """Wraps a training engine with a weight-sharing generate path.

    Train API passes through (``train_step``/``backward``/``step``/…);
    ``generate`` runs the model's prefill/decode programs against the
    engine's CURRENT params — after any ``train_step``, generation uses the
    updated weights with zero copies or re-init.
    """

    def __init__(self, engine: Any, max_out_tokens: int = 512):
        if not callable(getattr(engine.module, "prefill", None)):
            raise TypeError(
                "hybrid engine needs a model with prefill/decode_step "
                f"(got {type(engine.module)})")
        self.engine = engine
        self.module = engine.module
        self.max_out_tokens = int(max_out_tokens)
        self._prefill = tracked_jit(self.module.prefill, "hybrid/prefill",
                                    tracker=get_compile_tracker())
        self._decode = tracked_jit(self.module.decode_step, "hybrid/decode",
                                   tracker=get_compile_tracker())
        self._gen_tokens = 0
        self._gen_time = 0.0
        self._train_time = 0.0

    # -- train passthrough -------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        # anything not defined here is the training engine's surface
        return getattr(self.engine, name)

    def train_step(self, batch) -> Dict[str, Any]:
        t0 = time.perf_counter()
        out = self.engine.train_step(batch)
        self._train_time += time.perf_counter() - t0
        return out

    # -- generate phase ----------------------------------------------------

    def generate(self, input_ids: Any, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 eos_token_id: Optional[int] = None) -> jnp.ndarray:
        """Greedy/sampled generation with the training engine's live params
        (reference ``DeepSpeedHybridEngine.generate``)."""
        max_new = int(max_new_tokens or self.max_out_tokens)
        input_ids = jnp.asarray(input_ids)
        B, S = input_ids.shape
        params = self.engine.state.params  # ZeRO-sharded, latest step
        t0 = time.perf_counter()
        cache = self.module.init_cache(B, S + max_new)
        logits, cache = self._prefill(params, input_ids, cache)
        rng = jax.random.PRNGKey(seed)
        out: List[jnp.ndarray] = [input_ids]
        last = None
        done = jnp.zeros((B,), bool)
        # device-side decoded-token counter: NO host fetch inside the loop
        # (a per-token device→host sync serializes decode — exactly the
        # throughput this class exists to report); the early-exit all-done
        # check runs only every few steps, and only when eos is set
        produced = jnp.int32(0)
        check_every = 8
        for i in range(max_new):
            if temperature > 0:
                rng, sub = jax.random.split(rng)
                scaled = logits / temperature
                if top_k > 0:
                    kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
                    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
                tok = jax.random.categorical(sub, scaled).astype(jnp.int32)
            else:
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if eos_token_id is not None and last is not None:
                tok = jnp.where(done, last, tok)
            produced = produced + (B - jnp.sum(done))
            out.append(tok[:, None])
            last = tok
            if eos_token_id is not None:
                done = done | (tok == eos_token_id)
                if (i + 1) % check_every == 0 and bool(jnp.all(done)):
                    pad = jnp.tile(tok[:, None], (1, max_new - i - 1))
                    out.append(pad)
                    break
            if i < max_new - 1:
                logits, cache = self._decode(params, cache, tok)
        result = jnp.concatenate(out, axis=1)
        self._gen_tokens += int(produced)  # single sync, after the loop
        self._gen_time += time.perf_counter() - t0
        return result

    # -- reference surface shims -------------------------------------------

    def eval(self):
        self.engine.eval()
        return self

    def train(self, mode: bool = True):
        self.engine.train(mode)
        return self

    def release_inference_cache(self) -> None:
        """Reference API: drop inference buffers between phases.  Caches
        here are per-call locals, so this only clears the jit caches."""
        self._prefill = tracked_jit(self.module.prefill, "hybrid/prefill",
                                    tracker=get_compile_tracker())
        self._decode = tracked_jit(self.module.decode_step, "hybrid/decode",
                                   tracker=get_compile_tracker())

    def print_latency_log(self) -> None:
        tps = self._gen_tokens / self._gen_time if self._gen_time else 0.0
        log_dist(f"hybrid engine: generated {self._gen_tokens} tokens "
                 f"({tps:.1f} tok/s), train time {self._train_time:.2f}s, "
                 f"generate time {self._gen_time:.2f}s")
