"""Data loaders.

Capability parity with the reference ``deepspeed/runtime/dataloader.py`` [K]:
``DeepSpeedDataLoader`` (micro-batch sizing + distributed sharding) and
``RepeatingLoader``.  TPU-native: single-controller, one process feeds the
GLOBAL batch and sharding over DP ranks is a ``jax.device_put`` with the
batch NamedSharding, not a per-rank sampler.  Multi-controller
(``jax.process_count() > 1``): each process materializes ONLY its own rows
and ``make_array_from_process_local_data`` assembles the global array —
per-rank feeding, exercised by ``tests/unit/multiprocess/``.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np

from ..parallel.mesh import batch_sharding, global_feed, global_put


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference name)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self._iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = iter(self.loader)
            return next(self._iter)


class DeepSpeedDataLoader:
    """Yields device-placed global batches sharded over the DP mesh axes.

    ``dataset`` may be any indexable of pytrees (dict of arrays etc.) or an
    iterable of numpy batches.  ``batch_size`` is the GLOBAL batch
    (micro × gas × dp_world) consumed by one ``engine.train_step``.
    """

    def __init__(self, dataset: Any, batch_size: int, mesh=None,
                 collate_fn: Optional[Callable] = None, shuffle: bool = False,
                 seed: int = 0, sp_shard_sequence: bool = False,
                 drop_last: bool = True):
        from ..utils import groups as groups_mod

        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.mesh = mesh if mesh is not None else groups_mod.get_mesh()
        self.collate_fn = collate_fn
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.sharding = batch_sharding(self.mesh, sp_shard_sequence)
        self._epoch = 0
        #: batches of the NEXT epoch to skip before yielding (set by
        #: resume_from_samples after a cross-mesh resume; cleared once
        #: consumed)
        self._resume_skip_batches = 0
        self._local_rows_cache: dict = {}

    def __len__(self):
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def _sharding_for(self, n: int):
        """Batch sharding, degrading to replicated when a (final partial)
        batch doesn't divide across the batch mesh axes."""
        axes = self.sharding.spec[0] or ()
        axes = (axes,) if isinstance(axes, str) else axes
        dp = int(np.prod([self.mesh.shape[a] for a in axes])) if axes else 1
        if n % max(dp, 1):
            from ..parallel.mesh import replicated

            return replicated(self.mesh)
        return self.sharding

    def _local_rows(self, n: int):
        """This process's contiguous batch-row block [start, stop) under
        the dp sharding — derived from the ACTUAL device index map, so
        permuted mesh device orders still feed the right rows — or None
        when the process's addressable rows aren't one contiguous 1/pw
        block (batch axes not process-major, e.g. a model-parallel plane
        per process): then every process materializes the full batch.
        Deterministic per (mesh, n) — memoized off the input hot path."""
        if n in self._local_rows_cache:
            return self._local_rows_cache[n]
        self._local_rows_cache[n] = rows = self._compute_local_rows(n)
        return rows

    def _compute_local_rows(self, n: int):
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec

        # the fast path hands FULL-width local rows to the full sharding,
        # so every non-batch dim must be entirely process-local: a mesh
        # axis sharding e.g. the sequence dim across processes (ALST
        # sp_shard_sequence on a multi-host seq axis) means this process's
        # addressable block is narrower than the rows we'd build — fall
        # back to the global_put path there.
        mesh_devs = np.asarray(self.mesh.devices)
        names = list(self.mesh.axis_names)
        for entry in self.sharding.spec[1:]:
            for a in ((entry,) if isinstance(entry, str) else (entry or ())):
                moved = np.moveaxis(mesh_devs, names.index(a), 0)
                for col in moved.reshape(moved.shape[0], -1).T:
                    if len({d.process_index for d in col}) > 1:
                        return None  # non-batch axis spans processes

        probe = NamedSharding(self.mesh, PartitionSpec(self.sharding.spec[0]))
        ivs = sorted({(sl[0].start or 0,
                       n if sl[0].stop is None else sl[0].stop)
                      for sl in probe.addressable_devices_indices_map(
                          (n,)).values()})
        start, stop = ivs[0]
        for a, b in ivs[1:]:
            if a > stop:
                return None  # non-contiguous ownership
            stop = max(stop, b)
        if stop - start != n // _jax.process_count():
            return None  # overlapping/replicated ownership
        return start, stop

    def _order(self) -> np.ndarray:
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        return idx

    def resume_from_samples(self, consumed: int) -> None:
        """Re-point the cursor at an absolute SAMPLE position — the
        mesh-elastic resume path: a snapshot taken at global batch A
        resumed under global batch B converts its progress to samples
        (steps × A) and hands it here, so no data window is ever
        double-consumed.  Position lands on the next batch-B boundary
        AT-OR-PAST ``consumed``, rounding up — skipping a few unseen
        samples (including a drop_last remainder the ORIGIN batch size
        would have dropped anyway) beats refeeding seen ones.  Epochs
        are dataset-length-denominated on purpose: the origin run's
        per-epoch drop_last remainder depends on a batch size this
        loader cannot know, and rounding that ambiguity UP keeps the
        no-refeed contract."""
        consumed = max(int(consumed), 0)
        n = len(self.dataset)
        if n <= 0 or self.batch_size <= 0:
            self._epoch, self._resume_skip_batches = 0, 0
            return
        self._epoch = consumed // n
        within = consumed - self._epoch * n
        skip = -(-within // self.batch_size)  # ceil
        per_epoch_batches = n // self.batch_size if self.drop_last \
            else -(-n // self.batch_size)
        if skip >= per_epoch_batches:
            # the offset lands past what THIS batch size can yield from
            # the epoch (a cross-batch-size remainder): advance to the
            # next epoch head instead of iterating an empty epoch
            self._epoch += 1
            skip = 0
        self._resume_skip_batches = skip

    def __iter__(self) -> Iterator[Any]:
        order = self._order()
        self._epoch += 1
        skip, self._resume_skip_batches = self._resume_skip_batches, 0
        if skip:
            order = order[skip * self.batch_size:]
        pw = jax.process_count()
        for start in range(0, len(order), self.batch_size):
            sel = order[start:start + self.batch_size]
            if len(sel) < self.batch_size and self.drop_last:
                break
            sh = self._sharding_for(len(sel))
            rows = (self._local_rows(len(sel))
                    if pw > 1 and len(sel) % pw == 0 and sh is self.sharding
                    else None)
            if rows is not None:
                # multi-controller: each process materializes ONLY its own
                # rows (per-rank feeding, the reference's DistributedSampler
                # contract) and the global dp-sharded array is assembled
                # from the local slices.  Only when the dp sharding really
                # applies — a replicated fallback (partial batch) must see
                # the FULL batch on every process, below.
                items = [self.dataset[int(i)] for i in sel[rows[0]:rows[1]]]
                local = (self.collate_fn(items) if self.collate_fn
                         else jax.tree.map(lambda *xs: np.stack(xs), *items))
                yield jax.tree.map(
                    lambda x: global_feed(np.asarray(x), sh), local)
                continue
            items = [self.dataset[int(i)] for i in sel]
            batch = (self.collate_fn(items) if self.collate_fn
                     else jax.tree.map(lambda *xs: np.stack(xs), *items))
            # global_put: multi-host-safe for replicated AND sharded specs
            # (every process holds the full batch here)
            yield jax.tree.map(lambda x: global_put(np.asarray(x), sh),
                               batch)
