from .config import DeepSpeedConfig
from .config_utils import AUTO, DeepSpeedConfigModel, is_auto

__all__ = ["DeepSpeedConfig", "DeepSpeedConfigModel", "AUTO", "is_auto"]
