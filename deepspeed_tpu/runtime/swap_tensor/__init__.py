"""ZeRO-Infinity tiering: layer-granular param/optimizer swap (cpu/nvme).

Reference tree: ``deepspeed/runtime/swap_tensor/`` [K] (SURVEY §2.1).
"""

from .infinity_engine import LayerStreamingEngine
from .partitioned_param_swapper import PartitionedParamSwapper

__all__ = ["LayerStreamingEngine", "PartitionedParamSwapper"]
