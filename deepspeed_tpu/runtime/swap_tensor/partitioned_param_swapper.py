"""Layer-granular param/optimizer tiering — the ZeRO-Infinity swap core.

Role parity: ``deepspeed/runtime/swap_tensor/{partitioned_param_swapper,
partitioned_optimizer_swapper,pipelined_optimizer_swapper}.py`` + the
``csrc/aio`` engine behind them (SURVEY §2.1 NVMe/CPU swap row, §2.2 AIO).

TPU-first shape: instead of the reference's per-tensor swap of flattened
fp16 partitions inside the ZeRO-3 hook machinery, tiering is *layer
granular* — the natural prefetch unit of a scan-over-layers decoder.  Each
layer owns four contiguous host planes:

    wire    compute-dtype (bf16) copy — what streams h2d for fwd/bwd
    master  fp32 params               — what the host optimizer updates
    m, v    fp32 Adam moments

``cpu`` tier: all planes live in host RAM permanently.
``nvme`` tier: planes persist as files; a small ring of reusable staging
buffers (``buffer_count``) holds the layers in flight, read ahead/written
behind through the C++ AIO engine (``ops/aio``).  Host memory is then
O(buffer_count × layer), not O(num_layers × layer) — params can exceed
host RAM, the Infinity property.

The optimizer update is the fused C++ ``ds_adam_step_bf16``: one pass
updates master+moments AND emits the refreshed bf16 wire plane (no separate
cast step), which then writes behind to NVMe while earlier layers compute.
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.op_builder import CPUAdamBuilder
from ...telemetry import get_telemetry
from ...utils.logging import log_dist

_f32p = ctypes.POINTER(ctypes.c_float)
_u16p = ctypes.POINTER(ctypes.c_uint16)


def _fp(a: np.ndarray):
    return a.ctypes.data_as(_f32p)


def _leaf_layout(tree: Any) -> Tuple[Any, List[Tuple[Tuple[int, ...], int]]]:
    """(treedef, [(shape, offset_elems)]) for one layer's param pytree."""
    leaves, treedef = jax.tree.flatten(tree)
    layout = []
    off = 0
    for leaf in leaves:
        shape = tuple(np.shape(leaf))
        layout.append((shape, off))
        off += int(np.prod(shape)) if shape else 1
    return treedef, layout


class _Planes:
    """One layer's staging buffers (contiguous 1-D host arrays).  The grad
    plane ``g`` is allocated lazily — only the stash path (gradient
    accumulation / global clipping) needs it."""

    __slots__ = ("wire", "master", "m", "v", "g")

    def __init__(self, n: int, wire_dtype):
        self.wire = np.zeros((n,), wire_dtype)
        self.master = np.zeros((n,), np.float32)
        self.m = np.zeros((n,), np.float32)
        self.v = np.zeros((n,), np.float32)
        self.g = None

    def ensure_g(self) -> np.ndarray:
        if self.g is None:
            self.g = np.zeros_like(self.master)
        return self.g


class _OptPipeline:
    """Bounded single-worker pipeline hiding the host optimizer behind
    device compute — the reference's
    ``runtime/swap_tensor/pipelined_optimizer_swapper.py`` role.

    The main thread submits (layer, grads, ...) right after dispatching
    that layer's vjp; the d2h of the grads is started asynchronously AT
    SUBMIT (``copy_to_host_async``), so while the worker runs layer i's
    fused C++ Adam (ctypes releases the GIL — real CPU parallelism),
    layer i-1's grads are in flight over DMA and the device is computing
    layer i-2's backward.  Depth-bounded queue: at most ``depth`` layers
    of grads stay live on device — depth-1 queued plus the one the worker
    popped and is processing (the double-buffer memory contract)."""

    def __init__(self, run, depth: int = 2):
        self._run = run
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth - 1))
        self._err: Optional[BaseException] = None
        self._t = threading.Thread(target=self._loop, daemon=True,
                                   name="ds-opt-pipeline")
        self._t.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            try:
                if self._err is None:  # after an error: drain, don't run
                    self._run(*item)
            except BaseException as e:  # surfaced on drain()
                self._err = e
            finally:
                self._q.task_done()

    def submit(self, *item: Any) -> None:
        if self._err is not None:
            self.drain()
        self._q.put(item)

    def drain(self) -> None:
        """Block until every submitted update has completed; re-raise the
        first worker error (the step must not silently lose an update)."""
        self._q.join()
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def close(self) -> None:
        self._q.put(None)
        self._t.join(timeout=30)


class PartitionedParamSwapper:
    """Layer-granular param + optimizer-state store with cpu/nvme tiers.

    Construction takes the per-layer param pytrees (host numpy / jax arrays)
    and immediately owns them: masters seeded fp32, wire planes cast once,
    moments zeroed.  The executor drives ``prefetch → get_device → release``
    for forward, and ``prefetch_full → step_layer`` for backward.
    """

    def __init__(self, layer_trees: List[Any], *, wire_dtype=jnp.bfloat16,
                 nvme_path: Optional[str] = None, buffer_count: int = 4,
                 aio_config: Any = None, adam_hparams: Optional[Dict] = None,
                 placement: Optional[Any] = None,
                 shard: Optional[Dict[str, Any]] = None,
                 pipeline: bool = False):
        assert layer_trees, "need at least one layer"
        #: tree → device tree; the streaming executor injects a mesh-aware
        #: fn (NamedSharding device_put per leaf) for multi-chip runs.  MUST
        #: snapshot (np.array) each leaf: on the CPU backend device_put
        #: aliases the numpy buffer, and slots/planes are reused in place.
        self._placement = placement
        self.L = len(layer_trees)
        self.treedef, self.layout = _leaf_layout(layer_trees[0])
        self.n_elems = sum(int(np.prod(s)) if s else 1 for s, _ in self.layout)
        # ``shard``: MULTI-CONTROLLER host planes.  Each process owns the
        # global index SEGMENTS its addressable devices cover in the
        # device-sharded flat plane — the reference's partitioned optimizer
        # state (ZeRO-3 under Infinity, SURVEY §2.1 #17): host RAM AND nvme
        # bytes per process are O(layer/world).  Segments, not a rank-
        # derived contiguous chunk: mesh construction may permute device
        # order (ICI topology), so a process's slice of the flat plane need
        # not be [rank*k, (rank+1)*k).  The local plane concatenates the
        # segments in global order; the executor assembles/scatters the
        # device arrays with the same ordering rule.
        #   shard = {"rank", "world", "n_pad",
        #            "segments": [(start, stop), ...]            # mine
        #            "gather_map": [[(start, stop), ...], ...]}  # per rank
        if shard is not None:
            self.shard_rank = int(shard["rank"])
            self.shard_world = int(shard["world"])
            self.n_pad = int(shard["n_pad"])
            self.segments = [(int(a), int(b)) for a, b in shard["segments"]]
            self._gather_map = shard["gather_map"]
            self.n_plane = sum(b - a for a, b in self.segments)
        else:
            self.n_pad = self.n_elems
            self.n_plane = self.n_elems
            self.shard_rank, self.shard_world = 0, 1
            self.segments = [(0, self.n_elems)]
            self._gather_map = None
        self.wire_np_dtype = np.dtype(wire_dtype)
        self._wire_is_bf16 = wire_dtype == jnp.bfloat16
        self.nvme_dir = nvme_path
        if pipeline and int(buffer_count) < 2:
            # the worker pins the layer it is mid-update on; with a single
            # staging slot every eviction candidate could be pinned and the
            # read-ahead would deadlock against the update it overlaps
            raise ValueError(
                f"buffer_count={buffer_count} is too small for the "
                f"pipelined optimizer (pipeline=True needs >= 2: one slot "
                f"for the in-flight update, one for read-ahead)")
        self.buffer_count = max(2, int(buffer_count))
        # memory-plane handle BEFORE tier setup: the nvme branch below
        # persists every layer through _write_layer_sync, which records
        # its disk_write bytes against this ledger
        from ...telemetry.memory import get_memory_ledger

        self._mem = get_memory_ledger()

        hp = dict(adam_hparams or {})
        self.lr = float(hp.get("lr", 1e-3))
        self.betas = tuple(hp.get("betas", (0.9, 0.999)))
        self.eps = float(hp.get("eps", 1e-8))
        self.weight_decay = float(hp.get("weight_decay", 0.0))
        self.adamw_mode = bool(hp.get("adamw_mode", True))
        self.bias_correction = bool(hp.get("bias_correction", True))
        self.state_step = 0

        self._lib = CPUAdamBuilder.load()
        self._lib.ds_adam_step.argtypes = [
            _f32p, _f32p, _f32p, _f32p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int]
        self._lib.ds_adam_step_bf16.argtypes = [
            _f32p, _f32p, _f32p, _f32p, _u16p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int]

        if self.nvme_dir is None:
            # cpu tier: one resident plane set per layer
            self._resident = [self._seed_planes(t) for t in layer_trees]
            self._aio = None
            self._slots = None
        else:
            os.makedirs(self.nvme_dir, exist_ok=True)
            from ...ops.aio import AIOHandle

            ac = aio_config
            self._aio = AIOHandle(
                block_size=getattr(ac, "block_size", 1 << 20),
                queue_depth=getattr(ac, "queue_depth", 8),
                single_submit=getattr(ac, "single_submit", False),
                overlap_events=getattr(ac, "overlap_events", True),
                thread_count=getattr(ac, "thread_count", 2))
            # persist every layer once, then keep only the staging ring
            scratch = _Planes(self.n_plane, self.wire_np_dtype)
            for i, tree in enumerate(layer_trees):
                self._fill_planes(scratch, tree)
                self._write_layer_sync(i, scratch, init=True)
            del scratch
            self._resident = None
            self._slots = [_Planes(self.n_plane, self.wire_np_dtype)
                           for _ in range(self.buffer_count)]
            self._slot_of: Dict[int, int] = {}      # layer -> slot idx
            self._slot_state: Dict[int, str] = {}   # layer -> wire|full|reading
            self._free = list(range(self.buffer_count))
            self._lru: List[int] = []               # layers, oldest first
            self._dirty_writes = 0

        self._device_cache: Dict[int, Any] = {}
        self._gplanes: Dict[int, np.ndarray] = {}  # stashed grads per layer
        self._scratch_g: Optional[np.ndarray] = None  # fused-path grad buf
        # pipelined optimizer (reference pipelined_optimizer_swapper role):
        # a worker thread runs grad-flatten + fused C++ Adam + write-behind
        # while the main thread keeps dispatching device work.  The lock
        # guards nvme slot/aio bookkeeping shared between the threads;
        # _pinned stops _evict_for_slot from reusing a slot mid-update.
        self._lock = threading.RLock()
        self._pinned: set = set()
        self._pipe_g: Optional[np.ndarray] = None  # worker-exclusive buf
        self._pipe = _OptPipeline(self._pipe_step) if pipeline else None
        tier = "nvme" if self.nvme_dir else "cpu"
        per_layer = self.n_plane * (12 + self.wire_np_dtype.itemsize)
        host_mib = (self.buffer_count if self.nvme_dir else self.L) \
            * per_layer / 2**20
        log_dist(f"ZeRO-Infinity swapper: {self.L} layers × "
                 f"{self.n_elems:,} params, tier={tier}, "
                 f"host planes ≈ {host_mib:.0f} MiB")
        # memory plane (telemetry/memory): the staging planes are the
        # swap tier's real host allocation; NVMe/HBM traffic feeds the
        # ledger's swap-IO lanes at the read/write/put sites above
        if self._mem.enabled:
            n_planes = self.buffer_count if self.nvme_dir else self.L
            self._mem.register(
                "swap_staging", "infinity/host_planes",
                n_planes * per_layer, space="host",
                tag=f"Infinity {tier}-tier staging planes "
                    f"({n_planes} × {per_layer / 2**20:.0f} MiB)")

    # ------------------------------------------------------------------
    # plane helpers
    # ------------------------------------------------------------------

    def _seed_planes(self, tree: Any) -> _Planes:
        planes = _Planes(self.n_plane, self.wire_np_dtype)
        self._fill_planes(planes, tree)
        return planes

    def _fill_planes(self, planes: _Planes, tree: Any,
                     zero_moments: bool = True) -> None:
        """Seed planes from a GLOBAL layer pytree.  Sharded: only the
        intersections of each leaf's flat range with this process's
        segments land in the (plane-relative) positions; plane positions
        past ``n_elems`` (padding) are zeroed."""
        leaves = jax.tree.leaves(tree)
        flats = [None] * len(leaves)
        poff = 0  # plane offset of the current segment
        for lo, hi in self.segments:
            for li, (leaf, (shape, off)) in enumerate(
                    zip(leaves, self.layout)):
                n = int(np.prod(shape)) if shape else 1
                a, b = max(off, lo), min(off + n, hi)
                if a >= b:
                    continue
                if flats[li] is None:
                    flats[li] = np.asarray(
                        leaf, dtype=np.float32).reshape(-1)
                seg = flats[li][a - off:b - off]
                pa = poff + (a - lo)
                planes.master[pa:pa + (b - a)] = seg
                planes.wire[pa:pa + (b - a)] = seg.astype(
                    self.wire_np_dtype)
            if hi > self.n_elems:  # padding tail of this segment
                pa = poff + (max(lo, self.n_elems) - lo)
                pb = poff + (hi - lo)
                planes.master[pa:pb] = 0.0
                planes.wire[pa:pb] = 0.0
            poff += hi - lo
        if zero_moments:
            planes.m[:] = 0.0
            planes.v[:] = 0.0

    def _leaf_views(self, plane: np.ndarray) -> Any:
        assert self.shard_world == 1, (
            "sharded planes hold a process-local chunk; whole-leaf views "
            "only exist after a cross-process gather (gather_plane)")
        views = [plane[off:off + (int(np.prod(s)) if s else 1)].reshape(s)
                 for s, off in self.layout]
        return jax.tree.unflatten(self.treedef, views)

    def gather_plane(self, plane: np.ndarray) -> np.ndarray:
        """All-gather per-process planes into the full flat plane (every
        process participates and receives the full copy) — checkpoint and
        introspection path only; the hot path all-gathers in-graph.  Each
        rank's plane is scattered back through its segment table, so
        permuted device orders reassemble correctly."""
        if self.shard_world == 1:
            return plane
        from jax.experimental import multihost_utils

        stacked = np.asarray(multihost_utils.process_allgather(plane))
        full = np.zeros((self.n_pad,), plane.dtype)
        for p, segs in enumerate(self._gather_map):
            poff = 0
            for a, b in segs:
                full[a:b] = stacked[p, poff:poff + (b - a)]
                poff += b - a
        return full

    # ------------------------------------------------------------------
    # nvme file plumbing
    # ------------------------------------------------------------------

    def _path(self, i: int, kind: str) -> str:
        # sharded: each process persists only ITS chunk (distinct files —
        # nvme bytes per process stay O(layer/world))
        suffix = (f".r{self.shard_rank}" if self.shard_world > 1 else "")
        return os.path.join(self.nvme_dir, f"layer_{i:05d}{suffix}.{kind}")

    def _write_layer_sync(self, i: int, planes: _Planes, init: bool) -> None:
        for kind, buf in (("wire", planes.wire), ("master", planes.master),
                          ("m", planes.m), ("v", planes.v)):
            self._aio.async_pwrite(buf, self._path(i, kind), truncate=True)
            if self._mem.enabled:
                self._mem.record_io("disk_write", buf.nbytes)
        failed = self._aio.wait()
        if failed:
            raise IOError(f"AIO write of layer {i} failed ({failed} ops)")

    def _evict_for_slot(self) -> int:
        if self._free:
            return self._free.pop()
        # all writes are issued immediately after update; draining the queue
        # makes every slot content safely on disk before reuse
        if self._dirty_writes:
            failed = self._aio.wait()
            if failed:
                raise IOError(f"AIO write-behind failed ({failed} ops)")
            self._dirty_writes = 0
        # never evict a layer the pipeline worker is mid-update on (its
        # planes object must stay that slot's); buffer_count >= 2 and at
        # most one in-flight update guarantee an unpinned victim exists
        victim = next((l for l in self._lru if l not in self._pinned), None)
        if victim is None:
            raise RuntimeError(
                f"swap: no evictable staging slot — all "
                f"{len(self._lru)} resident layers are pinned by in-flight "
                f"optimizer updates (buffer_count={self.buffer_count}, "
                f"pinned={sorted(self._pinned)}); raise buffer_count "
                f"(pipelined updates need >= 2) or drain_updates() before "
                f"prefetching more layers")
        self._lru.remove(victim)
        slot = self._slot_of.pop(victim)
        self._slot_state.pop(victim, None)
        self._device_cache.pop(victim, None)
        get_telemetry().inc_counter(
            "swap/evictions", help="staging-slot evictions (LRU victim "
            "written back and reused for a new layer)")
        return slot

    # ------------------------------------------------------------------
    # executor API
    # ------------------------------------------------------------------

    def prefetch(self, i: int, full: bool = False) -> None:
        """Start moving layer ``i`` toward the device: NVMe→host read (async)
        and, for resident layers, host→device transfer (async device_put).
        ``full=True`` also stages master+moments (backward/update path)."""
        if not (0 <= i < self.L):
            return
        if self.nvme_dir is None:
            if i not in self._device_cache:
                if self._placement is not None or self.shard_world > 1:
                    self.get_device(i)  # placement/sharded assembly path
                else:
                    self._device_cache[i] = jax.tree.map(
                        jax.device_put,
                        self._leaf_views(self._resident[i].wire))
            return
        with self._lock:  # slot/aio state shared with the pipeline worker
            state = self._slot_state.get(i)
            if state == "full" or (state in ("wire", "reading") and not full):
                if i in self._lru:
                    self._lru.remove(i)
                self._lru.append(i)
                return
            if state is None:
                slot = self._evict_for_slot()
                self._slot_of[i] = slot
                self._lru.append(i)
            planes = self._slots[self._slot_of[i]]
            self._aio.async_pread(planes.wire, self._path(i, "wire"))
            read_bytes = planes.wire.nbytes
            if full:
                self._aio.async_pread(planes.master, self._path(i, "master"))
                self._aio.async_pread(planes.m, self._path(i, "m"))
                self._aio.async_pread(planes.v, self._path(i, "v"))
                read_bytes += (planes.master.nbytes + planes.m.nbytes
                               + planes.v.nbytes)
            if self._mem.enabled:
                self._mem.record_io("disk_read", read_bytes)
            self._slot_state[i] = "reading" if not full else "full"

    def _ensure_host(self, i: int, full: bool = False) -> _Planes:
        if self.nvme_dir is None:
            return self._resident[i]
        with self._lock:  # slot/aio state shared with the pipeline worker
            state = self._slot_state.get(i)
            if state is None or (full and state in ("wire", "reading")):
                self.prefetch(i, full=full)
            # refresh recency: the layer being used must never be the
            # eviction victim of its own read-ahead
            if i in self._lru:
                self._lru.remove(i)
            self._lru.append(i)
            failed = self._aio.wait()  # drain reads (and writes) for safety
            if failed:
                raise IOError(f"AIO read of layer {i} failed ({failed} ops)")
            self._dirty_writes = 0
            self._slot_state[i] = "full" if (full or self._slot_state.get(i)
                                             == "full") else "wire"
            return self._slots[self._slot_of[i]]

    def get_device(self, i: int) -> Any:
        """Device pytree of layer ``i``'s wire (compute-dtype) params."""
        if i not in self._device_cache:
            planes = self._ensure_host(i)
            if self._mem.enabled:
                self._mem.record_io("h2d", planes.wire.nbytes)
            if self.shard_world > 1:
                # multi-controller: hand the executor the LOCAL flat chunk;
                # it builds the device-sharded global plane and all-gathers
                # in-graph (params partitioned on host, gathered for
                # compute — the reference ZeRO-3-under-Infinity shape)
                self._device_cache[i] = self._placement(
                    np.array(planes.wire))
                return self._device_cache[i]
            views = self._leaf_views(planes.wire)
            if self._placement is not None:
                self._device_cache[i] = self._placement(views)
            else:
                # device_put is async (and on the CPU test backend it ALIASES
                # the numpy buffer for the array's whole lifetime) — hand it a
                # private snapshot so slot reuse / in-place adam updates can't
                # race the transfer or the compute reading it
                self._device_cache[i] = jax.tree.map(
                    lambda v: jax.device_put(np.array(v)), views)
        return self._device_cache[i]

    def release(self, i: int) -> None:
        """Drop the device copy (host/NVMe tiers keep theirs)."""
        self._device_cache.pop(i, None)

    # ------------------------------------------------------------------
    # optimizer update (PartitionedOptimizerSwapper role)
    # ------------------------------------------------------------------

    def begin_step(self) -> None:
        self.drain_updates()  # no update may straddle a step boundary
        self.state_step += 1

    def __del__(self):
        try:
            if getattr(self, "_pipe", None) is not None:
                self._pipe.close()
        except Exception as e:  # interpreter teardown
            from ...utils.logging import debug_once

            debug_once("swap/pipeline_del",
                       f"opt-pipeline close in __del__ failed ({e!r})")

    def _flatten_grads(self, buf: np.ndarray, grads_tree: Any,
                       accumulate: bool = False) -> None:
        """d2h the layer grad tree into a contiguous fp32 plane (optionally
        += for gradient accumulation); transfers issued async up front.

        Sharded mode: ``grads_tree`` is already this process's flat LOCAL
        chunk (the executor reduce-scatters in-graph and hands over the
        addressable slice) — land it directly."""
        if self.shard_world > 1:
            g_np = np.asarray(grads_tree, dtype=np.float32).reshape(-1)
            if self._mem.enabled:
                self._mem.record_io("d2h", g_np.nbytes)
            if accumulate:
                buf += g_np
            else:
                buf[:] = g_np
            return
        grad_leaves = jax.tree.leaves(grads_tree)
        for g in grad_leaves:
            if hasattr(g, "copy_to_host_async"):
                g.copy_to_host_async()
        for g, (shape, off) in zip(grad_leaves, self.layout):
            n = int(np.prod(shape)) if shape else 1
            g_np = np.asarray(g).reshape(-1)
            if self._mem.enabled:
                self._mem.record_io("d2h", g_np.nbytes)
            if g_np.dtype != np.float32:
                g_np = g_np.astype(np.float32)
            if accumulate:
                buf[off:off + n] += g_np
            else:
                buf[off:off + n] = g_np

    def _adam_planes(self, planes: _Planes, g: np.ndarray, lr: float) -> None:
        """ONE fused C++ Adam(W) call over the whole contiguous layer plane
        (master/m/v updated in place, bf16 wire emitted in the same pass)."""
        common = [ctypes.c_int64(self.n_plane), ctypes.c_int(self.state_step),
                  ctypes.c_float(lr), ctypes.c_float(self.betas[0]),
                  ctypes.c_float(self.betas[1]), ctypes.c_float(self.eps),
                  ctypes.c_float(self.weight_decay),
                  ctypes.c_int(int(self.adamw_mode)),
                  ctypes.c_int(int(self.bias_correction))]
        if self._wire_is_bf16:
            self._lib.ds_adam_step_bf16(
                _fp(planes.master), _fp(g), _fp(planes.m), _fp(planes.v),
                planes.wire.view(np.uint16).ctypes.data_as(_u16p), *common)
        else:
            self._lib.ds_adam_step(_fp(planes.master), _fp(g), _fp(planes.m),
                                   _fp(planes.v), *common)
            planes.wire[:] = planes.master.astype(self.wire_np_dtype)

    def step_layer(self, i: int, grads_tree: Any,
                   lr: Optional[float] = None) -> None:
        """Fused host update of layer ``i`` from device grads: d2h, C++
        Adam(W) over master/m/v, bf16 wire emit, NVMe write-behind."""
        with get_telemetry().span("swap/step_layer", args={"layer": i}):
            return self._step_layer_impl(i, grads_tree, lr)

    def _step_layer_impl(self, i: int, grads_tree: Any,
                         lr: Optional[float] = None) -> None:
        planes = self._ensure_host(i, full=True)
        # ONE shared scratch plane for the fused path (grads are consumed
        # immediately) — per-layer grad planes are stash-path-only
        if self._scratch_g is None:
            self._scratch_g = np.zeros((self.n_plane,), np.float32)
        g = self._scratch_g
        self._flatten_grads(g, grads_tree)
        self._adam_planes(planes, g, float(self.lr if lr is None else lr))
        self._device_cache.pop(i, None)
        if self.nvme_dir is not None:
            for kind, buf in (("wire", planes.wire),
                              ("master", planes.master),
                              ("m", planes.m), ("v", planes.v)):
                self._aio.async_pwrite(buf, self._path(i, kind))
            self._dirty_writes += 4

    # -- pipelined update (worker thread; see _OptPipeline) ---------------

    def step_layer_async(self, i: int, grads_tree: Any,
                         lr: Optional[float] = None) -> None:
        """Fused-path update of layer ``i``, handed to the pipeline worker
        so the device keeps computing earlier layers' backward.  The grad
        d2h starts HERE (async) — by the time the worker flattens, bytes
        are on host or in flight.  Falls back to the synchronous
        :meth:`step_layer` when the pipeline is off."""
        if self._pipe is None:
            return self.step_layer(i, grads_tree, lr)
        for g in jax.tree.leaves(grads_tree):
            if hasattr(g, "copy_to_host_async"):
                g.copy_to_host_async()
        self._device_cache.pop(i, None)  # stale wire must not serve again
        self._pipe.submit("fused", i, grads_tree,
                          None if lr is None else float(lr), 1.0)

    def apply_stashed_async(self, i: int, lr: Optional[float] = None,
                            scale: float = 1.0) -> None:
        """Pipelined second-pass update from the stashed grad plane: the
        worker's C++ Adam on layer ``i`` overlaps the main thread's
        read-ahead of layer ``i+1`` (and, nvme tier, its write-behind)."""
        if self._pipe is None:
            return self.apply_stashed(i, lr, scale)
        self._device_cache.pop(i, None)
        self._pipe.submit("stash", i, None,
                          None if lr is None else float(lr), float(scale))

    def _pipe_step(self, kind: str, i: int, grads_tree: Any,
                   lr: Optional[float], scale: float) -> None:
        """Worker body: flatten (fused path) → fused C++ Adam → tier
        write-behind.  Pins ``i`` so slot eviction can't reuse its planes
        mid-update; nvme slot/aio mutations ride ``self._lock``."""
        with self._lock:
            self._pinned.add(i)
        try:
            planes = self._ensure_host(i, full=True)
            if kind == "fused":
                if self._pipe_g is None:
                    self._pipe_g = np.zeros((self.n_plane,), np.float32)
                g = self._pipe_g
                self._flatten_grads(g, grads_tree)
            else:
                g = self._gplanes.pop(i)
                if scale != 1.0:
                    np.multiply(g, np.float32(scale), out=g)
            self._adam_planes(planes, g, float(self.lr if lr is None else lr))
            with self._lock:
                self._device_cache.pop(i, None)
                if self.nvme_dir is not None:
                    for kind2, buf in (("wire", planes.wire),
                                       ("master", planes.master),
                                       ("m", planes.m), ("v", planes.v)):
                        self._aio.async_pwrite(buf, self._path(i, kind2))
                    self._dirty_writes += 4
        finally:
            with self._lock:
                self._pinned.discard(i)

    # -- deferred update (gradient accumulation / global clipping) -------
    #
    # Grad planes ride host RAM on BOTH tiers (the reference's optimizer
    # swapper likewise stages grads in host buffers; spilling them to NVMe
    # is an option it exposes that we don't need yet): host cost is one
    # extra fp32 plane per layer only while a step is in flight.

    def stash_grads(self, i: int, grads_tree: Any,
                    accumulate: bool = False) -> None:
        """Land layer ``i``'s grads in its host grad plane instead of
        updating immediately — used when the update must wait for the
        global grad norm (clipping) or later microbatches (gas > 1)."""
        g = self._gplanes.get(i)
        if g is None:
            g = self._gplanes[i] = np.zeros((self.n_plane,), np.float32)
            accumulate = False
        self._flatten_grads(g, grads_tree, accumulate=accumulate)

    def discard_stashed(self) -> None:
        """Drop every stashed grad plane without applying (fp16 overflow
        skip: the step never happened)."""
        self._gplanes.clear()

    def cancel_step(self) -> None:
        """Roll back :meth:`begin_step`'s counter bump (fp16 overflow
        skip — Adam bias correction must not advance on a skipped step)."""
        self.drain_updates()
        self.state_step = max(self.state_step - 1, 0)

    def stashed_sq_norm(self) -> float:
        """Σ‖g‖² over every stashed grad plane — THE place that knows where
        grad planes live (today host RAM; if they ever spill to NVMe this
        method must read them back, keeping global clipping correct)."""
        return sum(float(np.dot(g, g)) for g in self._gplanes.values())

    def apply_stashed(self, i: int, lr: Optional[float] = None,
                      scale: float = 1.0) -> None:
        """Second pass: fused update of layer ``i`` from its stashed grad
        plane, scaled by ``scale`` (global clip factor)."""
        planes = self._ensure_host(i, full=True)
        g = self._gplanes.pop(i)
        if scale != 1.0:
            np.multiply(g, np.float32(scale), out=g)
        self._adam_planes(planes, g, float(self.lr if lr is None else lr))
        self._device_cache.pop(i, None)
        if self.nvme_dir is not None:
            for kind, buf in (("wire", planes.wire),
                              ("master", planes.master),
                              ("m", planes.m), ("v", planes.v)):
                self._aio.async_pwrite(buf, self._path(i, kind))
            self._dirty_writes += 4

    def flush(self) -> None:
        """Drain in-flight pipelined updates, then outstanding write-behind
        IO (end of step / checkpoint)."""
        self.drain_updates()
        with self._lock:
            if self._aio is not None and self._dirty_writes:
                failed = self._aio.wait()
                if failed:
                    raise IOError(f"AIO flush failed ({failed} ops)")
                self._dirty_writes = 0

    def drain_updates(self) -> None:
        """Wait for every pipelined optimizer update submitted so far;
        re-raises the first worker failure.  MUST run before anything that
        reads planes for a layer with an in-flight update (next-step
        ``get_device``, checkpoint export, grad-norm reads)."""
        if self._pipe is not None:
            self._pipe.drain()

    # ------------------------------------------------------------------
    # checkpoint surface
    # ------------------------------------------------------------------

    def layer_master_tree(self, i: int) -> Any:
        """fp32 master params of layer ``i`` as a (copied) pytree.
        Sharded: cross-process gather — every process gets the full tree
        (collective: all processes must call this together)."""
        self.drain_updates()
        planes = self._ensure_host(i, full=True)
        if self.shard_world > 1:
            full = self.gather_plane(planes.master)[:self.n_elems]
            views = [full[off:off + (int(np.prod(s)) if s else 1)].reshape(s)
                     for s, off in self.layout]
            return jax.tree.unflatten(self.treedef,
                                      [np.array(v) for v in views])
        return jax.tree.map(np.array, self._leaf_views(planes.master))

    def layer_moments(self, i: int) -> Dict[str, np.ndarray]:
        self.drain_updates()
        planes = self._ensure_host(i, full=True)
        if self.shard_world > 1:
            return {"m": self.gather_plane(planes.m)[:self.n_elems],
                    "v": self.gather_plane(planes.v)[:self.n_elems]}
        return {"m": np.array(planes.m), "v": np.array(planes.v)}

    def load_layer(self, i: int, master_tree: Any,
                   moments: Optional[Dict[str, np.ndarray]] = None) -> None:
        """Install restored masters (+ moments).  ``moments=None`` = a
        params-only load: existing moments are PRESERVED, not zeroed."""
        self.drain_updates()
        planes = self._ensure_host(i, full=True)
        self._fill_planes(planes, master_tree, zero_moments=False)
        if moments is not None:
            # checkpoints store GLOBAL moment vectors; sharded planes take
            # their segments (segment tails in padding are zeroed)
            gm = np.asarray(moments["m"], np.float32)
            gv = np.asarray(moments["v"], np.float32)
            poff = 0
            for lo, hi in self.segments:
                k = max(0, min(hi, self.n_elems) - lo)
                planes.m[poff:poff + k] = gm[lo:lo + k]
                planes.v[poff:poff + k] = gv[lo:lo + k]
                planes.m[poff + k:poff + (hi - lo)] = 0.0
                planes.v[poff + k:poff + (hi - lo)] = 0.0
                poff += hi - lo
        self._device_cache.pop(i, None)
        if self.nvme_dir is not None:
            self._write_layer_sync(i, planes, init=False)
